"""Tests for the OPTIONAL ML workload extension and the harness
contract (__graft_entry__.py). See tasksrunner/ml/__init__.py for why
this is an extension, not ported capability."""

import asyncio
import pathlib
import sys

import numpy as np
import pytest

import pathlib as _pathlib
import sys as _sys

_sys.path.insert(0, str(_pathlib.Path(__file__).resolve().parent.parent))

from tasksrunner.ml.platform import pin_cpu_platform  # noqa: E402

if not pin_cpu_platform():
    pytest.skip("jax cpu platform unavailable", allow_module_level=True)

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tasksrunner.ml.model import (  # noqa: E402
    ModelConfig,
    forward,
    hash_tokens,
    init_params,
    loss_fn,
    make_train_step,
    shard_params,
)

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

TINY = ModelConfig(vocab=256, seq_len=8, d_model=32, n_heads=2, d_ff=64,
                   n_layers=2, n_classes=5)


def test_forward_shapes_and_determinism():
    params = init_params(TINY, jax.random.PRNGKey(0))
    tokens = hash_tokens(["fix the deploy", "write docs now"], TINY)
    assert tokens.shape == (2, TINY.seq_len)
    logits = forward(params, tokens, cfg=TINY)
    assert logits.shape == (2, TINY.n_classes)
    assert jnp.allclose(logits, forward(params, tokens, cfg=TINY))


def test_train_step_reduces_loss_single_device():
    params = init_params(TINY, jax.random.PRNGKey(0))
    step = make_train_step(TINY, learning_rate=0.1)
    tokens = hash_tokens([f"task number {i}" for i in range(8)], TINY)
    labels = jnp.asarray([i % TINY.n_classes for i in range(8)], jnp.int32)
    _, first_loss = make_train_step(TINY)(
        jax.tree.map(jnp.copy, params), tokens, labels)
    for _ in range(10):
        params, loss = step(params, tokens, labels)
    assert float(loss) < float(first_loss)


def test_sharded_train_step_matches_single_device():
    """dp×tp sharded step must be numerically equivalent (up to bf16
    noise) to the single-device step — the correctness check for the
    sharding layout."""
    from jax.sharding import Mesh

    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provide the virtual 8-cpu mesh"
    mesh = Mesh(np.array(devices[:8]).reshape(4, 2), ("dp", "tp"))

    params = init_params(TINY, jax.random.PRNGKey(1))
    tokens = hash_tokens([f"alpha beta {i}" for i in range(16)], TINY)
    labels = jnp.asarray([i % TINY.n_classes for i in range(16)], jnp.int32)

    single_params, single_loss = make_train_step(TINY)(
        jax.tree.map(jnp.copy, params), tokens, labels)

    with mesh:
        sharded = shard_params(jax.tree.map(jnp.copy, params), mesh, TINY)
        step = make_train_step(TINY, mesh)
        new_params, loss = step(sharded, tokens, labels)
        jax.block_until_ready(loss)

    assert abs(float(loss) - float(single_loss)) < 1e-2
    # spot-check one updated weight agrees across layouts
    a = np.asarray(single_params["head"])
    b = np.asarray(new_params["head"])
    np.testing.assert_allclose(a, b, atol=2e-2)


def test_graft_entry_contract():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]

    g.dryrun_multichip(8)
    g.dryrun_multichip(4)

    with pytest.raises(RuntimeError, match="need"):
        g.dryrun_multichip(1024)


def test_dryrun_multichip_catches_broken_collective(monkeypatch):
    """The dryrun must be SELF-verifying: sabotage the sequence-parallel
    collective (ring attention sees only its local K/V block — one ring
    hop missing) and the dryrun has to fail, not print a plausible
    loss."""
    import __graft_entry__ as g
    import tasksrunner.ml.ring as ring_mod

    real = ring_mod.ring_attention

    def broken_ring_attention(q, k, v, *, mesh):
        # zero the second half of K/V: the blocks a working ring would
        # deliver from the other sp shard arrive corrupted — the
        # forward loss visibly shifts and the dryrun must notice
        half = k.shape[1] // 2
        return real(q, k.at[:, half:].set(0), v.at[:, half:].set(0),
                    mesh=mesh)

    monkeypatch.setattr(ring_mod, "ring_attention", broken_ring_attention)
    with pytest.raises(AssertionError, match="diverge"):
        g.dryrun_multichip(8)


def test_ring_attention_matches_dense():
    """Ring attention over an sp axis must equal full attention (up to
    bf16 noise): the per-block flash accumulation and ppermute rotation
    see every K/V block exactly once."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from tasksrunner.ml.ring import ring_attention

    devices = jax.devices()
    mesh = Mesh(np.array(devices[:8]).reshape(1, 4, 2), ("dp", "sp", "tp"))
    b, s, h, dh = 2, 16, 4, 8
    q, k, v = (jax.random.normal(key, (b, s, h, dh), jnp.float32)
               for key in jax.random.split(jax.random.PRNGKey(7), 3))

    scale = 1.0 / dh ** 0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.bfloat16),
                        k.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32) * scale
    probs = jax.nn.softmax(logits, -1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(jnp.bfloat16),
                     v.astype(jnp.bfloat16),
                     preferred_element_type=jnp.float32)

    with mesh:
        sh = NamedSharding(mesh, P("dp", "sp", "tp", None))
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh))(
            jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


def test_sequence_parallel_train_step_matches_single_device():
    """Full train step on a dp×sp×tp mesh (ring attention path,
    sequence-sharded tokens) must match the single-device step."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    mesh = Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))

    params = init_params(TINY, jax.random.PRNGKey(2))
    tokens = hash_tokens([f"gamma delta {i}" for i in range(8)], TINY)
    labels = jnp.asarray([i % TINY.n_classes for i in range(8)], jnp.int32)

    single_params, single_loss = make_train_step(TINY)(
        jax.tree.map(jnp.copy, params), tokens, labels)

    with mesh:
        sharded = shard_params(jax.tree.map(jnp.copy, params), mesh, TINY)
        step = make_train_step(TINY, mesh)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
        lab_sh = jax.device_put(labels, NamedSharding(mesh, P("dp")))
        new_params, loss = step(sharded, tok_sh, lab_sh)
        jax.block_until_ready(loss)

    assert abs(float(loss) - float(single_loss)) < 2e-2
    np.testing.assert_allclose(np.asarray(single_params["head"]),
                               np.asarray(new_params["head"]), atol=2e-2)


@pytest.mark.asyncio
async def test_scorer_service_on_the_runtime():
    """The workload service slots into the building blocks like any
    other app: invoke /score synchronously, and saved-task events get
    scored via the subscription and written to the scores state."""
    from tasksrunner import App, InProcCluster
    from tasksrunner.component.spec import parse_component
    from tasksrunner.ml.service import PRIORITY_LABELS, make_app

    specs = [
        parse_component({"componentType": "state.in-memory"},
                        default_name="scores"),
        parse_component({"componentType": "pubsub.in-memory"},
                        default_name="taskspubsub"),
    ]
    scorer = make_app()
    publisher = App("some-api")

    cluster = InProcCluster(specs)
    cluster.add_app(scorer)
    cluster.add_app(publisher)
    await cluster.start()
    try:
        client = cluster.client("some-api")
        # synchronous inference over service invocation
        resp = await client.invoke_method(
            "priority-scorer", "score", data={"taskName": "fix prod outage"})
        assert resp.status == 200
        doc = resp.json()
        assert doc["priority"] in PRIORITY_LABELS
        assert 0.0 < doc["confidence"] <= 1.0

        # async scoring through the pub/sub block
        await client.publish_event(
            "taskspubsub", "tasksavedtopic",
            {"taskId": "t-42", "taskName": "water the plants"})
        deadline = asyncio.get_running_loop().time() + 10
        score = None
        while score is None:
            assert asyncio.get_running_loop().time() < deadline
            r = await client.invoke_method("priority-scorer", "scores/t-42",
                                           http_method="GET")
            if r.status == 200:
                score = r.json()
            else:
                await asyncio.sleep(0.05)
        assert score["priority"] in PRIORITY_LABELS
    finally:
        await cluster.stop()


# -- Pallas flash kernels (tasksrunner/ml/flash.py) ----------------------
# Off-TPU these run in interpreter mode, so the EXACT kernel bodies are
# exercised on CPU against the einsum reference.

def _einsum_attention(q, k, v):
    dh = q.shape[-1]
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits / jnp.sqrt(jnp.float32(dh)), axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16), preferred_element_type=jnp.float32)


def test_flash_attention_matches_einsum_forward_and_grad():
    from tasksrunner.ml.flash import flash_attention

    key = jax.random.key(7)
    b, s, h, d = 2, 64, 4, 32
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))

    out = flash_attention(q, k, v)
    ref = _einsum_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-3, rtol=5e-3)

    # gradients: the custom VJP (flash backward kernel) against
    # autodiff through the einsum pair
    def loss_of(attn):
        return lambda *qkv: jnp.sum(jnp.sin(attn(*qkv)))

    g_flash = jax.grad(loss_of(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_of(_einsum_attention), argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-2, rtol=1e-2)


def test_ring_block_update_pallas_matches_einsum():
    from tasksrunner.ml.flash import ring_block_update
    from tasksrunner.ml.ring import _block_update

    key = jax.random.key(9)
    b, sq, sk, h, d = 2, 16, 24, 2, 32
    ks = jax.random.split(key, 6)
    q = jax.random.normal(ks[0], (b, sq, h, d))
    k_blk = jax.random.normal(ks[1], (b, sk, h, d))
    v_blk = jax.random.normal(ks[2], (b, sk, h, d))
    m = jax.random.normal(ks[3], (b, h, sq))
    num = jax.random.normal(ks[4], (b, h, sq, d))
    den = jax.nn.softplus(jax.random.normal(ks[5], (b, h, sq)))
    scale = 1.0 / d ** 0.5

    got = ring_block_update(q, k_blk, v_blk, m, num, den, scale=scale)
    want = _block_update(q, k_blk, v_blk, m, num, den, scale=scale)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=1e-5, rtol=1e-5)


def test_flash_toggle_changes_attention_core(monkeypatch):
    """TASKSRUNNER_FLASH=0 falls back to the einsum pair; both cores
    produce the same logits for the same params."""
    from tasksrunner.ml import model as model_mod

    key = jax.random.key(3)
    params = init_params(TINY, key)
    tokens = jax.random.randint(key, (4, TINY.seq_len), 0, TINY.vocab,
                                dtype=jnp.int32)
    monkeypatch.setenv("TASKSRUNNER_FLASH", "0")
    ref = forward(params, tokens, cfg=TINY)
    monkeypatch.setenv("TASKSRUNNER_FLASH", "1")
    got = forward(params, tokens, cfg=TINY)
    # bf16 rounding differs slightly between the two cores and
    # accumulates over layers — this asserts same-computation, not
    # bit-identity
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("n,dp,sp,tp", [
    (2, 1, 1, 2),   # tp-only: both heads-halves on separate devices
    (2, 2, 1, 1),   # dp-only: pure data parallelism, no collectives
                    # inside the model at all
    (4, 2, 1, 2),   # dp x tp: the classic 2D layout, no ring
    (8, 4, 1, 2),   # asymmetric: wide dp, tp pair, sp off
    (8, 1, 4, 2),   # sp-heavy: 4-stage ring attention + tp pair
])
def test_dryrun_mesh_factorization_matrix(n, dp, sp, tp):
    """Round-5 verdict item 6: the multichip path must hold under MORE
    than the one 2x2x2 happy path. Each factorization runs the same
    self-verifying dryrun (sharded loss AND updated params must match
    the single-device step) — a PartitionSpec that only works when
    every axis is 2 fails here."""
    import __graft_entry__ as g

    g._dryrun_factored(n, dp=dp, sp=sp, tp=tp)


def test_dryrun_factored_rejects_bad_factorization():
    import __graft_entry__ as g

    with pytest.raises(ValueError, match="devices"):
        g._dryrun_factored(8, dp=2, sp=1, tp=2)   # 4 != 8
    with pytest.raises(ValueError, match="divide"):
        g._dryrun_factored(8, dp=1, sp=1, tp=8)   # 8 ∤ n_heads=4


@pytest.mark.parametrize("env", [
    {"TASKSRUNNER_FLASH_BWD_DELTA": "precompute"},
    {"TASKSRUNNER_FLASH_HBLK_BWD": "1"},
    {"TASKSRUNNER_FLASH_HBLK_BWD": "2",
     "TASKSRUNNER_FLASH_HBLK_FWD": "2"},
    {"TASKSRUNNER_FLASH_BWD_DELTA": "precompute",
     "TASKSRUNNER_FLASH_HBLK_BWD": "4",
     "TASKSRUNNER_FLASH_HBLK_FWD": "4"},
])
def test_flash_backward_variants_match_einsum(monkeypatch, env):
    """Every sweepable kernel configuration (scripts/sweep_flash_bwd.py
    explores these on-chip) must be numerically interchangeable: the
    sweep may only ever trade SPEED. Exercised in interpret mode so
    the exact kernel bodies run on CPU."""
    from tasksrunner.ml.flash import flash_attention

    for k, v in env.items():
        monkeypatch.setenv(k, v)
    key = jax.random.key(11)
    b, s, h, d = 2, 64, 4, 32
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_of(attn):
        return lambda *qkv: jnp.sum(jnp.sin(attn(*qkv)))

    out = flash_attention(q, k, v)
    ref = _einsum_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-3, rtol=5e-3)
    g_flash = jax.grad(loss_of(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_of(_einsum_attention), argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-2, rtol=1e-2)


def test_flash_hblk_override_rejects_nondivisor(monkeypatch):
    from tasksrunner.ml.flash import flash_attention

    monkeypatch.setenv("TASKSRUNNER_FLASH_HBLK_FWD", "3")
    q = jnp.zeros((1, 8, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="divide n_heads"):
        flash_attention(q, q, q)
