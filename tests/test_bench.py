"""Performance regression guard for the benchmark topology.

Runs a scaled-down version of bench.py's headline measurement — the
faithful cross-process topology (separate api/processor OS processes,
the [PB] process boundaries of SURVEY.md §3.1 over real localhost
HTTP) — and fails if throughput or tail latency regress.

Calibration (round 3, this hardware): ~1,180 tasks/s, p50 7.3 ms,
p99 19 ms. Floors sit within ~2.5x of those so a real regression (a
serialization bug, an accidental per-request reconnect, a reintroduced
intra-process HTTP hop, a broker poll pathology) trips the suite while
ordinary host noise does not. A deliberate 3x slowdown MUST fail here.

On a machine slower than the calibration host (shared CI), skip these
wall-clock tests with TASKSRUNNER_PERF_TESTS=0 rather than loosening
the floors — loose floors guard nothing.
"""

import os
import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from bench import run_xproc  # noqa: E402

from tasksrunner.envflag import env_flag  # noqa: E402

pytestmark = pytest.mark.skipif(
    not env_flag("TASKSRUNNER_PERF_TESTS"),
    reason="wall-clock perf gates disabled (TASKSRUNNER_PERF_TESTS=0)")


async def test_xproc_write_path_throughput_and_latency():
    result = await run_xproc(
        n_tasks=200, warmup=20, rounds=2, latency_probe=True)
    # measured 1,181 tasks/s; floor at 450 = a 2.6x regression budget
    assert result["throughput"] > 450, (
        f"cross-process write path regressed: {result['throughput']} tasks/s")
    # measured p99 15-22 ms at concurrency 8 across runs; floor at 45 ms
    assert result["p99_ms"] < 45, (
        f"write-path p99 regressed: {result['p99_ms']} ms")


async def test_xproc_competing_consumers_scale():
    # with 25 ms of work per message one replica caps at ~40/s; three
    # replicas must demonstrably beat one (competing-consumer contract,
    # SURVEY.md §5.8). Measured ~2.8x on this host; floor at 2.0x.
    one = await run_xproc(n_tasks=60, warmup=5, rounds=1, work_ms=25.0)
    three = await run_xproc(n_tasks=60, warmup=5, rounds=1,
                            n_processors=3, work_ms=25.0)
    assert three["throughput"] > 2.0 * one["throughput"], (
        f"scale-out broken: 1 replica {one['throughput']} tasks/s, "
        f"3 replicas {three['throughput']} tasks/s")
