"""Performance regression guard for the benchmark topology.

Runs a scaled-down version of bench.py's headline measurement — the
faithful cross-process topology (separate api/processor OS processes,
every [PB] hop of SURVEY.md §3.1 over real localhost HTTP) — and fails
if throughput or tail latency regress past conservative floors.

The floors are ~5x below the measured numbers on this hardware
(≈330 tasks/s, p99 ≈70 ms) so the test only trips on a real
regression (a serialization bug, an accidental per-request reconnect,
a broker poll pathology), not on host noise.
"""

import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from bench import run_xproc  # noqa: E402


async def test_xproc_write_path_throughput_and_latency():
    result = await run_xproc(
        n_tasks=120, warmup=10, rounds=1, latency_probe=True)
    assert result["throughput"] > 60, (
        f"cross-process write path regressed: {result['throughput']} tasks/s")
    assert result["p99_ms"] < 500, (
        f"write-path p99 regressed: {result['p99_ms']} ms")


async def test_xproc_competing_consumers_scale():
    # with 25 ms of work per message one replica caps at ~40/s; three
    # replicas must demonstrably beat one (competing-consumer contract,
    # SURVEY.md §5.8) — floor at 1.5x to stay noise-proof
    one = await run_xproc(n_tasks=60, warmup=5, rounds=1, work_ms=25.0)
    three = await run_xproc(n_tasks=60, warmup=5, rounds=1,
                            n_processors=3, work_ms=25.0)
    assert three["throughput"] > 1.5 * one["throughput"], (
        f"scale-out broken: 1 replica {one['throughput']} tasks/s, "
        f"3 replicas {three['throughput']} tasks/s")
