"""Performance regression guard for the benchmark topology.

Runs a scaled-down version of bench.py's headline measurement — the
faithful cross-process topology (separate api/processor OS processes,
the [PB] process boundaries of SURVEY.md §3.1 over real localhost
transports) — and fails if throughput or tail latency regress.

The floors are CALIBRATION-RELATIVE (round 4): a fixed-work probe
(json + hashing + sqlite commits — the write path's instruction mix)
measures how fast THIS host executes the framework's kind of work, and
the floors scale by the ratio to the dev-host baseline. A slower CI
runner gets a proportionally lower floor instead of a skipped gate —
fixed floors had to be disabled on shared runners, which meant a 2x
regression merged green everywhere (round-3 verdict). Hosts measuring
under half the baseline are outside the calibration's linear range:
the gate SKIPS there with the measured ratio in the message (visible
in the test summary, unlike a permanently-exported env var), and
TASKSRUNNER_PERF_TESTS=0 stays available as the manual override.
Faster hosts cap at 1.5x, and the p99 ceiling never tightens below
its baseline (tail latency is fixed-cost dominated).

A deliberate slowdown MUST trip the gate: the last test injects one
(per-message work in the consumer, capping the pipeline well under the
floor) and asserts the same gate logic fails it.

Dev-host baselines (1-core, round 4): calibration ~110k ops/s; gate
topology ~1,600-2,400 tasks/s (200-task rounds), p99 12-22 ms.
"""

import functools
import hashlib
import json
import pathlib
import sqlite3
import sys
import time

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from bench import run_xproc  # noqa: E402

from tasksrunner.envflag import env_flag  # noqa: E402

pytestmark = pytest.mark.skipif(
    not env_flag("TASKSRUNNER_PERF_TESTS"),
    reason="wall-clock perf gates disabled (TASKSRUNNER_PERF_TESTS=0)")

#: calibration ops/s on the host the floors were tuned on
CAL_BASELINE = 110_000.0
#: throughput floor AT the calibration baseline — ~2.2x under the
#: measured 1,600-2,400 tasks/s band for this scaled-down run
BASE_THROUGHPUT_FLOOR = 900.0
#: p99 ceiling at the baseline (measured 12-22 ms at concurrency 8)
BASE_P99_CEILING_MS = 40.0


def calibrate(n: int = 3000, rounds: int = 3) -> float:
    """ops/s of a fixed probe with the write path's instruction mix:
    JSON encode/decode, hashing, sqlite inserts with batched commits.
    Best-of-rounds — transient host contention only lowers a round."""
    doc = {"taskName": "calibration task", "taskCreatedBy": "cal@x.com",
           "taskDueDate": "2026-08-01T00:00:00", "isCompleted": False}
    best = 0.0
    for _ in range(rounds):
        conn = sqlite3.connect(":memory:")
        conn.execute("CREATE TABLE t (k TEXT PRIMARY KEY, v TEXT)")
        t0 = time.perf_counter()
        for i in range(n):
            s = json.dumps({**doc, "taskId": f"t{i}"})
            json.loads(s)
            h = hashlib.sha256(s.encode()).hexdigest()
            conn.execute("INSERT OR REPLACE INTO t VALUES (?, ?)",
                         (h[:16], s))
            if i % 64 == 0:
                conn.commit()
        conn.commit()
        conn.close()
        best = max(best, n / (time.perf_counter() - t0))
    return best


@functools.cache
def host_ratio() -> float:
    """This host's speed relative to the calibration baseline (cached:
    every gate in the session must judge against the SAME ratio).

    Hosts measuring below half the baseline are outside the
    calibration's linear range — the gate SKIPS there, visibly, rather
    than failing every run on a floor that was never calibrated for
    them (the round-3 fixed floors died exactly that death). Faster
    hosts are capped at 1.5x so a probe overestimate cannot raise the
    floor past the measured band."""
    ratio = calibrate() / CAL_BASELINE
    if ratio < 0.5:
        pytest.skip(
            f"host measures {ratio:.2f}x the calibration baseline — "
            f"outside the perf gate's linear range (<0.5x); floors "
            f"would be uncalibrated noise here")
    return min(1.5, ratio)


def check_gate(result: dict, ratio: float) -> list[str]:
    """The gate logic, shared by the real gate and the
    simulated-regression test: [] = pass, else failure messages."""
    failures = []
    floor = BASE_THROUGHPUT_FLOOR * ratio
    if result["throughput"] <= floor:
        failures.append(
            f"cross-process write path regressed: {result['throughput']} "
            f"tasks/s <= floor {floor:.0f} (host ratio {ratio:.2f})")
    if "p99_ms" in result:
        # slower hosts get a raised ceiling; faster hosts KEEP the
        # baseline ceiling (tail latency is dominated by fixed costs —
        # localhost RTT, event-loop scheduling — that do not shrink
        # with per-core speed, so tightening would false-positive)
        ceiling = BASE_P99_CEILING_MS / min(ratio, 1.0)
        if result["p99_ms"] >= ceiling:
            failures.append(
                f"write-path p99 regressed: {result['p99_ms']} ms >= "
                f"ceiling {ceiling:.0f} ms (host ratio {ratio:.2f})")
    return failures


async def test_xproc_write_path_throughput_and_latency():
    # one bounded retry: on this 1-core host the calibration probe and
    # the topology run sample load at DIFFERENT moments, so a transient
    # spike between them (another test's teardown, page-cache churn)
    # can skew the ratio. A real regression fails both attempts; the
    # second attempt re-calibrates so the ratio matches its own run.
    last_failures: list[str] = []
    for attempt in range(2):
        if attempt:
            host_ratio.cache_clear()
        ratio = host_ratio()
        result = await run_xproc(
            n_tasks=200, warmup=20, rounds=2, latency_probe=True)
        # the latency gate must never silently vanish: the probe's key
        # is part of run_xproc's contract for this call
        assert "p99_ms" in result, f"latency probe missing from {result}"
        last_failures = check_gate(result, ratio)
        if not last_failures:
            return
    assert not last_failures, last_failures


async def test_xproc_competing_consumers_scale():
    # with 25 ms of work per message one replica caps at ~40/s; three
    # replicas must demonstrably beat one (competing-consumer contract,
    # SURVEY.md §5.8). A ratio of throughputs — host-speed independent.
    one = await run_xproc(n_tasks=60, warmup=5, rounds=1, work_ms=25.0)
    three = await run_xproc(n_tasks=60, warmup=5, rounds=1,
                            n_processors=3, work_ms=25.0)
    assert three["throughput"] > 2.0 * one["throughput"], (
        f"scale-out broken: 1 replica {one['throughput']} tasks/s, "
        f"3 replicas {three['throughput']} tasks/s")


async def test_gate_catches_simulated_regression():
    """The gate's reason to exist, proven every run: inject a real
    slowdown (3 ms of per-message consumer work drags pipeline
    completion under ~350 tasks/s — like a reintroduced blocking call
    in the delivery handler) and the SAME gate logic must fail it."""
    ratio = host_ratio()
    slowed = await run_xproc(n_tasks=120, warmup=10, rounds=1, work_ms=3.0)
    failures = check_gate(slowed, ratio)
    assert failures, (
        f"gate failed to catch a simulated regression: "
        f"{slowed['throughput']} tasks/s passed floor "
        f"{BASE_THROUGHPUT_FLOOR * ratio:.0f}")


def test_gate_skips_visibly_below_linear_range(monkeypatch):
    """A host slower than the calibration's linear range must SKIP
    with the measured ratio in the message — neither fail on
    uncalibrated floors (the round-3 death) nor silently pass."""
    import test_bench as tb
    monkeypatch.setattr(tb, "calibrate", lambda *a, **k: CAL_BASELINE * 0.3)
    tb.host_ratio.cache_clear()
    try:
        with pytest.raises(pytest.skip.Exception, match="0.30x"):
            tb.host_ratio()
    finally:
        tb.host_ratio.cache_clear()
