"""The shared boolean-env-knob parser (tasksrunner/envflag.py): every
toggle (TASKSRUNNER_ACCESS_LOG, TASKSRUNNER_FLASH,
TASKSRUNNER_PERF_TESTS) must accept the same spellings."""

import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tasksrunner.envflag import env_flag


@pytest.mark.parametrize("raw", ["0", "false", "off", "no", "OFF", " False "])
def test_disable_spellings(monkeypatch, raw):
    monkeypatch.setenv("X_FLAG", raw)
    assert env_flag("X_FLAG") is False


@pytest.mark.parametrize("raw", ["1", "true", "on", "yes", "anything"])
def test_enable_spellings(monkeypatch, raw):
    monkeypatch.setenv("X_FLAG", raw)
    assert env_flag("X_FLAG") is True


def test_unset_uses_default(monkeypatch):
    monkeypatch.delenv("X_FLAG", raising=False)
    assert env_flag("X_FLAG") is True
    assert env_flag("X_FLAG", default=False) is False


def test_consumers_share_the_parser(monkeypatch):
    """The knob consumers must all flip with one spelling — a
    per-call-site tuple would drift."""
    from tasksrunner.hosting import _access_log

    monkeypatch.setenv("TASKSRUNNER_ACCESS_LOG", "off")
    assert _access_log() is None
    monkeypatch.setenv("TASKSRUNNER_ACCESS_LOG", "on")
    assert _access_log() is not None

    from tasksrunner.runtime import _delivery_logs
    monkeypatch.setenv("TASKSRUNNER_ACCESS_LOG", "no")
    assert _delivery_logs() is False
