"""Mesh mTLS (tasksrunner/invoke/pki.py + the mesh lane under TLS).

≙ the reference's architecture note that Dapr sidecars communicate
over mutual TLS with workload certs from a trust-domain CA
(docs/aca/03-aca-dapr-integration/index.md:30-38). The contract under
test: with certs provisioned, the mesh refuses anonymous dialers and
imposters; the dialing side PINS the app-id it meant to reach; and
the whole orchestrated environment keeps working with `mesh_tls: true`
— the security upgrade is invisible to apps.
"""

import asyncio
import ssl

import pytest

from tasksrunner.invoke.mesh import MeshConnectError, MeshPool, MeshServer
from tasksrunner.invoke.pki import (
    CA_ENV,
    CERT_ENV,
    KEY_ENV,
    generate_ca,
    issue_cert,
    write_pki,
)
from tests.test_mesh import FakeRuntime


@pytest.fixture
def pki(tmp_path, monkeypatch):
    """A provisioned environment: CA + certs for two apps; this
    process runs as 'backend-api'."""
    paths = write_pki(tmp_path / "pki", ["backend-api", "frontend"])
    monkeypatch.setenv(CA_ENV, paths["backend-api"]["ca"])
    monkeypatch.setenv(CERT_ENV, paths["backend-api"]["cert"])
    monkeypatch.setenv(KEY_ENV, paths["backend-api"]["key"])
    return tmp_path / "pki"


@pytest.mark.asyncio
async def test_mtls_roundtrip_and_identity_pinning(pki):
    srv = MeshServer(FakeRuntime(), api_token=None)
    await srv.start()
    pool = MeshPool()
    try:
        # the dial names the identity it expects — the server's cert
        # carries SAN backend-api, so this handshake succeeds
        status, _, body = await pool.request(
            "127.0.0.1", srv.port, "backend-api", "GET", "/x", body=b"hi")
        assert status == 200

        # pinning: dialing the SAME port expecting a DIFFERENT app must
        # fail the handshake (a hijacked registry entry pointing a
        # frontend invoke at this port gets no connection at all)
        pool2 = MeshPool()
        try:
            with pytest.raises(MeshConnectError):
                await pool2.request(
                    "127.0.0.1", srv.port, "frontend", "GET", "/x")
        finally:
            await pool2.close()
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_anonymous_client_refused(pki, monkeypatch):
    """The 'm' in mTLS: a dialer with no client cert is dropped during
    the handshake — non-members cannot even speak the protocol."""
    srv = MeshServer(FakeRuntime(), api_token=None)
    await srv.start()
    try:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        import os
        ctx.load_verify_locations(os.environ[CA_ENV])
        # the refusal may surface as a handshake alert (SSLError), a
        # reset, or a clean EOF on the first read (IncompleteReadError)
        with pytest.raises((ssl.SSLError, ConnectionError, OSError,
                            asyncio.IncompleteReadError)):
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", srv.port, ssl=ctx,
                server_hostname="backend-api")
            # TLS 1.3: the missing-cert alert can arrive on first read
            writer.write(b"\x00\x00\x00\x04\x00\x00\x00\x00")
            await writer.drain()
            await asyncio.wait_for(reader.readexactly(4), timeout=5)
    finally:
        await srv.stop()


@pytest.mark.asyncio
async def test_foreign_ca_refused(pki, tmp_path, monkeypatch):
    """A client cert from a DIFFERENT CA (another environment) fails
    verification — trust is per-environment, exactly like the
    reference's trust domain."""
    srv = MeshServer(FakeRuntime(), api_token=None)
    await srv.start()
    # a parallel universe: its own CA, its own 'backend-api' cert
    evil_ca, evil_key = generate_ca("evil-ca")
    cert, key = issue_cert(evil_ca, evil_key, "backend-api")
    (tmp_path / "evil-cert.pem").write_bytes(cert)
    (tmp_path / "evil-key.pem").write_bytes(key)
    monkeypatch.setenv(CERT_ENV, str(tmp_path / "evil-cert.pem"))
    monkeypatch.setenv(KEY_ENV, str(tmp_path / "evil-key.pem"))
    pool = MeshPool()
    try:
        with pytest.raises((MeshConnectError, ConnectionError, OSError)):
            await pool.request(
                "127.0.0.1", srv.port, "backend-api", "GET", "/x")
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_plaintext_client_cannot_reach_tls_mesh(pki):
    """With TLS on, a plaintext mesh frame is not a valid handshake —
    downgrade is impossible by construction."""
    srv = MeshServer(FakeRuntime(), api_token=None)
    await srv.start()
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", srv.port)
        try:
            writer.write(b"\x00\x00\x00\x08\x00\x00\x00\x04{}\x00\x00")
            await writer.drain()
            # the server never answers with ANYTHING readable as a mesh
            # frame: the failed handshake kills the connection (at most
            # a TLS alert arrives before EOF, never a frame header)
            data = await asyncio.wait_for(reader.read(4096), timeout=5)
            assert not data.startswith(b"\x00\x00"), data
            rest = await asyncio.wait_for(reader.read(4096), timeout=5)
            assert rest == b""
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
    finally:
        await srv.stop()


# ---------------------------------------------------------------------------
# the orchestrated environment with mesh_tls: the upgrade is invisible
# ---------------------------------------------------------------------------

def test_run_config_mesh_tls_roundtrip(tmp_path):
    """manifest security.mesh_tls → emitted run config → RunConfig."""
    from tasksrunner.orchestrator.config import load_run_config

    cfg = tmp_path / "run.yaml"
    cfg.write_text(
        "mesh_tls: true\n"
        "apps:\n"
        "  - app_id: a\n"
        "    module: x:make_app\n")
    rc = load_run_config(cfg)
    assert rc.mesh_tls is True
    assert rc.mesh_certs == {}


@pytest.mark.asyncio
async def test_no_plaintext_downgrade_on_handshake_failure(tmp_path,
                                                          monkeypatch):
    """THE security property: with certs provisioned, a mesh endpoint
    that fails the handshake must cause a REFUSAL — never a silent
    fallback that hands the request (token header included) to the
    very endpoint that just failed to prove itself over plaintext
    HTTP."""
    from tests.test_mesh import COMPONENTS, _apps
    from tasksrunner import AppHost, load_components
    from tasksrunner.errors import TasksRunnerError
    from tasksrunner.invoke.resolver import AppAddress

    paths = write_pki(tmp_path / "pki", ["backend-api", "frontend"])
    monkeypatch.setenv(CA_ENV, paths["backend-api"]["ca"])
    monkeypatch.setenv(CERT_ENV, paths["backend-api"]["cert"])
    monkeypatch.setenv(KEY_ENV, paths["backend-api"]["key"])
    monkeypatch.delenv("TASKSRUNNER_MESH", raising=False)

    (tmp_path / "components.yaml").write_text(COMPONENTS)
    specs = load_components(tmp_path)
    registry = str(tmp_path / "apps.json")
    api, front = _apps()
    hosts = [AppHost(api, specs=specs, registry_file=registry),
             AppHost(front, specs=specs, registry_file=registry)]
    for h in hosts:
        await h.start()

    # the attack: a rogue plain-TCP listener; the registry entry for
    # backend-api is re-pointed at it for the mesh, while the HTTP
    # port still leads to the REAL sidecar — a downgrade would
    # "succeed", which is exactly what must not happen
    async def rogue(reader, writer):
        await reader.read(-1)
        writer.close()

    rogue_srv = await asyncio.start_server(rogue, "127.0.0.1", 0)
    rogue_port = rogue_srv.sockets[0].getsockname()[1]
    try:
        real = hosts[0].resolver.resolve("backend-api")
        hosts[0].resolver.register(AppAddress(
            app_id="backend-api", host=real.host,
            sidecar_port=real.sidecar_port, app_port=real.app_port,
            pid=real.pid, mesh_port=rogue_port))
        with pytest.raises(TasksRunnerError):
            await hosts[1].app.client.invoke_method(
                "backend-api", "api/echo", http_method="POST", data={})
    finally:
        rogue_srv.close()
        await rogue_srv.wait_closed()
        for h in hosts:
            await h.stop()


@pytest.mark.asyncio
async def test_no_plaintext_fallback_for_meshless_peer(tmp_path,
                                                       monkeypatch):
    """The quieter downgrade: a registry entry with NO mesh_port at all
    (legacy registration, a TASKSRUNNER_MESH=0 peer, or a tampered
    entry that simply dropped the field). Nothing fails loudly — the
    old behavior was to route straight over plaintext HTTP, token
    header and all, with no peer identity check. Under mesh_tls that
    path must be refused exactly like a failed handshake."""
    from tests.test_mesh import COMPONENTS, _apps
    from tasksrunner import AppHost, load_components
    from tasksrunner.errors import TasksRunnerError
    from tasksrunner.invoke.resolver import AppAddress

    paths = write_pki(tmp_path / "pki", ["backend-api", "frontend"])
    monkeypatch.setenv(CA_ENV, paths["backend-api"]["ca"])
    monkeypatch.setenv(CERT_ENV, paths["backend-api"]["cert"])
    monkeypatch.setenv(KEY_ENV, paths["backend-api"]["key"])
    monkeypatch.delenv("TASKSRUNNER_MESH", raising=False)

    (tmp_path / "components.yaml").write_text(COMPONENTS)
    specs = load_components(tmp_path)
    registry = str(tmp_path / "apps.json")
    api, front = _apps()
    hosts = [AppHost(api, specs=specs, registry_file=registry),
             AppHost(front, specs=specs, registry_file=registry)]
    for h in hosts:
        await h.start()
    try:
        # strip the mesh lane from backend-api's entry; its HTTP port
        # still leads to the real, working sidecar — so the ONLY way
        # this invoke can "succeed" is by the forbidden plaintext hop
        real = hosts[0].resolver.resolve("backend-api")
        hosts[0].resolver.register(AppAddress(
            app_id="backend-api", host=real.host,
            sidecar_port=real.sidecar_port, app_port=real.app_port,
            pid=real.pid, mesh_port=None))
        with pytest.raises(TasksRunnerError):
            await hosts[1].app.client.invoke_method(
                "backend-api", "api/echo", http_method="POST", data={})
    finally:
        for h in hosts:
            await h.stop()


@pytest.mark.asyncio
async def test_apphost_pair_over_mtls(tmp_path, monkeypatch):
    """Two AppHosts with provisioned certs: invokes ride the TLS mesh
    end-to-end, and the app observes nothing different."""
    from tests.test_mesh import COMPONENTS, _apps
    from tasksrunner import AppHost, load_components

    paths = write_pki(tmp_path / "pki", ["backend-api", "frontend"])
    # both hosts share this process: use backend-api's identity for
    # serving; the pinning test above covers identity mismatches
    monkeypatch.setenv(CA_ENV, paths["backend-api"]["ca"])
    monkeypatch.setenv(CERT_ENV, paths["backend-api"]["cert"])
    monkeypatch.setenv(KEY_ENV, paths["backend-api"]["key"])
    monkeypatch.delenv("TASKSRUNNER_MESH", raising=False)

    (tmp_path / "components.yaml").write_text(COMPONENTS)
    specs = load_components(tmp_path)
    registry = str(tmp_path / "apps.json")
    api, front = _apps()
    hosts = [AppHost(api, specs=specs, registry_file=registry),
             AppHost(front, specs=specs, registry_file=registry)]
    for h in hosts:
        await h.start()
    try:
        resp = await hosts[1].app.client.invoke_method(
            "frontend", "go", query="n=9", http_method="GET")
        assert resp.json() == {"got": {"n": 9}, "app": "backend-api"}
        pool = hosts[1].sidecar.runtime._mesh_pool
        assert pool is not None and len(pool._conns) == 1
    finally:
        for h in hosts:
            await h.stop()


@pytest.mark.asyncio
async def test_local_mesh_disabled_under_mtls_fails_fast(tmp_path,
                                                         monkeypatch):
    """Certs provisioned but TASKSRUNNER_MESH=0 on THIS node: a local
    misconfiguration, not a peer problem. The invoke must refuse
    plaintext (same fence) but fail FAST with an error naming the
    local node — burning retries on re-resolve could never help."""
    from tests.test_mesh import COMPONENTS, _apps
    from tasksrunner import AppHost, load_components
    from tasksrunner.errors import InvocationError

    paths = write_pki(tmp_path / "pki", ["backend-api", "frontend"])
    monkeypatch.setenv(CA_ENV, paths["backend-api"]["ca"])
    monkeypatch.setenv(CERT_ENV, paths["backend-api"]["cert"])
    monkeypatch.setenv(KEY_ENV, paths["backend-api"]["key"])
    monkeypatch.setenv("TASKSRUNNER_MESH", "0")

    (tmp_path / "components.yaml").write_text(COMPONENTS)
    specs = load_components(tmp_path)
    registry = str(tmp_path / "apps.json")
    api, front = _apps()
    hosts = [AppHost(api, specs=specs, registry_file=registry),
             AppHost(front, specs=specs, registry_file=registry)]
    for h in hosts:
        await h.start()
    try:
        with pytest.raises(InvocationError, match="disabled on this node"):
            await hosts[1].app.client.invoke_method(
                "backend-api", "api/echo", http_method="POST", data={})
    finally:
        for h in hosts:
            await h.stop()


@pytest.mark.asyncio
async def test_tls_handshake_failure_fails_over_without_downgrade(
        tmp_path, monkeypatch):
    """Fault injection under mTLS: replica 0's mesh entry is re-pointed
    at an endpoint that cannot complete the TLS handshake, while its
    REAL plaintext HTTP sidecar stays alive and would happily serve.
    Every invoke must fail over to the healthy replica over TLS — and
    none may ever reach replica 0 over plaintext HTTP (the served_by
    counter is the downgrade detector)."""
    import collections

    from tests.test_multireplica import _start_pair, _tamper_replica0

    paths = write_pki(tmp_path / "pki", ["backend-api", "frontend"])
    monkeypatch.setenv(CA_ENV, paths["backend-api"]["ca"])
    monkeypatch.setenv(CERT_ENV, paths["backend-api"]["cert"])
    monkeypatch.setenv(KEY_ENV, paths["backend-api"]["key"])
    monkeypatch.delenv("TASKSRUNNER_MESH", raising=False)

    counter: collections.Counter = collections.Counter()
    hosts, fhost = await _start_pair(tmp_path, counter)

    async def no_tls_here(reader, writer):  # a plain socket: any TLS
        try:                                # ClientHello dies here
            await reader.read(-1)
        except (ConnectionError, OSError):
            pass
        writer.close()

    tarpit = await asyncio.start_server(no_tls_here, "127.0.0.1", 0)
    try:
        await _tamper_replica0(
            hosts, mesh_port=tarpit.sockets[0].getsockname()[1])
        before_r0 = counter["r0"]
        for _ in range(6):
            resp = await fhost.app.client.invoke_method(
                "backend-api", "api/work", http_method="POST", data={})
            assert resp.status == 200
            assert resp.json()["served_by"] == "r1"
        # the downgrade detector: replica 0's live HTTP sidecar never
        # saw a request after the poisoning
        assert counter["r0"] == before_r0
    finally:
        # hosts first: their mesh-pool close EOFs the tar-pit readers,
        # which py3.12's wait_closed() awaits
        for h in [*hosts, fhost]:
            await h.stop()
        tarpit.close()  # no wait_closed(): py3.12 can await handler
        # coroutines forever here; the loop is torn down right after
