"""Framed sidecar↔sidecar mesh transport (tasksrunner/invoke/mesh.py).

The behavioral contract under test: the mesh lane must be
indistinguishable from the sidecar HTTP invoke route
(``/v1.0/invoke/{app-id}/method/{path}``) in everything an app can
observe — status/headers/body, token policy, trace adoption, error
mapping — while being the transport peers *prefer* when the target
advertises a mesh port (≙ Dapr's internal sidecar↔sidecar gRPC,
reference docs/aca/03-aca-dapr-integration/index.md:30-38).
"""

import asyncio
import json

import pytest

from tasksrunner import App, AppHost, load_components
from tasksrunner.invoke.mesh import (
    MAX_FRAME,
    MeshConnectError,
    MeshPool,
    MeshServer,
    _pack,
)
from tasksrunner.security import TOKEN_HEADER, hash_token


# ---------------------------------------------------------------------------
# a minimal Runtime stand-in: the mesh server only needs .invoke()
# ---------------------------------------------------------------------------

class FakeRuntime:
    def __init__(self):
        self.calls = []

    async def invoke(self, target, path, *, http_method="POST", query="",
                     headers=None, body=b""):
        self.calls.append((target, path, http_method, query,
                           dict(headers or {}), bytes(body)))
        if path.endswith("boom"):
            raise RuntimeError("handler exploded")
        if path.endswith("slow"):
            await asyncio.sleep(0.2)
        payload = json.dumps({"path": path, "echo": body.decode() or None})
        return 200, {"content-type": "application/json",
                     "x-from-app": "yes",
                     # hop-by-hop noise the mesh must strip, exactly
                     # like the HTTP route does
                     "Connection": "keep-alive",
                     "Content-Length": "999"}, payload.encode()


async def start_server(**kw):
    srv = MeshServer(FakeRuntime(), **kw)
    await srv.start()
    return srv


@pytest.mark.asyncio
async def test_request_response_roundtrip():
    srv = await start_server(api_token=None)
    pool = MeshPool()
    try:
        status, headers, body = await pool.request(
            "127.0.0.1", srv.port, "backend-api", "POST", "/api/tasks",
            query="a=1", headers={"content-type": "application/json"},
            body=b'{"x": 1}')
        assert status == 200
        doc = json.loads(body)
        assert doc == {"path": "/api/tasks", "echo": '{"x": 1}'}
        assert headers["x-from-app"] == "yes"
        # hop-by-hop headers stripped, matching the HTTP route
        assert "connection" not in {k.lower() for k in headers}
        assert "content-length" not in {k.lower() for k in headers}
        target, path, method, query, hdrs, sent = srv.runtime.calls[0]
        assert (target, path, method, query) == (
            "backend-api", "/api/tasks", "POST", "a=1")
        assert sent == b'{"x": 1}'
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_multiplexing_one_connection_interleaves():
    """A slow request must not stall a fast one sharing the connection,
    and responses must correlate to their own requests."""
    srv = await start_server(api_token=None)
    pool = MeshPool()
    try:
        slow = asyncio.create_task(pool.request(
            "127.0.0.1", srv.port, "t", "GET", "/slow"))
        await asyncio.sleep(0.02)  # slow is in flight on the connection
        t0 = asyncio.get_running_loop().time()
        status, _, body = await pool.request(
            "127.0.0.1", srv.port, "t", "GET", "/fast", body=b"hi")
        fast_elapsed = asyncio.get_running_loop().time() - t0
        assert status == 200 and json.loads(body)["echo"] == "hi"
        assert fast_elapsed < 0.15  # did not queue behind the 200 ms call
        status, _, body = await slow
        assert status == 200 and json.loads(body)["path"] == "/slow"
        # both rode one multiplexed connection
        assert len(pool._conns) == 1
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_error_mapping_matches_http_route():
    """Unhandled handler error → 500 {"error": ...}, like the sidecar."""
    srv = await start_server(api_token=None)
    pool = MeshPool()
    try:
        status, headers, body = await pool.request(
            "127.0.0.1", srv.port, "t", "GET", "/boom")
        assert status == 500
        assert json.loads(body)["error"] == "handler exploded"
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_token_policy_own_peer_and_reject():
    """Same gate as the HTTP invoke route (allow_peer=True): the app's
    own token or a registered peer's token (digest match) is accepted;
    anything else is 401 before dispatch."""
    srv = await start_server(api_token="own-secret",
                             peer_tokens={hash_token("peer-secret")})
    pool = MeshPool()
    try:
        for token, want in [(None, 401), ("wrong", 401),
                            ("own-secret", 200), ("peer-secret", 200)]:
            headers = {} if token is None else {TOKEN_HEADER: token}
            status, _, body = await pool.request(
                "127.0.0.1", srv.port, "t", "GET", "/x", headers=headers)
            assert status == want, (token, status, body)
        # rejected requests never reached the runtime
        assert len(srv.runtime.calls) == 2
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_pool_redials_after_peer_restart():
    """Dead connections are dropped and re-dialed; an unreachable peer
    raises MeshConnectError (the fall-back-to-HTTP signal)."""
    srv = await start_server(api_token=None)
    port = srv.port
    pool = MeshPool()
    try:
        status, _, _ = await pool.request("127.0.0.1", port, "t", "GET", "/a")
        assert status == 200
        await srv.stop()
        await asyncio.sleep(0.05)
        # in-flight-less but dead: next request either sees the closed
        # conn and re-dials (refused → MeshConnectError) or fails on
        # the dropped stream (ConnectionError) — both are retriable
        with pytest.raises((MeshConnectError, ConnectionError, OSError)):
            await pool.request("127.0.0.1", port, "t", "GET", "/b")
        # peer comes back on the same port
        srv2 = MeshServer(FakeRuntime(), api_token=None, port=port)
        await srv2.start()
        try:
            status, _, _ = await pool.request(
                "127.0.0.1", port, "t", "GET", "/c")
            assert status == 200
        finally:
            await srv2.stop()
    finally:
        await pool.close()


@pytest.mark.asyncio
async def test_oversized_request_gets_413_connection_survives():
    """Parity with the HTTP route's client_max_size: an oversized
    request body is answered 413 — and the multiplexed connection
    keeps serving other requests (one bad request must not fail its
    neighbours with a teardown)."""
    srv = await start_server(api_token=None)
    pool = MeshPool()
    try:
        import tasksrunner.invoke.mesh as mesh_mod
        # shrink the limit for the test so we don't allocate 16 MiB
        orig = mesh_mod.MAX_FRAME
        mesh_mod.MAX_FRAME = 1024
        try:
            status, _, body = await pool.request(
                "127.0.0.1", srv.port, "t", "POST", "/big",
                body=b"x" * 2048)
            assert status == 413
            assert "limit" in json.loads(body)["error"]
            # same connection still works
            status, _, _ = await pool.request(
                "127.0.0.1", srv.port, "t", "GET", "/after")
            assert status == 200
            assert len(pool._conns) == 1
        finally:
            mesh_mod.MAX_FRAME = orig
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_corrupt_frame_drops_connection():
    srv = await start_server(api_token=None)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", srv.port)
        try:
            import struct
            # header length larger than the frame: structurally corrupt
            writer.write(struct.pack(">I", 8) + struct.pack(">I", 100))
            await writer.drain()
            assert await reader.read(1) == b""
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
    finally:
        await srv.stop()


@pytest.mark.asyncio
async def test_malformed_response_fails_pending_not_hangs():
    """A server speaking garbage must fail in-flight requests promptly
    — pending futures must never be stranded (the reader task dies,
    _fail_all resolves them with ConnectionError)."""
    async def bad_server(reader, writer):
        await reader.read(64)  # swallow the request
        writer.write(b"\x00\x00\x00\x08\x00\x00\x00\x04nope")  # header not JSON
        await writer.drain()
        await reader.read()  # hold until the client hangs up...
        writer.close()       # ...then close, so wait_closed() returns

    server = await asyncio.start_server(bad_server, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    pool = MeshPool()
    try:
        with pytest.raises((ConnectionError, OSError)):
            await asyncio.wait_for(
                pool.request("127.0.0.1", port, "t", "GET", "/x"), timeout=5)
    finally:
        await pool.close()
        server.close()
        await server.wait_closed()


@pytest.mark.asyncio
async def test_traceparent_adopted_by_server():
    """A caller-supplied traceparent must reach the dispatched handler's
    forwarded headers (trace continuity over the mesh = over HTTP)."""
    srv = await start_server(api_token=None)
    pool = MeshPool()
    try:
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        status, _, _ = await pool.request(
            "127.0.0.1", srv.port, "t", "GET", "/x",
            headers={"traceparent": tp, "x-keep": "1", "cookie": "drop"})
        assert status == 200
        hdrs = srv.runtime.calls[0][4]
        # x-* and content-type/accept travel inward; cookie is filtered
        assert hdrs.get("x-keep") == "1"
        assert "cookie" not in hdrs
    finally:
        await pool.close()
        await srv.stop()


# ---------------------------------------------------------------------------
# end-to-end: AppHost pairs pick the mesh lane automatically
# ---------------------------------------------------------------------------

COMPONENTS = """
apiVersion: dapr.io/v1alpha1
kind: Component
metadata:
  name: statestore
spec:
  type: state.in-memory
  version: v1
"""


def _apps():
    api = App("backend-api")

    @api.post("/api/echo")
    async def echo(req):
        return {"got": req.json(), "app": "backend-api"}

    front = App("frontend")

    @front.get("/go")
    async def go(req):
        resp = await front.client.invoke_method(
            "backend-api", "api/echo", http_method="POST",
            data={"n": int(req.query.get("n", "0"))})
        resp.raise_for_status()
        return resp.json()

    return api, front


@pytest.mark.asyncio
async def test_apphost_pair_prefers_mesh(tmp_path, monkeypatch):
    monkeypatch.delenv("TASKSRUNNER_MESH", raising=False)
    (tmp_path / "components.yaml").write_text(COMPONENTS)
    specs = load_components(tmp_path)
    registry = str(tmp_path / "apps.json")
    api, front = _apps()
    hosts = [AppHost(api, specs=specs, registry_file=registry),
             AppHost(front, specs=specs, registry_file=registry)]
    for h in hosts:
        await h.start()
    try:
        assert hosts[0].sidecar.mesh_port  # advertised in the registry
        resp = await hosts[1].client.invoke_method("frontend", "go", query="n=7",
                                                   http_method="GET")
        assert resp.json() == {"got": {"n": 7}, "app": "backend-api"}
        # the frontend's runtime dialed the mesh lane, not HTTP
        pool = hosts[1].sidecar.runtime._mesh_pool
        assert pool is not None and len(pool._conns) == 1
    finally:
        for h in hosts:
            await h.stop()


@pytest.mark.asyncio
async def test_apphost_pair_mesh_disabled_uses_http(tmp_path, monkeypatch):
    monkeypatch.setenv("TASKSRUNNER_MESH", "0")
    (tmp_path / "components.yaml").write_text(COMPONENTS)
    specs = load_components(tmp_path)
    registry = str(tmp_path / "apps.json")
    api, front = _apps()
    hosts = [AppHost(api, specs=specs, registry_file=registry),
             AppHost(front, specs=specs, registry_file=registry)]
    for h in hosts:
        await h.start()
    try:
        assert hosts[0].sidecar.mesh_port is None
        resp = await hosts[1].client.invoke_method("frontend", "go", query="n=3",
                                                   http_method="GET")
        assert resp.json() == {"got": {"n": 3}, "app": "backend-api"}
        assert hosts[1].sidecar.runtime._mesh_pool is None
    finally:
        for h in hosts:
            await h.stop()


@pytest.mark.asyncio
async def test_prune_skips_in_progress_dials():
    """_prune, run while some task is inside a key's dial section, must
    not sweep that key: popping its lock would let a new caller mint a
    SECOND lock object for the same peer and dial concurrently — the
    losing connection's socket and reader task then leak until the
    peer closes them."""
    pool = MeshPool()
    key = ("127.0.0.1", 1234, None)

    class _Dead:
        closed = True

    pool._conns[key] = _Dead()
    lock = pool._dial_locks.setdefault(key, asyncio.Lock())
    await lock.acquire()  # a dialer currently holds this key's lock
    pool._dialing[key] = 1
    try:
        pool._prune()
        # untouched: the dialer's lock object is still THE lock, and
        # the dead conn is left for the dialer itself to replace
        assert pool._dial_locks[key] is lock
        assert key in pool._conns
    finally:
        lock.release()
    # with the dial section exited, the stale key is sweepable again
    del pool._dialing[key]
    pool._prune()
    assert key not in pool._conns and key not in pool._dial_locks


@pytest.mark.asyncio
async def test_failed_dial_reclaims_lock():
    """A key whose dial never succeeds has no _conns entry, so _prune
    can never sweep its lock — the last failing dialer must reclaim it
    itself, or every dead-peer address leaks one Lock forever."""
    pool = MeshPool()
    with pytest.raises((MeshConnectError, ConnectionError, OSError)):
        await pool.request("127.0.0.1", 1, "x", "GET", "/")
    assert pool._dial_locks == {}
    assert pool._dialing == {}
    await pool.close()
