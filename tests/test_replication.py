"""Replicated state plane (tasksrunner/state/replication.py + replmesh).

Covers the tentpole contract end to end: the per-shard record stream
(monotonic seq, follower apply order, exact-hwm acks), lease/epoch
leadership with zombie fencing, ack-after-replication quorum semantics
under chaos, follower resync (log catch-up AND snapshot reinstall past
the pruned retention window), stale-tolerant follower reads bounded by
``maxLagRecords``, the mesh transport for cross-process members, and
the two acceptance drills: ``kill -9`` the shard leader process
mid-load (follower promotes, zombie's late commit fenced, zero lost
acked writes at RF 2) and the declarative chaos replication-lane
targets.
"""

import asyncio
import json
import os
import pathlib
import sqlite3
import sys
import textwrap
import time

import pytest

from tasksrunner.chaos.engine import ChaosPolicies
from tasksrunner.chaos.spec import parse_chaos
from tasksrunner.errors import (
    ReplicaFencedError,
    ReplicationQuorumError,
    StaleReadError,
)
from tasksrunner.state.replication import (
    Lease,
    ReplicaSetStore,
    ReplicationNode,
    build_replicated_store,
)
from tasksrunner.state.sqlite import SqliteStateStore

REPO = pathlib.Path(__file__).resolve().parent.parent

#: fast lease for tests — promotion paths complete in well under a
#: second instead of the production 5 s default
LEASE = 0.4


def _build(tmp_path, name="repl", *, replicas=2, **kw):
    kw.setdefault("lease_seconds", LEASE)
    return build_replicated_store(
        name, tmp_path / f"{name}.db", replicas=replicas, **kw)


async def _wait_for(predicate, *, timeout=6.0, message="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not predicate():
        assert asyncio.get_running_loop().time() < deadline, \
            f"timed out waiting for {message}"
        await asyncio.sleep(0.02)


# -- record stream ----------------------------------------------------------

@pytest.mark.asyncio
async def test_replicates_to_followers_exact_hwm(tmp_path):
    """Every committed mutation reaches every follower in order; all
    members converge on the same high-water mark and the same rows."""
    store = _build(tmp_path, replicas=3, ack_quorum=3)
    try:
        for i in range(40):
            await store.set(f"k{i}", {"v": i})
        await store.delete("k3")
        from tasksrunner.state.base import TransactionOp
        await store.transact([TransactionOp("upsert", "tx-a", {"t": 1}),
                              TransactionOp("upsert", "tx-b", {"t": 2})])
        positions = {n.node_id: n.store.repl_position() for n in store.nodes}
        hwms = {hwm for hwm, _ in positions.values()}
        assert len(hwms) == 1, f"members diverged: {positions}"
        # quorum 3 means acks waited for both followers: check a
        # follower's own sqlite copy, not the leader's
        leader = store.leader_member()
        follower = next(n for n in store.nodes if n.node_id != leader)
        assert (await follower.store.get("k7")).value == {"v": 7}
        assert await follower.store.get("k3") is None
        assert (await follower.store.get("tx-b")).value == {"t": 2}
    finally:
        await store.aclose()


@pytest.mark.asyncio
async def test_rf1_is_plain_unreplicated_store(tmp_path):
    """``replicas: 1`` is the exact pre-replication code path: a plain
    SqliteStateStore on the configured file, no repl tables."""
    store = _build(tmp_path, replicas=1)
    try:
        assert type(store) is SqliteStateStore
        await store.set("k", {"v": 1})
    finally:
        store.close()
    con = sqlite3.connect(tmp_path / "repl.db")
    tables = {r[0] for r in con.execute(
        "SELECT name FROM sqlite_master WHERE type='table'")}
    con.close()
    assert "repl_log" not in tables and "repl_meta" not in tables


@pytest.mark.asyncio
async def test_driver_metadata_builds_replica_set(tmp_path):
    """``replicas: 2`` on a state.sqlite component builds the replica
    set through the normal driver path; default metadata stays plain."""
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import parse_component

    def build(extra):
        spec = parse_component({
            "componentType": "state.sqlite",
            "metadata": [
                {"name": "databasePath", "value": str(tmp_path / "s.db")},
                *extra,
            ],
        }, default_name="st")
        return ComponentRegistry([spec]).get("st")

    plain = build([])
    assert type(plain) is SqliteStateStore
    plain.close()

    store = build([{"name": "replicas", "value": "2"},
                   {"name": "ackQuorum", "value": "2"}])
    try:
        assert isinstance(store, ReplicaSetStore)
        await store.set("driver-key", {"ok": True})
        assert (await store.get("driver-key")).value == {"ok": True}
    finally:
        await store.aclose()
    assert (tmp_path / "s-r1.db").is_file()


# -- leadership: lease, epochs, fencing -------------------------------------

class _SeverableLink:
    """Wraps a follower link; while severed, every protocol call fails
    like a dropped connection (one-way partition test double)."""

    def __init__(self, inner):
        self.inner = inner
        self.severed = True

    def _check(self):
        if self.severed:
            raise OSError("link severed (test partition)")

    async def position(self):
        self._check()
        return await self.inner.position()

    async def append(self, records):
        self._check()
        return await self.inner.append(records)

    async def install(self, snapshot):
        self._check()
        return await self.inner.install(snapshot)

@pytest.mark.asyncio
async def test_lease_epochs_are_monotonic():
    meta = SqliteStateStore("meta")
    lease = Lease(meta, "l", lease_seconds=0.25)
    try:
        assert await lease.acquire("a") == 1
        assert await lease.renew("a") is True
        assert await lease.acquire("b") is None  # holder alive
        await asyncio.sleep(0.3)                 # expire
        assert await lease.acquire("b") == 2     # takeover bumps epoch
        await lease.release("b")
        assert await lease.acquire("a") == 3     # release keeps epoch line
        assert await lease.renew("b") is False
    finally:
        await meta.aclose()


@pytest.mark.asyncio
async def test_zombie_leader_fenced_and_no_acked_write_lost(tmp_path):
    """The acceptance drill, in-process: the leader stops renewing
    (zombie), a follower promotes within the lease window, the
    zombie's late commit fails fenced and is NOT applied anywhere
    durable, and every previously acked write survives at RF 2."""
    store = _build(tmp_path, replicas=2, ack_quorum=2)
    acked = []
    try:
        for i in range(25):
            await store.set(f"k{i}", {"v": i})
            acked.append(f"k{i}")
        zombie = next(n for n in store.nodes
                      if n.node_id == store.leader_member())
        zombie.renewal_paused = True
        survivor = next(n for n in store.nodes if n is not zombie)
        # one-way partition: the survivor can't reach the zombie (so
        # its epoch-2 barrier can't demote it in place — the zombie
        # genuinely still believes it leads), but the zombie can still
        # ship — which is exactly how its late commit gets refused
        partition = _SeverableLink(survivor.links[zombie.node_id])
        survivor.links[zombie.node_id] = partition
        t0 = time.monotonic()
        await _wait_for(lambda: survivor.is_leader,
                        message="follower promotion")
        assert time.monotonic() - t0 < 3.0 * LEASE + 1.0, \
            "promotion exceeded the lease window"

        # the zombie still *thinks* it leads; its late commit must die
        # fenced when the survivor's higher epoch rejects the record
        with pytest.raises(ReplicaFencedError):
            await zombie.store.set("zombie-write", {"evil": True})
        assert await survivor.store.get("zombie-write") is None

        # partition heals; the facade followed leadership and the
        # new leader can reach quorum 2 again: writes keep working
        partition.severed = False
        await store.set("post-failover", {"ok": True})
        acked.append("post-failover")
        lost = [k for k in acked if await store.get(k) is None]
        assert lost == []

        # the fenced ex-leader resyncs from the new leader and drops
        # its divergent unacked commit
        await _wait_for(
            lambda: zombie.store.repl_position()
            == survivor.store.repl_position(),
            message="zombie resync")
        assert await zombie.store.get("zombie-write") is None
    finally:
        await store.aclose()


@pytest.mark.asyncio
async def test_crashed_leader_failover_keeps_acked_writes(tmp_path):
    """kill-style crash (no renewals, no shipping): the follower
    promotes and serves the full acked history."""
    store = _build(tmp_path, replicas=2, ack_quorum=2)
    try:
        for i in range(15):
            await store.set(f"k{i}", {"v": i})
        victim = next(n for n in store.nodes
                      if n.node_id == store.leader_member())
        victim.crash()
        # the crashed process restarts shortly after (supervisor
        # restart); until then quorum 2 can't be met, so the rejoin is
        # what lets the post-failover write ack
        asyncio.get_running_loop().call_later(0.15, victim.revive)
        await store.set("after", {"v": -1})  # blocks until promotion
        assert store.leader_member() != victim.node_id
        for i in range(15):
            assert (await store.get(f"k{i}")).value == {"v": i}
    finally:
        await store.aclose()


# -- quorum semantics -------------------------------------------------------

@pytest.mark.asyncio
async def test_ack_quorum_timeout_fails_the_write(tmp_path):
    """With the only follower crashed and ackQuorum 2, a write commits
    locally but must fail its ack within the quorum deadline."""
    store = _build(tmp_path, replicas=2, ack_quorum=2, ack_timeout=0.4)
    try:
        await store.set("seed", {"v": 0})
        leader = next(n for n in store.nodes
                      if n.node_id == store.leader_member())
        follower = next(n for n in store.nodes if n is not leader)
        follower.crash()
        with pytest.raises(ReplicationQuorumError):
            await store.set("unreplicated", {"v": 1})
        follower.revive()
        # quorum restored: the next write acks normally
        await _wait_for(lambda: True, timeout=0.1, message="beat")
        await store.set("replicated-again", {"v": 2})
    finally:
        await store.aclose()


# -- resync -----------------------------------------------------------------

@pytest.mark.asyncio
async def test_follower_resync_after_gap(tmp_path):
    """A follower that was down while the leader committed rejoins and
    catches up to the exact high-water mark via the retained log."""
    store = _build(tmp_path, replicas=2, ack_quorum=1)
    try:
        await store.set("warm", {"v": 0})
        leader = next(n for n in store.nodes
                      if n.node_id == store.leader_member())
        follower = next(n for n in store.nodes if n is not leader)
        follower.crash()
        for i in range(30):
            await store.set(f"gap{i}", {"v": i})
        l_hwm, _ = leader.store.repl_position()
        f_hwm, _ = follower.store.repl_position()
        assert f_hwm < l_hwm
        follower.revive()
        await _wait_for(
            lambda: follower.store.repl_position()[0]
            == leader.store.repl_position()[0],
            message="follower catch-up")
        assert (await follower.store.get("gap29")).value == {"v": 29}
    finally:
        await store.aclose()


@pytest.mark.asyncio
async def test_follower_resync_via_snapshot_past_pruned_log(tmp_path):
    """When the gap exceeds the retained log, catch-up falls back to a
    full snapshot install and still lands on the exact hwm."""
    store = _build(tmp_path, replicas=2, ack_quorum=1, log_retain=4)
    try:
        await store.set("warm", {"v": 0})
        leader = next(n for n in store.nodes
                      if n.node_id == store.leader_member())
        follower = next(n for n in store.nodes if n is not leader)
        follower.crash()
        for i in range(40):  # >> log_retain: the gap is unfillable
            await store.set(f"s{i}", {"v": i})
        follower.revive()
        await _wait_for(
            lambda: follower.store.repl_position()[0]
            == leader.store.repl_position()[0],
            message="snapshot resync")
        assert (await follower.store.get("s0")).value == {"v": 0}
        assert (await follower.store.get("s39")).value == {"v": 39}
        assert (await follower.store.get("warm")).value == {"v": 0}
    finally:
        await store.aclose()


# -- follower reads ---------------------------------------------------------

@pytest.mark.asyncio
async def test_stale_follower_reads_bounded_by_max_lag(tmp_path):
    """``followerReads`` within the bound serve from a follower;
    beyond ``maxLagRecords`` the facade redirects to the leader and
    the member-addressed read fails loudly with StaleReadError."""
    store = _build(tmp_path, replicas=2, ack_quorum=1,
                   follower_reads=True, max_lag=5)
    try:
        await store.set("k", {"v": "fresh"})
        leader = next(n for n in store.nodes
                      if n.node_id == store.leader_member())
        follower = next(n for n in store.nodes if n is not leader)
        await _wait_for(
            lambda: follower.store.repl_position()[0]
            == leader.store.repl_position()[0],
            message="follower in sync")
        item = await store.read_follower("k", member=follower.node_id)
        assert item.value == {"v": "fresh"}

        follower.crash()
        for i in range(20):  # lag 20 > maxLagRecords 5
            await store.set(f"lag{i}", {"v": i})
        await store.set("k", {"v": "newer"})
        with pytest.raises(StaleReadError):
            await store.read_follower("k", member=follower.node_id)
        # the facade read path redirects instead of serving stale data
        assert (await store.get("k")).value == {"v": "newer"}

        follower.revive()
        await _wait_for(
            lambda: follower.store.repl_position()[0]
            == leader.store.repl_position()[0],
            message="follower back in bound")
        item = await store.read_follower("k", member=follower.node_id)
        assert item.value == {"v": "newer"}
    finally:
        await store.aclose()


# -- chaos replication-lane targets -----------------------------------------

def _chaos_policies(seed=7):
    spec = parse_chaos({
        "apiVersion": "tasksrunner/v1alpha1",
        "kind": "Chaos",
        "metadata": {"name": "repl-chaos"},
        "spec": {
            "seed": seed,
            "faults": {
                "deadLane": {"blackhole": {"deadline": "2s"}},
                "slowLane": {"latency": {"duration": "5ms"}},
            },
            "targets": {
                "replication": {
                    "repl/0/r1": ["deadLane"],
                    "repl": ["slowLane"],
                },
            },
        },
    })
    return ChaosPolicies([spec])


def test_chaos_replication_targets_parse_and_resolve():
    """Declarative replication-lane targets parse and resolve most-
    specific-first: the per-member key beats the store-wide key."""
    policies = _chaos_policies()
    specific = policies.for_replication("repl", 0, "r1")
    assert specific is not None
    assert [i.rule.name for i in specific.injectors] == ["deadLane"]
    fallback = policies.for_replication("repl", 0, "r2")
    assert fallback is not None
    assert [i.rule.name for i in fallback.injectors] == ["slowLane"]
    assert policies.for_replication("other", 0, "r1") is None


@pytest.mark.asyncio
async def test_chaos_blackhole_on_replication_lane_fails_quorum(tmp_path):
    """A blackholed leader→follower lane stalls the record stream;
    with ackQuorum 2 the write fails its quorum deadline — seeded,
    declarative, and scoped to exactly one lane."""
    store = _build(tmp_path, replicas=2, ack_quorum=2, ack_timeout=0.4)
    try:
        await store.set("before-faults", {"v": 0})  # links warm
        store.attach_chaos(_chaos_policies())
        with pytest.raises(ReplicationQuorumError):
            await store.set("into-the-void", {"v": 1})
    finally:
        await store.aclose()


# -- mesh transport ---------------------------------------------------------

@pytest.mark.asyncio
async def test_mesh_follower_link_replicates_over_tcp(tmp_path):
    """A follower behind the mesh-framed transport behaves like a
    local member: records apply in order, acks carry the exact hwm,
    and a log gap resyncs through the same typed-error protocol."""
    from tasksrunner.state.replmesh import MeshFollowerLink, ReplicationServer

    meta = SqliteStateStore("mesh.repl-meta", tmp_path / "meta.db")
    leader = ReplicationNode("mesh", tmp_path / "leader.db", member=0,
                             shard=0, meta_store=meta, lease_seconds=LEASE,
                             ack_quorum=2, ack_timeout=5.0)
    follower = ReplicationNode("mesh", tmp_path / "follower.db", member=1,
                               shard=0, meta_store=meta, lease_seconds=LEASE,
                               ack_quorum=2, ack_timeout=5.0)
    server = ReplicationServer()
    server.register(follower)
    await server.start()
    link = MeshFollowerLink("mesh", 0, follower.node_id,
                            "127.0.0.1", server.port)
    leader.links[follower.node_id] = link
    try:
        await leader.start()
        await _wait_for(lambda: leader.is_leader, message="mesh leader")
        for i in range(25):
            await leader.store.set(f"m{i}", {"v": i})
        assert follower.store.repl_position() == leader.store.repl_position()
        assert (await follower.store.get("m24")).value == {"v": 24}
    finally:
        await leader.stop()
        await link.aclose()
        await server.aclose()
        leader.store.close()
        follower.store.close()
        await meta.aclose()


_DRILL_CHILD = textwrap.dedent("""
    import asyncio, sys

    from tasksrunner.state.replication import ReplicationNode
    from tasksrunner.state.replmesh import MeshFollowerLink, ReplicationServer
    from tasksrunner.state.sqlite import SqliteStateStore


    async def main():
        tmp, parent_port = sys.argv[1], int(sys.argv[2])
        meta = SqliteStateStore("drill.repl-meta", f"{tmp}/meta.db")
        node = ReplicationNode("drill", f"{tmp}/leader.db", member=0,
                               shard=0, meta_store=meta, lease_seconds=0.6,
                               ack_quorum=2, ack_timeout=10.0)
        node.links["r1"] = MeshFollowerLink(
            "drill", 0, "r1", "127.0.0.1", parent_port)
        server = ReplicationServer()
        server.register(node)
        await server.start()
        await node.start()
        while not node.is_leader:
            await asyncio.sleep(0.02)
        print(f"CHILD_PORT {server.port}", flush=True)
        i = 0
        while True:
            await node.store.set(f"k-{i}", {"v": i})
            # quorum 2: this line is only printed once the follower
            # has durably applied the record
            print(f"ACKED k-{i}", flush=True)
            i += 1


    asyncio.run(main())
""")


@pytest.mark.asyncio
async def test_kill9_leader_process_failover_drill(tmp_path):
    """THE acceptance drill, cross-process: ``kill -9`` the shard
    leader's OS process mid-load. The surviving follower (this
    process) promotes within the lease window and every write the
    dead leader ever acked is durably present — lost_acked_keys must
    be empty at RF 2."""
    import signal as signal_mod

    from tasksrunner.state.replmesh import MeshFollowerLink, ReplicationServer

    meta = SqliteStateStore("drill.repl-meta", tmp_path / "meta.db")
    follower = ReplicationNode("drill", tmp_path / "follower.db", member=1,
                               shard=0, meta_store=meta, lease_seconds=0.6,
                               ack_quorum=1, ack_timeout=5.0)
    server = ReplicationServer()
    server.register(follower)
    await server.start()

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (str(REPO), env.get("PYTHONPATH")) if p)
    script = tmp_path / "leader_child.py"
    script.write_text(_DRILL_CHILD)
    child = await asyncio.create_subprocess_exec(
        sys.executable, str(script), str(tmp_path), str(server.port),
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT,
        env=env)
    acked: list[str] = []
    try:
        child_port = None
        deadline = asyncio.get_running_loop().time() + 30
        while len(acked) < 30:
            assert asyncio.get_running_loop().time() < deadline, \
                f"child never produced 30 acks (got {len(acked)})"
            line = (await asyncio.wait_for(child.stdout.readline(), 30)
                    ).decode().strip()
            if line.startswith("CHILD_PORT "):
                child_port = int(line.split()[1])
                # the leader is up: join as a follower with a return
                # link so promotion can check the peer's position
                follower.links["r0"] = MeshFollowerLink(
                    "drill", 0, "r0", "127.0.0.1", child_port)
                await follower.start()
            elif line.startswith("ACKED "):
                acked.append(line.split()[1])
        assert child_port is not None, "child never announced its port"

        child.kill()  # SIGKILL: no shutdown path, no lease release
        t0 = time.monotonic()
        # drain: acks already printed before the kill still count
        rest = (await child.stdout.read()).decode()
        for line in rest.splitlines():
            if line.strip().startswith("ACKED "):
                acked.append(line.strip().split()[1])
        await child.wait()

        await _wait_for(lambda: follower.is_leader, timeout=6.0,
                        message="follower promotion after kill -9")
        await follower.store.set("post-failover", {"ok": True})
        failover_s = time.monotonic() - t0
        assert failover_s < 5.0, f"failover took {failover_s:.2f}s"

        lost = [k for k in acked
                if await follower.store.get(k) is None]
        assert lost == [], f"lost {len(lost)} acked writes: {lost[:5]}"
        assert (await follower.store.get("post-failover")).value == {"ok": True}
    finally:
        if child.returncode is None:
            child.kill()
            await child.wait()
        await follower.stop()
        for link in follower.links.values():
            await link.aclose()
        await server.aclose()
        follower.store.close()
        await meta.aclose()


# -- sharded + replicated ---------------------------------------------------

@pytest.mark.asyncio
async def test_sharded_replicated_store_routes_and_survives(tmp_path):
    """shards × replicas compose: each shard is its own replica set
    with its own lease; a one-shard leader crash only stalls that
    shard's writes until its follower promotes."""
    store = build_replicated_store(
        "grid", tmp_path / "grid.db", shards=2, replicas=2,
        ack_quorum=2, lease_seconds=LEASE)
    try:
        for i in range(30):
            await store.set(f"k{i}", {"v": i})
        shard0 = store._shards[0]
        victim = next(n for n in shard0.nodes
                      if n.node_id == shard0.leader_member())
        victim.crash()
        for i in range(30):  # both shards keep serving
            assert (await store.get(f"k{i}")).value == {"v": i}
        await store.set("k0-after", {"v": 1})
        assert (await store.get("k0-after")).value == {"v": 1}
    finally:
        await store.aclose()
    # on-disk layout: shard files plus -rN follower copies, one meta db
    names = {p.name for p in tmp_path.iterdir()}
    assert {"grid-shard0.db", "grid-shard1.db", "grid-shard0-r1.db",
            "grid-shard1-r1.db", "grid-repl-meta.db"} <= names


# -- CLI status -------------------------------------------------------------

@pytest.mark.asyncio
async def test_cli_repl_status_reads_databases(tmp_path, capsys):
    """``tasksrunner repl <databasePath>`` reports leases and member
    positions straight from the sqlite files, live runtime or not."""
    from tasksrunner.cli import main as cli_main

    store = _build(tmp_path, replicas=2, ack_quorum=2)
    try:
        for i in range(5):
            await store.set(f"k{i}", {"v": i})
    finally:
        await store.aclose()
    cli_main(["repl", str(tmp_path / "repl.db"), "--json"])
    doc = json.loads(capsys.readouterr().out)
    entry = doc["replication"][0]
    assert entry["store"] == "repl" and entry["shard"] == 0
    members = {m["member"]: m["hwm"] for m in entry["members"]}
    assert set(members) == {"r0", "r1"}
    assert len(set(members.values())) == 1, "members should agree on hwm"
