"""Deploy layer tests: manifest validation, what-if diffing, apply.

Contract source: the reference's IaC layer (bicep/main.bicep
composition, app modules' ingress/scale blocks) and its CI pipeline
verbs lint → validate → what-if → deploy
(.github/workflows/infra-deploy.yml:33-160; SURVEY.md §2.5-2.6).
"""

import pathlib

import pytest
import yaml

from tasksrunner.deploy import (
    apply_manifest,
    load_manifest,
    validate_manifest,
    what_if,
)
from tasksrunner.deploy.plan import destroy, diff_states
from tasksrunner.errors import ComponentError

REPO = pathlib.Path(__file__).resolve().parent.parent
SAMPLE_MANIFEST = REPO / "samples" / "tasks_tracker" / "environment.yaml"


def test_sample_manifest_is_valid():
    manifest = load_manifest(SAMPLE_MANIFEST)
    assert manifest.name == "tasks-tracker-env"
    assert [a.app_id for a in manifest.apps] == [
        "tasksmanager-backend-api",
        "tasksmanager-frontend-webapp",
        "tasksmanager-backend-processor",
    ]
    assert validate_manifest(manifest) == []
    processor = manifest.apps[2]
    assert processor.max_replicas == 5
    assert processor.scale_rules[0]["metadata"]["messageCount"] == "10"


def _write_manifest(tmp_path, doc):
    p = tmp_path / "env.yaml"
    p.write_text(yaml.safe_dump(doc, sort_keys=False))
    return p


BASE_DOC = {
    "environment": {"name": "test-env"},
    "components": [],
    "apps": [
        {"app_id": "api", "module": "samples.tasks_tracker.backend_api:make_app",
         "app_port": 9103, "sidecar_port": 9500, "ingress": "internal"},
    ],
}


def test_validate_catches_problems(tmp_path):
    doc = {
        "environment": {"name": "bad"},
        "components": [
            {"name": "ghost", "file": "missing.yaml"},
        ],
        "apps": [
            {"app_id": "a", "module": "nonexistent.module:make_app",
             "ingress": "sideways", "app_port": 1000,
             "scale": {"min_replicas": 0, "max_replicas": 5}},
            {"app_id": "a", "module": "also.missing:make_app", "app_port": 1000},
        ],
    }
    manifest = load_manifest(_write_manifest(tmp_path, doc))
    problems = "\n".join(validate_manifest(manifest))
    assert "duplicate app_id" in problems
    assert "ingress" in problems
    assert "min_replicas" in problems
    assert "not importable" in problems
    assert "port 1000" in problems
    assert "missing.yaml" in problems


def test_validate_scope_and_rule_refs(tmp_path):
    comp = tmp_path / "c.yaml"
    comp.write_text("componentType: state.sqlite\nscopes: [ghost-app]\n")
    doc = {
        "environment": {"name": "e"},
        "components": [{"name": "store", "file": "c.yaml"}],
        "apps": [
            {"app_id": "api", "module": "samples.tasks_tracker.backend_api:make_app",
             "scale": {"max_replicas": 3,
                       "rules": [{"type": "pubsub-backlog",
                                  "metadata": {"component": "nope"}}]}},
        ],
    }
    problems = "\n".join(validate_manifest(load_manifest(_write_manifest(tmp_path, doc))))
    assert "scope 'ghost-app'" in problems
    assert "unknown component 'nope'" in problems


def test_diff_states():
    changes = diff_states(
        {"apps": {"a": {"x": 1}, "b": {"y": 2}}},
        {"apps": {"a": {"x": 9}, "c": {"z": 3}}},
    )
    ops = {(c["op"], c["path"]) for c in changes}
    assert ("modify", "apps.a.x") in ops
    assert ("delete", "apps.b") in ops
    assert ("create", "apps.c") in ops


def test_what_if_apply_cycle(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    manifest_path = _write_manifest(tmp_path, BASE_DOC)
    manifest = load_manifest(manifest_path)

    preview = what_if(manifest)
    assert preview["valid"] and preview["first_deploy"]

    result = apply_manifest(manifest)
    assert result["first_deploy"]
    run_cfg = yaml.safe_load(pathlib.Path(result["run_config"]).read_text())
    assert run_cfg["apps"][0]["app_id"] == "api"
    assert run_cfg["apps"][0]["host"] == "127.0.0.1"

    # the emitted run config loads in the orchestrator's parser
    from tasksrunner.orchestrator.config import load_run_config
    parsed = load_run_config(result["run_config"])
    assert parsed.apps[0].app_id == "api"

    # idempotent: second what-if shows no changes
    preview2 = what_if(manifest)
    assert preview2["changes"] == [] and not preview2["first_deploy"]

    # mutate: change a port → exactly one modify
    doc2 = dict(BASE_DOC)
    doc2["apps"] = [dict(BASE_DOC["apps"][0], app_port=9104)]
    manifest2 = load_manifest(_write_manifest(tmp_path, doc2))
    changes = what_if(manifest2)["changes"]
    assert [c["op"] for c in changes] == ["modify"]
    assert changes[0]["path"] == "apps.api.app_port"

    assert destroy(manifest) is True
    assert what_if(manifest)["first_deploy"]


def test_apply_resolves_env_secrets(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("MY_KEY_VALUE", "s3cr3t")
    doc = dict(BASE_DOC)
    doc["apps"] = [dict(BASE_DOC["apps"][0],
                        secrets={"appinsights-key": {"env": "MY_KEY_VALUE"},
                                 "literal-key": "plain"})]
    manifest = load_manifest(_write_manifest(tmp_path, doc))
    result = apply_manifest(manifest)
    run_cfg = yaml.safe_load(pathlib.Path(result["run_config"]).read_text())
    env = run_cfg["apps"][0]["env"]
    assert env["APPINSIGHTS_KEY"] == "s3cr3t"
    assert env["LITERAL_KEY"] == "plain"

    monkeypatch.delenv("MY_KEY_VALUE")
    with pytest.raises(ComponentError, match="unset env var"):
        apply_manifest(manifest)


def test_external_ingress_binds_all_interfaces(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    doc = dict(BASE_DOC)
    doc["apps"] = [dict(BASE_DOC["apps"][0], ingress="external")]
    manifest = load_manifest(_write_manifest(tmp_path, doc))
    result = apply_manifest(manifest)
    run_cfg = yaml.safe_load(pathlib.Path(result["run_config"]).read_text())
    assert run_cfg["apps"][0]["host"] == "0.0.0.0"


def test_apply_rejects_invalid(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    doc = {"environment": {"name": "x"},
           "apps": [{"app_id": "a", "module": "missing.mod:f"}]}
    manifest = load_manifest(_write_manifest(tmp_path, doc))
    with pytest.raises(ComponentError, match="invalid"):
        apply_manifest(manifest)


def test_prod_manifest_secure_baseline(tmp_path, monkeypatch):
    """environment.prod.yaml (≙ module 11 landing-zone baseline):
    valid, refuses to apply without the API token, emits health blocks
    and the secret default fallback."""
    from tasksrunner.security import TOKEN_ENV

    prod = REPO / "samples" / "tasks_tracker" / "environment.prod.yaml"
    manifest = load_manifest(prod)
    assert manifest.require_api_token is True
    assert validate_manifest(manifest) == []

    monkeypatch.chdir(tmp_path)
    # re-point at a scratch dir so apply writes under tmp
    import shutil
    workdir = tmp_path / "sample"
    shutil.copytree(prod.parent, workdir)
    manifest = load_manifest(workdir / "environment.prod.yaml")

    monkeypatch.delenv(TOKEN_ENV, raising=False)
    with pytest.raises(ComponentError, match="API token"):
        apply_manifest(manifest)

    monkeypatch.setenv(TOKEN_ENV, "testtoken")
    monkeypatch.delenv("SENDGRID_API_KEY", raising=False)
    result = apply_manifest(manifest)
    run_cfg = yaml.safe_load(pathlib.Path(result["run_config"]).read_text())
    apps = {a["app_id"]: a for a in run_cfg["apps"]}
    # health blocks pass through to the orchestrator config
    assert apps["tasksmanager-backend-api"]["health"]["failure_threshold"] == 3
    # secret default fallback (≙ the reference's 'dummy' sendgrid key)
    assert apps["tasksmanager-backend-processor"]["env"]["SENDGRID_API_KEY"] == "dummy"
    # only the frontend is externally reachable
    assert apps["tasksmanager-frontend-webapp"]["host"] == "0.0.0.0"
    assert apps["tasksmanager-backend-api"]["host"] == "127.0.0.1"
    # the posture travels with the artifact...
    assert run_cfg["require_api_token"] is True

    # ...and the orchestrator refuses to start it unauthenticated
    import asyncio as aio

    from tasksrunner.orchestrator import load_run_config
    from tasksrunner.orchestrator.run import run_from_config

    cfg = load_run_config(result["run_config"])
    assert cfg.require_api_token is True
    monkeypatch.delenv(TOKEN_ENV, raising=False)
    with pytest.raises(SystemExit, match="API token"):
        aio.run(run_from_config(cfg))


def test_health_block_validation(tmp_path):
    doc = {"environment": {"name": "x"},
           "apps": [{"app_id": "a", "module": "tasksrunner:App",
                     "health": "often"}]}
    manifest = load_manifest(_write_manifest(tmp_path, doc))
    problems = validate_manifest(manifest, check_imports=False)
    assert any("health" in p for p in problems)


def test_emitted_run_config_anchors_base_dir_at_manifest(tmp_path, monkeypatch):
    """Regression: the emitted run config lives in <manifest-dir>/
    .tasksrunner/, and load_run_config's default base_dir (the config's
    own parent) would make every relative component path —
    .tasksrunner/statestore.db etc. — resolve to a NESTED
    .tasksrunner/.tasksrunner/. The apply-emitted config must pin
    base_dir to the manifest's directory instead."""
    monkeypatch.chdir(tmp_path)
    manifest_path = _write_manifest(tmp_path, BASE_DOC)
    result = apply_manifest(load_manifest(manifest_path))

    emitted = pathlib.Path(result["run_config"])
    assert emitted.parent == tmp_path / ".tasksrunner"

    from tasksrunner.orchestrator.config import load_run_config
    parsed = load_run_config(emitted)
    assert parsed.base_dir == tmp_path, (
        f"base_dir {parsed.base_dir} would nest runtime state under "
        f"{parsed.base_dir / '.tasksrunner'}")
