"""Per-app authorization: component grants + per-app tokens.

≙ the reference's least-privilege identity model (SURVEY.md §5.10):
each app has its own managed identity with scoped role assignments —
Cosmos Data Contributor (webapi-backend-service.bicep:146-154), SB Data
Sender (:157-165), SB Data Receiver (processor-backend-service.bicep:
190-198), KV Secrets User (secrets/...-secrets.bicep:66-74). Here:
``grants:`` blocks in the run config / environment manifest, enforced
transport-neutrally in the Runtime, plus per-app API tokens where a
peer's token unlocks inbound invocation ONLY.
"""

import json
import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tasksrunner import App, InProcCluster
from tasksrunner.component.spec import parse_component
from tasksrunner.errors import ComponentError, PermissionDenied
from tasksrunner.security import AppGrants

API = "tasksmanager-backend-api"
FRONTEND = "tasksmanager-frontend-webapp"
PROCESSOR = "tasksmanager-backend-processor"


def specs(tmp_path):
    return [
        parse_component({
            "componentType": "state.memory",
        }, default_name="statestore"),
        parse_component({
            "componentType": "pubsub.memory",
        }, default_name="dapr-pubsub-servicebus"),
        parse_component({
            "componentType": "bindings.localblob",
            "metadata": [{"name": "rootPath", "value": str(tmp_path / "blobs")}],
        }, default_name="externaltasksblobstore"),
    ]


SAMPLE_GRANTS = {
    API: {
        "statestore": ["read", "write"],
        "dapr-pubsub-servicebus": [{"publish": ["tasksavedtopic"]}],
    },
    FRONTEND: {},  # the frontend holds no component roles
    PROCESSOR: {
        "dapr-pubsub-servicebus": [{"subscribe": ["tasksavedtopic"]}],
        "externaltasksblobstore": ["invoke"],
    },
}


def build_cluster(tmp_path, *, processor_subscribes=True):
    cluster = InProcCluster(specs(tmp_path), grants=SAMPLE_GRANTS)
    api, frontend, processor = App(API), App(FRONTEND), App(PROCESSOR)
    if processor_subscribes:
        @processor.subscribe(pubsub="dapr-pubsub-servicebus",
                             topic="tasksavedtopic", route="/on-saved")
        async def on_saved(req):
            return 200
    for a in (api, frontend, processor):
        cluster.add_app(a)
    return cluster


# -- parsing -------------------------------------------------------------

def test_parse_rejects_unknown_op():
    with pytest.raises(ComponentError, match="unknown operation"):
        AppGrants.parse({"statestore": ["fly"]})


def test_parse_rejects_non_mapping():
    with pytest.raises(ComponentError, match="must be a mapping"):
        AppGrants.parse(["statestore"])


def test_parse_topic_restriction_shapes():
    g = AppGrants.parse({
        "ps": ["subscribe", {"publish": ["a", "b"]}],
        "store": "read",          # bare string promotes to [read]
    })
    g.check("ps", "subscribe", topic="anything")
    g.check("ps", "publish", topic="a")
    with pytest.raises(PermissionDenied):
        g.check("ps", "publish", topic="c")
    g.check("store", "read")
    # round-trips through JSON (orchestrator → replica env hand-off)
    again = AppGrants.parse(json.loads(json.dumps(g.to_json())))
    with pytest.raises(PermissionDenied):
        again.check("ps", "publish", topic="c")


# -- runtime enforcement (the VERDICT's two named proofs) ---------------

@pytest.mark.asyncio
async def test_frontend_cannot_write_statestore(tmp_path):
    cluster = build_cluster(tmp_path)
    await cluster.start()
    try:
        frontend = cluster.client(FRONTEND)
        with pytest.raises(PermissionDenied):
            await frontend.save_state("statestore", "k", {"v": 1})
        with pytest.raises(PermissionDenied):
            await frontend.get_state("statestore", "k")
        # the API, with its Data-Contributor-analog grant, can
        api = cluster.client(API)
        await api.save_state("statestore", "k", {"v": 1})
        assert await api.get_state("statestore", "k") == {"v": 1}
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_processor_cannot_publish_ungranted_topic(tmp_path):
    cluster = build_cluster(tmp_path)
    await cluster.start()
    try:
        processor = cluster.client(PROCESSOR)
        # no publish grant at all on the pubsub it subscribes to
        with pytest.raises(PermissionDenied):
            await processor.publish_event(
                "dapr-pubsub-servicebus", "tasksavedtopic", {"x": 1})
        # the API may publish — but only to its granted topic
        api = cluster.client(API)
        await api.publish_event(
            "dapr-pubsub-servicebus", "tasksavedtopic", {"x": 1})
        with pytest.raises(PermissionDenied):
            await api.publish_event(
                "dapr-pubsub-servicebus", "some-other-topic", {"x": 1})
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_binding_invoke_grant(tmp_path):
    cluster = build_cluster(tmp_path)
    await cluster.start()
    try:
        await cluster.client(PROCESSOR).invoke_binding(
            "externaltasksblobstore", "create", {"a": 1},
            {"blobName": "a.json"})
        with pytest.raises(PermissionDenied):
            await cluster.client(API).invoke_binding(
                "externaltasksblobstore", "create", {"a": 1},
                {"blobName": "b.json"})
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_ungranted_subscription_fails_startup(tmp_path):
    """An app declaring a subscription it has no grant for must fail
    fast (≙ missing SB Data Receiver role), not start silently deaf."""
    grants = dict(SAMPLE_GRANTS)
    grants[PROCESSOR] = {}  # revoke the receiver role
    cluster = InProcCluster(specs(tmp_path), grants=grants)
    processor = App(PROCESSOR)

    @processor.subscribe(pubsub="dapr-pubsub-servicebus",
                         topic="tasksavedtopic", route="/on-saved")
    async def on_saved(req):
        return 200

    cluster.add_app(processor)
    with pytest.raises(PermissionDenied):
        await cluster.start()
    await cluster.stop()


@pytest.mark.asyncio
async def test_apps_without_grants_block_run_unrestricted(tmp_path):
    cluster = InProcCluster(specs(tmp_path))  # no grants anywhere
    app = App(API)
    cluster.add_app(app)
    await cluster.start()
    try:
        await cluster.client(API).save_state("statestore", "k", 1)
        await cluster.client(API).publish_event(
            "dapr-pubsub-servicebus", "any-topic", {})
    finally:
        await cluster.stop()


# -- HTTP surface: PermissionDenied maps to 403 --------------------------

@pytest.mark.asyncio
async def test_denied_op_maps_to_403_over_http(tmp_path):
    import aiohttp

    from tasksrunner.hosting import AppHost

    host = AppHost(App(FRONTEND), specs=specs(tmp_path),
                   grants=AppGrants.parse(SAMPLE_GRANTS[FRONTEND],
                                          app_id=FRONTEND),
                   register=False)
    await host.start()
    try:
        async with aiohttp.ClientSession() as session:
            async with session.post(
                f"http://127.0.0.1:{host.sidecar_port}/v1.0/state/statestore",
                json=[{"key": "k", "value": 1}],
            ) as resp:
                assert resp.status == 403
                assert "grant" in (await resp.json())["error"]
    finally:
        await host.stop()


# -- per-app tokens ------------------------------------------------------

@pytest.mark.asyncio
async def test_peer_token_unlocks_invoke_only(tmp_path, monkeypatch):
    """With per-app tokens, another app's identity may invoke me but
    may NOT read my state/secrets or publish as me."""
    import aiohttp

    from tasksrunner.hosting import AppHost

    from tasksrunner.security import hash_token

    api_token, frontend_token = "tok-api-1", "tok-frontend-2"
    # the distributed map carries sha256 digests, never plaintext —
    # holding the map must not let an app impersonate its peers
    tokens_file = tmp_path / "tokens.json"
    tokens_file.write_text(json.dumps(
        {API: hash_token(api_token), FRONTEND: hash_token(frontend_token)}))
    monkeypatch.setenv("TASKSRUNNER_TOKENS_FILE", str(tokens_file))
    monkeypatch.setenv("TASKSRUNNER_API_TOKEN", api_token)

    app = App(API)

    @app.get("/ping")
    async def ping(req):
        return 200, {"pong": True}

    host = AppHost(app, specs=specs(tmp_path), register=False)
    await host.start()
    base = f"http://127.0.0.1:{host.sidecar_port}"
    try:
        async with aiohttp.ClientSession() as session:
            async def req(path, token, method="GET", **kw):
                async with session.request(
                    method, base + path,
                    headers={"tr-api-token": token} if token else {},
                    **kw,
                ) as resp:
                    return resp.status

            # own token: everything works
            assert await req("/v1.0/state/statestore/k", api_token) in (200, 204)
            assert await req(f"/v1.0/invoke/{API}/method/ping", api_token) == 200
            # peer token: invocation only
            assert await req(f"/v1.0/invoke/{API}/method/ping",
                             frontend_token) == 200
            assert await req("/v1.0/state/statestore/k", frontend_token) == 401
            assert await req("/v1.0/publish/dapr-pubsub-servicebus/t",
                             frontend_token, method="POST", json={}) == 401
            # unknown token: nothing
            assert await req(f"/v1.0/invoke/{API}/method/ping", "bogus") == 401
            assert await req(f"/v1.0/invoke/{API}/method/ping", None) == 401
    finally:
        await host.stop()


# -- config / manifest plumbing ------------------------------------------

def test_run_config_parses_and_validates_grants(tmp_path):
    from tasksrunner.orchestrator.config import load_run_config

    cfg = tmp_path / "run.yaml"
    cfg.write_text("""
apps:
  - app_id: a
    module: x:make_app
    grants:
      store: [read, bogus-op]
""")
    with pytest.raises(ComponentError, match="unknown operation"):
        load_run_config(cfg)


def test_manifest_validate_catches_grant_for_unknown_component(tmp_path):
    from tasksrunner.deploy.manifest import load_manifest, validate_manifest

    comp = tmp_path / "store.yaml"
    comp.write_text(
        "componentType: state.memory\nmetadata: []\n")
    man = tmp_path / "env.yaml"
    man.write_text(f"""
environment:
  name: t
components:
  - name: statestore
    file: {comp}
apps:
  - app_id: a
    module: tasksrunner:App
    grants:
      statestore: [read]
      not-a-component: [write]
""")
    problems = validate_manifest(load_manifest(man), check_imports=False)
    assert any("not-a-component" in p for p in problems), problems
    assert not any("statestore" in p for p in problems), problems


def test_orchestrator_issues_per_app_tokens(tmp_path):
    from tasksrunner.orchestrator.config import AppSpec, RunConfig
    from tasksrunner.orchestrator.run import Orchestrator

    config = RunConfig(
        apps=[AppSpec(app_id="a", module="x:y"),
              AppSpec(app_id="b", module="x:y")],
        registry_file=str(tmp_path / ".tasksrunner" / "apps.json"),
        base_dir=tmp_path,
        per_app_tokens=True,
    )
    orch = Orchestrator(config)
    orch._issue_app_tokens()
    assert set(config.app_tokens) == {"a", "b"}
    assert config.app_tokens["a"] != config.app_tokens["b"]
    # the file on disk carries sha256 digests, never the plaintext
    # tokens: any replica can VERIFY a peer, none can IMPERSONATE one
    from tasksrunner.security import hash_token
    written = json.loads(pathlib.Path(config.tokens_file).read_text())
    assert written == {
        app_id: hash_token(tok) for app_id, tok in config.app_tokens.items()}
    for plaintext in config.app_tokens.values():
        assert plaintext not in pathlib.Path(config.tokens_file).read_text()
    mode = pathlib.Path(config.tokens_file).stat().st_mode & 0o777
    assert mode == 0o600


@pytest.mark.asyncio
async def test_stats_probe_is_token_gated(tmp_path, monkeypatch):
    """GET /tasksrunner/stats on the app ingress port must require the
    app's API token when one is configured — an ingress:external app
    must not leak load numbers to unauthenticated callers. The
    orchestrator's http-concurrency scaler authenticates like any
    client (autoscale._read_inflight sends the token)."""
    import aiohttp

    from tasksrunner.hosting import AppHost
    from tasksrunner.orchestrator.autoscale import _read_inflight

    monkeypatch.setenv("TASKSRUNNER_API_TOKEN", "stats-tok")
    app = App(API)
    host = AppHost(app, specs=specs(tmp_path), register=False)
    await host.start()
    try:
        url = f"http://127.0.0.1:{host.app_port}/tasksrunner/stats"
        async with aiohttp.ClientSession() as session:
            async with session.get(url) as resp:
                assert resp.status == 401
            async with session.get(
                    url, headers={"tr-api-token": "stats-tok"}) as resp:
                assert resp.status == 200
                doc = await resp.json()
                assert "inflight" in doc
        replicas = [{"pid": 1, "app_port": host.app_port,
                     "host": "127.0.0.1"}]
        # the scaler's reader: 0 without the token (401 → counts 0),
        # real number with it
        assert _read_inflight(replicas) == 0
        assert _read_inflight(replicas, api_token="stats-tok") == 0  # idle
    finally:
        await host.stop()
