"""Test configuration.

JAX-touching tests (the optional ML workload extension) run on a
virtual 8-device CPU mesh; everything else is pure Python. The env vars
must be set before jax initialises, hence here.
"""

import os
import sys
import pathlib

# hard-set (not setdefault): the machine profile exports
# JAX_PLATFORMS=axon (one real TPU chip); tests always run on the
# virtual 8-device CPU mesh. The axon plugin also prepends itself to
# jax.config.jax_platforms, so pin the config too, before any test
# module can query devices.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import asyncio
import inspect

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "asyncio: run this coroutine test on a fresh event loop"
    )
    config.addinivalue_line(
        "markers",
        "slow: soak-style tests excluded from the tier-1 fast run "
        "(-m 'not slow'); run them explicitly or via `make soak`",
    )


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests without pytest-asyncio (not installed)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
