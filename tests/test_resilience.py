"""Resilience + bulk-state tests.

Covers the framework analogs of SURVEY.md §5.3: sidecar invoke retries
(Dapr's built-in service-invocation retries), crash → restart →
re-registration recovery, and the bulk state API.
"""

import asyncio

import pytest

from tasksrunner import App, AppHost, InProcCluster, load_components
from tasksrunner.component.spec import parse_component
from tasksrunner.errors import InvocationError


def state_spec():
    return parse_component({"componentType": "state.in-memory"},
                           default_name="statestore")


@pytest.mark.asyncio
async def test_bulk_get_state_both_transports(tmp_path):
    api = App("api")

    @api.post("/fill")
    async def fill(req):
        await api.client.save_state_bulk("statestore", [
            {"key": "a", "value": 1}, {"key": "b", "value": 2},
        ])
        return 200

    cluster = InProcCluster([state_spec()])
    cluster.add_app(api)
    await cluster.start()
    try:
        client = cluster.client("api")
        await client.invoke_method("api", "fill", http_method="POST")
        result = await client.bulk_get_state("statestore", ["a", "missing", "b"])
        assert result[0] == {"key": "a", "data": 1, "etag": result[0]["etag"]}
        assert result[1] == {"key": "missing"}
        assert result[2]["data"] == 2
    finally:
        await cluster.stop()

    # same through the HTTP sidecar
    host = AppHost(api, specs=[state_spec()],
                   registry_file=str(tmp_path / "apps.json"))
    await host.start()
    try:
        await host.client.invoke_method("api", "fill", http_method="POST")
        result = await host.client.bulk_get_state("statestore", ["a", "nope"])
        assert result[0]["data"] == 1 and result[1] == {"key": "nope"}
    finally:
        await host.stop()


@pytest.mark.asyncio
async def test_invoke_retries_when_peer_restarts(tmp_path):
    """A peer that crashes and re-registers on a NEW port is reached on
    retry — the local analog of ACA restart + sidecar retries."""
    registry_file = str(tmp_path / "apps.json")

    api = App("api")

    @api.get("/ping")
    async def ping(req):
        return {"pong": True}

    caller = App("caller")

    @caller.get("/call")
    async def call(req):
        return await caller.client.invoke_json("api", "ping")

    api_host = AppHost(api, registry_file=registry_file)
    caller_host = AppHost(caller, registry_file=registry_file)
    await api_host.start()
    await caller_host.start()
    try:
        assert (await caller_host.client.invoke_json("caller", "call"))["pong"]

        # kill the api's host entirely, then bring it back on new ports
        await api_host.stop()
        api2 = App("api")

        @api2.get("/ping")
        async def ping2(req):
            return {"pong": True}

        api_host2 = AppHost(api2, registry_file=registry_file)

        async def delayed_restart():
            await asyncio.sleep(0.25)  # longer than the first retry delay
            await api_host2.start()

        restart = asyncio.create_task(delayed_restart())
        # the invoke must survive the window where the peer is down
        result = await caller_host.client.invoke_json("caller", "call")
        assert result["pong"]
        await restart
        await api_host2.stop()
    finally:
        await caller_host.stop()


@pytest.mark.asyncio
async def test_invoke_fails_cleanly_after_retries_exhausted(tmp_path):
    """Dead peer that never comes back -> InvocationError, not a hang."""
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.invoke.resolver import AppAddress, NameResolver
    from tasksrunner.runtime import Runtime

    resolver = NameResolver()
    resolver.register(AppAddress(app_id="ghost", host="127.0.0.1",
                                 sidecar_port=1))  # nothing listens there
    runtime = Runtime("caller", ComponentRegistry([]), resolver=resolver,
                      invoke_retries=2, invoke_retry_delay=0.01)
    with pytest.raises(InvocationError, match="after 2 attempts"):
        await runtime.invoke("ghost", "x", http_method="GET")
    await runtime.stop()
