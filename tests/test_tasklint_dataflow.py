"""Dataflow tasklint: CFG rules + SARIF + cache mechanics.

Same two-layer shape as test_tasklint_program.py: seeded-bad-code
fixtures prove each dataflow rule fires (and stays quiet on the
healthy variant — including the idioms that bit the first cut of each
rule: guarded releases in a finally, closure-owned resources, the
cancel-then-reap pattern, connection-checkout ownership transfer), and
the mechanics tests pin the phase contracts — chain-aware suppression,
the SARIF 2.1.0 round trip, the deleted-file cache prune, and the
wall-time budget over the real tree.
"""

import io
import json
import pathlib
import sys
import textwrap
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tasksrunner.analysis.cache import ResultCache, ruleset_signature
from tasksrunner.analysis.core import DATAFLOW_RULES
from tasksrunner.analysis.dataflow import DataflowAnalysis
from tasksrunner.analysis.engine import (
    DEFAULT_TARGET, _program_suppressed, run,
)
from tasksrunner.analysis.program import ProgramGraph

DATAFLOW_ONLY = tuple(sorted(DATAFLOW_RULES))


def _dataflow(tmp_path, sources, rules=DATAFLOW_ONLY):
    """Run the dataflow rules over ``sources`` ({relpath: code}) with
    controlled relpaths, through the real suppression filter."""
    files = []
    for name, src in sources.items():
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
        files.append((path, name))
    graph = ProgramGraph.build(files)
    dfa = DataflowAnalysis(graph)
    raw = []
    for rid in rules:
        raw.extend(DATAFLOW_RULES[rid].check(dfa))
    findings = sorted(f for f in raw if not _program_suppressed(graph, f))
    return findings, len(raw) - len(findings)


# -- secret-taint -------------------------------------------------------


TAINT_BAD = """\
import logging
import os

logger = logging.getLogger("x")


def boom():
    token = os.environ.get("TASKSRUNNER_API_TOKEN")
    logger.info("auth token is %s", token)
"""


def test_secret_taint_env_to_log(tmp_path):
    findings, _ = _dataflow(tmp_path, {"mod.py": TAINT_BAD},
                            rules=("secret-taint",))
    (f,) = findings
    assert f.rule == "secret-taint"
    assert (f.path, f.line) == ("mod.py", 9)  # the logger.info sink
    assert "TASKSRUNNER_API_TOKEN" in f.message
    assert "redact()" in f.message
    # chain: source -> sink
    assert f.chain == ("mod.py:8", "mod.py:9")


def test_secret_taint_interprocedural_chain(tmp_path):
    """The secret enters in the caller, the sink lives in the callee —
    the finding is reported at the *call site* with the callee's sink
    frame appended to the chain."""
    findings, _ = _dataflow(tmp_path, {
        "creds.py": """\
            import os


            def fetch_token():
                return os.environ.get("TASKSRUNNER_API_TOKEN")
            """,
        "app.py": """\
            import logging

            from creds import fetch_token

            logger = logging.getLogger("x")


            def log_it(value):
                logger.warning("got %s", value)


            def boom():
                log_it(fetch_token())
            """,
    }, rules=("secret-taint",))
    (f,) = findings
    assert f.path == "app.py" and f.line == 13  # log_it(fetch_token())
    # chain: the env read in creds.py, the call site, the callee's sink
    assert f.chain[0].startswith("creds.py:")
    assert "app.py:13" in f.chain
    assert any(frame == "app.py:9" for frame in f.chain)  # callee sink


def test_secret_taint_sanitizer_interposed(tmp_path):
    clean = TAINT_BAD.replace("logger.info(\"auth token is %s\", token)",
                              "logger.info(\"auth %s\", redact(token))")
    findings, _ = _dataflow(tmp_path, {"mod.py": clean},
                            rules=("secret-taint",))
    assert findings == []


def test_secret_taint_len_is_not_a_leak(tmp_path):
    clean = TAINT_BAD.replace("logger.info(\"auth token is %s\", token)",
                              "logger.info(\"%d bytes\", len(token))")
    findings, _ = _dataflow(tmp_path, {"mod.py": clean},
                            rules=("secret-taint",))
    assert findings == []


def test_secret_taint_suppression_on_sink_and_chain_line(tmp_path):
    # on the sink line
    src = TAINT_BAD.replace(
        "logger.info(\"auth token is %s\", token)",
        "logger.info(\"auth token is %s\", token)"
        "  # tasklint: disable=secret-taint")
    findings, suppressed = _dataflow(tmp_path, {"mod.py": src},
                                     rules=("secret-taint",))
    assert findings == [] and suppressed == 1
    # on the *source* line (chain-aware suppression)
    src = TAINT_BAD.replace(
        'token = os.environ.get("TASKSRUNNER_API_TOKEN")',
        'token = os.environ.get("TASKSRUNNER_API_TOKEN")'
        "  # tasklint: disable=secret-taint")
    findings, suppressed = _dataflow(tmp_path / "b", {"mod.py": src},
                                     rules=("secret-taint",))
    assert findings == [] and suppressed == 1


# -- resource-lifetime --------------------------------------------------


LEAK_BAD = """\
import sqlite3


def leak(flag):
    conn = sqlite3.connect("db")
    if flag:
        return None
    conn.close()
    return True
"""


def test_lifetime_reports_the_leaking_early_return(tmp_path):
    findings, _ = _dataflow(tmp_path, {"mod.py": LEAK_BAD},
                            rules=("resource-lifetime",))
    (f,) = findings
    assert f.rule == "resource-lifetime"
    assert f.line == 5  # the acquisition
    assert "the return at line 7" in f.message  # names the leaking path
    assert f.chain == ("mod.py:5", "mod.py:7")


def test_lifetime_reports_raise_path_for_inpackage_class(tmp_path):
    findings, _ = _dataflow(tmp_path, {"mod.py": """\
        class Conn:
            async def aclose(self):
                pass


        def leak():
            c = Conn()
            raise ValueError("boom")
        """}, rules=("resource-lifetime",))
    (f,) = findings
    assert "Conn" in f.message and "aclose" in f.message
    assert "the raise at line 8" in f.message


def test_lifetime_clean_variants(tmp_path):
    """with-block, finally-close, owner hand-off, and return-the-
    resource all discharge the obligation."""
    findings, _ = _dataflow(tmp_path, {"mod.py": """\
        import sqlite3


        def ctx():
            with sqlite3.connect("db") as conn:
                conn.execute("select 1")


        def fin():
            conn = sqlite3.connect("db")
            try:
                conn.execute("select 1")
            finally:
                conn.close()


        def owner(pool):
            conn = sqlite3.connect("db")
            pool.append(conn)


        def transfer():
            return sqlite3.connect("db")
        """}, rules=("resource-lifetime",))
    assert findings == []


def test_lifetime_guarded_release_in_finally_is_clean(tmp_path):
    """``if conn is not None: conn.close()`` in a finally — the None
    branch is exactly the never-acquired path, not a leak."""
    findings, _ = _dataflow(tmp_path, {"mod.py": """\
        import sqlite3


        def loop(items):
            conn = None
            try:
                for item in items:
                    if conn is None:
                        conn = sqlite3.connect("db")
                    conn.execute("insert")
            finally:
                if conn is not None:
                    conn.close()
        """}, rules=("resource-lifetime",))
    assert findings == []


def test_lifetime_closure_capture_is_ownership(tmp_path):
    """A nested def that closes over the resource (the CLI's
    ``async def main(): ... await host.stop()`` shape) owns it."""
    findings, _ = _dataflow(tmp_path, {"mod.py": """\
        import sqlite3


        def hold(runner):
            conn = sqlite3.connect("db")

            def closer():
                conn.close()

            runner(closer)
        """}, rules=("resource-lifetime",))
    assert findings == []


# -- cancellation-safety ------------------------------------------------


def test_cancel_await_in_finally_fires_and_shield_is_safe(tmp_path):
    findings, _ = _dataflow(tmp_path, {"mod.py": """\
        import asyncio


        async def bad(server):
            try:
                await asyncio.sleep(1)
            finally:
                await server.stop()


        async def good(server):
            try:
                await asyncio.sleep(1)
            finally:
                await asyncio.shield(server.stop())


        async def guarded(server):
            try:
                await asyncio.sleep(1)
            finally:
                try:
                    await server.stop()
                except asyncio.CancelledError:
                    raise
        """}, rules=("cancellation-safety",))
    (f,) = findings
    assert f.line == 8 and "await in finally" in f.message
    assert "bad" in f.message


def test_cancel_swallow_fires_and_reap_idiom_is_exempt(tmp_path):
    findings, _ = _dataflow(tmp_path, {"mod.py": """\
        import asyncio


        async def bad(task):
            try:
                await task
            except asyncio.CancelledError:
                pass


        async def reap(task):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        """}, rules=("cancellation-safety",))
    (f,) = findings  # only bad() fires; reap() at line 13 is exempt
    assert f.line == 7 and "swallows CancelledError" in f.message
    assert f.message.startswith("bad ")


def test_cancel_acquire_release_placement(tmp_path):
    findings, _ = _dataflow(tmp_path, {"mod.py": """\
        async def bad(lock, work):
            await lock.acquire()
            await work()
            lock.release()


        async def good(lock, work):
            await lock.acquire()
            try:
                await work()
            finally:
                lock.release()


        async def checkout(sem, dial):
            await sem.acquire()
            try:
                conn = await dial()
            except BaseException:
                sem.release()
                raise
            return conn
        """}, rules=("cancellation-safety",))
    (f,) = findings
    assert f.line == 2 and "outside a finally" in f.message


# -- exception-flow -----------------------------------------------------


ROUTES_PRELUDE = """\
class _Routes:
    def get(self, path):
        def deco(fn):
            return fn
        return deco


routes = _Routes()
"""


def test_exflow_reports_untyped_escape_with_chain(tmp_path):
    findings, _ = _dataflow(tmp_path, {"app.py": ROUTES_PRELUDE + """\


@routes.get("/boom")
async def handler(request):
    helper()


def helper():
    raise ValueError("nope")
"""}, rules=("exception-flow",))
    (f,) = findings
    assert f.rule == "exception-flow"
    assert "handler" in f.message and "ValueError" in f.message
    # chain walks handler def -> call site -> the leaf raise
    assert f.chain[0] == "app.py:12"  # the handler def
    assert f.chain[-1] == "app.py:17"  # the leaf raise in helper


def test_exflow_taxonomy_and_cancel_are_allowed(tmp_path):
    findings, _ = _dataflow(tmp_path, {
        "tasksrunner/errors.py": """\
            class AppError(Exception):
                http_status = 400
            """,
        "app.py": ROUTES_PRELUDE + """\


import asyncio

from tasksrunner.errors import AppError


@routes.get("/typed")
async def handler(request):
    raise AppError("known")


@routes.get("/gone")
async def handler2(request):
    raise asyncio.CancelledError()
"""}, rules=("exception-flow",))
    assert findings == []


def test_exflow_handler_catching_locally_is_clean(tmp_path):
    findings, _ = _dataflow(tmp_path, {"app.py": ROUTES_PRELUDE + """\


@routes.get("/safe")
async def handler(request):
    try:
        helper()
    except ValueError:
        return None


def helper():
    raise ValueError("nope")
"""}, rules=("exception-flow",))
    assert findings == []


# -- mechanics: SARIF, cache prune, budget ------------------------------


def test_sarif_round_trip(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(TAINT_BAD)
    sarif_path = tmp_path / "out.sarif"
    rc = run([target], DATAFLOW_ONLY, json_out=True, out=io.StringIO(),
             baseline_path=tmp_path / "baseline.json",
             sarif_path=sarif_path)
    assert rc == 1
    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    (run_obj,) = doc["runs"]
    driver = run_obj["tool"]["driver"]
    assert driver["name"] == "tasklint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(rule_ids)
    assert "secret-taint" in rule_ids
    (result,) = run_obj["results"]
    assert result["ruleId"] == "secret-taint"
    assert rule_ids[result["ruleIndex"]] == "secret-taint"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 9
    assert result["partialFingerprints"]["tasklint/v1"]
    # the source->sink chain became a codeFlow
    steps = result["codeFlows"][0]["threadFlows"][0]["locations"]
    lines = [s["location"]["physicalLocation"]["region"]["startLine"]
             for s in steps]
    assert lines == [8, 9]

    # green tree -> empty results, rules still listed
    target.write_text("x = 1\n")
    rc = run([target], DATAFLOW_ONLY, out=io.StringIO(),
             baseline_path=tmp_path / "baseline.json",
             sarif_path=sarif_path)
    assert rc == 0
    doc = json.loads(sarif_path.read_text())
    assert doc["runs"][0]["results"] == []
    assert [r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]]


def test_cache_prunes_deleted_file_entries(tmp_path):
    """Regression: entries for deleted/renamed sources used to live in
    the cache forever (save() only sweeps old-signature rows)."""
    target = tmp_path / "mod.py"
    target.write_text("x = 1\n")
    cache_file = tmp_path / "cache.json"
    sig = ruleset_signature(("blocking-call-in-async",))

    cache = ResultCache(cache_file, sig)
    cache.put(target, [])
    cache.put_program("treehash", [], 0)
    cache.save()
    assert str(target) in json.loads(cache_file.read_text())

    target.unlink()
    reloaded = ResultCache(cache_file, sig)
    assert str(target) not in reloaded._table  # pruned on load
    assert "__program__" in reloaded._table   # reserved keys survive
    reloaded.save()                           # prune marked it dirty
    on_disk = json.loads(cache_file.read_text())
    assert str(target) not in on_disk
    assert "__program__" in on_disk


def test_dataflow_zero_findings_and_wall_time_budget(tmp_path):
    """The tree must stay clean under the dataflow rules with an empty
    baseline, cold under 30s and tree-digest-warm under 5s."""
    cache_file = tmp_path / "cache.json"
    t0 = time.perf_counter()
    rc = run([DEFAULT_TARGET], DATAFLOW_ONLY, cache_path=cache_file,
             baseline_path=tmp_path / "baseline.json", out=io.StringIO())
    cold = time.perf_counter() - t0
    assert rc == 0
    t0 = time.perf_counter()
    rc = run([DEFAULT_TARGET], DATAFLOW_ONLY, cache_path=cache_file,
             baseline_path=tmp_path / "baseline.json", out=io.StringIO())
    warm = time.perf_counter() - t0
    assert rc == 0
    assert cold < 30.0, f"cold dataflow lint took {cold:.1f}s"
    assert warm < 5.0, f"warm dataflow lint took {warm:.1f}s"
