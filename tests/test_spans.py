"""Span collection + trace query tests (the App Insights analog,
SURVEY.md §5.1): one user action produces one trace spanning all three
hops, queryable by trace id, with service-map edges."""

import asyncio

import pytest

from tasksrunner import App, InProcCluster
from tasksrunner.component.spec import parse_component
from tasksrunner.observability import spans as spans_mod


@pytest.fixture
def trace_db(tmp_path):
    db = tmp_path / "traces.db"
    rec = spans_mod.configure_spans("test-proc", db)
    yield str(db)
    rec.close()
    spans_mod._recorder = None


@pytest.mark.asyncio
async def test_trace_recorded_across_hops(trace_db, tmp_path):
    specs = [parse_component({
        "componentType": "pubsub.sqlite",
        "metadata": [{"name": "brokerPath", "value": str(tmp_path / "b.db")},
                     {"name": "pollIntervalSeconds", "value": "0.01"}],
    }, default_name="ps")]

    api = App("api")

    @api.post("/api/tasks")
    async def create(req):
        await api.client.publish_event("ps", "saved", req.json())
        return 201, {"ok": True}

    got = asyncio.Event()
    worker = App("worker")

    @worker.subscribe("ps", "saved", route="/on-saved")
    async def on_saved(req):
        got.set()
        return 200

    caller = App("caller")

    @caller.post("/go")
    async def go(req):
        resp = await caller.client.invoke_method(
            "api", "api/tasks", http_method="POST", data={"n": 1})
        return resp.status

    cluster = InProcCluster(specs)
    for a in (api, worker, caller):
        cluster.add_app(a)
    await cluster.start()
    try:
        root = "00-" + "ef" * 16 + "-" + "12" * 8 + "-01"
        await caller.handle("POST", "/go", headers={"traceparent": root},
                            body=b"{}")
        await asyncio.wait_for(got.wait(), timeout=5)
    finally:
        await cluster.stop()

    spans_mod.recorder().flush()
    trace_id = "ef" * 16
    spans = spans_mod.trace_spans(trace_db, trace_id)
    kinds = {(s["kind"], s["name"]) for s in spans}
    assert ("server", "POST /go") in kinds
    assert ("client", "invoke api/api/tasks") in kinds
    assert ("server", "POST /api/tasks") in kinds
    assert ("producer", "publish ps/saved") in kinds
    assert ("consumer", "POST /on-saved") in kinds
    assert all(s["trace_id"] == trace_id for s in spans)

    # transaction search
    listing = spans_mod.list_traces(trace_db)
    assert any(t["trace_id"] == trace_id for t in listing)

    # service map has the invoke and publish edges
    edges = {(e["from"], e["to"]) for e in spans_mod.service_map(trace_db)}
    assert ("test-proc", "api") in edges
    assert ("test-proc", "ps/saved") in edges


def test_recording_disabled_is_noop(tmp_path):
    assert spans_mod.recorder() is None
    spans_mod.record_span(kind="server", name="x", status=200,
                          start=0.0, duration=0.1)  # must not raise


def test_child_preserves_all_fields():
    """TraceContext.child constructs explicitly (hot path); this pins
    the field set so a new field cannot be silently dropped from
    children — extend child() AND this test together."""
    import dataclasses

    from tasksrunner.observability.tracing import TraceContext

    assert {f.name for f in dataclasses.fields(TraceContext)} == {
        "trace_id", "span_id", "flags", "parent_id", "baggage"}

    ctx = dataclasses.replace(TraceContext.new(), flags="00",
                              baggage={"k": 1})
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.flags == ctx.flags
    assert child.baggage == ctx.baggage
    assert child.parent_id == ctx.span_id
    assert child.span_id != ctx.span_id


def test_span_retention_prunes_old_spans(tmp_path, monkeypatch):
    """Spans age out (≙ the reference's 30-day Log Analytics
    retention), newest stay."""
    import sqlite3
    import time as time_mod

    from tasksrunner.observability import spans as spans_mod
    from tasksrunner.observability.tracing import TraceContext, trace_scope

    db = tmp_path / "traces.db"
    rec = spans_mod.SpanRecorder("api", db, flush_interval=999,
                                 retention_seconds=3600)
    with trace_scope(TraceContext.new()):
        rec.record(kind="server", name="old", status=200,
                   start=time_mod.time() - 7200, duration=0.01)
        rec.record(kind="server", name="new", status=200,
                   start=time_mod.time(), duration=0.01)
    rec.flush()
    rec.close()
    names = [r[0] for r in sqlite3.connect(db).execute(
        "SELECT name FROM spans").fetchall()]
    assert names == ["new"]


def test_retention_sweep_runs_at_most_once_a_minute(tmp_path):
    """The prune rides a flush but is rate-limited: back-to-back
    flushes inside the 60 s window must not re-scan the table."""
    import sqlite3
    import time as time_mod

    from tasksrunner.observability.tracing import TraceContext, trace_scope

    db = tmp_path / "traces.db"
    rec = spans_mod.SpanRecorder("api", db, flush_interval=999,
                                 retention_seconds=3600)
    try:
        with trace_scope(TraceContext.new()):
            rec.record(kind="server", name="a", status=200,
                       start=time_mod.time(), duration=0.01)
        rec.flush()  # first flush sweeps and stamps _last_prune
        first_prune = rec._last_prune
        assert first_prune > 0
        with trace_scope(TraceContext.new()):
            # old enough to be prunable — but the sweep must not rerun yet
            rec.record(kind="server", name="expired", status=200,
                       start=time_mod.time() - 7200, duration=0.01)
        rec.flush()
        assert rec._last_prune == first_prune
        names = {r[0] for r in sqlite3.connect(db).execute(
            "SELECT name FROM spans").fetchall()}
        assert names == {"a", "expired"}
        # a minute later (simulated) the next flush prunes it
        rec._last_prune = time_mod.time() - 61
        with trace_scope(TraceContext.new()):
            rec.record(kind="server", name="b", status=200,
                       start=time_mod.time(), duration=0.01)
        rec.flush()
        names = {r[0] for r in sqlite3.connect(db).execute(
            "SELECT name FROM spans").fetchall()}
        assert names == {"a", "b"}
    finally:
        rec.close()


def test_nonpositive_retention_keeps_everything(tmp_path):
    import sqlite3
    import time as time_mod

    from tasksrunner.observability.tracing import TraceContext, trace_scope

    db = tmp_path / "traces.db"
    rec = spans_mod.SpanRecorder("api", db, flush_interval=999,
                                 retention_seconds=0)
    try:
        with trace_scope(TraceContext.new()):
            rec.record(kind="server", name="ancient", status=200,
                       start=time_mod.time() - 10 * 365 * 24 * 3600,
                       duration=0.01)
        rec.flush()
        names = [r[0] for r in sqlite3.connect(db).execute(
            "SELECT name FROM spans").fetchall()]
        assert names == ["ancient"]
    finally:
        rec.close()


def test_close_wins_race_against_inflight_tick(tmp_path):
    """A _tick() that already fired when close() cancelled the timer
    must not resurrect the flush loop: post-close, no new timer may be
    scheduled and late records must not crash."""
    rec = spans_mod.SpanRecorder("api", tmp_path / "traces.db",
                                 flush_interval=999)
    rec.close()
    closed_timer = rec._timer
    # simulate the in-flight tick finishing after close
    rec._tick()
    assert rec._closed
    assert rec._timer is closed_timer  # _schedule refused to rearm
    # cancel() set the timer's finished event; it will never fire
    assert rec._timer.finished.is_set()
    rec._schedule()
    assert rec._timer is closed_timer
    # close is idempotent
    rec.close()


def test_service_map_aggregates_per_edge_not_per_operation(tmp_path):
    """Two different operations against the same target are ONE
    App-Map edge: span names embed the method path, so grouping by
    name alone would print `api --client--> api` once per distinct
    route (observed with 3 duplicate rows in `tasksrunner traces map`)."""
    from tasksrunner.observability.tracing import ensure_trace, trace_scope

    trace_db = str(tmp_path / "spans.db")
    rec = spans_mod.configure_spans("frontend", trace_db)
    try:
        import time as _time

        with trace_scope(ensure_trace(None)):
            for name in ("invoke api/api/tasks", "invoke api/api/overduetasks",
                         "invoke api/api/tasks"):
                # start must be recent: the flush-time retention sweep
                # prunes old-epoch spans
                rec.record(kind="client", name=name, status=200,
                           start=_time.time(), duration=0.01,
                           attrs={"target": "api"})
        rec.flush()
        edges = spans_mod.service_map(trace_db)
        client_edges = [e for e in edges if e["kind"] == "client"]
        assert len(client_edges) == 1
        assert client_edges[0]["from"] == "frontend"
        assert client_edges[0]["to"] == "api"
        assert client_edges[0]["calls"] == 3
    finally:
        rec.close()
        spans_mod._recorder = None


def test_service_map_mermaid_output(tmp_path, capsys):
    """`traces map --mermaid` emits a valid mermaid graph: one edge per
    aggregated (caller, target), dashed arrows for producer edges."""
    import argparse

    from tasksrunner.cli import _cmd_traces
    from tasksrunner.observability.tracing import ensure_trace, trace_scope
    import time as _time

    trace_db = str(tmp_path / "spans.db")
    rec = spans_mod.configure_spans("frontend", trace_db)
    try:
        with trace_scope(ensure_trace(None)):
            rec.record(kind="client", name="invoke api/api/tasks", status=200,
                       start=_time.time(), duration=0.01,
                       attrs={"target": "api"})
            rec.record(kind="producer", name="publish ps/saved", status=200,
                       start=_time.time(), duration=0.001)
        rec.flush()
        args = argparse.Namespace(action="map", db=trace_db, trace_id=None,
                                  limit=20, mermaid=True)
        _cmd_traces(args)
        out = capsys.readouterr().out
        assert out.startswith("graph LR")
        assert '-->|"1 calls' in out           # client edge, solid
        assert '-.->|"1 calls' in out          # producer edge, dashed
        assert 'nfrontend["frontend"]' in out
        assert 'napi["api"]' in out
    finally:
        rec.close()
        spans_mod._recorder = None


def test_service_map_mermaid_escapes_and_disambiguates(tmp_path, capsys):
    """Names differing only in punctuation must stay distinct nodes,
    and quotes in names must not break the mermaid syntax."""
    import argparse

    from tasksrunner.cli import _cmd_traces
    from tasksrunner.observability.tracing import ensure_trace, trace_scope
    import time as _time

    trace_db = str(tmp_path / "spans.db")
    rec = spans_mod.configure_spans("caller", trace_db)
    try:
        with trace_scope(ensure_trace(None)):
            for target in ("ps/saved", "ps-saved", 'q="x"'):
                rec.record(kind="client", name=f"invoke {target}", status=200,
                           start=_time.time(), duration=0.01,
                           attrs={"target": target})
        rec.flush()
        _cmd_traces(argparse.Namespace(action="map", db=trace_db,
                                       trace_id=None, limit=20, mermaid=True))
        out = capsys.readouterr().out
        # three distinct target nodes despite id sanitization collisions
        import re as _re
        target_ids = set()
        for line in out.splitlines()[1:]:
            m = _re.search(r"\| (\w+)\[", line)
            assert m, line
            target_ids.add(m.group(1))
        assert len(target_ids) == 3, out
        # raw double quotes never appear inside a label
        assert '#quot;' in out and 'q="x"' not in out
    finally:
        rec.close()
        spans_mod._recorder = None
