"""CLI probe commands against a live multi-process cluster.

``tasksrunner state/invoke/publish/secret`` are the workshop's manual
verification checkpoints (docs/aca/04-aca-dapr-stateapi/index.md:41-75
curl probes; docs/aca/05-aca-dapr-pubsubapi/index.md:60-88 publish +
watch consumer) promoted to first-class commands, ≙ `dapr invoke` /
`dapr publish` / `dapr stop`.
"""

import asyncio
import json
import os
import pathlib
import sys

import pytest

from tasksrunner.orchestrator import AppSpec
from tasksrunner.orchestrator.config import RunConfig
from tasksrunner.orchestrator.run import Orchestrator

REPO = pathlib.Path(__file__).resolve().parent.parent


async def run_cli(*argv, registry, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "tasksrunner", *argv,
        "--registry-file", str(registry),
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE,
        env=env, cwd=str(cwd))
    out, err = await asyncio.wait_for(proc.communicate(), timeout=30)
    return proc.returncode, out.decode(), err.decode()


@pytest.mark.asyncio
async def test_cli_probes_against_running_cluster(tmp_path):
    registry = tmp_path / "apps.json"
    config = RunConfig(
        apps=[
            AppSpec(app_id="tasksmanager-backend-api",
                    module="samples.tasks_tracker.backend_api:make_app",
                    env={"TASKS_MANAGER": "store"}),
            AppSpec(app_id="tasksmanager-backend-processor",
                    module="samples.tasks_tracker.processor:make_app"),
        ],
        resources_path=str(REPO / "samples" / "tasks_tracker" / "components"),
        registry_file=str(registry),
        base_dir=tmp_path,
    )
    orch = Orchestrator(config)
    await orch.start()
    try:
        deadline = asyncio.get_running_loop().time() + 30
        while True:
            entries = json.loads(registry.read_text() or "{}") \
                if registry.is_file() else {}
            if len(entries) == 2:
                break
            assert asyncio.get_running_loop().time() < deadline, \
                "apps never registered"
            await asyncio.sleep(0.2)

        api = "tasksmanager-backend-api"

        # state set / get / query / delete (module-4 probe flow)
        rc, out, err = await run_cli(
            "state", "set", "statestore", "probe-1",
            "--app-id", api, "--data",
            '{"taskName": "cli-probe", "taskCreatedBy": "cli@x.com"}',
            registry=registry, cwd=tmp_path)
        assert rc == 0, err
        rc, out, err = await run_cli(
            "state", "get", "statestore", "probe-1",
            "--app-id", api, registry=registry, cwd=tmp_path)
        assert rc == 0 and "cli-probe" in out, (out, err)
        rc, out, err = await run_cli(
            "state", "query", "statestore",
            "--app-id", api, "--data",
            '{"filter": {"EQ": {"taskCreatedBy": "cli@x.com"}}}',
            registry=registry, cwd=tmp_path)
        assert rc == 0 and "probe-1" in out, (out, err)
        rc, out, err = await run_cli(
            "state", "delete", "statestore", "probe-1",
            "--app-id", api, registry=registry, cwd=tmp_path)
        assert rc == 0, err

        # invoke: the REST surface through the sidecar
        rc, out, err = await run_cli(
            "invoke", api, "api/tasks?createdBy=cli@x.com",
            registry=registry, cwd=tmp_path)
        assert rc == 0 and out.strip().startswith("["), (out, err)
        rc, out, err = await run_cli(
            "invoke", api, "api/tasks", "--verb", "POST", "--data",
            '{"taskName": "via-invoke", "taskCreatedBy": "cli@x.com",'
            ' "taskDueDate": "2026-08-09", "taskAssignedTo": "a@x.com"}',
            registry=registry, cwd=tmp_path)
        assert rc == 0, (out, err)

        # publish: event lands at the processor (sendgrid outbox file)
        rc, out, err = await run_cli(
            "publish", "dapr-pubsub-servicebus", "tasksavedtopic",
            "--app-id", api, "--data",
            '{"taskId": "pub-1", "taskName": "published",'
            ' "taskAssignedTo": "p@x.com"}',
            registry=registry, cwd=tmp_path)
        assert rc == 0, (out, err)
        outbox = tmp_path / ".tasksrunner" / "outbox"
        deadline = asyncio.get_running_loop().time() + 15
        while not (outbox.is_dir() and list(outbox.glob("*.json"))):
            assert asyncio.get_running_loop().time() < deadline, \
                "published event never reached the processor"
            await asyncio.sleep(0.2)

        # unknown app id → helpful error, nonzero exit
        rc, out, err = await run_cli(
            "state", "get", "statestore", "x", "--app-id", "nope",
            registry=registry, cwd=tmp_path)
        assert rc != 0 and "not registered" in err, (out, err)
    finally:
        await orch.stop()


@pytest.mark.asyncio
async def test_cli_stop_unknown_app_errors(tmp_path):
    registry = tmp_path / "apps.json"
    registry.write_text("{}")
    rc, out, err = await run_cli("stop", "ghost",
                                 registry=registry, cwd=tmp_path)
    assert rc != 0 and "not registered" in err


@pytest.mark.asyncio
async def test_cli_stop_terminates_host(tmp_path):
    registry = tmp_path / "apps.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    host = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "tasksrunner", "host",
        "samples.tasks_tracker.processor:make_app",
        "--registry-file", str(registry),
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT,
        env=env, cwd=str(tmp_path))
    try:
        deadline = asyncio.get_running_loop().time() + 30
        while True:
            entries = json.loads(registry.read_text() or "{}") \
                if registry.is_file() else {}
            if entries:
                break
            assert asyncio.get_running_loop().time() < deadline, \
                "host never registered"
            await asyncio.sleep(0.2)
        rc, out, err = await run_cli(
            "stop", "tasksmanager-backend-processor",
            registry=registry, cwd=tmp_path)
        assert rc == 0 and "SIGTERM" in out, (out, err)
        await asyncio.wait_for(host.wait(), timeout=15)
    finally:
        if host.returncode is None:
            host.kill()
            await host.wait()


def test_bad_module_spec_is_a_clean_error():
    """A typo'd --module must produce ERROR: lines, not an import
    traceback — same operator-error contract as bad manifests."""
    import subprocess
    import sys

    for spec, needle in [
        ("nosuch.module:make_app", "cannot import app module"),
        ("samples.tasks_tracker.backend_api:no_such", "no attribute"),
    ]:
        p = subprocess.run(
            [sys.executable, "-m", "tasksrunner", "host", spec],
            capture_output=True, text=True, timeout=30,
            cwd=str(pathlib.Path(__file__).resolve().parents[1]))
        assert p.returncode == 1
        err = p.stderr.strip().splitlines()[-1]
        assert err.startswith("ERROR:") and needle in err, p.stderr[-400:]
