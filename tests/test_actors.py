"""Virtual actors: placement, turn discipline, fencing, durable
reminders, and crash failover (docs module 18).

The multi-replica tests build several ``Runtime`` objects by hand
sharing ONE in-memory state store (the registry lets tests inject a
live instance), which models N replicas of the same app against one
durable store without OS processes. Failover is driven by
``simulate_crash()`` — die like SIGKILL: no lease release, activations
kept hot so the dead replica acts as a zombie if resurrected — plus
short leases, so every scenario is deterministic and fast.
"""

import asyncio
import os
import subprocess
import sys
import time

import pytest
import yaml

from tasksrunner.app import App
from tasksrunner.chaos.engine import ChaosPolicies
from tasksrunner.chaos.spec import parse_chaos
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import (
    ActorError,
    ActorFencedError,
    ActorNotRegistered,
    ComponentError,
    TasksRunnerError,
    ValidationError,
)
from tasksrunner.runtime import InProcAppChannel, Runtime
from tasksrunner.state.memory import InMemoryStateStore

LEASE = 0.25  # tests shorten per-runtime after start(); see make_runtime


@pytest.fixture
def actor_env(monkeypatch):
    monkeypatch.setenv("TASKSRUNNER_ACTORS", "1")
    # long defaults: tests that need failover shorten lease_seconds on
    # the built runtime; the background sweep is effectively disabled
    # (poll 30s) so every sweep in a test is an explicit, deterministic
    # sweep() call
    monkeypatch.setenv("TASKSRUNNER_ACTOR_LEASE_SECONDS", "5")
    monkeypatch.setenv("TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS", "30")


def build_app(app_id="svc", events=None):
    app = App(app_id)

    @app.actor("Counter")
    async def counter(turn):
        if turn.is_reminder:
            turn.state["reminded"] = turn.state.get("reminded", 0) + 1
            turn.state.setdefault("fired_as", []).append(turn.method)
            return None
        turn.state["n"] = turn.state.get("n", 0) + 1
        return turn.state["n"]

    @app.actor("Slow")
    async def slow(turn):
        if events is not None:
            events.append(("start", turn.data))
        await asyncio.sleep(0.03)
        if events is not None:
            events.append(("end", turn.data))
        return None

    return app


def make_runtime(shared, *, app_id="svc", chaos=None, crash_on_chaos=False,
                 lease=None, events=None):
    spec = ComponentSpec(name="statestore", type="state.in-memory")
    reg = ComponentRegistry([spec], app_id=app_id)
    reg._instances["statestore"] = shared
    rt = Runtime(app_id, reg,
                 app_channel=InProcAppChannel(build_app(app_id, events)),
                 chaos=chaos)
    if crash_on_chaos:
        rt._actor_crash_on_chaos = True
    rt._test_lease = lease
    return rt


async def start_all(*rts):
    for rt in rts:
        await rt.start()
        assert rt.actors is not None
        if rt._test_lease is not None:
            rt.actors.lease_seconds = rt._test_lease


async def shutdown(*rts):
    # stop every actor runtime while the shared store is still open,
    # THEN stop the runtimes (the first Runtime.stop closes the store)
    for rt in rts:
        if rt.actors is not None:
            await rt.actors.stop()
            rt.actors = None
    for rt in rts:
        await rt.stop()


async def retry_turn(rt, actor_id, *, deadline=5.0):
    """Drive one turn, retrying while placement moves (lease expiry)."""
    end = time.time() + deadline
    while True:
        try:
            return await rt.invoke_actor("Counter", actor_id, "bump")
        except TasksRunnerError:
            if time.time() > end:
                raise
            await asyncio.sleep(0.02)


# -- registration ----------------------------------------------------------


def test_actor_decorator_rejects_sync_handlers():
    app = App("svc")
    with pytest.raises(ValidationError):
        @app.actor("Bad")
        def bad(turn):  # noqa: ARG001 - shape under test
            return None


def test_actor_decorator_rejects_duplicate_type():
    app = App("svc")

    @app.actor("Dup")
    async def one(turn):
        return None

    with pytest.raises(ValidationError):
        @app.actor("Dup")
        async def two(turn):
            return None


# -- gate ------------------------------------------------------------------


async def test_gate_off_no_actor_runtime(monkeypatch):
    monkeypatch.delenv("TASKSRUNNER_ACTORS", raising=False)
    rt = make_runtime(InMemoryStateStore("statestore"))
    await rt.start()
    try:
        assert rt.actors is None
        assert "actors" not in rt.metadata()
        with pytest.raises(ActorError):
            await rt.invoke_actor("Counter", "x", "bump")
    finally:
        await rt.stop()


async def test_gate_on_but_no_handlers(actor_env):
    spec = ComponentSpec(name="statestore", type="state.in-memory")
    reg = ComponentRegistry([spec], app_id="plain")
    reg._instances["statestore"] = InMemoryStateStore("statestore")
    rt = Runtime("plain", reg, app_channel=InProcAppChannel(App("plain")))
    await rt.start()
    try:
        assert rt.actors is None  # handshake returned no actor types
    finally:
        await rt.stop()


# -- turns -----------------------------------------------------------------


async def test_turns_and_state_persistence(actor_env):
    rt = make_runtime(InMemoryStateStore("statestore"))
    await start_all(rt)
    try:
        assert await rt.invoke_actor("Counter", "c1", "bump") == 1
        assert await rt.invoke_actor("Counter", "c1", "bump") == 2
        assert await rt.invoke_actor("Counter", "other", "bump") == 1
        doc = await rt.get_actor_state("Counter", "c1")
        assert doc["data"] == {"n": 2}
        assert doc["epoch"] == 1
        with pytest.raises(ActorNotRegistered):
            await rt.invoke_actor("Nope", "c1", "bump")
        assert rt.metadata()["actors"]["owned"] == {"Counter": 2}
    finally:
        await shutdown(rt)


async def test_turns_serialize_per_actor(actor_env):
    events = []
    rt = make_runtime(InMemoryStateStore("statestore"), events=events)
    await start_all(rt)
    try:
        await asyncio.gather(
            rt.invoke_actor("Slow", "s1", "go", 1),
            rt.invoke_actor("Slow", "s1", "go", 2),
            rt.invoke_actor("Slow", "s1", "go", 3),
        )
        # one turn at a time: every start is immediately followed by
        # its own end — no interleaving on a single actor id
        assert len(events) == 6
        for i in range(0, 6, 2):
            assert events[i][0] == "start"
            assert events[i + 1] == ("end", events[i][1])
    finally:
        await shutdown(rt)


async def test_forwarding_to_live_owner(actor_env):
    shared = InMemoryStateStore("statestore")
    r1, r2 = make_runtime(shared), make_runtime(shared)
    await start_all(r1, r2)
    try:
        assert await r1.invoke_actor("Counter", "f1", "bump") == 1
        # r2 does not own f1: the turn forwards to r1 in-process and
        # the single counter keeps incrementing — one owner, one state
        assert await r2.invoke_actor("Counter", "f1", "bump") == 2
        assert await r1.invoke_actor("Counter", "f1", "bump") == 3
        assert ("Counter", "f1") in r1.actors._activations
        assert ("Counter", "f1") not in r2.actors._activations
    finally:
        await shutdown(r1, r2)


# -- reminders -------------------------------------------------------------


async def test_reminder_fires_exactly_once_per_schedule(actor_env):
    rt = make_runtime(InMemoryStateStore("statestore"))
    await start_all(rt)
    try:
        await rt.invoke_actor("Counter", "r1", "bump")
        # one-shot: fires once, then deletes itself
        await rt.register_actor_reminder("Counter", "r1", "once",
                                         due_seconds=0.0)
        stats = await rt.actors.sweep()
        assert stats["fired"] == 1
        assert (await rt.actors.sweep())["fired"] == 0
        doc = await rt.get_actor_state("Counter", "r1")
        assert doc["data"]["reminded"] == 1
        assert "once" not in doc["reminders"]
        # periodic: fires, re-arms, fires again after the period —
        # and never twice inside one period
        await rt.register_actor_reminder("Counter", "r1", "tick",
                                         due_seconds=0.0,
                                         period_seconds=0.15)
        assert (await rt.actors.sweep())["fired"] == 1
        assert (await rt.actors.sweep())["fired"] == 0
        await asyncio.sleep(0.2)
        assert (await rt.actors.sweep())["fired"] == 1
        await rt.unregister_actor_reminder("Counter", "r1", "tick")
        await asyncio.sleep(0.2)
        assert (await rt.actors.sweep())["fired"] == 0
        doc = await rt.get_actor_state("Counter", "r1")
        assert doc["data"]["reminded"] == 3
        assert doc["reminders"] == {}
    finally:
        await shutdown(rt)


async def test_reminders_survive_replica_restart(actor_env):
    shared = InMemoryStateStore("statestore")
    r1 = make_runtime(shared)
    await start_all(r1)
    await r1.invoke_actor("Counter", "d1", "bump")
    await r1.register_actor_reminder("Counter", "d1", "tick",
                                     due_seconds=0.0, period_seconds=0.1)
    # the replica goes away cleanly (released lease, reminder durable)
    await r1.actors.stop()
    r1.actors = None
    r2 = make_runtime(shared)
    await start_all(r2)
    try:
        # the sweep ADOPTS the released reminder-holding actor and
        # fires the due reminder — automatic failover, nobody invoked
        stats = await r2.actors.sweep()
        assert stats["adopted"] == 1
        assert stats["fired"] == 1
        doc = await r2.get_actor_state("Counter", "d1")
        assert doc["data"]["reminded"] == 1
        assert doc["epoch"] == 2  # adoption bumped the fencing epoch
    finally:
        await shutdown(r2, r1)


# -- crash failover & fencing ----------------------------------------------


async def test_crash_failover_zero_lost_acked_turns(actor_env):
    shared = InMemoryStateStore("statestore")
    r1 = make_runtime(shared, lease=LEASE)
    r2 = make_runtime(shared, lease=LEASE)
    await start_all(r1, r2)
    try:
        acked = 0
        for _ in range(5):
            acked = await r1.invoke_actor("Counter", "c2", "bump")
        r1.actors.simulate_crash()
        t0 = time.time()
        v = await retry_turn(r2, "c2")
        took = time.time() - t0
        # every acked turn survived: the survivor's first turn sees
        # exactly the acked count
        assert v == acked + 1
        # bounded failover: one lease TTL plus scheduling slack
        assert took < LEASE + 2.0
        doc = await r2.get_actor_state("Counter", "c2")
        assert doc["epoch"] == 2
    finally:
        await shutdown(r2, r1)


async def test_zombie_commit_is_fenced(actor_env):
    shared = InMemoryStateStore("statestore")
    r1 = make_runtime(shared, lease=LEASE)
    r2 = make_runtime(shared, lease=LEASE)
    await start_all(r1, r2)
    try:
        await r1.invoke_actor("Counter", "z1", "bump")
        r1.actors.simulate_crash()
        await retry_turn(r2, "z1")  # r2 takes over, epoch 2
        # resurrect the zombie: it still holds its activation (cached
        # etag, epoch 1) and believes its lease is alive
        r1.actors.crashed = False
        act = r1.actors._activations[("Counter", "z1")]
        act.lease_expires = time.time() + 99
        with pytest.raises(ActorFencedError) as exc:
            await r1.invoke_actor("Counter", "z1", "bump")
        assert "NOT applied" in str(exc.value)
        # the fenced turn changed nothing; the zombie dropped the actor
        doc = await r2.get_actor_state("Counter", "z1")
        assert doc["data"]["n"] == 2
        assert doc["epoch"] == 2
        assert ("Counter", "z1") not in r1.actors._activations
    finally:
        await shutdown(r2, r1)


async def test_double_failover_epochs_monotonic(actor_env):
    shared = InMemoryStateStore("statestore")
    rts = [make_runtime(shared, lease=LEASE) for _ in range(3)]
    await start_all(*rts)
    r1, r2, r3 = rts
    try:
        for _ in range(3):
            await r1.invoke_actor("Counter", "m1", "bump")
        r1.actors.simulate_crash()
        assert await retry_turn(r2, "m1") == 4
        assert (await r2.get_actor_state("Counter", "m1"))["epoch"] == 2
        r2.actors.simulate_crash()
        assert await retry_turn(r3, "m1") == 5
        doc = await r3.get_actor_state("Counter", "m1")
        assert doc["epoch"] == 3
        assert doc["data"]["n"] == 5
    finally:
        await shutdown(r3, r2, r1)


# -- pid recycling (satellite: lease expiry vs /proc starttime) ------------


def _place_doc(pid, registered_at, *, lease_expires):
    return {"owner": {"replica": "ghost@x.y", "app_id": "svc",
                      "host": "127.0.0.1", "pid": pid,
                      "registered_at": registered_at},
            "epoch": 7, "lease_expires": lease_expires,
            "granted_at": registered_at}


async def test_owner_dead_predicate_no_ghost_passes_both(actor_env,
                                                         monkeypatch):
    from tasksrunner.actors.runtime import ActorRuntime

    child = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"])
    try:
        registered_at = time.time()
        live = _place_doc(child.pid, registered_at,
                          lease_expires=time.time() + 60)
        # live pid, honest starttime, valid lease -> alive
        assert not ActorRuntime.owner_dead(live)
        # expired lease -> dead, however alive the pid looks (the
        # wedged-owner case: fencing, not pid checks, protects state)
        stale = _place_doc(child.pid, registered_at,
                           lease_expires=time.time() - 1)
        assert ActorRuntime.owner_dead(stale)
        # recycled pid: the number is in use, but its holder was born
        # AFTER the owner registered -> the owner is gone, lease or not
        monkeypatch.setattr("tasksrunner.invoke.resolver._pid_started_at",
                            lambda pid: registered_at + 100.0)
        assert ActorRuntime.owner_dead(live)
    finally:
        child.kill()
        child.wait()


async def test_pid_recycled_owner_is_preempted(actor_env, monkeypatch):
    from tasksrunner.actors.runtime import place_key

    child = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"])
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        registered_at = time.time()
        await rt.save_state_item(
            "statestore", place_key("Counter", "p1"),
            _place_doc(child.pid, registered_at,
                       lease_expires=time.time() + 60))
        # the ghost's lease is valid and its pid exists: unreachable,
        # NOT preemptable — the caller is told to retry, state is safe
        with pytest.raises(ActorError, match="unreachable"):
            await rt.invoke_actor("Counter", "p1", "bump")
        # now the pid turns out to be recycled (current holder born
        # after the registration): provably dead -> immediate takeover
        # fencing ABOVE the ghost's epoch, no lease wait
        monkeypatch.setattr("tasksrunner.invoke.resolver._pid_started_at",
                            lambda pid: registered_at + 100.0)
        assert await rt.invoke_actor("Counter", "p1", "bump") == 1
        doc = await rt.get_actor_state("Counter", "p1")
        assert doc["epoch"] == 8  # ghost claimed 7; the fence went above
    finally:
        child.kill()
        child.wait()
        await shutdown(rt)


# -- the chaos drill (satellite: crashEveryN follows placement) ------------

CHAOS_YAML = """
apiVersion: tasksrunner/v1alpha1
kind: Chaos
metadata: {name: actor-drill}
spec:
  seed: 7
  faults:
    poison:
      crashEveryN: {n: 5, raise: OSError}
  targets:
    actors:
      Counter: [poison]
"""


def test_chaos_actor_targets_parse_and_resolve():
    spec = parse_chaos(yaml.safe_load(CHAOS_YAML))
    assert spec.actor_targets == {"Counter": ("poison",)}
    pol = ChaosPolicies([spec], app_id="svc")
    assert pol.for_actor("Counter") is not None
    assert pol.for_actor("Other") is None
    assert any("actors/Counter/turn" in d["targets"] for d in pol.describe())


def test_chaos_actor_target_dangling_ref_fails_at_load():
    doc = yaml.safe_load(CHAOS_YAML)
    doc["spec"]["targets"]["actors"]["Counter"] = ["typo"]
    with pytest.raises(ComponentError, match="unknown fault rule"):
        parse_chaos(doc)


async def test_seeded_crash_every_n_failover_drill(actor_env):
    """The tentpole proof: a seeded crashEveryN rule fells whichever
    replica CURRENTLY owns the actor (the fault injects inside the
    owner's turn), survivors take over with monotonically increasing
    epochs, zero acked turns are lost, and the durable reminder
    resumes on the final owner. Deterministic: crashEveryN is
    call-counted per replica, so the schedule is fixed — replica 1
    dies on its 5th turn, replica 2 on its 5th, replica 3 survives."""
    shared = InMemoryStateStore("statestore")
    spec = parse_chaos(yaml.safe_load(CHAOS_YAML))
    rts = [make_runtime(shared, lease=LEASE,
                        chaos=ChaosPolicies([spec], app_id="svc"),
                        crash_on_chaos=True)
           for _ in range(3)]
    await start_all(*rts)
    try:
        # a durable reminder registered up front must ride through
        # every failover (registration is not a turn: no chaos)
        await rts[0].register_actor_reminder(
            "Counter", "d1", "tick", due_seconds=0.0, period_seconds=0.2)

        acked = 0
        crashes = 0
        deadline = time.time() + 30
        while acked < 11:
            assert time.time() < deadline, \
                f"drill stalled at {acked} acked turns"
            alive = next(rt for rt in rts
                         if rt.actors is not None and not rt.actors.crashed)
            try:
                v = await alive.invoke_actor("Counter", "d1", "bump")
            except (TasksRunnerError, OSError):
                # OSError is the configured fault class: the owner fell
                # mid-turn and the turn is UNacked; TasksRunnerError is
                # the takeover window (lease not yet expired) — retry
                crashes = sum(1 for rt in rts
                              if rt.actors is not None and rt.actors.crashed)
                await asyncio.sleep(0.02)
                continue
            acked += 1
            assert v == acked  # each ack sees every prior acked turn

        assert crashes == 2  # replicas 1 and 2 each died on turn 5
        survivor = rts[2]
        assert not survivor.actors.crashed
        doc = await survivor.get_actor_state("Counter", "d1")
        assert doc["data"]["n"] == 11   # zero lost acked turns
        assert doc["epoch"] == 3        # one fence bump per failover
        assert "tick" in doc["reminders"]

        # the reminder, long overdue, fires on the final owner (the
        # reminder turn is the survivor's 4th call — under the crash
        # schedule, not at a crash point)
        stats = await survivor.actors.sweep()
        assert stats["fired"] == 1
        doc = await survivor.get_actor_state("Counter", "d1")
        assert doc["data"]["reminded"] == 1
    finally:
        await shutdown(*rts)


# -- surfacing: sidecar routes, placement table, CLI -----------------------


async def test_sidecar_actor_routes_gated_off(monkeypatch):
    monkeypatch.delenv("TASKSRUNNER_ACTORS", raising=False)
    from tasksrunner.sidecar import build_sidecar_app

    app = build_sidecar_app(make_runtime(InMemoryStateStore("statestore")),
                            api_token=None, peer_tokens=set())
    assert not any("/v1.0/actors" in str(r.resource.canonical)
                   for r in app.router.routes() if r.resource is not None)


async def test_sidecar_actor_api_end_to_end(actor_env):
    import aiohttp

    from tasksrunner.sidecar import Sidecar

    rt = make_runtime(InMemoryStateStore("statestore"))
    sc = Sidecar(rt, port=0)
    await sc.start()
    try:
        base = f"http://127.0.0.1:{sc.port}"
        async with aiohttp.ClientSession() as session:
            resp = await session.put(
                f"{base}/v1.0/actors/Counter/web1/method/bump", json=None)
            assert resp.status == 200
            assert (await resp.json())["result"] == 1
            resp = await session.post(
                f"{base}/v1.0/actors/Counter/web1/reminders/tick",
                json={"dueSeconds": 0.0, "periodSeconds": 5})
            assert resp.status == 204
            assert (await rt.actors.sweep())["fired"] == 1
            resp = await session.get(
                f"{base}/v1.0/actors/Counter/web1/state")
            doc = await resp.json()
            assert doc["data"] == {"n": 1, "reminded": 1,
                                   "fired_as": ["tick"]}
            resp = await session.delete(
                f"{base}/v1.0/actors/Counter/web1/reminders/tick")
            assert resp.status == 204
            resp = await session.get(f"{base}/v1.0/actors")
            view = await resp.json()
            assert view["replica"]["owned"] == {"Counter": 1}
            assert view["placement"][0]["id"] == "web1"
            assert view["placement"][0]["alive"] is True
            resp = await session.put(
                f"{base}/v1.0/actors/Nope/x/method/m", json=None)
            assert resp.status == 404
    finally:
        await sc.stop()


async def test_placement_table_rows(actor_env):
    shared = InMemoryStateStore("statestore")
    r1, r2 = make_runtime(shared), make_runtime(shared)
    await start_all(r1, r2)
    try:
        await r1.invoke_actor("Counter", "t1", "bump")
        await r2.invoke_actor("Counter", "t2", "bump")
        # both replicas render the SAME table from the shared store
        t_from_r1 = await r1.actors.placement_table()
        t_from_r2 = await r2.actors.placement_table()
        owners = {row["id"]: row["owner"] for row in t_from_r1}
        assert owners == {row["id"]: row["owner"] for row in t_from_r2}
        assert owners["t1"] == r1.actors.replica_id
        assert owners["t2"] == r2.actors.replica_id
        by_id = {row["id"]: row for row in t_from_r1}
        assert by_id["t1"]["owned_here"] is True
        assert by_id["t2"]["owned_here"] is False
        assert all(row["alive"] for row in t_from_r1)
        assert all(row["epoch"] == 1 for row in t_from_r1)
    finally:
        await shutdown(r1, r2)


def test_cli_has_actors_surface():
    from tasksrunner.cli import _cmd_actors, build_parser

    args = build_parser().parse_args(["actors", "--app-id", "svc", "--ids"])
    assert args.fn is _cmd_actors
    assert args.app_id == "svc"
    assert args.ids is True
