"""Multi-replica ingress: every serving replica registers, invokes
round-robin across them, and one replica's death degrades a request to
a retry — never to an outage.

≙ ACA's HTTP ingress load-balancing across an app's replicas: the
reference's scale rules add replicas precisely so traffic spreads over
them (docs/aca/09-aca-autoscale-keda/index.md), not only so competing
consumers drain faster. Round 4 brings the same to the local runtime:
the registry holds a replica LIST per app-id, `resolve` rotates, and
the invoke path's re-resolve-per-attempt turns a stale entry into the
next replica instead of an error.
"""

import asyncio
import collections

import pytest

from tasksrunner import App, AppHost, load_components
from tasksrunner.errors import AppNotFound
from tasksrunner.invoke.resolver import AppAddress, NameResolver


# ---------------------------------------------------------------------------
# resolver unit behavior
# ---------------------------------------------------------------------------

def _addr(app_id, port, pid):
    return AppAddress(app_id=app_id, host="127.0.0.1", sidecar_port=port,
                      app_port=port + 1, pid=pid)


def test_resolver_round_robin_and_scoped_unregister(tmp_path):
    reg = tmp_path / "apps.json"
    w = NameResolver(registry_file=reg)
    w.register(_addr("api", 1000, pid=11))
    w.register(_addr("api", 2000, pid=22))

    r = NameResolver(registry_file=reg)
    assert len(r.resolve_all("api")) == 2
    ports = [r.resolve("api").sidecar_port for _ in range(4)]
    assert sorted(set(ports)) == [1000, 2000]          # both serve
    assert ports[0] != ports[1]                        # and they rotate

    # re-register (same pid+port) replaces, never duplicates
    w.register(_addr("api", 2000, pid=22))
    assert len(NameResolver(registry_file=reg).resolve_all("api")) == 2

    # a stopping replica removes ONLY its own entry
    w.unregister("api", pid=22, sidecar_port=2000)
    survivors = NameResolver(registry_file=reg).resolve_all("api")
    assert [a.sidecar_port for a in survivors] == [1000]

    # unscoped unregister clears the app
    w.unregister("api")
    with pytest.raises(AppNotFound):
        NameResolver(registry_file=reg).resolve("api")


def test_resolver_reads_legacy_single_entry_format(tmp_path):
    """Registry files written before multi-replica hold one dict per
    app-id; they must keep resolving (mixed-version topologies during
    an upgrade)."""
    import dataclasses, json
    reg = tmp_path / "apps.json"
    reg.write_text(json.dumps(
        {"api": dataclasses.asdict(_addr("api", 1000, pid=11))}))
    r = NameResolver(registry_file=reg)
    assert r.resolve("api").sidecar_port == 1000
    # and a new-style register upgrades the entry to a list in place
    r.register(_addr("api", 2000, pid=22))
    assert len(NameResolver(registry_file=reg).resolve_all("api")) == 2


# ---------------------------------------------------------------------------
# end-to-end: two replicas behind one app-id
# ---------------------------------------------------------------------------

COMPONENTS = """
apiVersion: dapr.io/v1alpha1
kind: Component
metadata:
  name: statestore
spec:
  type: state.in-memory
  version: v1
"""


def _backend(counter: collections.Counter, tag: str) -> App:
    app = App("backend-api")

    @app.post("/api/work")
    async def work(req):
        counter[tag] += 1
        return {"served_by": tag}

    return app


async def _start_pair(tmp_path, counter):
    (tmp_path / "components.yaml").write_text(COMPONENTS)
    specs = load_components(tmp_path)
    registry = str(tmp_path / "apps.json")
    hosts = [AppHost(_backend(counter, "r0"), specs=specs,
                     registry_file=registry),
             AppHost(_backend(counter, "r1"), specs=specs,
                     registry_file=registry)]
    for h in hosts:
        await h.start()

    front = App("frontend")
    fhost = AppHost(front, specs=specs, registry_file=registry)
    await fhost.start()
    return hosts, fhost


@pytest.mark.asyncio
async def test_invokes_spread_across_replicas(tmp_path):
    counter: collections.Counter = collections.Counter()
    hosts, fhost = await _start_pair(tmp_path, counter)
    try:
        for _ in range(10):
            resp = await fhost.app.client.invoke_method(
                "backend-api", "api/work", http_method="POST", data={})
            assert resp.status == 200
        # ingress semantics: BOTH replicas served (round-robin ⇒ 5/5)
        assert counter["r0"] == 5 and counter["r1"] == 5, counter
    finally:
        for h in [*hosts, fhost]:
            await h.stop()


@pytest.mark.asyncio
async def test_replica_loss_degrades_to_retry_not_outage(tmp_path):
    counter: collections.Counter = collections.Counter()
    hosts, fhost = await _start_pair(tmp_path, counter)
    stopped = False
    try:
        # kill replica 0 WITHOUT unregistering it (the crash case: a
        # SIGKILLed process leaves its stale entry in the registry)
        hosts[0].resolver.register(  # keep a copy of the real entry
            AppAddress(app_id="backend-api", host="127.0.0.1",
                       sidecar_port=hosts[0].sidecar_port,
                       app_port=hosts[0].app_port,
                       mesh_port=hosts[0].sidecar.mesh_port))
        real_unregister = hosts[0].resolver.unregister
        hosts[0].resolver.unregister = lambda *a, **k: None  # simulate SIGKILL
        await hosts[0].stop()
        stopped = True
        hosts[0].resolver.unregister = real_unregister

        # every request must still succeed: the stale entry costs a
        # retry that re-resolves onto the live replica
        for _ in range(6):
            resp = await fhost.app.client.invoke_method(
                "backend-api", "api/work", http_method="POST", data={})
            assert resp.status == 200
            assert resp.json()["served_by"] == "r1"
        assert counter["r1"] >= 6
    finally:
        for h in ([hosts[1], fhost] if stopped else [*hosts, fhost]):
            await h.stop()


# ---------------------------------------------------------------------------
# fault injection on the mesh lane: established connections that die
# ---------------------------------------------------------------------------

async def _tamper_replica0(hosts, *, mesh_port):
    """Re-point replica 0's registry entry at a different mesh port,
    keeping its real HTTP sidecar port (so only the mesh lane is
    poisoned — exactly the shape of a half-dead peer)."""
    victim = next(a for a in hosts[0].resolver.resolve_all("backend-api")
                  if a.sidecar_port == hosts[0].sidecar_port)
    hosts[0].resolver.register(AppAddress(
        app_id="backend-api", host=victim.host,
        sidecar_port=victim.sidecar_port, app_port=victim.app_port,
        pid=victim.pid, mesh_port=mesh_port))


async def _ack_hello(reader, writer):
    """Consume the client's codec hello and ack it at v1 — the tarpit
    then counts as an ESTABLISHED connection (negotiation done), so the
    fault it injects next lands mid-flight, not at dial time (where the
    pool would classify it MeshConnectError and fall back to HTTP
    within the same attempt)."""
    import struct

    from tasksrunner.invoke.mesh import _pack

    (frame_len,) = struct.unpack(">I", await reader.readexactly(4))
    await reader.readexactly(frame_len)
    writer.write(_pack({"i": 0, "hello": 1}, b""))
    await writer.drain()


@pytest.mark.asyncio
async def test_established_mesh_conn_dropped_midflight_fails_over(tmp_path):
    """The connection DIALS fine, then the peer dies after reading the
    request frame (crash mid-handling, RST, a dying VM). That is an
    in-flight drop — not a refused dial — so it must burn one retry,
    re-resolve, and land on the healthy replica. Requests keep
    succeeding throughout."""
    counter: collections.Counter = collections.Counter()
    hosts, fhost = await _start_pair(tmp_path, counter)

    async def drop_after_first_frame(reader, writer):
        try:
            await _ack_hello(reader, writer)  # dial + handshake succeed,
            await reader.readexactly(4)   # the request frame arrives,
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        writer.transport.abort()          # then die abruptly mid-flight

    tarpit = await asyncio.start_server(
        drop_after_first_frame, "127.0.0.1", 0)
    try:
        await _tamper_replica0(
            hosts, mesh_port=tarpit.sockets[0].getsockname()[1])
        for _ in range(6):
            resp = await fhost.app.client.invoke_method(
                "backend-api", "api/work", http_method="POST", data={})
            assert resp.status == 200
            assert resp.json()["served_by"] == "r1"
    finally:
        # hosts first: closing their mesh pools EOFs the tar-pit's
        # reader coroutines, which wait_closed() awaits on py3.12
        for h in [*hosts, fhost]:
            await h.stop()
        tarpit.close()  # no wait_closed(): py3.12 can await handler
        # coroutines forever here; the loop is torn down right after


@pytest.mark.asyncio
async def test_blackholed_mesh_conn_times_out_and_fails_over(
        tmp_path, monkeypatch):
    """The nastier variant: the peer accepts the connection and the
    frame, then answers NOTHING (network partition after SYN/ACK, a
    wedged process). The per-request ceiling must convert the silence
    into a retriable timeout and the retry must land on the healthy
    replica — bounded, not an unbounded hang."""
    from tasksrunner.invoke import mesh as mesh_mod

    monkeypatch.setattr(mesh_mod, "REQUEST_TIMEOUT", 0.5)
    counter: collections.Counter = collections.Counter()
    hosts, fhost = await _start_pair(tmp_path, counter)

    async def blackhole(reader, writer):
        try:
            await _ack_hello(reader, writer)  # handshake completes, then
            await reader.read(-1)         # consume forever, reply never
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass

    tarpit = await asyncio.start_server(blackhole, "127.0.0.1", 0)
    try:
        await _tamper_replica0(
            hosts, mesh_port=tarpit.sockets[0].getsockname()[1])
        import time as _time
        t0 = _time.perf_counter()
        for _ in range(4):
            resp = await fhost.app.client.invoke_method(
                "backend-api", "api/work", http_method="POST", data={})
            assert resp.status == 200
            assert resp.json()["served_by"] == "r1"
        # 4 requests, worst case ~2 blackhole timeouts each at 0.5 s.
        # The ceiling is deliberately HUGE relative to that (~25x):
        # it only distinguishes "bounded" from "stuck on the 300 s
        # default REQUEST_TIMEOUT", so shared-runner noise can never
        # trip it (the perf-gate lesson from tests.yml applies here)
        assert _time.perf_counter() - t0 < 60
    finally:
        # hosts first (see above): their pool close EOFs the blackhole
        # readers so wait_closed() can finish
        for h in [*hosts, fhost]:
            await h.stop()
        tarpit.close()  # no wait_closed(): py3.12 can await handler
        # coroutines forever here; the loop is torn down right after


def test_prune_dead_local_removes_sigkill_debris(tmp_path):
    """A SIGKILLed topology cannot unregister; its registry entries
    linger and — because a new incarnation reuses the same ports —
    answer health probes through the NEW process, so `ps` shows ghost
    replicas as ok. prune_dead_local() sweeps loopback entries whose
    pid is gone; live pids and remote hosts are untouched."""
    import os

    reg = tmp_path / "apps.json"
    w = NameResolver(registry_file=reg)
    # a pid that certainly exists (ours) and one that certainly doesn't
    w.register(AppAddress(app_id="api", host="127.0.0.1",
                          sidecar_port=1000, app_port=1001,
                          pid=os.getpid()))
    dead_pid = 2 ** 22 + 7919     # beyond default pid_max
    w.register(AppAddress(app_id="api", host="127.0.0.1",
                          sidecar_port=2000, app_port=2001, pid=dead_pid))
    # remote-host entry with the same dead pid: a missing LOCAL pid
    # proves nothing about another machine — must survive
    w.register(AppAddress(app_id="remote", host="10.0.0.9",
                          sidecar_port=3000, app_port=3001, pid=dead_pid))

    pruned = NameResolver(registry_file=reg).prune_dead_local()
    assert pruned == [("api", dead_pid)]
    fresh = NameResolver(registry_file=reg)
    assert [a.pid for a in fresh.resolve_all("api")] == [os.getpid()]
    assert len(fresh.resolve_all("remote")) == 1
