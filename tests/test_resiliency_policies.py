"""Declarative resiliency: timeouts, retries, circuit breakers.

The reference inherits these from its platform (Dapr 1.14 sidecar
retries, broker redelivery, ACA restarts — SURVEY.md §5.3); here they
are first-class, declarative, and tested per policy type.
"""

import asyncio

import pytest

from tasksrunner import App, InProcCluster, parse_resiliency
from tasksrunner.component.loader import load_component_file
from tasksrunner.component.spec import parse_component
from tasksrunner.errors import CircuitOpenError, ComponentError
from tasksrunner.resiliency.policy import (
    CircuitBreaker,
    CircuitBreakerSpec,
    ResiliencyPolicies,
    RetrySpec,
    parse_duration,
    parse_trip,
)
from tasksrunner.resiliency.spec import load_resiliency

# ---------------------------------------------------------------------------
# spec parsing


RESILIENCY_YAML = {
    "apiVersion": "dapr.io/v1alpha1",
    "kind": "Resiliency",
    "metadata": {"name": "tasks-resiliency"},
    "spec": {
        "policies": {
            "timeouts": {"fast": "250ms", "general": "5s"},
            "retries": {
                "important": {
                    "policy": "exponential",
                    "duration": "10ms",
                    "maxInterval": "80ms",
                    "maxRetries": 3,
                },
            },
            "circuitBreakers": {
                "simpleCB": {
                    "maxRequests": 1,
                    "timeout": "100ms",
                    "trip": "consecutiveFailures >= 3",
                },
            },
        },
        "targets": {
            "apps": {
                "backend": {
                    "timeout": "fast",
                    "retry": "important",
                    "circuitBreaker": "simpleCB",
                },
            },
            "components": {
                "statestore": {"outbound": {"retry": "important"}},
            },
        },
    },
}


def test_parse_durations():
    assert parse_duration("500ms") == 0.5
    assert parse_duration("5s") == 5.0
    assert parse_duration("1m30s") == 90.0
    assert parse_duration(2) == 2.0
    with pytest.raises(ComponentError):
        parse_duration("soon")


def test_parse_trip_expressions():
    assert parse_trip("consecutiveFailures >= 5") == 5
    assert parse_trip("consecutiveFailures > 5") == 6
    with pytest.raises(ComponentError):
        parse_trip("errorRate > 0.5")


def test_parse_resiliency_document():
    spec = parse_resiliency(RESILIENCY_YAML)
    assert spec.name == "tasks-resiliency"
    assert spec.timeouts == {"fast": 0.25, "general": 5.0}
    retry = spec.retries["important"]
    assert retry.policy == "exponential" and retry.max_retries == 3
    cb = spec.breakers["simpleCB"]
    assert cb.trip_threshold == 3 and cb.timeout == pytest.approx(0.1)
    assert "backend" in spec.app_targets
    assert "outbound" in spec.component_targets["statestore"]


def test_load_resiliency_beside_components(tmp_path):
    """Resiliency docs share the resources dir; the component loader
    skips them and load_resiliency collects them."""
    import yaml

    comp = {"componentType": "state.in-memory"}
    (tmp_path / "statestore.yaml").write_text(yaml.dump(comp))
    (tmp_path / "resiliency.yaml").write_text(yaml.dump(RESILIENCY_YAML))

    specs = load_component_file(tmp_path / "resiliency.yaml")
    assert specs == []  # skipped, not an error
    res = load_resiliency(tmp_path)
    assert len(res) == 1 and res[0].name == "tasks-resiliency"


def test_resolution_and_scoping():
    spec = parse_resiliency(RESILIENCY_YAML)
    pols = ResiliencyPolicies([spec])
    p = pols.for_app("backend")
    assert p.timeout == 0.25 and p.retry.max_retries == 3
    assert p.breaker is not None
    assert pols.for_app("unknown") is None
    assert pols.for_component("statestore").retry is not None
    assert pols.for_component("statestore").breaker is None
    # breaker instance is shared across resolutions (state persists)
    assert pols.for_app("backend").breaker is pols.for_app("backend").breaker

    scoped = parse_resiliency({**RESILIENCY_YAML, "scopes": ["other-app"]})
    assert ResiliencyPolicies([scoped], app_id="not-other").for_app("backend") is None
    assert ResiliencyPolicies([scoped], app_id="other-app").for_app("backend") is not None


def test_dangling_policy_refs_rejected_at_parse_time():
    """A typo'd policy name must fail at load, not on the first call."""
    doc = {
        "kind": "Resiliency",
        "metadata": {"name": "r"},
        "spec": {
            "policies": {"retries": {"fast": {"duration": "1ms"}}},
            "targets": {"apps": {"api": {"retry": "fsat"}}},
        },
    }
    with pytest.raises(ComponentError, match="unknown retry 'fsat'"):
        parse_resiliency(doc)


# ---------------------------------------------------------------------------
# policy engine


def test_retry_delays():
    constant = RetrySpec(policy="constant", duration=0.5, max_retries=2)
    assert list(constant.delays()) == [0.5, 0.5]
    expo = RetrySpec(policy="exponential", duration=0.1, max_interval=0.35,
                     max_retries=4)
    assert list(expo.delays()) == [0.1, 0.2, 0.35, 0.35]


@pytest.mark.asyncio
async def test_retry_until_success():
    from tasksrunner.resiliency.policy import TargetPolicy

    calls = 0

    async def flaky():
        nonlocal calls
        calls += 1
        if calls < 3:
            raise OSError("connection refused")
        return "ok"

    policy = TargetPolicy(
        target="t", retry=RetrySpec(duration=0.001, max_retries=5))
    assert await policy.execute(flaky) == "ok"
    assert calls == 3


@pytest.mark.asyncio
async def test_retry_budget_exhausted():
    from tasksrunner.resiliency.policy import TargetPolicy

    async def always_down():
        raise OSError("connection refused")

    policy = TargetPolicy(
        target="t", retry=RetrySpec(duration=0.001, max_retries=2))
    with pytest.raises(OSError):
        await policy.execute(always_down)


@pytest.mark.asyncio
async def test_timeout_policy():
    from tasksrunner.resiliency.policy import TargetPolicy

    async def slow():
        await asyncio.sleep(5)

    policy = TargetPolicy(target="t", timeout=0.05)
    with pytest.raises(TimeoutError):
        await policy.execute(slow)


@pytest.mark.asyncio
async def test_circuit_breaker_state_machine():
    spec = CircuitBreakerSpec(name="cb", trip_threshold=3, timeout=0.08,
                              max_requests=1)
    cb = CircuitBreaker(spec, target="t")

    for _ in range(3):
        cb.before_call()
        cb.record_failure()
    assert cb.state == CircuitBreaker.OPEN
    with pytest.raises(CircuitOpenError):
        cb.before_call()

    await asyncio.sleep(0.1)  # open → half-open after timeout
    cb.before_call()
    assert cb.state == CircuitBreaker.HALF_OPEN
    with pytest.raises(CircuitOpenError):  # probe limit: maxRequests=1
        cb.before_call()
    cb.record_success()
    assert cb.state == CircuitBreaker.CLOSED

    # a failed probe reopens immediately
    for _ in range(3):
        cb.before_call()
        cb.record_failure()
    await asyncio.sleep(0.1)
    cb.before_call()
    cb.record_failure()
    assert cb.state == CircuitBreaker.OPEN


# ---------------------------------------------------------------------------
# runtime integration


@pytest.mark.asyncio
async def test_invoke_circuit_breaker_fails_fast():
    """After trip_threshold consecutive transport failures, the breaker
    opens: further invokes get CircuitOpenError WITHOUT touching the
    peer, and the breaker closes again once a probe succeeds."""
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.runtime import AppChannel, Runtime

    class FlakyChannel(AppChannel):
        def __init__(self):
            self.calls = 0
            self.down = True

        async def request(self, method, path, *, query="", headers=None, body=b""):
            self.calls += 1
            if self.down:
                raise OSError("connection refused")
            return 200, {}, b"{}"

    doc = {
        "kind": "Resiliency",
        "metadata": {"name": "r"},
        "spec": {
            "policies": {
                "circuitBreakers": {
                    "cb": {"timeout": "50ms", "trip": "consecutiveFailures >= 2"},
                },
            },
            "targets": {"apps": {"backend": {"circuitBreaker": "cb"}}},
        },
    }
    channel = FlakyChannel()
    runtime = Runtime(
        "caller", ComponentRegistry([], app_id="caller"),
        resiliency=ResiliencyPolicies([parse_resiliency(doc)], app_id="caller"))
    runtime.peers["backend"] = channel

    from tasksrunner.errors import InvocationError

    for _ in range(2):
        with pytest.raises(InvocationError):
            await runtime.invoke("backend", "work", http_method="GET")
    assert channel.calls == 2

    # breaker now open: rejected without reaching the channel
    with pytest.raises(CircuitOpenError):
        await runtime.invoke("backend", "work", http_method="GET")
    assert channel.calls == 2

    # after the open timeout, a successful probe closes the breaker
    channel.down = False
    await asyncio.sleep(0.07)
    status, _, _ = await runtime.invoke("backend", "work", http_method="GET")
    assert status == 200
    status, _, _ = await runtime.invoke("backend", "work", http_method="GET")
    assert status == 200
    assert channel.calls == 4


@pytest.mark.asyncio
async def test_output_binding_retry_via_policy(tmp_path):
    """A component outbound retry policy re-runs a failing binding
    operation until it succeeds."""
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.runtime import Runtime
    from tasksrunner.bindings.base import BindingResponse, OutputBinding

    class FlakyBinding(OutputBinding):
        def __init__(self):
            self.name = "flaky"
            self.calls = 0

        async def invoke(self, operation, data, metadata=None):
            self.calls += 1
            if self.calls < 3:
                raise OSError("backend down")
            return BindingResponse(data={"ok": True}, metadata={})

    doc = {
        "kind": "Resiliency",
        "metadata": {"name": "r"},
        "spec": {
            "policies": {
                "retries": {"fast": {"duration": "1ms", "maxRetries": 5}},
            },
            "targets": {"components": {"flaky": {"retry": "fast"}}},
        },
    }
    binding = FlakyBinding()
    registry = ComponentRegistry([], app_id="app")
    runtime = Runtime(
        "app", registry,
        resiliency=ResiliencyPolicies([parse_resiliency(doc)], app_id="app"))
    registry._instances["flaky"] = binding
    registry._specs["flaky"] = parse_component(
        {"componentType": "bindings.noop"}, default_name="flaky")

    resp = await runtime.invoke_output_binding("flaky", "create", {"x": 1})
    assert resp.data == {"ok": True}
    assert binding.calls == 3


@pytest.mark.asyncio
async def test_cancelled_half_open_probe_releases_slot():
    """A cancelled probe is not a verdict: its slot must be freed or
    the breaker would stay half-open (rejecting everything) forever."""
    from tasksrunner.resiliency.policy import TargetPolicy

    spec = CircuitBreakerSpec(name="cb", trip_threshold=1, timeout=0.01,
                              max_requests=1)
    breaker = CircuitBreaker(spec, target="t")
    policy = TargetPolicy(target="t", breaker=breaker)

    async def failing():
        raise OSError("down")

    with pytest.raises(OSError):
        await policy.execute(failing)
    assert breaker.state == CircuitBreaker.OPEN
    await asyncio.sleep(0.02)

    async def hang():
        await asyncio.sleep(30)

    task = asyncio.ensure_future(policy.execute(hang))
    await asyncio.sleep(0.01)  # let it enter half-open and occupy the slot
    task.cancel()
    with pytest.raises(asyncio.CancelledError):
        await task
    assert breaker.state == CircuitBreaker.HALF_OPEN

    # the slot is free again: a successful probe closes the breaker
    async def ok():
        return "up"

    assert await policy.execute(ok) == "up"
    assert breaker.state == CircuitBreaker.CLOSED


@pytest.mark.asyncio
async def test_save_state_retry_is_per_item():
    """A transient failure on item N must re-run only item N — replaying
    earlier etag-guarded writes (whose etags already rotated) would turn
    the blip into a spurious 409 conflict."""
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.runtime import Runtime

    doc = {
        "kind": "Resiliency",
        "metadata": {"name": "r"},
        "spec": {
            "policies": {"retries": {"fast": {"duration": "1ms", "maxRetries": 3}}},
            "targets": {"components": {"statestore": {"retry": "fast"}}},
        },
    }
    registry = ComponentRegistry(
        [parse_component({"componentType": "state.in-memory"},
                         default_name="statestore")],
        app_id="app")
    runtime = Runtime(
        "app", registry,
        resiliency=ResiliencyPolicies([parse_resiliency(doc)], app_id="app"))

    await runtime.save_state("statestore", [{"key": "a", "value": 1}])
    etag_a = (await runtime.get_state("statestore", "a")).etag

    store = registry.get("statestore")
    real_set = store.set
    set_calls = {"a": 0, "b": 0}
    failed = {"b": False}

    async def flaky_set(key, value, *, etag=None):
        short = key.rsplit("||", 1)[-1]
        set_calls[short] += 1
        if short == "b" and not failed["b"]:
            failed["b"] = True
            raise OSError("transient store blip")
        return await real_set(key, value, etag=etag)

    store.set = flaky_set
    await runtime.save_state("statestore", [
        {"key": "a", "value": 2, "etag": etag_a},
        {"key": "b", "value": 3},
    ])
    # item a wrote exactly once (its etag would be stale on a replay);
    # item b failed once, retried once
    assert set_calls == {"a": 1, "b": 2}
    assert (await runtime.get_state("statestore", "a")).value == 2
    assert (await runtime.get_state("statestore", "b")).value == 3


@pytest.mark.asyncio
async def test_invoke_timeout_policy_fails_slow_target(tmp_path):
    """An app-target timeout bounds a hung handler."""
    doc = {
        "kind": "Resiliency",
        "metadata": {"name": "r"},
        "spec": {
            "policies": {"timeouts": {"fast": "100ms"}},
            "targets": {"apps": {"backend": {"timeout": "fast"}}},
        },
    }
    backend = App("backend")

    @backend.get("/hang")
    async def hang(req):
        await asyncio.sleep(10)
        return 200

    caller = App("caller")
    cluster = InProcCluster([], resiliency_specs=[parse_resiliency(doc)])
    cluster.add_app(backend)
    cluster.add_app(caller)
    await cluster.start()
    try:
        from tasksrunner.errors import InvocationError
        with pytest.raises(InvocationError):
            await cluster.client("caller").invoke_method(
                "backend", "hang", http_method="GET")
    finally:
        await cluster.stop()
