"""Elastic shard placement (tasksrunner/state/placement.py + the
sharding facade's migration machinery).

Covers the tentpole contract end to end: the epoched routing flip
(strictly monotone, atomic under concurrent load, 409-with-new-epoch
for stale routers), live shard migration over the replication plane
(leadership transfer with fenced handoff, zero lost acked writes with
a mid-migration leader kill), the online split's movement bound
against the PR 5 golden router, the chaos ``targets.placement`` lane
(a blackholed catch-up stream aborts the migration cleanly with
routing untouched), the heat tracker's EWMA/hysteresis/sketch, the
pure planning helpers, and the epoch handshake through runtime +
sidecar + client.
"""

import asyncio
import time

import pytest

from tasksrunner.chaos.engine import ChaosPolicies
from tasksrunner.chaos.spec import parse_chaos
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import PlacementEpochError, StateError
from tasksrunner.runtime import Runtime
from tasksrunner.state.placement import (
    PLACEMENT_EPOCH_HEADER,
    PlacementMap,
    ShardHeatTracker,
    merge_heat_docs,
    plan_rebalance,
    rank_shards,
)
from tasksrunner.state.replication import build_replicated_store
from tasksrunner.state.sharding import ShardRouter
from tasksrunner.state.sqlite import SqliteStateStore, build_sharded_store

KEYS = [f"task-{i}" for i in range(2000)]
LEASE = 0.4


async def _wait_for(predicate, *, timeout=6.0, message="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not predicate():
        assert asyncio.get_running_loop().time() < deadline, \
            f"timed out waiting for {message}"
        await asyncio.sleep(0.02)


def make_runtime(store):
    """A runtime whose only component is ``store`` under the name
    ``statestore`` (the test_actors pattern, minus the app channel)."""
    spec = ComponentSpec(name="statestore", type="state.in-memory")
    reg = ComponentRegistry([spec])
    reg._instances["statestore"] = store
    return Runtime("svc", reg)


# -- PlacementMap -----------------------------------------------------------

def test_placement_map_epoch_is_strictly_monotone():
    base = PlacementMap(shards=4)
    assert base.epoch == 1
    nxt = base.advanced(assignment={2: "hostB"})
    assert nxt.epoch == 2 and nxt.shards == 4
    assert nxt.assignment == {2: "hostB"}
    # successor merges, never drops, prior assignments
    third = nxt.advanced(shards=5, assignment={4: "hostC"})
    assert third.epoch == 3 and third.shards == 5
    assert third.assignment == {2: "hostB", 4: "hostC"}


def test_placement_map_migration_status_does_not_move_epoch():
    base = PlacementMap(shards=2)
    busy = base.with_migration({"phase": "catchup", "shard": 1})
    assert busy.epoch == base.epoch
    assert busy.migration["phase"] == "catchup"


def test_placement_map_doc_roundtrip():
    m = PlacementMap(shards=3, epoch=7, assignment={0: "r1"},
                     migration={"phase": "flip"})
    again = PlacementMap.from_doc(m.to_doc())
    assert (again.epoch, again.shards, again.assignment, again.migration) \
        == (7, 3, {0: "r1"}, {"phase": "flip"})


# -- epoch validation (the 409 redirect) ------------------------------------

@pytest.mark.asyncio
async def test_check_epoch_rejects_stale_and_future_routers(tmp_path):
    """ANY mismatch is a 409 carrying the live epoch: a lower caller
    routed with a pre-flip map (classic stale), a higher caller knows a
    flip this instance missed — either way the bytes must not land
    until somebody resynchronizes."""
    store = build_sharded_store("ck", tmp_path / "ck.db", shards=2)
    try:
        current = store.placement.epoch
        store.check_epoch(current)  # exact match passes silently
        with pytest.raises(PlacementEpochError) as exc_info:
            store.check_epoch(current - 1)
        assert exc_info.value.http_status == 409
        assert exc_info.value.current_epoch == current
        with pytest.raises(PlacementEpochError):
            store.check_epoch(current + 1)
    finally:
        await store.aclose()


@pytest.mark.asyncio
async def test_runtime_check_placement_epoch_duck_types(tmp_path):
    """The runtime helper validates only stores that HAVE a placement
    map; unsharded engines and absent headers pass untouched."""
    sharded = build_sharded_store("statestore", tmp_path / "s.db", shards=2)
    rt = make_runtime(sharded)
    try:
        rt.check_placement_epoch("statestore", None)  # no header → no-op
        rt.check_placement_epoch("statestore", sharded.placement.epoch)
        with pytest.raises(PlacementEpochError):
            rt.check_placement_epoch("statestore", 99)
    finally:
        await sharded.aclose()

    plain = SqliteStateStore("statestore", ":memory:")
    rt = make_runtime(plain)
    try:
        rt.check_placement_epoch("statestore", 99)  # no map → no check
    finally:
        await plain.aclose()


@pytest.mark.asyncio
async def test_sidecar_409_carries_new_epoch_and_client_retries(tmp_path):
    """End to end through real HTTP: a client that routed with a stale
    epoch gets 409 + the live epoch in the reply header, refreshes its
    cache, retries once, and the write lands — a live flip costs one
    round trip, never a failed operation."""
    import aiohttp

    from tasksrunner.client import AppClient
    from tasksrunner.sidecar import Sidecar

    store = build_sharded_store("statestore", tmp_path / "s.db", shards=2)
    rt = make_runtime(store)
    sc = Sidecar(rt, port=0)
    await sc.start()
    try:
        base = f"http://127.0.0.1:{sc.port}"
        async with aiohttp.ClientSession() as session:
            # raw probe: stale epoch → 409, reply header names the truth
            resp = await session.post(
                f"{base}/v1.0/state/statestore",
                json=[{"key": "k1", "value": {"v": 1}}],
                headers={PLACEMENT_EPOCH_HEADER: "99"})
            assert resp.status == 409
            assert resp.headers[PLACEMENT_EPOCH_HEADER] == \
                str(store.placement.epoch)
            # matching epoch passes
            resp = await session.post(
                f"{base}/v1.0/state/statestore",
                json=[{"key": "k1", "value": {"v": 1}}],
                headers={PLACEMENT_EPOCH_HEADER:
                         str(store.placement.epoch)})
            assert resp.status == 204

        # SDK client: poison its epoch cache, then watch it self-heal
        client = AppClient.http(port=sc.port)
        client._t._placement_epochs["statestore"] = 99
        await client.save_state("statestore", "k2", {"v": 2})
        assert await client.get_state("statestore", "k2") == {"v": 2}
        assert client._t._placement_epochs["statestore"] == \
            store.placement.epoch
        await client.close()
    finally:
        await sc.stop()
        await store.aclose()


# -- online shard split -----------------------------------------------------

@pytest.mark.asyncio
async def test_split_moves_bounded_fraction_to_new_shard(tmp_path):
    """Growing 4→5 must stream ~1/5 of the keyspace, all TO the new
    shard — the same movement bound the PR 5 router test pins, now
    verified through the LIVE path with data attached."""
    store = build_sharded_store("split", tmp_path / "split.db", shards=4)
    try:
        before = {k: store.router.shard_of(k) for k in KEYS}
        for k in before:
            await store.set(k, {"k": k})
        result = await store.split_shard()
        assert result["action"] == "split"
        assert result["shards"] == 5 and result["new_shard"] == 4
        assert store.placement.epoch == result["epoch"] == 2
        moved = [k for k in KEYS if store.router.shard_of(k) != before[k]]
        assert 0 < len(moved) < len(KEYS) / 5 * 1.35
        assert all(store.router.shard_of(k) == 4 for k in moved)
        assert result["keys_moved"] >= len(moved)
        # every key — moved or not — reads back through the new map
        for k in KEYS:
            assert (await store.get(k)).value == {"k": k}
        # moved keys were deleted at their sources under the fence: the
        # bytes live in exactly one engine
        for k in moved[:50]:
            assert await store._shards[before[k]].get(k) is None
            assert (await store._shards[4].get(k)).value == {"k": k}
    finally:
        await store.aclose()


@pytest.mark.asyncio
async def test_split_flip_is_atomic_under_concurrent_writers(tmp_path):
    """Writers hammer the store while the split streams and flips; no
    write may be lost or land at a shard the new router won't read."""
    store = build_sharded_store("atomic", tmp_path / "atomic.db", shards=3)
    acked: list[tuple[str, int]] = []
    stop = asyncio.Event()

    async def writer(wid: int):
        # 50 distinct keys per writer: the catch-up ladder converges
        # when the MOVING slice of the dirty set fits the final paused
        # round (~64 keys) — a working set it can never outrun is a
        # misconfigured migration, not an atomicity test
        i = 0
        while not stop.is_set():
            key = f"w{wid}-{i % 50}"
            await store.set(key, {"v": i})
            acked.append((key, i))
            i += 1

    try:
        for i in range(600):
            await store.set(f"seed-{i}", {"v": i})
        writers = [asyncio.create_task(writer(w)) for w in range(4)]
        await asyncio.sleep(0.05)
        result = await store.split_shard()
        await asyncio.sleep(0.05)
        stop.set()
        await asyncio.gather(*writers)
        assert store.placement.epoch == 2 and result["shards"] == 4
        # last acked value per key must be the one that reads back
        last: dict[str, int] = {}
        for key, v in acked:
            last[key] = v
        for key, v in last.items():
            item = await store.get(key)
            assert item is not None, f"lost acked write {key}"
            assert item.value == {"v": v}
        for i in range(600):
            assert (await store.get(f"seed-{i}")).value == {"v": i}
    finally:
        stop.set()
        await store.aclose()


@pytest.mark.asyncio
async def test_migrate_shard_to_fresh_engine_retires_source(tmp_path):
    """Whole-shard copy migration: keys stream to the target engine,
    routing flips at epoch+1, the source engine retires."""
    store = build_sharded_store("mv", tmp_path / "mv.db", shards=3)
    try:
        for k in KEYS[:400]:
            await store.set(k, {"k": k})
        shard2 = [k for k in KEYS[:400] if store.router.shard_of(k) == 2]
        assert shard2
        target = SqliteStateStore("mv", tmp_path / "mv-new.db", shard=2)
        result = await store.migrate_shard(2, target=target)
        assert result["action"] == "move" and result["epoch"] == 2
        assert store._shards[2] is target
        for k in shard2:
            assert (await store.get(k)).value == {"k": k}
        await store.set(shard2[0], {"k": "after"})
        assert (await target.get(shard2[0])).value == {"k": "after"}
    finally:
        await store.aclose()


# -- migration over the replication plane -----------------------------------

@pytest.mark.asyncio
async def test_leadership_migration_fenced_handoff(tmp_path):
    """Planned handoff: catch-up to zero lag, fence under the pause,
    transfer the lease, flip the map. The old leader must reject
    writes afterwards — no write can land at the old leader post-fence."""
    store = build_replicated_store(
        "hand", tmp_path / "hand.db", shards=2, replicas=2,
        ack_quorum=2, lease_seconds=LEASE)
    try:
        for i in range(40):
            await store.set(f"k{i}", {"v": i})
        rset = store._shards[0]
        old_leader = rset.leader_member()
        target = next(n.node_id for n in rset.nodes
                      if n.node_id != old_leader)
        result = await store.migrate_shard(0, member=target)
        assert result["target"] == target
        assert store.placement.epoch == result["epoch"] == 2
        assert store.placement.assignment[0] == target
        await _wait_for(lambda: rset.leader_member() == target,
                        message="lease records the new leader")
        old_node = next(n for n in rset.nodes if n.node_id == old_leader)
        assert not old_node.is_leader, \
            "old leader still thinks it leads post-fence"
        # data plane kept its promises across the handoff
        for i in range(40):
            assert (await store.get(f"k{i}")).value == {"v": i}
        await store.set("post-handoff", {"v": -1})
        assert (await store.get("post-handoff")).value == {"v": -1}
    finally:
        await store.aclose()


@pytest.mark.asyncio
async def test_leader_kill_mid_migration_loses_no_acked_write(tmp_path):
    """THE chaos drill: writers bank acked keys while a migration is
    in flight, and the OLD leader is crashed mid-catch-up (kill -9
    semantics: no lease release). The migration must converge — the
    target promotes via the normal lease takeover — and every acked
    key must read back. Zero lost acked writes, not 'few'."""
    store = build_replicated_store(
        "kill", tmp_path / "kill.db", shards=2, replicas=3,
        ack_quorum=2, lease_seconds=LEASE)
    acked: list[str] = []
    stop = asyncio.Event()

    async def writer():
        i = 0
        while not stop.is_set():
            key = f"mid-{i}"
            try:
                await store.set(key, {"v": i})
            except (StateError, OSError):
                await asyncio.sleep(0.05)  # promotion window: retry
                continue
            acked.append(key)
            i += 1

    try:
        for i in range(30):
            await store.set(f"pre-{i}", {"v": i})
            acked.append(f"pre-{i}")
        rset = store._shards[0]
        old_leader = rset.leader_member()
        victim = next(n for n in rset.nodes if n.node_id == old_leader)
        target = next(n.node_id for n in rset.nodes
                      if n.node_id != old_leader)
        wtask = asyncio.create_task(writer())
        await asyncio.sleep(0.05)
        migration = asyncio.create_task(store.migrate_shard(0, member=target))
        victim.crash()  # mid-migration, lease NOT released
        try:
            await asyncio.wait_for(migration, timeout=10.0)
            assert store.placement.epoch >= 2
        except StateError:
            # transfer raced the crash and aborted: routing untouched,
            # and the lease takeover below must still restore service
            assert store.placement.epoch >= 1
        await _wait_for(
            lambda: rset.leader_member() not in (None, old_leader),
            message="survivor takes the lease after the crash")
        await asyncio.sleep(0.1)
        stop.set()
        await wtask
        lost = [k for k in acked if await store.get(k) is None]
        assert lost == [], f"lost {len(lost)} acked writes: {lost[:5]}"
    finally:
        stop.set()
        await store.aclose()


@pytest.mark.asyncio
async def test_blackholed_catchup_lane_aborts_cleanly(tmp_path):
    """chaos ``targets.placement``: a blackholed catch-up stream must
    fail the migration with routing untouched — same epoch, every key
    still served — never wedge the fenced pause open."""
    spec = parse_chaos({
        "apiVersion": "tasksrunner/v1alpha1",
        "kind": "Chaos",
        "metadata": {"name": "placement-chaos"},
        "spec": {
            "faults": {"dead": {"blackhole": {"deadline": "200ms"}}},
            "targets": {"placement": {"bh/1": ["dead"]}},
        },
    })
    store = build_sharded_store("bh", tmp_path / "bh.db", shards=3)
    store.attach_chaos(ChaosPolicies([spec]))
    try:
        for k in KEYS[:300]:
            await store.set(k, {"k": k})
        epoch_before = store.placement.epoch
        target = SqliteStateStore("bh", tmp_path / "bh-new.db", shard=1)
        with pytest.raises((TimeoutError, asyncio.TimeoutError)):
            await store.migrate_shard(1, target=target)
        assert store.placement.epoch == epoch_before, \
            "aborted migration must not flip routing"
        assert store.placement.migration is None, \
            "aborted migration must clear its status"
        for k in KEYS[:300]:
            assert (await store.get(k)).value == {"k": k}
        # the OTHER shards migrate fine: the rule is shard-scoped
        target0 = SqliteStateStore("bh", tmp_path / "bh-new0.db", shard=0)
        result = await store.migrate_shard(0, target=target0)
        assert result["epoch"] == epoch_before + 1
        await target.aclose()
    finally:
        await store.aclose()


# -- heat telemetry + planning ----------------------------------------------

def test_heat_tracker_ewma_and_hysteresis():
    clock = [0.0]
    t = ShardHeatTracker(2, halflife=1.0, threshold=10.0, hysteresis=2.0,
                         clock=lambda: clock[0])
    for _ in range(100):
        t.note_write(0, "hot-key")
    clock[0] = 1.0
    rates = t.sample()
    assert rates[0] > 10.0 and rates[1] == 0.0
    # above threshold but not yet for the whole hysteresis window
    assert t.hot_shards() == []
    for _ in range(100):
        t.note_write(0)
    clock[0] = 3.5
    t.sample()
    assert t.hot_shards() == [0], "sustained heat must rank hot"
    # cooling below threshold resets the hysteresis clock
    clock[0] = 30.0
    t.sample()
    assert t.hot_shards() == []


def test_heat_tracker_hot_key_sketch_is_bounded():
    t = ShardHeatTracker(1)
    for i in range(10_000):
        t.note_write(0, f"key-{i % 500}")
        t.note_write(0, "heavy")
    assert len(t._key_counts[0]) <= t.KEY_CAP + 1
    assert t.hot_keys(0, limit=1)[0][0] == "heavy", \
        "halve-and-prune must keep heavy hitters"


def test_heat_tracker_grow_starts_cold():
    t = ShardHeatTracker(2, threshold=1.0)
    t.grow(1)
    assert t.shards == 3
    assert t.rates() == [0.0, 0.0, 0.0]


def test_merge_and_rank_across_replicas():
    rates = merge_heat_docs([
        {"heat": {"rates": [1.0, 40.0]}},
        {"heat": {"rates": [2.0, 30.0, 5.0]}},
    ])
    assert rates == [3.0, 70.0, 5.0]
    ranking = rank_shards(rates, threshold=50.0)
    assert ranking[0] == {"shard": 1, "rate": 70.0, "hot": True, "rank": 0}
    assert [r["shard"] for r in ranking] == [1, 2, 0]


def test_plan_rebalance_split_vs_move():
    base = {"store": "s", "epoch": 1, "shards": 2}
    # hot across many keys → ring growth redistributes them: split
    plan = plan_rebalance(
        dict(base, heat={"rates": [90.0, 1.0], "hot": [0],
                         "top_keys": {"0": ["a", "b", "c"]}}),
        threshold=50.0)
    assert plan["action"] == "split" and plan["shard"] == 0
    # one dominant key cannot be split away from itself: move
    plan = plan_rebalance(
        dict(base, heat={"rates": [90.0, 1.0], "hot": [0],
                         "top_keys": {"0": ["solo"]}}),
        threshold=50.0)
    assert plan["action"] == "move"
    assert plan["coldest_shard"] == 1
    # nothing past hysteresis → no plan (anti-thrash)
    assert plan_rebalance(
        dict(base, heat={"rates": [90.0, 1.0], "hot": [],
                         "top_keys": {}}), threshold=50.0) is None


@pytest.mark.asyncio
async def test_placement_doc_published_and_locality_rank(tmp_path):
    store = build_sharded_store("doc", tmp_path / "doc.db", shards=2)
    try:
        for i in range(50):
            await store.set(f"k{i}", {"v": i})
        doc = store.placement_doc()
        assert doc["epoch"] == 1 and doc["shards"] == 2
        assert doc["store"] == "doc"
        assert len(doc["heat"]["rates"]) == 2
        # no local member configured → every key ranks local (1.0)
        assert store.locality_rank("k0") == 1.0
        # with an identity, unassigned shards still rank local; a
        # shard assigned elsewhere ranks 0.0
        store.local_member = "hostA"
        assert store.locality_rank("k0") == 1.0
        shard = store.router.shard_of("k0")
        store.placement = store.placement.advanced(
            assignment={shard: "hostB"})
        assert store.locality_rank("k0") == 0.0
    finally:
        await store.aclose()


@pytest.mark.asyncio
async def test_orchestrator_controller_merges_and_plans(tmp_path):
    """The control loop's merge: freshest epoch wins the routing view,
    rates sum across replicas, and the plan comes from the cluster
    heat, not one replica's."""
    from tasksrunner.orchestrator.placement import PlacementController

    controller = PlacementController("app", lambda: [])
    view = controller._merge([
        {"placement": {"statestore": {
            "store": "statestore", "epoch": 2, "shards": 2,
            "assignment": {"0": "r1"}, "migration": None,
            "heat": {"rates": [30.0, 1.0], "hot": [0],
                     "top_keys": {"0": ["a", "b"]}}}}},
        {"placement": {"statestore": {
            "store": "statestore", "epoch": 1, "shards": 2,
            "assignment": {}, "migration": None,
            "heat": {"rates": [40.0, 2.0], "hot": [0],
                     "top_keys": {"0": ["b", "c"]}}}}},
    ])
    entry = view["statestore"]
    assert entry["epoch"] == 2, "freshest routing truth wins"
    assert entry["assignment"] == {"0": "r1"}
    assert entry["replicas_reporting"] == 2
    assert entry["ranking"][0]["rate"] == 70.0
    assert entry["plan"]["action"] == "split"  # 3 distinct warm keys
