"""Opt-in endurance soak (TASKSRUNNER_SOAK=1): sustained load through
the full in-process pipeline with a memory-flatness assertion.

The round-4 soak (BASELINE.md "Round 4 endurance") caught what the
functional suite structurally cannot: per-message memory retention —
CPython 3.12's pathlib interning every unique outbox/blob filename
forever. This test is that soak, distilled: drive thousands of
messages through subscribe → handler → output binding and assert the
process does NOT retain memory per message. Off by default (it runs
minutes-scale work under load-sensitive assertions); enable with
TASKSRUNNER_SOAK=1 for release checks and leak hunts.
"""

import asyncio
import gc
import tracemalloc

import pytest

from tasksrunner import App, InProcCluster
from tasksrunner.component.spec import parse_component
from tasksrunner.envflag import env_flag

pytestmark = pytest.mark.skipif(
    not env_flag("TASKSRUNNER_SOAK", default=False),
    reason="endurance soak is opt-in (TASKSRUNNER_SOAK=1)")

#: net retained bytes allowed across the measured 5k messages —
#: the pre-fix leak measured ~1.9 MB here; post-fix ~47 KiB of
#: transient buffers. 400 KiB keeps headroom without letting a
#: per-message leak (>80 B/msg) back in.
RETAINED_BUDGET = 400 * 1024


@pytest.mark.asyncio
async def test_no_per_message_memory_retention(tmp_path):
    specs = [
        parse_component({
            "componentType": "pubsub.sqlite",
            "metadata": [
                {"name": "brokerPath", "value": str(tmp_path / "broker.db")},
                {"name": "pollIntervalSeconds", "value": "0.002"},
            ]}, default_name="pubsub"),
        parse_component({
            "componentType": "bindings.twilio.sendgrid",
            "metadata": [{"name": "outboxPath",
                          "value": str(tmp_path / "outbox")}],
        }, default_name="sendgrid"),
        parse_component({
            "componentType": "bindings.azure.blobstorage",
            "metadata": [{"name": "rootPath",
                          "value": str(tmp_path / "blobs")}],
        }, default_name="blobstore"),
    ]

    received = 0
    target = 0
    done = asyncio.Event()
    app = App("proc")

    @app.subscribe(pubsub="pubsub", topic="t", route="/on")
    async def on(req):
        nonlocal received
        # the production processor's per-message work: one outbox mail
        # + one blob archive, both with UNIQUE names (the leak shape)
        task_id = req.data["taskId"]
        await app.client.invoke_binding(
            "sendgrid", "create", {"body": "x" * 200},
            {"emailTo": "a@b.com"})
        await app.client.invoke_binding(
            "blobstore", "create", req.data, {"blobName": f"{task_id}.json"})
        received += 1
        if received >= target:
            done.set()
        return 200

    pub = App("pub")
    cluster = InProcCluster(specs)
    cluster.add_app(app)
    cluster.add_app(pub)
    await cluster.start()
    try:
        client = cluster.client("pub")

        async def drive(n: int, start: int) -> None:
            nonlocal target
            done.clear()
            target = received + n
            for i in range(start, start + n):
                await client.publish_event("pubsub", "t", {"taskId": f"s{i}"})
            await asyncio.wait_for(done.wait(), timeout=240)

        await drive(1000, 0)          # warmup: caches, pools, lazy init
        gc.collect()
        tracemalloc.start(10)
        base = tracemalloc.take_snapshot()
        await drive(5000, 1000)       # the measured window
        gc.collect()
        snap = tracemalloc.take_snapshot()
        retained = sum(s.size_diff for s in snap.compare_to(base, "lineno"))
        assert retained < RETAINED_BUDGET, (
            f"retained {retained/1024:.0f} KiB across 5k messages "
            f"(budget {RETAINED_BUDGET/1024:.0f} KiB) — top sites:\n" +
            "\n".join(str(s) for s in snap.compare_to(base, "lineno")[:5]))
    finally:
        tracemalloc.stop()
        await cluster.stop()
