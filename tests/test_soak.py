"""Endurance soak: sustained load through the full in-process pipeline
with a memory-flatness assertion.

The round-4 soak (BASELINE.md "Round 4 endurance") caught what the
functional suite structurally cannot: per-message memory retention —
CPython 3.12's pathlib interning every unique outbox/blob filename
forever. This file is that soak, distilled: drive thousands of
messages through subscribe → handler → output binding and assert the
process does NOT retain memory per message.

Two tiers (round-5 verdict item 3 — the leak detector must not depend
on someone remembering to run it):

* ``test_no_per_message_memory_retention_bounded`` — ALWAYS ON in the
  default suite; a ~1-minute bounded window sized for the 1-core host.
* ``test_no_per_message_memory_retention`` — the full opt-in soak
  (TASKSRUNNER_SOAK=1) for release checks and leak hunts.
"""

import asyncio
import gc
import tracemalloc

import pytest

from tasksrunner import App, InProcCluster
from tasksrunner.component.spec import parse_component
from tasksrunner.envflag import env_flag

#: net retained bytes allowed per measured message. The pre-fix leak
#: measured ~380 B/msg (pathlib interning); post-fix retention is
#: ~10 B/msg of transient buffers amortized. 80 B/msg keeps headroom
#: for allocator noise without letting a real per-message leak back in.
RETAINED_BUDGET_PER_MSG = 80


async def _retention_probe(tmp_path, *, warmup: int, measured: int) -> int:
    """Run the processor-shaped pipeline (subscribe → unique-name
    outbox mail + unique-name blob archive per message) and return net
    retained bytes across the measured window."""
    specs = [
        parse_component({
            "componentType": "pubsub.sqlite",
            "metadata": [
                {"name": "brokerPath", "value": str(tmp_path / "broker.db")},
                {"name": "pollIntervalSeconds", "value": "0.002"},
            ]}, default_name="pubsub"),
        parse_component({
            "componentType": "bindings.twilio.sendgrid",
            "metadata": [{"name": "outboxPath",
                          "value": str(tmp_path / "outbox")}],
        }, default_name="sendgrid"),
        parse_component({
            "componentType": "bindings.azure.blobstorage",
            "metadata": [{"name": "rootPath",
                          "value": str(tmp_path / "blobs")}],
        }, default_name="blobstore"),
    ]

    received = 0
    target = 0
    done = asyncio.Event()
    app = App("proc")

    @app.subscribe(pubsub="pubsub", topic="t", route="/on")
    async def on(req):
        nonlocal received
        # the production processor's per-message work: one outbox mail
        # + one blob archive, both with UNIQUE names (the leak shape)
        task_id = req.data["taskId"]
        await app.client.invoke_binding(
            "sendgrid", "create", {"body": "x" * 200},
            {"emailTo": "a@b.com"})
        await app.client.invoke_binding(
            "blobstore", "create", req.data, {"blobName": f"{task_id}.json"})
        received += 1
        if received >= target:
            done.set()
        return 200

    pub = App("pub")
    cluster = InProcCluster(specs)
    cluster.add_app(app)
    cluster.add_app(pub)
    await cluster.start()
    try:
        client = cluster.client("pub")

        async def drive(n: int, start: int) -> None:
            nonlocal target
            done.clear()
            target = received + n
            for i in range(start, start + n):
                await client.publish_event("pubsub", "t", {"taskId": f"s{i}"})
            await asyncio.wait_for(done.wait(), timeout=240)
            # quiesce before any snapshot: done fires when the LAST
            # handler returns, but broker acks, coalesced writes, and
            # executor work items trail it — that in-flight tail is
            # load-dependent transient state, not per-message
            # retention, and must not be measured as such. A real leak
            # (the pathlib interning this soak exists to catch)
            # survives quiescence untouched.
            await asyncio.sleep(0.5)

        await drive(warmup, 0)        # warmup: caches, pools, lazy init
        gc.collect()
        tracemalloc.start(10)
        try:
            base = tracemalloc.take_snapshot()
            await drive(measured, warmup)   # the measured window
            gc.collect()
            snap = tracemalloc.take_snapshot()
            diff = snap.compare_to(base, "lineno")
            retained = sum(s.size_diff for s in diff)
            budget = RETAINED_BUDGET_PER_MSG * measured
            assert retained < budget, (
                f"retained {retained/1024:.0f} KiB across {measured} "
                f"messages (budget {budget/1024:.0f} KiB) — top sites:\n"
                + "\n".join(str(s) for s in diff[:5]))
            return retained
        finally:
            tracemalloc.stop()
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_no_per_message_memory_retention_bounded(tmp_path):
    """Default-suite leak detector: small enough for every run on the
    1-core host, large enough that the round-4 leak class (~380 B per
    message of immortal interned strings) overshoots the budget ~5x."""
    await _retention_probe(tmp_path, warmup=400, measured=1600)


@pytest.mark.asyncio
@pytest.mark.skipif(
    not env_flag("TASKSRUNNER_SOAK", default=False),
    reason="full endurance soak is opt-in (TASKSRUNNER_SOAK=1)")
async def test_no_per_message_memory_retention(tmp_path):
    """The full-size opt-in soak (release checks, leak hunts)."""
    await _retention_probe(tmp_path, warmup=1000, measured=5000)


@pytest.mark.slow
@pytest.mark.asyncio
async def test_tasks_pipeline_converges_despite_faulty_broker(
        tmp_path, monkeypatch):
    """Chaos soak (tasksrunner/chaos): the tasks-tracker-shaped pipeline
    — publish → subscribe → per-task state write — converges to exactly
    the published task set even when ~10% of deliveries fail with an
    injected broker-side fault. Redelivery absorbs the faults; nothing
    is lost, nothing is processed into a wrong state.

    The scenario is fully deterministic: the injector PRNG is seeded, so
    a failure here reproduces bit-for-bit on every run.
    """
    from tasksrunner.chaos import parse_chaos
    from tasksrunner.observability.metrics import metrics

    monkeypatch.setenv("TASKSRUNNER_CHAOS", "1")
    total = 300
    specs = [
        parse_component({
            "componentType": "pubsub.sqlite",
            "metadata": [
                {"name": "brokerPath", "value": str(tmp_path / "broker.db")},
                {"name": "pollIntervalSeconds", "value": "0.002"},
                {"name": "retryDelaySeconds", "value": "0.01"},
                # enough redelivery budget that a 10% fault rate cannot
                # plausibly exhaust it (p(dead-letter) = 0.1^6 per msg)
                {"name": "maxRetries", "value": "6"},
            ]}, default_name="taskspubsub"),
        parse_component({"componentType": "state.in-memory"},
                        default_name="statestore"),
    ]
    chaos = parse_chaos({
        "kind": "Chaos",
        "metadata": {"name": "soak-chaos"},
        "spec": {
            "seed": 1337,
            "faults": {"flakyBroker": {
                "error": {"probability": 0.1, "raise": "PubSubError"}}},
            "targets": {"components": {
                "taskspubsub": {"inbound": ["flakyBroker"]}}},
        },
    })

    done = asyncio.Event()
    seen: dict[str, int] = {}
    app = App("processor")

    @app.subscribe(pubsub="taskspubsub", topic="tasks", route="/on-task")
    async def on_task(req):
        task_id = req.data["taskId"]
        # redelivery makes at-least-once visible: count arrivals, store once
        seen[task_id] = seen.get(task_id, 0) + 1
        await app.client.save_state("statestore", task_id, req.data)
        if len(seen) >= total:
            done.set()
        return 200

    pub = App("frontend")
    cluster = InProcCluster(specs, chaos_specs=[chaos])
    cluster.add_app(app)
    cluster.add_app(pub)
    await cluster.start()
    try:
        assert cluster.chaos is not None  # the gate really is on
        client = cluster.client("frontend")
        for i in range(total):
            await client.publish_event(
                "taskspubsub", "tasks", {"taskId": f"task-{i}", "n": i})
        await asyncio.wait_for(done.wait(), timeout=120)
        # convergence: every published task landed in the store exactly
        # under its own key, despite the injected failures
        runtime = cluster.runtimes["processor"]
        for i in range(total):
            item = await runtime.get_state("statestore", f"task-{i}")
            assert item is not None and item.value["n"] == i
        injected = metrics.get(
            "chaos_injected_total",
            target="components/taskspubsub/inbound", fault="flakyBroker")
        assert injected > 0  # the adversary genuinely interfered
        # ~10% of ~total+injected deliveries failed → redeliveries ≈ injected
        redelivered = sum(seen.values()) - len(seen)
        assert redelivered <= injected  # every extra arrival traces to a fault
    finally:
        await cluster.stop()
