"""The chaos overload drill: shed → scale out → recover, with zero
lost acked writes. See tasksrunner/testing/overload.py for the
harness; ``make bench-overload`` prints the same trajectory.
"""

from __future__ import annotations

import os

import pytest

from tasksrunner.testing.overload import run_overload_drill

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _assert_trajectory(result: dict, *, max_replicas: int) -> None:
    # 1. shed, never collapse: the flood's excess got clean 429s with
    # the Retry-After contract — not refused connections, not timeouts
    assert result["shed"] > 0, f"admission never shed: {result}"
    assert result["shed_without_retry_after"] == 0
    assert result["connection_errors"] == 0, \
        f"connection collapse is what shedding exists to prevent: {result}"
    assert not result["unexpected_statuses"], result["unexpected_statuses"]
    assert result["retry_after_min"] >= 1
    assert result["retry_after_max"] <= 30

    # 2. scale out: the target-p99 rule saw the chaos-slowed store and
    # argued for more replicas, visibly (gauge) and actually (fleet)
    assert result["desired_gauge_peak"] >= 2, result
    assert result["max_replicas_seen"] >= 2, result
    assert result["max_replicas_seen"] <= max_replicas

    # 3. recover: flood over, windowed p99 cleared, cooldown elapsed,
    # fleet back at min; the replica stopped shedding
    assert result["recovered_to_min"], result
    assert result["final_replicas"] == 1, result
    assert result["admission_state_after"] == 0.0, result

    # 4. no lost acks: every 2xx the clients saw is durable
    assert result["acked"] > 0, "drill made no progress at all"
    assert result["lost_acked_keys"] == [], result["lost_acked_keys"]

    # the trajectory is externally observable: the shed counter made it
    # into the /metrics exposition
    assert result["shed_metric_total"] > 0


async def test_overload_drill_closed_loop(tmp_path, monkeypatch):
    monkeypatch.setenv("PYTHONPATH", REPO)
    result = await run_overload_drill(tmp_path)
    _assert_trajectory(result, max_replicas=2)


@pytest.mark.slow
async def test_overload_drill_soak(tmp_path, monkeypatch):
    """Longer flood, wider fleet: the loop holds under sustained
    pressure, not just a burst."""
    monkeypatch.setenv("PYTHONPATH", REPO)
    result = await run_overload_drill(
        tmp_path, flood_seconds=8.0, concurrency=24, max_replicas=3,
        settle_timeout=60.0)
    _assert_trajectory(result, max_replicas=3)
