"""Full-lane causal tracing: the propagation matrix and its drills.

One traceparent arriving at ingress must flow — as ONE trace id with
unbroken parent links — through every lane the runtime owns: invoke,
actor forward, the actor turn itself, workflow start/activity, pub/sub
publish and delivery, and the group-committed state write. On top of
the matrix:

* a cross-process ``kill -9`` of a workflow owner proving the adopter
  continues the SAME logical instance trace (the trace identity rides
  workflow state, not the process),
* a cross-process replication shipment (mesh binary AND forced-JSON
  codecs) proving ship → apply spans land in two different span DBs
  under the committing write's trace,
* unit coverage for the mesh RREQ trace-context tail, W3C baggage,
  critical-path extraction, per-request ML batch spans, trace
  exemplars on the new lanes, and the black-box flight recorder.
"""

import asyncio
import json
import os
import sys
import time

import pytest

from tasksrunner.app import App
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.observability import spans as spans_mod
from tasksrunner.observability.tracing import (
    current_trace,
    ensure_trace,
    outgoing_headers,
    parse_baggage,
    serialize_baggage,
    trace_scope,
)
from tasksrunner.runtime import InProcAppChannel, Runtime

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRACE = "ab" * 16
PARENT_SPAN = "12" * 8
ROOT_TRACEPARENT = f"00-{TRACE}-{PARENT_SPAN}-01"

LEASE = 0.25
DRIVE = 0.1


@pytest.fixture
def trace_env(monkeypatch, tmp_path):
    monkeypatch.setenv("TASKSRUNNER_ACTORS", "1")
    monkeypatch.setenv("TASKSRUNNER_WORKFLOWS", "1")
    monkeypatch.setenv("TASKSRUNNER_ACTOR_LEASE_SECONDS", "5")
    monkeypatch.setenv("TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS", "30")
    db = tmp_path / "local-traces.db"
    rec = spans_mod.configure_spans("matrix-proc", db)
    yield str(db)
    rec.close()
    spans_mod._recorder = None


def _child_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH")) if p)
    env.update(extra or {})
    return env


def _by_prefix(spans, prefix):
    return [s for s in spans if s["name"].startswith(prefix)]


# -- the in-process propagation matrix -------------------------------------


def _matrix_app(app_id, holder, got):
    app = App(app_id)

    @app.actor("Box")
    async def box(turn):
        if turn.method == "bump":
            holder["actor_ctx"] = current_trace()
        turn.state["n"] = turn.state.get("n", 0) + 1
        return turn.state["n"]

    @app.workflow("simple")
    async def simple(ctx, inp):
        return await ctx.call_activity("add", {"x": inp, "y": 1})

    @app.activity("add")
    async def add(actx, data):
        actx.stage_effect(f"eff||{actx.instance}||{actx.seq}", data)
        return data["x"] + data["y"]

    @app.subscribe("ps", "saved", route="/on-saved")
    async def on_saved(req):
        holder["deliver_ctx"] = current_trace()
        got.set()
        return 200

    @app.post("/go")
    async def go(req):
        holder["ingress_ctx"] = current_trace()
        rt = holder["rt2"]
        await rt.invoke_actor("Box", "b1", "bump")  # owner: rt1 → forward
        await rt.publish("ps", "saved", {"n": 1})
        await rt.workflows.start("simple", 1, instance="matrix-1")
        return 200, {"ok": True}

    return app


def _matrix_runtime(app, state_db, broker_db):
    specs = [
        ComponentSpec(name="statestore", type="state.sqlite",
                      metadata={"databasePath": str(state_db)}),
        ComponentSpec(name="ps", type="pubsub.sqlite",
                      metadata={"brokerPath": str(broker_db),
                                "pollIntervalSeconds": "0.01"}),
    ]
    reg = ComponentRegistry(specs, app_id="svc")
    return Runtime("svc", reg, app_channel=InProcAppChannel(app))


@pytest.mark.asyncio
async def test_propagation_matrix_one_trace_end_to_end(trace_env, tmp_path):
    """Ingress → actor forward → actor turn → workflow start → activity
    → publish → delivery → state write: one trace id, linked parents,
    baggage intact at every hop."""
    holder, got = {}, asyncio.Event()
    state_db, broker_db = tmp_path / "state.db", tmp_path / "broker.db"
    rt1 = _matrix_runtime(_matrix_app("svc", holder, got),
                          state_db, broker_db)
    rt2 = _matrix_runtime(_matrix_app("svc", holder, got),
                          state_db, broker_db)
    await rt1.start()
    await rt2.start()
    for rt in (rt1, rt2):
        rt.actors.lease_seconds = LEASE
        rt.app_channel.app.workflow_engine.drive_period = DRIVE
    holder["rt2"] = rt2
    try:
        # plant ownership of Box/b1 on rt1 so rt2's turn must forward
        await rt1.invoke_actor("Box", "b1", "warm")

        resp = await rt2.app_channel.app.handle(
            "POST", "/go", body=b"{}",
            headers={"traceparent": ROOT_TRACEPARENT,
                     "baggage": "tenant=acme"})
        assert resp.status == 200
        await asyncio.wait_for(got.wait(), timeout=5)
        deadline = time.monotonic() + 8
        while True:
            status = await rt2.workflows.status("matrix-1")
            if status["status"] == "completed":
                break
            assert time.monotonic() < deadline, status
            await asyncio.sleep(0.05)
        assert status["result"] == 2
    finally:
        for rt in (rt2, rt1):
            if rt.workflows is not None:
                rt.workflows.detach()
                rt.workflows = None
            if rt.actors is not None:
                await rt.actors.stop()
                rt.actors = None
        await rt2.stop()
        await rt1.stop()

    spans_mod.recorder().flush()
    spans = spans_mod.trace_spans(trace_env, TRACE)
    assert spans and all(s["trace_id"] == TRACE for s in spans)
    by_id = {s["span_id"]: s for s in spans}

    # every lane produced its span under the one trace
    for prefix, kind in [("POST /go", "server"),
                         ("actor-forward Box/bump", "client"),
                         ("actor-turn Box/bump", "server"),
                         ("ACTOR Box/b1.bump", "server"),
                         ("publish ps/saved", "producer"),
                         ("POST /on-saved", "consumer"),
                         ("workflow-turn simple", "internal"),
                         ("workflow-activity add", "internal"),
                         ("state-write statestore", "internal")]:
        hits = [s for s in _by_prefix(spans, prefix) if s["kind"] == kind]
        assert hits, f"missing {kind} span {prefix!r} in {sorted(s['name'] for s in spans)}"

    # linked parents, not nine parallel orphans: apart from the ingress
    # span (whose parent is the test's synthetic caller), every span's
    # parent is another span of this trace
    ingress = _by_prefix(spans, "POST /go")[0]
    assert ingress["parent_id"] == PARENT_SPAN
    orphans = [s["name"] for s in spans
               if s["span_id"] != ingress["span_id"]
               and s["parent_id"] not in by_id]
    assert orphans == [], orphans

    # the forward hop parents the owner's turn
    fwd = _by_prefix(spans, "actor-forward Box/bump")[0]
    turn = _by_prefix(spans, "actor-turn Box/bump")[0]
    assert turn["parent_id"] == fwd["span_id"]

    # the activity nests under a workflow turn of the instance trace
    act = _by_prefix(spans, "workflow-activity add")[0]
    assert by_id[act["parent_id"]]["name"].startswith("workflow-turn")

    # the write span carries the queue-wait/service split
    wr_attrs = json.loads(_by_prefix(spans, "state-write")[0]["attrs"])
    assert "queue_wait" in wr_attrs and "service" in wr_attrs

    # baggage crossed the actor and delivery hops
    assert holder["ingress_ctx"].baggage == {"tenant": "acme"}
    assert holder["actor_ctx"].baggage == {"tenant": "acme"}
    assert holder["deliver_ctx"].trace_id == TRACE
    assert holder["deliver_ctx"].baggage == {"tenant": "acme"}


# -- cross-process: kill -9 the workflow owner -----------------------------

_KILL9_TRACE_CHILD = '''
import asyncio, os, sys

os.environ["TASKSRUNNER_WORKFLOWS"] = "1"
os.environ["TASKSRUNNER_ACTOR_LEASE_SECONDS"] = "0.5"
os.environ["TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS"] = "30"

from tasksrunner.app import App
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.observability import spans as spans_mod
from tasksrunner.observability.tracing import ensure_trace, trace_scope
from tasksrunner.runtime import InProcAppChannel, Runtime


def build():
    app = App("svc")

    @app.workflow("steps")
    async def steps(ctx, n):
        total = 0
        for i in range(n):
            total += await ctx.call_activity("slowstep", {"i": i})
        return total

    @app.activity("slowstep")
    async def slowstep(actx, data):
        print(f"STEP {actx.seq}", flush=True)
        await asyncio.sleep(0.12)
        return 1

    return app


async def main():
    spans_mod.configure_spans("owner", sys.argv[2])
    spec = ComponentSpec(name="statestore", type="state.sqlite",
                         metadata={"databasePath": sys.argv[1]})
    reg = ComponentRegistry([spec], app_id="svc")
    rt = Runtime("svc", reg, app_channel=InProcAppChannel(build()))
    await rt.start()
    rt.actors.lease_seconds = 0.5
    rt.app_channel.app.workflow_engine.drive_period = 0.2
    print("READY", flush=True)
    with trace_scope(ensure_trace(sys.argv[3])):
        await rt.workflows.start("steps", 12, instance="xtrace-1")
    await asyncio.sleep(60)  # the parent kills us long before this


asyncio.run(main())
'''


@pytest.mark.asyncio
async def test_kill9_owner_instance_trace_contiguity(trace_env, tmp_path):
    """``kill -9`` the process that owns a running workflow. The trace
    identity is committed in workflow state, so the replica that adopts
    the instance keeps appending to the SAME logical trace the dead
    owner started — one trace id, the adopter's turns parented under
    the root span the dead process created, no replayed-duplicate
    activity spans."""
    db = tmp_path / "wf.db"
    owner_traces = tmp_path / "owner-traces.db"
    script = tmp_path / "owner_child.py"
    script.write_text(_KILL9_TRACE_CHILD)
    child = await asyncio.create_subprocess_exec(
        sys.executable, str(script), str(db), str(owner_traces),
        ROOT_TRACEPARENT,
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT,
        env=_child_env())
    try:
        # kill mid-run: late enough that the child's 0.5 s flush timer
        # has landed its early spans, early enough that the adopter
        # still has real work left
        steps_seen = 0
        deadline = asyncio.get_running_loop().time() + 30
        while steps_seen < 5:
            assert asyncio.get_running_loop().time() < deadline, \
                f"child never progressed (saw {steps_seen} steps)"
            line = (await asyncio.wait_for(child.stdout.readline(), 30)
                    ).decode().strip()
            if line.startswith("STEP "):
                steps_seen = int(line.split()[1])
        child.kill()
        await child.wait()

        app = App("svc")

        @app.workflow("steps")
        async def steps(ctx, n):
            total = 0
            for i in range(n):
                total += await ctx.call_activity("slowstep", {"i": i})
            return total

        @app.activity("slowstep")
        async def slowstep(actx, data):
            return 1

        spec = ComponentSpec(name="statestore", type="state.sqlite",
                             metadata={"databasePath": str(db)})
        reg = ComponentRegistry([spec], app_id="svc")
        rt = Runtime("svc", reg, app_channel=InProcAppChannel(app))
        await rt.start()
        rt.actors.lease_seconds = LEASE
        rt.app_channel.app.workflow_engine.drive_period = DRIVE
        try:
            deadline = time.monotonic() + 15
            while True:
                await rt.actors.sweep()
                status = await rt.workflows.status("xtrace-1")
                if status["status"] == "completed":
                    break
                assert time.monotonic() < deadline, status
                await asyncio.sleep(0.05)
            assert status["result"] == 12
        finally:
            rt.workflows.detach()
            rt.workflows = None
            await rt.actors.stop()
            rt.actors = None
            await rt.stop()
    finally:
        if child.returncode is None:
            child.kill()
            await child.wait()

    spans_mod.recorder().flush()
    merged = spans_mod.assemble_trace([str(owner_traces), trace_env], TRACE)
    assert merged, "no spans joined the instance trace"
    roles = {s["role"] for s in merged}
    assert {"owner", "matrix-proc"} <= roles, roles

    # the dead owner's first traced turn minted the instance's root
    # span id and committed it in workflow state; SIGKILL lost the
    # in-flight turn span itself, but the durable id is the anchor:
    # the owner's activity spans AND every adopter turn hang off it
    acts = [s for s in merged if s["name"] == "workflow-activity slowstep"]
    owner_acts = sorted((s for s in acts if s["role"] == "owner"),
                        key=lambda s: json.loads(s["attrs"])["seq"])
    assert owner_acts, "owner's pre-kill activity spans never flushed"
    # the first activity ran inside the instance's root turn, so its
    # parent IS the root span id the dead owner minted and committed
    root_id = owner_acts[0]["parent_id"]
    turns = [s for s in merged if s["name"] == "workflow-turn steps"
             and s["role"] == "matrix-proc"]
    assert turns, "adopter recorded no turn spans"
    assert all(s["parent_id"] == root_id for s in turns), turns

    # replay re-records nothing: each activity seq has at most one
    # span across both processes, and the adopter only recorded the
    # continuation, not the replayed prefix
    seqs = [json.loads(s["attrs"])["seq"] for s in acts]
    assert len(seqs) == len(set(seqs)), sorted(seqs)
    adopter_seqs = {json.loads(s["attrs"])["seq"] for s in acts
                    if s["role"] == "matrix-proc"}
    owner_seqs = {json.loads(s["attrs"])["seq"] for s in owner_acts}
    assert 12 in adopter_seqs and adopter_seqs.isdisjoint(owner_seqs)
    assert min(adopter_seqs) > max(owner_seqs)


# -- cross-process: replication ship → apply -------------------------------

_REPL_TRACE_CHILD = '''
import asyncio, sys

from tasksrunner.observability import spans as spans_mod
from tasksrunner.observability.tracing import ensure_trace, trace_scope
from tasksrunner.state.replication import ReplicationNode
from tasksrunner.state.replmesh import MeshFollowerLink
from tasksrunner.state.sqlite import SqliteStateStore


async def main():
    tmp, parent_port, trace_db, tp = (sys.argv[1], int(sys.argv[2]),
                                      sys.argv[3], sys.argv[4])
    spans_mod.configure_spans("leader", trace_db)
    meta = SqliteStateStore("drill.repl-meta", f"{tmp}/meta.db")
    node = ReplicationNode("drill", f"{tmp}/leader.db", member=0,
                           shard=0, meta_store=meta, lease_seconds=5.0,
                           ack_quorum=2, ack_timeout=10.0)
    node.links["r1"] = MeshFollowerLink(
        "drill", 0, "r1", "127.0.0.1", parent_port)
    await node.start()
    while not node.is_leader:
        await asyncio.sleep(0.02)
    with trace_scope(ensure_trace(tp)):
        for i in range(5):
            await node.store.set(f"k-{i}", {"v": i})
    spans_mod.recorder().flush()
    print("SHIPPED", flush=True)
    await asyncio.sleep(60)


asyncio.run(main())
'''


@pytest.mark.parametrize("codec_env", ["", "json"])
@pytest.mark.asyncio
async def test_cross_process_replication_trace(trace_env, tmp_path,
                                               codec_env):
    """A quorum-acked write's trace context crosses the process
    boundary with the replicated record: the leader process records
    ``repl-ship``/``repl-ack`` into ITS span DB, the follower (this
    process) records ``repl-apply`` into OURS, all under the committing
    write's trace — over the v2 binary codec and, forced via
    ``TASKSRUNNER_MESH_CODEC=json``, over the legacy v1 JSON frames."""
    from tasksrunner.state.replication import ReplicationNode
    from tasksrunner.state.replmesh import ReplicationServer
    from tasksrunner.state.sqlite import SqliteStateStore

    meta = SqliteStateStore("drill.repl-meta", tmp_path / "fmeta.db")
    follower = ReplicationNode("drill", tmp_path / "follower.db", member=1,
                               shard=0, meta_store=meta, lease_seconds=5.0,
                               ack_quorum=2, ack_timeout=5.0)
    server = ReplicationServer()
    server.register(follower)
    await server.start()

    leader_traces = tmp_path / "leader-traces.db"
    script = tmp_path / "leader_child.py"
    script.write_text(_REPL_TRACE_CHILD)
    extra = {"TASKSRUNNER_MESH_CODEC": codec_env} if codec_env else {}
    child = await asyncio.create_subprocess_exec(
        sys.executable, str(script), str(tmp_path), str(server.port),
        str(leader_traces), ROOT_TRACEPARENT,
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT,
        env=_child_env(extra))
    try:
        line = ""
        deadline = asyncio.get_running_loop().time() + 30
        while line != "SHIPPED":
            assert asyncio.get_running_loop().time() < deadline, \
                f"leader child never shipped (last line: {line!r})"
            line = (await asyncio.wait_for(child.stdout.readline(), 30)
                    ).decode().strip()
    finally:
        if child.returncode is None:
            child.kill()
            await child.wait()
    try:
        spans_mod.recorder().flush()
        applies = [s for s in spans_mod.trace_spans(trace_env, TRACE)
                   if s["name"] == "repl-apply"]
        assert applies, "follower recorded no repl-apply span"
        assert applies[0]["kind"] == "consumer"

        leader_spans = spans_mod.trace_spans(str(leader_traces), TRACE)
        names = {s["name"] for s in leader_spans}
        assert "repl-ship" in names, names
        assert "repl-ack" in names, names
        writes = [s for s in leader_spans
                  if s["name"].startswith("state-write")]
        assert writes
        # the committing write's ambient span (a child of the synthetic
        # root) is the shared parent: the leader's state-write span and
        # the follower's repl-apply span — in two different span DBs,
        # two different processes — hang off the SAME span id
        assert applies[0]["parent_id"] == writes[0]["parent_id"]
    finally:
        await follower.stop()
        await server.aclose()
        follower.store.close()
        await meta.aclose()


# -- mesh codec: RREQ trace-context tail -----------------------------------


def test_rreq_binary_codec_tp_tail_roundtrip():
    """The trace context rides the v2 RREQ frame as an optional tail:
    with no context the frame is byte-identical to the original v2
    shape (old decoders keep working), with context it round-trips."""
    from tasksrunner.invoke.mesh import BinaryHeaderCodec

    bare = {"op": "append", "store": "orders", "shard": 3}
    raw = BinaryHeaderCodec.encode(bare)
    assert BinaryHeaderCodec.decode(raw) == bare

    with_tp = dict(bare, tp=ROOT_TRACEPARENT)
    raw_tp = BinaryHeaderCodec.encode(with_tp)
    assert raw_tp[:len(raw)] == raw  # tail is strictly additive
    assert BinaryHeaderCodec.decode(raw_tp) == with_tp


def test_rreq_json_codec_carries_tp_as_plain_key():
    from tasksrunner.invoke.mesh import JsonHeaderCodec

    header = {"op": "append", "store": "orders", "shard": 0,
              "tp": ROOT_TRACEPARENT}
    assert JsonHeaderCodec.decode(JsonHeaderCodec.encode(header)) == header


# -- W3C baggage -----------------------------------------------------------


def test_baggage_roundtrip_and_caps():
    assert parse_baggage("a=1, b=two%2Cthree") == {"a": "1", "b": "two,three"}
    assert parse_baggage(None) == {}
    assert parse_baggage("garbage-no-equals,,") == {}
    bag = {"k": "v v", "n": "1"}
    assert parse_baggage(serialize_baggage(bag)) == bag
    # caps: item count and total bytes both bound the header
    many = {f"k{i}": "x" for i in range(64)}
    assert len(parse_baggage(serialize_baggage(many))) <= 16
    huge = {"k": "x" * 4096}
    assert not serialize_baggage(huge)


def test_ensure_trace_adopts_incoming_baggage():
    ctx = ensure_trace(ROOT_TRACEPARENT, "tenant=acme,tier=gold")
    assert ctx.trace_id == TRACE
    assert ctx.baggage == {"tenant": "acme", "tier": "gold"}
    with trace_scope(ctx):
        hdrs = outgoing_headers()
    assert hdrs["traceparent"].split("-")[1] == TRACE
    assert parse_baggage(hdrs["baggage"]) == ctx.baggage


# -- critical path ---------------------------------------------------------


def _span(name, span_id, parent, start, dur, **attrs):
    return {"trace_id": TRACE, "span_id": span_id, "parent_id": parent,
            "role": "r", "kind": "internal", "name": name, "status": 200,
            "start": start, "duration": dur, "attrs": json.dumps(attrs)}


def test_critical_path_descends_into_latest_ending_child():
    spans = [
        _span("root", "r0", None, 0.0, 1.0),
        _span("fast", "c1", "r0", 0.1, 0.2),
        _span("slow", "c2", "r0", 0.2, 0.75,
              queue_wait=0.5, service=0.25),
        _span("leaf", "g1", "c2", 0.6, 0.3),
    ]
    hops = spans_mod.critical_path(spans)
    assert [h["name"] for h in hops] == ["root", "slow", "leaf"]
    # hop self-times reconstruct the root's wall time
    assert sum(h["self_time"] for h in hops) == pytest.approx(1.0, rel=0.1)
    # the batched hop surfaces its queue-wait/service split
    slow = hops[1]
    assert slow["queue_wait"] == pytest.approx(0.5)
    assert slow["service"] == pytest.approx(0.25)


def test_critical_path_empty_and_orphan_inputs():
    assert spans_mod.critical_path([]) == []
    lone = [_span("only", "s1", "dead-parent", 0.0, 0.5)]
    hops = spans_mod.critical_path(lone)
    assert [h["name"] for h in hops] == ["only"]


def test_assemble_trace_dedups_across_sources(tmp_path):
    row = _span("shared", "s1", None, 0.0, 0.1)
    other = _span("mine", "s2", "s1", 0.01, 0.05)
    merged = spans_mod.assemble_trace([[row], [dict(row), other]], TRACE)
    assert [s["span_id"] for s in merged] == ["s1", "s2"]
    # a missing DB path is a replica with no spans yet, not an error
    merged = spans_mod.assemble_trace(
        [str(tmp_path / "nope.db"), [row]], TRACE)
    assert [s["span_id"] for s in merged] == ["s1"]


# -- ML micro-batch spans --------------------------------------------------


@pytest.mark.asyncio
async def test_ml_batch_spans_split_queue_wait_from_service(trace_env):
    from tasksrunner.ml.batching import BatcherConfig, MicroBatcher
    from tasksrunner.observability.metrics import MetricsRegistry

    def run_batch(items, bucket):
        time.sleep(0.01)
        return [i * 2 for i in items]

    mb = MicroBatcher(run_batch, config=BatcherConfig(max_delay_ms=5),
                      registry=MetricsRegistry())
    mb.start()
    try:
        with trace_scope(ensure_trace(ROOT_TRACEPARENT)):
            submitter = current_trace()
            assert await mb.submit(21) == 42
    finally:
        await mb.stop()

    spans_mod.recorder().flush()
    spans = spans_mod.trace_spans(trace_env, TRACE)
    reqs = [s for s in spans if s["name"] == "ml-request"]
    assert len(reqs) == 1
    req = reqs[0]
    # the request span joins the SUBMITTER's trace, under its span
    assert req["parent_id"] == submitter.span_id
    attrs = json.loads(req["attrs"])
    assert attrs["queue_wait"] >= 0 and attrs["service"] > 0
    assert req["duration"] == pytest.approx(
        attrs["queue_wait"] + attrs["service"], rel=0.2)
    # ...and points at the batch-execution span, which roots its own
    # trace (N request traces converge on one batch)
    batch_trace = attrs["batch_trace"]
    assert batch_trace != TRACE
    batch = [s for s in spans_mod.trace_spans(trace_env, batch_trace)
             if s["name"] == "ml-batch"]
    assert len(batch) == 1
    assert json.loads(batch[0]["attrs"])["size"] == 1


# -- trace exemplars on the new lanes --------------------------------------


def test_observe_many_records_exemplars_per_request(monkeypatch):
    from tasksrunner.observability.metrics import MetricsRegistry

    monkeypatch.setenv("TASKSRUNNER_SLOW_THRESHOLD_SECONDS", "0.1")
    reg = MetricsRegistry()
    reg.observe_many("ml_infer_latency_seconds", [0.01, 0.5, 0.7],
                     traces=["t-fast", "t-slow", None], bucket=8)
    snap = reg.snapshot_histograms()
    series = snap["ml_infer_latency_seconds"]["series"]
    exemplars = [e for s in series for e in s["exemplars"]]
    # only the slow value WITH a trace id became an exemplar: the fast
    # one is under threshold, the None-trace one has nothing to link
    assert [e[0] for e in exemplars] == ["t-slow"]
    assert exemplars[0][1] == pytest.approx(0.5)


def test_workflow_activity_latency_captures_instance_trace(monkeypatch):
    from tasksrunner.observability.metrics import MetricsRegistry
    from tasksrunner.observability.tracing import TraceContext

    monkeypatch.setenv("TASKSRUNNER_SLOW_THRESHOLD_SECONDS", "0.05")
    reg = MetricsRegistry()
    ctx = TraceContext.new()
    with trace_scope(ctx):
        reg.observe("workflow_activity_latency_seconds", 0.2,
                    workflow="order", activity="charge")
    snap = reg.snapshot_histograms()
    series = snap["workflow_activity_latency_seconds"]["series"]
    exemplars = [e for s in series for e in s["exemplars"]]
    assert [e[0] for e in exemplars] == [ctx.trace_id]


# -- flight recorder -------------------------------------------------------


def test_flightrec_ring_is_bounded_and_dump_reads_back(tmp_path):
    from tasksrunner.observability.flightrec import FlightRecorder, read_dump

    rec = FlightRecorder("api", ring_size=4, out_dir=tmp_path)
    for i in range(10):
        rec.note(name=f"POST /n{i}", trace_id=f"t{i}", status=200,
                 duration=0.01)
    path = rec.dump("slow-exemplar", {"metric": "m"})
    assert path is not None
    doc = read_dump(path)
    assert doc["reason"] == "slow-exemplar"
    assert [e["name"] for e in doc["entries"]] == \
        ["POST /n6", "POST /n7", "POST /n8", "POST /n9"]


def test_flightrec_per_reason_dump_rate_limit(tmp_path):
    from tasksrunner.observability.flightrec import FlightRecorder

    rec = FlightRecorder("api", out_dir=tmp_path)
    rec.note(name="GET /x", trace_id=None, status=200, duration=0.0)
    assert rec.dump("admission-shed") is not None
    # same reason inside the window: suppressed; different reason: not
    assert rec.dump("admission-shed") is None
    assert rec.dump("unclean-shutdown") is not None


def test_flightrec_list_dumps_newest_first(tmp_path):
    from tasksrunner.observability.flightrec import (
        FlightRecorder,
        list_dumps,
    )

    rec = FlightRecorder("api", out_dir=tmp_path)
    rec.note(name="GET /x", trace_id="t1", status=200, duration=0.0)
    rec._last_dump.clear()
    first = rec.dump("admission-shed")
    rec._last_dump.clear()
    second = rec.dump("slow-exemplar")
    assert first and second
    listing = list_dumps(tmp_path)
    assert [d["reason"] for d in listing] == \
        ["slow-exemplar", "admission-shed"]
    assert all(d["entries"] == 1 for d in listing)


def test_admission_shed_entry_dumps_the_flight_recorder(tmp_path):
    """The acceptance drill's observable: crossing into shedding
    writes a black-box dump with the saturation score that tripped."""
    from tasksrunner.observability import flightrec as flightrec_mod
    from tasksrunner.observability.admission import AdmissionController
    from tasksrunner.observability.flightrec import (
        FlightRecorder,
        list_dumps,
    )
    from tasksrunner.observability.metrics import MetricsRegistry

    flightrec_mod._flightrec = FlightRecorder("api", out_dir=tmp_path)
    try:
        flightrec_mod._flightrec.note(name="POST /slow", trace_id="t1",
                                      status=200, duration=2.0)
        reg = MetricsRegistry()
        reg.set_gauge("event_loop_lag_seconds", 1.0)
        ctl = AdmissionController(max_lag_seconds=0.5, registry=reg)
        assert ctl.sample() >= 1.0 and ctl.shedding
        dumps = list_dumps(tmp_path)
        assert [d["reason"] for d in dumps] == ["admission-shed"]
        doc = flightrec_mod.read_dump(dumps[0]["path"])
        assert doc["detail"]["score"] >= 1.0
        assert doc["entries"][0]["name"] == "POST /slow"
        # re-entering shed later re-dumps, but not inside the window
        ctl.shedding = False
        assert ctl.sample() >= 1.0 and ctl.shedding
        assert len(list_dumps(tmp_path)) == 1
    finally:
        flightrec_mod._flightrec = None


def test_slow_exemplar_dumps_through_the_real_hook(tmp_path, monkeypatch):
    """End-to-end wire, not dump() called by hand: configure_flightrec
    must install the hook where Histogram exemplar capture actually
    reads it — the metrics MODULE global. Both package-attribute
    import spellings hand back the registry singleton (the package
    __init__ shadows the submodule name), which is exactly the miss
    this test exists to catch, so reach the true module via
    sys.modules."""
    real_metrics_mod = sys.modules["tasksrunner.observability.metrics"]
    from tasksrunner.observability import flightrec as flightrec_mod
    from tasksrunner.observability.flightrec import list_dumps
    from tasksrunner.observability.metrics import MetricsRegistry

    monkeypatch.setenv("TASKSRUNNER_SLOW_THRESHOLD_SECONDS", "0.05")
    monkeypatch.setattr(flightrec_mod, "_flightrec", None)
    monkeypatch.setattr(real_metrics_mod, "on_slow_exemplar", None)
    monkeypatch.setenv("TASKSRUNNER_FLIGHTREC_DIR", str(tmp_path))
    rec = flightrec_mod.configure_flightrec("api")
    assert rec is not None
    assert real_metrics_mod.on_slow_exemplar is not None
    rec.note(name="POST /api/tasks", trace_id="t1", status=201,
             duration=0.2)
    reg = MetricsRegistry()
    with trace_scope(ensure_trace()):
        reg.observe("invoke_latency_seconds", 0.2, target="api")
    # the atexit handler keeps this recorder alive past monkeypatch's
    # restore; mark it clean so it can't dump at interpreter exit
    rec.mark_clean()
    dumps = list_dumps(tmp_path)
    assert [d["reason"] for d in dumps] == ["slow-exemplar"]
    doc = flightrec_mod.read_dump(dumps[0]["path"])
    assert doc["detail"]["metric"] == "invoke_latency_seconds"
    assert doc["entries"][0]["name"] == "POST /api/tasks"


def test_flightrec_unclean_shutdown_dump_suppressed_by_mark_clean(tmp_path):
    from tasksrunner.observability.flightrec import FlightRecorder

    rec = FlightRecorder("api", out_dir=tmp_path)
    rec.note(name="GET /x", trace_id=None, status=200, duration=0.0)
    rec.mark_clean()
    rec._atexit()
    assert list(tmp_path.iterdir()) == []
    dirty = FlightRecorder("api2", out_dir=tmp_path)
    dirty.note(name="GET /y", trace_id=None, status=500, duration=0.0)
    dirty._atexit()
    dumped = list(tmp_path.iterdir())
    assert len(dumped) == 1
    assert dumped[0].name.endswith("-unclean-shutdown.json")
