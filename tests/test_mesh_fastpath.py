"""Mesh fast path (tasksrunner/invoke/mesh.py): v2 binary header codec
with per-connection hello negotiation, coalesced writes, pre-warmed
routing, and the hung-connection condemnation bugfix.

The rolling-upgrade contract under test: a v2 peer and a JSON-header
peer (pre-PR build, emulated faithfully by ``_legacy_json_server`` —
it answers a hello the only way an unaware server can, as a failed
request) must interoperate in BOTH directions, and the codec is always
chosen per connection by the first frame, never guessed per frame.
"""

import asyncio
import json
import os
import struct
import subprocess
import sys
import textwrap

import pytest

from tasksrunner import App, AppHost
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.chaos import ChaosPolicies, parse_chaos
from tasksrunner.errors import InvocationError
from tasksrunner.invoke.mesh import (
    MAX_FRAME,
    BinaryHeaderCodec,
    JsonHeaderCodec,
    MeshConnectError,
    MeshPool,
    MeshServer,
    _pack,
    pack_frame,
)
from tasksrunner.invoke.resolver import AppAddress, NameResolver
from tasksrunner.runtime import Runtime


class EchoRuntime:
    """Minimal Runtime stand-in: the mesh server only needs .invoke()."""

    def __init__(self):
        self.calls = []

    async def invoke(self, target, path, *, http_method="POST", query="",
                     headers=None, body=b""):
        self.calls.append((target, path))
        if path.endswith("hang"):
            await asyncio.sleep(30)
        payload = json.dumps({"path": path, "echo": body.decode() or None})
        return 200, {"content-type": "application/json"}, payload.encode()


async def _start_server(**kw):
    srv = MeshServer(EchoRuntime(), **kw)
    await srv.start()
    return srv


async def _read_json_frame(reader):
    (frame_len,) = struct.unpack(">I", await reader.readexactly(4))
    (hdr_len,) = struct.unpack(">I", await reader.readexactly(4))
    header = json.loads(await reader.readexactly(hdr_len))
    body = await reader.readexactly(frame_len - 4 - hdr_len)
    return header, body


async def _legacy_json_server():
    """The pre-v2 server loop, byte-faithful: JSON headers only, no
    hello awareness — EVERY frame (the hello included) is dispatched
    as a request and answered as one."""

    async def handler(reader, writer):
        try:
            while True:
                header, body = await _read_json_frame(reader)
                payload = json.dumps({"path": header.get("p"),
                                      "echo": body.decode() or None}).encode()
                writer.write(_pack({"i": header.get("i"), "s": 200, "h": {}},
                                   payload))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            writer.close()

    return await asyncio.start_server(handler, "127.0.0.1", 0)


# ---------------------------------------------------------------------------
# codec unit: every header shape round-trips the binary encoding
# ---------------------------------------------------------------------------

def test_binary_codec_roundtrips_every_header_shape():
    shapes = [
        {"i": 7, "t": "backend-api", "m": "POST", "p": "/api/tasks",
         "q": "a=1&b=2", "h": {"content-type": "application/json",
                               "x-corr": "abc"}},
        {"i": 7, "t": "x", "m": "GET", "p": "/", "q": "", "h": {}},
        {"i": 9, "s": 503, "h": {"retry-after": "1"}},
        {"i": 1 << 40, "s": 200, "h": {}},
        {"ping": 12}, {"pong": 12},
        {"op": "append", "store": "statestore", "shard": 3},
        {"op": "position", "store": "s", "shard": 0},
        {"ok": True},
        {"ok": False, "kind": "gap", "hwm": 41, "epoch": 0, "diverged": True},
        {"ok": False, "kind": "fenced", "error": "stale epoch 2 < 3"},
        {"ok": False, "kind": "error", "error": "KeyError: 'x'"},
    ]
    for header in shapes:
        raw = BinaryHeaderCodec.encode(header)
        assert raw[0] == 0xB2  # can never be mistaken for JSON's '{'
        assert BinaryHeaderCodec.decode(raw) == header


def test_binary_codec_rejects_garbage_with_connection_error():
    for raw in [b"", b"\xb2", b"\xb2\x63", b"\x7b\x01\x02",
                BinaryHeaderCodec.encode({"ping": 1}) + b"xx"]:
        with pytest.raises(ConnectionError):
            BinaryHeaderCodec.decode(raw)


# ---------------------------------------------------------------------------
# negotiation matrix — per connection, decided by the first frame
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_v2_to_v2_negotiates_binary():
    srv = await _start_server(api_token=None)
    pool = MeshPool()
    try:
        status, _, body = await pool.request(
            "127.0.0.1", srv.port, "t", "POST", "/api/x", body=b"hello")
        assert status == 200 and json.loads(body)["echo"] == "hello"
        (conn,) = pool._conns.values()
        assert conn.codec is BinaryHeaderCodec
        assert conn.peer_aware
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_v2_client_against_json_only_server_falls_back():
    server = await _legacy_json_server()
    port = server.sockets[0].getsockname()[1]
    pool = MeshPool()
    try:
        status, _, body = await pool.request(
            "127.0.0.1", port, "t", "POST", "/api/x", body=b"up")
        assert status == 200 and json.loads(body)["echo"] == "up"
        (conn,) = pool._conns.values()
        assert conn.codec is JsonHeaderCodec
        assert not conn.peer_aware  # the hello was answered as a request
    finally:
        await pool.close()
        server.close()
        await server.wait_closed()


@pytest.mark.asyncio
async def test_json_only_client_against_v2_server_stays_json():
    """A pre-PR client sends no hello; its first real request doubles
    as its codec declaration and the v2 server answers in kind."""
    srv = await _start_server(api_token=None)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", srv.port)
        try:
            writer.write(_pack({"i": 1, "t": "t", "m": "GET", "p": "/api/y",
                                "q": "", "h": {}}, b"legacy"))
            await writer.drain()
            header, body = await _read_json_frame(reader)
            assert header["i"] == 1 and header["s"] == 200
            assert json.loads(body)["echo"] == "legacy"
            # and the SAME connection keeps working (codec is sticky)
            writer.write(_pack({"i": 2, "t": "t", "m": "GET", "p": "/z",
                                "q": "", "h": {}}, b""))
            await writer.drain()
            header, _ = await _read_json_frame(reader)
            assert header["i"] == 2 and header["s"] == 200
        finally:
            writer.close()
    finally:
        await srv.stop()


@pytest.mark.asyncio
async def test_forced_json_client_skips_hello(monkeypatch):
    monkeypatch.setenv("TASKSRUNNER_MESH_CODEC", "json")
    srv = await _start_server(api_token=None)
    pool = MeshPool()
    try:
        status, _, body = await pool.request(
            "127.0.0.1", srv.port, "t", "POST", "/api/x", body=b"f")
        assert status == 200 and json.loads(body)["echo"] == "f"
        (conn,) = pool._conns.values()
        assert conn.codec is JsonHeaderCodec and not conn.peer_aware
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_forced_json_server_caps_negotiation_at_v1():
    srv = await _start_server(api_token=None)
    srv.max_version = 1  # what TASKSRUNNER_MESH_CODEC=json does server-side
    pool = MeshPool()
    try:
        status, _, _ = await pool.request(
            "127.0.0.1", srv.port, "t", "GET", "/api/x")
        assert status == 200
        (conn,) = pool._conns.values()
        assert conn.codec is JsonHeaderCodec
        assert conn.peer_aware  # hello was acked, so pings still work
        assert await conn.ping() is True
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_corrupt_hello_is_a_clean_connection_error():
    # server side: a non-integer hello closes the connection
    srv = await _start_server(api_token=None)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", srv.port)
        try:
            writer.write(_pack({"i": 0, "hello": "bogus"}, b""))
            await writer.drain()
            assert await reader.read(1) == b""
        finally:
            writer.close()
    finally:
        await srv.stop()

    # client side: a garbled hello ack surfaces as MeshConnectError
    # (the fall-back-to-HTTP signal), never a hang or a raw parse error
    async def bad_ack(reader, writer):
        await _read_json_frame(reader)
        writer.write(_pack({"i": 0, "hello": "zero-point-five"}, b""))
        await writer.drain()
        await reader.read()
        writer.close()

    server = await asyncio.start_server(bad_ack, "127.0.0.1", 0)
    pool = MeshPool()
    try:
        with pytest.raises(MeshConnectError):
            await pool.request("127.0.0.1",
                               server.sockets[0].getsockname()[1],
                               "t", "GET", "/x")
    finally:
        await pool.close()
        server.close()
        await server.wait_closed()


@pytest.mark.asyncio
async def test_binary_frame_before_hello_is_refused():
    """The codec is negotiated, never guessed: a v2 frame from a peer
    that skipped the handshake is a protocol violation → teardown."""
    srv = await _start_server(api_token=None)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", srv.port)
        try:
            writer.writelines(pack_frame(BinaryHeaderCodec, {"ping": 1}, b""))
            await writer.drain()
            assert await reader.read(1) == b""
        finally:
            writer.close()
    finally:
        await srv.stop()


# ---------------------------------------------------------------------------
# coalesced writes: ordering/interleaving under 64-way concurrency
# ---------------------------------------------------------------------------

async def _flood_64(pool, port):
    async def one(i):
        status, _, body = await pool.request(
            "127.0.0.1", port, "t", "POST", f"/api/{i}",
            body=f"payload-{i}".encode())
        assert status == 200
        doc = json.loads(body)
        assert doc == {"path": f"/api/{i}", "echo": f"payload-{i}"}

    await asyncio.gather(*(one(i) for i in range(64)))
    assert len(pool._conns) == 1  # all multiplexed on one connection


@pytest.mark.asyncio
async def test_coalesced_writes_keep_frame_integrity_64way():
    srv = await _start_server(api_token=None)
    pool = MeshPool()
    try:
        await _flood_64(pool, srv.port)
        (conn,) = pool._conns.values()
        assert conn.codec is BinaryHeaderCodec
    finally:
        await pool.close()
        await srv.stop()


@pytest.mark.asyncio
async def test_per_frame_drain_mode_matches(monkeypatch):
    monkeypatch.setenv("TASKSRUNNER_MESH_COALESCE", "0")
    srv = await _start_server(api_token=None)
    pool = MeshPool()
    try:
        await _flood_64(pool, srv.port)
    finally:
        await pool.close()
        await srv.stop()


# ---------------------------------------------------------------------------
# bugfix: consecutive request timeouts condemn the connection
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_stalled_handler_condemns_connection_and_pool_redials(
        monkeypatch):
    """Regression: a REQUEST_TIMEOUT expiry used to pop only the future
    and leave the hung connection pooled — every later request to that
    peer then queued behind the same dead socket for up to 300 s each.
    After TIMEOUTS_BEFORE_CLOSE consecutive expiries the connection
    must be condemned so the pool re-dials."""
    monkeypatch.setenv("TASKSRUNNER_MESH_REQUEST_TIMEOUT_SECONDS", "0.2")
    srv = await _start_server(api_token=None)
    pool = MeshPool()
    try:
        status, _, _ = await pool.request(
            "127.0.0.1", srv.port, "t", "GET", "/warm")
        assert status == 200
        (first,) = pool._conns.values()
        for _ in range(2):
            with pytest.raises(OSError):  # builtin TimeoutError ⊂ OSError
                await pool.request("127.0.0.1", srv.port, "t", "GET", "/hang")
        assert first.closed  # condemned, not left pooled
        # next request re-dials a fresh connection and succeeds
        status, _, _ = await pool.request(
            "127.0.0.1", srv.port, "t", "GET", "/after")
        assert status == 200
        (conn,) = pool._conns.values()
        assert conn is not first and not conn.closed
    finally:
        await pool.close()
        await srv.stop()


# ---------------------------------------------------------------------------
# pre-warmed routing: keepalive dials off the request path, pings detect
# dead peers early
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_keepalive_prewarms_and_detects_dead_peer():
    srv = await _start_server(api_token=None)
    pool = MeshPool()
    key = ("127.0.0.1", srv.port, None)
    try:
        pool.start_keepalive(lambda: [key], interval=0.05)
        pool.kick()
        for _ in range(100):
            if key in pool._conns and not pool._conns[key].closed:
                break
            await asyncio.sleep(0.01)
        conn = pool._conns[key]
        assert not conn.closed  # dialed with NO request issued
        assert await conn.ping() is True
        await srv.stop()
        for _ in range(100):
            if conn.closed:
                break
            await asyncio.sleep(0.02)
        assert conn.closed  # failed ping condemned it before any caller
    finally:
        await pool.close()


@pytest.mark.asyncio
async def test_runtime_prewarms_registered_peers(tmp_path):
    """Runtime.start wires the keepalive to the resolver: a peer that
    advertised a mesh port at registration is dialed off the request
    path, so the first invoke pays no CONNECT_TIMEOUT-class cost."""
    srv = await _start_server(api_token=None)
    resolver = NameResolver(registry_file=tmp_path / "apps.json")
    resolver.register(AppAddress(
        app_id="backend", host="127.0.0.1", sidecar_port=1, app_port=2,
        mesh_port=srv.port))
    runtime = Runtime("caller", ComponentRegistry([], app_id="caller"),
                      resolver=resolver)
    try:
        assert runtime._mesh_peers() == [("127.0.0.1", srv.port, None)]
        runtime._start_mesh_prewarm()
        runtime.kick_mesh_prewarm()
        pool = runtime._mesh_pool
        key = ("127.0.0.1", srv.port, None)
        for _ in range(100):
            if key in pool._conns and not pool._conns[key].closed:
                break
            await asyncio.sleep(0.01)
        assert key in pool._conns and not pool._conns[key].closed
    finally:
        await runtime.stop()
        await srv.stop()


# ---------------------------------------------------------------------------
# chaos still bites on the fast lane (faults inject before transport)
# ---------------------------------------------------------------------------

def _chaos_doc(faults, targets):
    return {"apiVersion": "tasksrunner/v1alpha1", "kind": "Chaos",
            "metadata": {"name": "fastlane"},
            "spec": {"faults": faults, "targets": targets}}


async def _chaos_runtime(tmp_path, srv, spec):
    resolver = NameResolver(registry_file=tmp_path / "apps.json")
    resolver.register(AppAddress(
        app_id="backend", host="127.0.0.1", sidecar_port=1, app_port=2,
        mesh_port=srv.port))
    return Runtime("caller", ComponentRegistry([], app_id="caller"),
                   resolver=resolver,
                   chaos=ChaosPolicies([spec], app_id="caller"))


@pytest.mark.asyncio
async def test_chaos_latency_bites_on_mesh_lane(tmp_path):
    spec = parse_chaos(_chaos_doc(
        faults={"lag": {"latency": {"duration": "120ms"}}},
        targets={"apps": {"backend": ["lag"]}}))
    srv = await _start_server(api_token=None)
    runtime = await _chaos_runtime(tmp_path, srv, spec)
    try:
        t0 = asyncio.get_running_loop().time()
        status, _, _ = await runtime.invoke("backend", "/api/x")
        elapsed = asyncio.get_running_loop().time() - t0
        assert status == 200
        assert elapsed >= 0.11  # the injected delay applied to the fast lane
        assert srv.runtime.calls  # and the request DID ride the mesh
    finally:
        await runtime.stop()
        await srv.stop()


@pytest.mark.asyncio
async def test_chaos_blackhole_bites_on_mesh_lane(tmp_path):
    spec = parse_chaos(_chaos_doc(
        faults={"dead": {"blackhole": {"deadline": "50ms"}}},
        targets={"apps": {"backend": ["dead"]}}))
    srv = await _start_server(api_token=None)
    runtime = await _chaos_runtime(tmp_path, srv, spec)
    try:
        with pytest.raises(InvocationError):
            await runtime.invoke("backend", "/api/x")
        assert srv.runtime.calls == []  # blackholed before the wire
    finally:
        await runtime.stop()
        await srv.stop()


# ---------------------------------------------------------------------------
# e2e: forced-JSON fallback passes the same AppHost mesh path, and a
# live cross-process JSON-header peer interoperates with a v2 peer
# ---------------------------------------------------------------------------

COMPONENTS = """
apiVersion: dapr.io/v1alpha1
kind: Component
metadata:
  name: statestore
spec:
  type: state.in-memory
  version: v1
"""


@pytest.mark.asyncio
async def test_apphost_pair_forced_json_passes_mesh_e2e(tmp_path, monkeypatch):
    from tasksrunner import load_components

    monkeypatch.setenv("TASKSRUNNER_MESH_CODEC", "json")
    monkeypatch.delenv("TASKSRUNNER_MESH", raising=False)
    (tmp_path / "components.yaml").write_text(COMPONENTS)
    specs = load_components(tmp_path)
    registry = str(tmp_path / "apps.json")

    api = App("backend-api")

    @api.post("/api/echo")
    async def echo(req):
        return {"got": req.json()}

    front = App("frontend")

    @front.get("/go")
    async def go(req):
        resp = await front.client.invoke_method(
            "backend-api", "api/echo", http_method="POST", data={"n": 5})
        resp.raise_for_status()
        return resp.json()

    hosts = [AppHost(api, specs=specs, registry_file=registry),
             AppHost(front, specs=specs, registry_file=registry)]
    for h in hosts:
        await h.start()
    try:
        resp = await hosts[1].client.invoke_method(
            "frontend", "go", http_method="GET")
        assert resp.json() == {"got": {"n": 5}}
        pool = hosts[1].sidecar.runtime._mesh_pool
        conns = [c for c in pool._conns.values() if not c.closed]
        assert conns and all(c.codec is JsonHeaderCodec for c in conns)
    finally:
        for h in hosts:
            await h.stop()


_CHILD_SCRIPT = textwrap.dedent("""
    import asyncio
    import sys

    from tasksrunner import App, AppHost

    async def main():
        app = App("legacy-api")

        @app.post("/api/chain")
        async def chain(req):
            # exercises the REVERSE direction too: this JSON-header
            # peer invokes the v2 peer over the mesh
            resp = await app.client.invoke_method(
                "modern-api", "api/pong", http_method="POST",
                data=req.json())
            resp.raise_for_status()
            return {"child": "json-peer", "parent_said": resp.json()}

        host = AppHost(app, specs=[], registry_file=sys.argv[1])
        await host.start()
        print("READY", flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await host.stop()

    asyncio.run(main())
""")


@pytest.mark.asyncio
async def test_live_cross_process_json_peer_interop(tmp_path, monkeypatch):
    """Rolling-upgrade drill with a real process boundary: the child
    speaks only JSON headers (TASKSRUNNER_MESH_CODEC=json) in both
    directions; the parent is a stock v2 build. One request chains
    parent → child → parent, so both codec mixes ride live sockets."""
    monkeypatch.delenv("TASKSRUNNER_MESH_CODEC", raising=False)
    monkeypatch.delenv("TASKSRUNNER_MESH", raising=False)
    registry = str(tmp_path / "apps.json")
    script = tmp_path / "json_peer.py"
    script.write_text(_CHILD_SCRIPT)

    import tasksrunner
    repo_root = os.path.dirname(os.path.dirname(tasksrunner.__file__))
    env = dict(os.environ, TASKSRUNNER_MESH_CODEC="json")
    env.pop("TASKSRUNNER_MESH", None)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, str(script), registry], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)

    api = App("modern-api")

    @api.post("/api/pong")
    async def pong(req):
        return {"pong": req.json(), "codec": "v2-peer"}

    host = AppHost(api, specs=[], registry_file=registry)
    try:
        line = await asyncio.wait_for(
            asyncio.to_thread(proc.stdout.readline), timeout=60)
        assert line.strip() == "READY", line
        await host.start()
        resp = await host.client.invoke_method(
            "legacy-api", "api/chain", http_method="POST", data={"k": 1})
        assert resp.status == 200
        assert resp.json() == {
            "child": "json-peer",
            "parent_said": {"pong": {"k": 1}, "codec": "v2-peer"}}
        # the parent's connection TO the json-forced peer degraded to
        # v1 headers via the hello (its server acks at version 1)
        pool = host.sidecar.runtime._mesh_pool
        conns = [c for c in pool._conns.values() if not c.closed]
        assert conns and all(c.codec is JsonHeaderCodec for c in conns)
    finally:
        proc.kill()
        proc.wait(timeout=10)
        await host.stop()


# ---------------------------------------------------------------------------
# replication lane inherits the codec
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_replication_lane_negotiates_binary_headers():
    from tasksrunner.state.replmesh import MeshFollowerLink, ReplicationServer

    class Node:
        name, shard = "store", 0

        def position(self):
            return 41, 3

    srv = ReplicationServer()
    await srv.start()
    srv.register(Node())
    link = MeshFollowerLink("store", 0, "m1", "127.0.0.1", srv.port)
    try:
        assert await link.position() == (41, 3)
        assert link._codec is BinaryHeaderCodec
    finally:
        await link.aclose()
        await srv.aclose()
