"""Sharded state plane invariants (tasksrunner/state/sharding.py).

The contract suite in tests/test_state.py already runs the full
CRUD/etag/transact/query battery against a 3-shard facade; this file
covers what sharding adds on top: routing stability, minimal key
movement on reshard, the cross-shard two-phase commit contract, the
``shards: 1`` compatibility promise, and the per-shard saturation
gauges.
"""

import asyncio
import sqlite3

import pytest

from tasksrunner.errors import (
    ComponentError, CrossShardAtomicityError, EtagMismatch, StateError,
)
from tasksrunner.observability.metrics import metrics
from tasksrunner.state import (
    ShardedStateStore, ShardRouter, SqliteStateStore, TransactionOp,
    build_sharded_store,
)

KEYS = [f"task-{i}" for i in range(2000)]


# -- routing ----------------------------------------------------------------

def test_routing_stable_under_fixed_seed():
    """Assignment is a pure function of (key, seed, shards): two router
    instances — two processes, two restarts — must agree on every key,
    or replicas would read shards their peers never wrote."""
    a = ShardRouter(4, "seed-a")
    b = ShardRouter(4, "seed-a")
    assert a.spread(KEYS) == b.spread(KEYS)


def test_routing_golden_snapshot():
    """A pinned sample of assignments: any change to the hash/mix/salt
    scheme strands every existing shard file's keys — it must show up
    as THIS test failing, never as silent data loss after an upgrade."""
    r = ShardRouter(4, "")
    assert r.spread(["task-0", "task-1", "task-2", "task-3", "task-4",
                     "alpha", "beta", "gamma", "", "k"]) == \
        [1, 3, 2, 0, 0, 0, 1, 0, 3, 3]


def test_routing_seed_changes_assignment():
    a = ShardRouter(8, "")
    b = ShardRouter(8, "other")
    assert a.spread(KEYS) != b.spread(KEYS)


def test_routing_balance():
    counts = [0] * 8
    r = ShardRouter(8, "bal")
    for k in KEYS:
        counts[r.shard_of(k)] += 1
    # uniform expectation 250/shard; rendezvous should stay well
    # inside ±40% even on a 2000-key sample
    assert min(counts) > 150 and max(counts) < 350


@pytest.mark.parametrize("n", [1, 2, 4, 7])
def test_minimal_movement_on_reshard(n):
    """Growing N→N+1 must move only the keys the NEW shard wins —
    expected 1/(N+1) of the space. Modulo hashing moves nearly all of
    them; this property is why reshard is an operation, not a rebuild."""
    before = ShardRouter(n, "grow")
    after = ShardRouter(n + 1, "grow")
    moved = [k for k in KEYS
             if before.shard_of(k) != after.shard_of(k)]
    expected = len(KEYS) / (n + 1)
    assert len(moved) < expected * 1.35
    # every moved key moved TO the new shard (salts 0..n-1 unchanged)
    assert all(after.shard_of(k) == n for k in moved)


def test_router_rejects_bad_shard_counts():
    with pytest.raises(ComponentError):
        ShardRouter(0)
    with pytest.raises(ComponentError):
        ShardRouter(-3)
    with pytest.raises(ComponentError):
        ShardRouter(65)


# -- cross-shard transactions ----------------------------------------------

def _cross_shard_keys(store, want=2):
    """First key found on each of ``want`` distinct shards."""
    found = {}
    for k in KEYS:
        found.setdefault(store.router.shard_of(k), k)
        if len(found) >= want:
            break
    return [found[i] for i in sorted(found)][:want]


@pytest.mark.asyncio
async def test_cross_shard_transact_commits_atomically(tmp_path):
    s = build_sharded_store("x", tmp_path / "x.db", shards=3)
    try:
        ka, kb = _cross_shard_keys(s)
        await s.transact([TransactionOp("upsert", ka, {"v": 1}),
                          TransactionOp("upsert", kb, {"v": 2})])
        assert (await s.get(ka)).value == {"v": 1}
        assert (await s.get(kb)).value == {"v": 2}
    finally:
        s.close()


@pytest.mark.asyncio
async def test_cross_shard_transact_aborts_atomically(tmp_path):
    """A stage-phase etag refusal on ANY shard rolls back EVERY shard:
    all-or-nothing holds across files, and the caller sees the
    original EtagMismatch, not an atomicity error (nothing committed)."""
    s = build_sharded_store("x", tmp_path / "x.db", shards=3)
    try:
        ka, kb = _cross_shard_keys(s)
        await s.set(ka, {"v": 0})
        await s.set(kb, {"v": 0})
        with pytest.raises(EtagMismatch):
            await s.transact([
                TransactionOp("upsert", ka, {"v": 9}),
                TransactionOp("upsert", kb, {"v": 9}, etag="999999999"),
            ])
        assert (await s.get(ka)).value == {"v": 0}
        assert (await s.get(kb)).value == {"v": 0}
    finally:
        s.close()


@pytest.mark.asyncio
async def test_cross_shard_transact_concurrent_no_deadlock(tmp_path):
    """Concurrent cross-shard transactions over the same shard pair:
    ascending shard-index staging means ordered lock acquisition —
    they serialize, they never deadlock."""
    s = build_sharded_store("x", tmp_path / "x.db", shards=3)
    try:
        ka, kb = _cross_shard_keys(s)
        await asyncio.wait_for(asyncio.gather(*(
            s.transact([TransactionOp("upsert", ka, {"i": i}),
                        TransactionOp("upsert", kb, {"i": i})])
            for i in range(12))), timeout=30)
        assert (await s.get(ka)).value == (await s.get(kb)).value
    finally:
        s.close()


@pytest.mark.asyncio
async def test_staged_transaction_decision_timeout(tmp_path, monkeypatch):
    """A coordinator that never decides must not wedge the shard: past
    the decision deadline the writer thread rolls back unilaterally
    and a late commit() raises instead of claiming durability."""
    monkeypatch.setattr(SqliteStateStore, "_STAGE_DECISION_TIMEOUT", 0.1)
    s = SqliteStateStore("t", tmp_path / "t.db")
    try:
        txn = await s.stage_transact([TransactionOp("upsert", "k", {"v": 1})])
        await asyncio.sleep(0.4)  # decision deadline passes
        with pytest.raises(StateError):
            await txn.commit()
        assert await s.get("k") is None  # rolled back, nothing durable
        # the shard is NOT wedged: normal writes proceed
        await asyncio.wait_for(s.set("k2", {"v": 2}), timeout=5)
    finally:
        s.close()


@pytest.mark.asyncio
async def test_staged_transaction_holds_commit_slot(tmp_path):
    """While staged, the shard's writer thread is parked: a queued
    write completes only after the decision."""
    s = SqliteStateStore("t", tmp_path / "t.db")
    try:
        txn = await s.stage_transact([TransactionOp("upsert", "k", {"v": 1})])
        queued = asyncio.ensure_future(s.set("other", {"v": 2}))
        done, _pending = await asyncio.wait({queued}, timeout=0.3)
        assert not done  # blocked behind the staged transaction
        await txn.commit()
        await asyncio.wait_for(queued, timeout=5)
        assert (await s.get("k")).value == {"v": 1}
    finally:
        s.close()


def test_cross_shard_atomicity_error_taxonomy():
    """The partial-failure ambiguity surfaces as a StateError subclass
    with a 500, so the sidecar's error mapping needs no special case."""
    assert issubclass(CrossShardAtomicityError, StateError)
    assert CrossShardAtomicityError.http_status == 500


@pytest.mark.asyncio
async def test_cross_shard_needs_staging_support():
    """Children without the staging protocol get a clean taxonomy
    error on cross-shard ops, not an AttributeError mid-commit."""
    from tasksrunner.state.memory import InMemoryStateStore
    s = ShardedStateStore(
        "m", [InMemoryStateStore("m"), InMemoryStateStore("m")])
    ka, kb = _cross_shard_keys(s)
    with pytest.raises(StateError, match="staged"):
        await s.transact([TransactionOp("upsert", ka, {}),
                          TransactionOp("upsert", kb, {})])
    # single-shard transactions still work on any child
    await s.transact([TransactionOp("upsert", ka, {"v": 1})])
    assert (await s.get(ka)).value == {"v": 1}


# -- shards: 1 compatibility ------------------------------------------------

def _build_driver_store(tmp_path, extra_metadata):
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import parse_component
    spec = parse_component({
        "componentType": "state.sqlite",
        "metadata": [
            {"name": "databasePath", "value": str(tmp_path / "s.db")},
            *extra_metadata,
        ],
    }, default_name="st")
    return ComponentRegistry([spec]).get("st")


@pytest.mark.asyncio
async def test_shards_1_is_plain_single_file_store(tmp_path):
    """``shards: 1`` (the default) keeps today's layout and code path:
    a plain SqliteStateStore on the configured file — no facade, no
    -shard0 rename — and the file stays readable by the seed layout."""
    store = _build_driver_store(tmp_path, [{"name": "shards", "value": "1"}])
    try:
        assert type(store) is SqliteStateStore
        assert store.path == str(tmp_path / "s.db")
        await store.set("k", {"v": 1})
    finally:
        store.close()
    assert (tmp_path / "s.db").exists()
    assert not (tmp_path / "s-shard0.db").exists()
    # raw sqlite sees the exact seed schema on the exact configured path
    conn = sqlite3.connect(tmp_path / "s.db")
    try:
        assert conn.execute("SELECT value FROM state WHERE key='k'")\
            .fetchone() == ('{"v":1}',)
    finally:
        conn.close()


@pytest.mark.asyncio
async def test_sharded_driver_builds_facade(tmp_path):
    store = _build_driver_store(tmp_path, [
        {"name": "shards", "value": "4"},
        {"name": "hashSeed", "value": "prod"},
    ])
    try:
        assert isinstance(store, ShardedStateStore)
        assert store.shard_count == 4
        assert store.router.seed == "prod"
        for i, k in enumerate(KEYS[:40]):
            await store.set(k, {"i": i})
        assert len(await store.keys()) == 40
    finally:
        store.close()
    present = sorted(p.name for p in tmp_path.glob("s-shard*.db"))
    assert present == ["s-shard0.db", "s-shard1.db",
                       "s-shard2.db", "s-shard3.db"]


def test_driver_rejects_bad_shard_counts(tmp_path):
    with pytest.raises(ComponentError, match="shards"):
        _build_driver_store(tmp_path, [{"name": "shards", "value": "0"}])
    with pytest.raises(ComponentError, match="shards"):
        _build_driver_store(tmp_path, [{"name": "shards", "value": "65"}])
    with pytest.raises(ComponentError, match="shards"):
        _build_driver_store(tmp_path, [{"name": "shards", "value": "many"}])


# -- observability ----------------------------------------------------------

@pytest.mark.asyncio
async def test_per_shard_queue_depth_gauges(tmp_path):
    """Each shard reports its own write-queue depth: saturation on a
    hot partition must be visible as THAT shard's series."""
    s = build_sharded_store("gaugestore", tmp_path / "g.db", shards=2)
    try:
        await asyncio.gather(*(s.set(k, {"i": 1}) for k in KEYS[:64]))
    finally:
        s.close()
    snap = metrics.snapshot()
    for i in (0, 1):
        assert f"state_write_queue_depth{{shard={i},store=gaugestore}}" in snap


@pytest.mark.asyncio
async def test_standalone_gauge_label_unchanged(tmp_path):
    """A non-sharded store keeps the PR 3 gauge identity (store label
    only) — dashboards keyed on it must not break."""
    s = SqliteStateStore("plaingauge", tmp_path / "p.db")
    try:
        await asyncio.gather(*(s.set(k, {"i": 1}) for k in KEYS[:16]))
    finally:
        s.close()
    assert "state_write_queue_depth{store=plaingauge}" in metrics.snapshot()
