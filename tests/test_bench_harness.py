"""bench.py harness logic that must not depend on host speed or a
live chip: the outage-proof TPU section (probe → bounded retry →
timestamped stale-cache fallback) and the mTLS topology variant.

These are correctness tests for the measurement harness itself — the
wall-clock perf gates live in test_bench.py behind
TASKSRUNNER_PERF_TESTS. The round-4 verdict's top item was a round
whose on-chip number never reached the driver artifact because the
bench gave up after one attempt with no carry-forward; this file pins
the fallback chain so that failure mode cannot return.
"""

import asyncio
import json
import subprocess
import sys

import pytest

sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))
import bench


class _FakeCompleted:
    def __init__(self, rc=0, stdout="", stderr=""):
        self.returncode = rc
        self.stdout = stdout
        self.stderr = stderr


def _no_sleep(monkeypatch):
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)


def test_tpu_section_stale_cache_on_dead_tunnel(tmp_path, monkeypatch):
    """All probes hang → the section embeds the cached on-chip result
    marked stale, with its timestamp and the failure reason."""
    _no_sleep(monkeypatch)
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({
        "measured_at": "2026-07-30T10:30:00+00:00",
        "provenance": "test",
        "result": {"step_ms": 84.3, "mfu": 0.645, "device": "TPU v5 lite"},
    }))
    monkeypatch.setattr(bench, "_TPU_CACHE", cache)

    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 0))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench.run_tpu_section()
    assert len(calls) == 3  # bounded retry, not single-shot, not forever
    assert out["stale"] is True
    assert out["mfu"] == 0.645
    assert out["measured_at"] == "2026-07-30T10:30:00+00:00"
    assert "unresponsive" in out["stale_reason"]


def test_tpu_section_no_cache_returns_none(tmp_path, monkeypatch):
    _no_sleep(monkeypatch)
    monkeypatch.setattr(bench, "_TPU_CACHE", tmp_path / "absent.json")

    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 0))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench.run_tpu_section() is None


def test_tpu_section_fresh_measurement_overwrites_cache(tmp_path,
                                                        monkeypatch):
    """A live chip → fresh result is returned non-stale AND written to
    the cache file for the next outage round."""
    _no_sleep(monkeypatch)
    cache = tmp_path / "cache.json"
    monkeypatch.setattr(bench, "_TPU_CACHE", cache)
    fresh = {"step_ms": 70.0, "mfu": 0.7, "device": "TPU v5 lite",
             "tflops_per_sec": 150.0}

    def fake_run(cmd, **kw):
        if "-c" in cmd:  # the liveness probe
            return _FakeCompleted(stdout="tpu\n")
        return _FakeCompleted(stdout=json.dumps(fresh) + "\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench.run_tpu_section()
    assert out["stale"] is False
    assert out["mfu"] == 0.7
    saved = json.loads(cache.read_text())
    assert saved["result"] == fresh
    assert saved["measured_at"] == out["measured_at"]


def test_tpu_section_recovers_after_one_failed_probe(monkeypatch,
                                                     tmp_path):
    """A single tunnel blip must cost one backoff, not the round's
    number: probe 1 hangs, probe 2 succeeds, the bench runs."""
    _no_sleep(monkeypatch)
    monkeypatch.setattr(bench, "_TPU_CACHE", tmp_path / "cache.json")
    fresh = {"step_ms": 70.0, "mfu": 0.7, "device": "TPU v5 lite"}
    state = {"probes": 0}

    def fake_run(cmd, **kw):
        if "-c" in cmd:
            state["probes"] += 1
            if state["probes"] == 1:
                raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 0))
            return _FakeCompleted(stdout="tpu\n")
        return _FakeCompleted(stdout=json.dumps(fresh) + "\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench.run_tpu_section()
    assert out["stale"] is False and state["probes"] == 2


def test_repo_cache_file_is_valid():
    """The committed cache must stay loadable — it is the artifact's
    fallback leg."""
    assert bench._TPU_CACHE.exists()
    cached = json.loads(bench._TPU_CACHE.read_text())
    assert cached["measured_at"]
    assert cached["result"]["mfu"] > 0
    assert cached["result"]["step_ms"] > 0


def test_xproc_mesh_tls_variant_runs_and_restores_env():
    """The mTLS bench topology: per-app certs provisioned, the run
    completes through the authenticated lane, and the driver's cert
    env vars do not leak into the calling process."""
    import os
    from tasksrunner.invoke.pki import CA_ENV, CERT_ENV, KEY_ENV

    before = {k: os.environ.get(k) for k in (CA_ENV, CERT_ENV, KEY_ENV)}
    out = asyncio.run(bench.run_xproc(
        n_tasks=40, warmup=5, rounds=1, concurrency=16, mesh_tls=True))
    assert out["throughput"] > 0
    after = {k: os.environ.get(k) for k in (CA_ENV, CERT_ENV, KEY_ENV)}
    assert before == after
