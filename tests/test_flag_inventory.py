"""The TASKSRUNNER_* flag inventory stays in sync with reality.

Three parties must agree on the flag set: the code that reads the
variables, the :data:`tasksrunner.envflag.FLAGS` inventory, and the
operator docs. Each pair is asserted here, so a flag can't be added in
one place and forgotten in another — the failure names the missing
entry and where to add it.
"""

import ast
import pathlib
import re
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tasksrunner.envflag import BOOL_FLAGS, FLAGS, Flag, env_flag

_NAME = re.compile(r"^TASKSRUNNER_[A-Z0-9_]+$")


def _flag_literals():
    """Every well-formed TASKSRUNNER_* string literal in the package,
    with the files that contain it. AST-based, so comments and prose
    docstrings don't count."""
    sites = {}
    for path in sorted((REPO / "tasksrunner").rglob("*.py")):
        for node in ast.walk(ast.parse(path.read_text())):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _NAME.match(node.value)):
                sites.setdefault(node.value, set()).add(
                    str(path.relative_to(REPO)))
    return sites


def test_every_flag_read_in_the_package_is_declared():
    undeclared = {
        name: sorted(files)
        for name, files in _flag_literals().items()
        if name not in FLAGS
    }
    assert not undeclared, (
        f"undeclared TASKSRUNNER_* reads {undeclared} — declare them in "
        "tasksrunner/envflag.py FLAGS (name, kind, default, doc)")


def test_every_declared_flag_is_actually_read():
    dead = sorted(set(FLAGS) - set(_flag_literals()))
    assert not dead, (
        f"flags declared but never read anywhere in the package: {dead} "
        "— remove them from FLAGS or wire them up")


def test_every_declared_flag_appears_in_docs():
    docs = "\n".join(
        p.read_text() for p in sorted((REPO / "docs").rglob("*.md")))
    missing = sorted(name for name in FLAGS if name not in docs)
    assert not missing, (
        f"flags missing from docs/: {missing} — add them to the flag "
        "inventory table in docs/modules/31-appendix-variables.md")


def test_inventory_entries_are_well_formed():
    assert list(FLAGS) == sorted(FLAGS), "keep the FLAGS table alphabetical"
    kinds = {"bool", "int", "float", "string", "path", "enum", "json"}
    for name, flag in FLAGS.items():
        assert isinstance(flag, Flag) and flag.name == name
        assert flag.kind in kinds, f"{name}: unknown kind {flag.kind!r}"
        assert flag.doc.strip(), f"{name}: doc line required"
        if flag.kind == "bool":
            assert flag.default in {"on", "off"}, (
                f"{name}: bool defaults are spelled 'on'/'off'")
    assert BOOL_FLAGS == frozenset(
        n for n, f in FLAGS.items() if f.kind == "bool")


def test_env_flag_refuses_undeclared_names():
    with pytest.raises(LookupError, match="TASKSRUNNER_NO_SUCH_FLAG"):
        env_flag("TASKSRUNNER_NO_SUCH_FLAG")
    # non-namespaced names stay permissive (external integrations)
    assert env_flag("SOME_OTHER_TOGGLE", default=True) is True


def test_env_flag_parses_declared_flags(monkeypatch):
    monkeypatch.delenv("TASKSRUNNER_CHAOS", raising=False)
    assert env_flag("TASKSRUNNER_CHAOS", default=False) is False
    for raw, expect in [("1", True), ("true", True), ("ON", True),
                        ("0", False), ("false", False), ("Off", False),
                        ("no", False), ("", False), ("   ", False)]:
        monkeypatch.setenv("TASKSRUNNER_CHAOS", raw)
        assert env_flag("TASKSRUNNER_CHAOS", default=False) is expect, raw
    # empty/unset falls back to the caller's default, whatever it is
    monkeypatch.setenv("TASKSRUNNER_CHAOS", "")
    assert env_flag("TASKSRUNNER_CHAOS", default=True) is True
