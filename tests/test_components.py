"""Component model tests: both YAML dialects, scoping, secrets chain.

Contract source: SURVEY.md §2.4 (component table, dev→prod secret
promotion) and the reference files components/*.yaml vs
aca-components/*.yaml.
"""

import textwrap

import pytest

from tasksrunner import ComponentRegistry, load_component_file, load_components
from tasksrunner.component.spec import SecretRef, parse_component
from tasksrunner.component.registry import driver, registered_types
from tasksrunner.errors import (
    ComponentError,
    ComponentNotFound,
    ComponentScopeError,
    SecretError,
)
from tasksrunner.secrets import SecretResolver, StaticSecretStore

LOCAL_YAML = textwrap.dedent(
    """
    apiVersion: dapr.io/v1alpha1
    kind: Component
    metadata:
      name: statestore
    spec:
      type: test.fake
      version: v1
      metadata:
      - name: url
        value: "http://localhost"
      - name: masterKey
        secretKeyRef:
          name: cosmos-key
          key: cosmos-key
    auth:
      secretStore: teststore
    scopes:
    - tasksmanager-backend-api
    """
)

CLOUD_YAML = textwrap.dedent(
    """
    componentType: test.fake
    version: v1
    metadata:
    - name: accountKey
      secretRef: storage-key
    secrets:
    - name: storage-key
      value: inline-secret-value
    scopes:
    - tasksmanager-backend-processor
    """
)


@driver("test.fake")
class _MemoryComponent:
    """Minimal driver used by these tests (real one comes with the
    state building block)."""

    def __init__(self, spec, metadata):
        self.spec = spec
        self.metadata = metadata
        self.closed = False

    def close(self):
        self.closed = True


def test_parse_local_dialect(tmp_path):
    p = tmp_path / "statestore.yaml"
    p.write_text(LOCAL_YAML)
    (spec,) = load_component_file(p)
    assert spec.name == "statestore"
    assert spec.type == "test.fake"
    assert spec.block == "test"
    assert spec.metadata["url"] == "http://localhost"
    assert spec.metadata["masterKey"] == SecretRef(key="cosmos-key", store="teststore")
    assert spec.scopes == ["tasksmanager-backend-api"]


def test_parse_cloud_dialect_name_from_filename(tmp_path):
    p = tmp_path / "containerapps-statestore.yaml"
    p.write_text(CLOUD_YAML)
    (spec,) = load_component_file(p, name="statestore")
    assert spec.name == "statestore"
    # inline secrets: resolved immediately from the file's secrets list
    assert spec.metadata["accountKey"] == "inline-secret-value"


def test_cloud_dialect_external_secret_ref():
    doc = {
        "componentType": "test.fake",
        "metadata": [{"name": "key", "secretRef": "external-key"}],
        "secretStoreComponent": "kvstore",
    }
    spec = parse_component(doc, default_name="s")
    assert spec.metadata["key"] == SecretRef(key="external-key", store="kvstore")


def test_unknown_schema_rejected():
    with pytest.raises(ComponentError):
        parse_component({"foo": 1}, default_name="x")


def test_malformed_yaml_is_component_error_naming_file(tmp_path):
    """Broken YAML must surface as a ComponentError that names the
    file, not a raw yaml.ParserError from the guts of pyyaml."""
    bad = tmp_path / "broken.yaml"
    bad.write_text("kind: Component\nmetadata: [unterminated")
    with pytest.raises(ComponentError, match="broken.yaml"):
        load_component_file(bad)


def test_load_directory_scope_filter_and_duplicates(tmp_path):
    (tmp_path / "a.yaml").write_text(LOCAL_YAML)
    (tmp_path / "b.yaml").write_text(CLOUD_YAML)
    all_specs = load_components(tmp_path)
    assert {s.name for s in all_specs} == {"statestore", "b"}

    api_view = load_components(tmp_path, app_id="tasksmanager-backend-api")
    assert [s.name for s in api_view] == ["statestore"]

    (tmp_path / "dup.yaml").write_text(LOCAL_YAML)
    with pytest.raises(ComponentError, match="duplicate"):
        load_components(tmp_path)


def test_registry_resolves_secrets_and_scopes(tmp_path):
    (tmp_path / "a.yaml").write_text(LOCAL_YAML)
    resolver = SecretResolver()
    resolver.add_store(StaticSecretStore("teststore", {"cosmos-key": "s3cr3t"}))

    reg = ComponentRegistry(
        load_components(tmp_path),
        app_id="tasksmanager-backend-api",
        secret_resolver=resolver,
    )
    comp = reg.get("statestore", block="test")
    assert comp.metadata == {"url": "http://localhost", "masterKey": "s3cr3t"}

    # wrong building block
    with pytest.raises(ComponentNotFound):
        reg.get("statestore", block="pubsub")

    # out-of-scope app sees nothing
    other = ComponentRegistry(load_components(tmp_path), app_id="frontend")
    with pytest.raises(ComponentNotFound):
        other.get("statestore")


def test_registry_missing_secret_fails_loudly(tmp_path):
    (tmp_path / "a.yaml").write_text(LOCAL_YAML)
    reg = ComponentRegistry(
        load_components(tmp_path), app_id="tasksmanager-backend-api"
    )
    with pytest.raises(SecretError, match="masterKey"):
        reg.get("statestore")


def test_registry_inline_secrets_register_store(tmp_path):
    (tmp_path / "b.yaml").write_text(CLOUD_YAML)
    reg = ComponentRegistry(load_components(tmp_path))
    comp = reg.get("b")
    assert comp.metadata["accountKey"] == "inline-secret-value"


def test_check_scope():
    spec = parse_component(
        {"componentType": "test.fake", "scopes": ["appA"]}, default_name="c"
    )
    reg = ComponentRegistry([spec])
    reg.check_scope("c", "appA")
    with pytest.raises(ComponentScopeError):
        reg.check_scope("c", "appB")


@pytest.mark.asyncio
async def test_registry_close_calls_component_close(tmp_path):
    (tmp_path / "b.yaml").write_text(CLOUD_YAML)
    reg = ComponentRegistry(load_components(tmp_path))
    comp = reg.get("b")
    await reg.close()
    assert comp.closed


def test_secretstore_component_types_registered():
    types = registered_types()
    assert "secretstores.local.env" in types
    assert "secretstores.azure.keyvault" in types  # reference file loads unchanged


def test_env_secret_store_kebab_case(monkeypatch):
    from tasksrunner.secrets import EnvSecretStore

    monkeypatch.setenv("SENDGRID_API_KEY", "k")
    store = EnvSecretStore()
    assert store.get("sendgrid-api-key") == "k"


def test_yaml_bool_scalars_render_lowercase():
    spec = parse_component(
        {
            "componentType": "test.fake",
            "metadata": [{"name": "decodeBase64", "value": True}],
        },
        default_name="c",
    )
    assert spec.metadata["decodeBase64"] == "true"


def test_env_store_prefix_does_not_leak_environment(monkeypatch):
    from tasksrunner.secrets import EnvSecretStore
    from tasksrunner.errors import SecretNotFound

    monkeypatch.setenv("HOME_SWEET", "leak")
    store = EnvSecretStore("s", prefix="TR_")
    with pytest.raises(SecretNotFound):
        store.get("HOME_SWEET")


def test_file_secret_store_nested(tmp_path):
    from tasksrunner.secrets import FileSecretStore

    f = tmp_path / "secrets.json"
    f.write_text('{"SendGrid": {"ApiKey": "abc"}, "flat": "v"}')
    store = FileSecretStore("files", f)
    assert store.get("SendGrid:ApiKey") == "abc"
    assert store.get("flat") == "v"
    assert store.keys() == ["SendGrid:ApiKey", "flat"]
