"""Pub/sub broker contract suite: fan-out per group, competing
consumers, at-least-once redelivery, dead-letter, durable groups.

Contract source: SURVEY.md §2.4/§5.8 — Service Bus topic + per-app
subscription semantics that the reference's processor relies on
(bicep/modules/service-bus.bicep:55-57; ack contract in docs module 5).
"""

import asyncio

import pytest

from tasksrunner.pubsub import InMemoryBroker, SqliteBroker


def make_memory(tmp_path):
    return InMemoryBroker("b", max_attempts=3, retry_delay=0.01)


def make_sqlite(tmp_path):
    return SqliteBroker("b", tmp_path / "broker.db", max_attempts=3,
                        retry_delay=0.01, poll_interval=0.01)


BROKERS = {"memory": make_memory, "sqlite": make_sqlite}


@pytest.fixture(params=sorted(BROKERS))
def broker_factory(request, tmp_path):
    # tests close their brokers themselves (aclose must run on the
    # test's own event loop, which is gone by fixture teardown)
    return lambda: BROKERS[request.param](tmp_path)


async def wait_until(cond, timeout=3.0, interval=0.01):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition not met in time")
        await asyncio.sleep(interval)


@pytest.mark.asyncio
async def test_groups_each_get_a_copy(broker_factory):
    broker = broker_factory()
    got_a, got_b = [], []

    async def ha(msg):
        got_a.append(msg.data)
        return True

    async def hb(msg):
        got_b.append(msg.data)
        return True

    await broker.subscribe("tasksavedtopic", "app-a", ha)
    await broker.subscribe("tasksavedtopic", "app-b", hb)
    await broker.publish("tasksavedtopic", {"n": 1})
    await broker.publish("tasksavedtopic", {"n": 2})
    await wait_until(lambda: len(got_a) == 2 and len(got_b) == 2)
    assert sorted(m["n"] for m in got_a) == [1, 2]
    assert sorted(m["n"] for m in got_b) == [1, 2]
    await broker.aclose()


@pytest.mark.asyncio
async def test_competing_consumers_share_one_group(broker_factory):
    broker = broker_factory()
    got_1, got_2 = [], []

    async def h1(msg):
        got_1.append(msg.data["n"])
        return True

    async def h2(msg):
        got_2.append(msg.data["n"])
        return True

    await broker.subscribe("t", "workers", h1)
    await broker.subscribe("t", "workers", h2)
    for n in range(10):
        await broker.publish("t", {"n": n})
    await wait_until(lambda: len(got_1) + len(got_2) == 10)
    await asyncio.sleep(0.05)
    assert len(got_1) + len(got_2) == 10  # exactly once per group
    assert sorted(got_1 + got_2) == list(range(10))
    await broker.aclose()


@pytest.mark.asyncio
async def test_nack_redelivers_then_dead_letters(broker_factory):
    broker = broker_factory()
    attempts = []

    async def failing(msg):
        attempts.append(msg.attempt)
        return False

    await broker.subscribe("t", "g", failing)
    await broker.publish("t", {"x": 1})
    await wait_until(lambda: len(attempts) >= 3)
    await asyncio.sleep(0.1)
    assert len(attempts) == 3  # max_attempts then dead-letter
    assert attempts == [1, 2, 3]
    await broker.aclose()


@pytest.mark.asyncio
async def test_handler_exception_counts_as_nack(broker_factory):
    broker = broker_factory()
    calls = []

    async def exploding(msg):
        calls.append(msg.attempt)
        if msg.attempt < 2:
            raise RuntimeError("boom")
        return True

    await broker.subscribe("t", "g", exploding)
    await broker.publish("t", {"x": 1})
    await wait_until(lambda: len(calls) == 2)
    await broker.aclose()


@pytest.mark.asyncio
async def test_durable_group_receives_while_consumer_down(broker_factory):
    """Consumers need not be up when messages arrive
    (docs/aca/05-aca-dapr-pubsubapi/index.md:27-29)."""
    broker = broker_factory()
    await broker.ensure_group("t", "g")  # provisioned, no consumer yet
    await broker.publish("t", {"n": 1})

    got = []

    async def h(msg):
        got.append(msg.data["n"])
        return True

    sub = await broker.subscribe("t", "g", h)
    await wait_until(lambda: got == [1])
    await sub.cancel()
    await broker.aclose()


@pytest.mark.asyncio
async def test_no_group_no_delivery(broker_factory):
    """A message published before the group exists is not seen by a
    group created later (Service Bus subscription semantics)."""
    broker = broker_factory()
    await broker.publish("t", {"n": 0})
    got = []

    async def h(msg):
        got.append(msg.data)
        return True

    await broker.subscribe("t", "late-group", h)
    await broker.publish("t", {"n": 1})
    await wait_until(lambda: len(got) == 1)
    assert got == [{"n": 1}]
    await broker.aclose()


@pytest.mark.asyncio
async def test_sqlite_broker_durable_across_reopen(tmp_path):
    b1 = SqliteBroker("b", tmp_path / "broker.db", poll_interval=0.01)
    await b1.ensure_group("t", "g")
    await b1.publish("t", {"n": 42})
    assert b1.backlog("t", "g") == 1
    await b1.aclose()

    b2 = SqliteBroker("b", tmp_path / "broker.db", poll_interval=0.01)
    got = []

    async def h(msg):
        got.append(msg.data["n"])
        return True

    await b2.subscribe("t", "g", h)
    await wait_until(lambda: got == [42])
    assert b2.backlog("t", "g") == 0
    await b2.aclose()


@pytest.mark.asyncio
async def test_sqlite_broker_cross_connection_competing(tmp_path):
    """Two broker objects on the same file (≙ two sidecar processes)
    compete for one group without double-delivery."""
    path = tmp_path / "broker.db"
    b1 = SqliteBroker("b", path, poll_interval=0.01)
    b2 = SqliteBroker("b", path, poll_interval=0.01)
    got_1, got_2 = [], []

    async def h1(msg):
        got_1.append(msg.data["n"])
        return True

    async def h2(msg):
        got_2.append(msg.data["n"])
        return True

    await b1.subscribe("t", "g", h1)
    await b2.subscribe("t", "g", h2)
    for n in range(20):
        await b1.publish("t", {"n": n})
    await wait_until(lambda: len(got_1) + len(got_2) == 20)
    await asyncio.sleep(0.1)
    assert sorted(got_1 + got_2) == list(range(20))
    await b1.aclose()
    await b2.aclose()


@pytest.mark.asyncio
async def test_backlog_and_dead_letters_visible(tmp_path):
    broker = SqliteBroker("b", tmp_path / "broker.db", max_attempts=1,
                          poll_interval=0.01)
    await broker.ensure_group("t", "g")
    await broker.publish("t", {"n": 1})
    assert broker.backlog("t", "g") == 1

    async def failing(msg):
        return False

    sub = await broker.subscribe("t", "g", failing)
    await wait_until(lambda: broker.dead_letters("t", "g") != [])
    assert broker.backlog("t", "g") == 0
    await sub.cancel()
    await broker.aclose()


@pytest.mark.asyncio
async def test_cancel_mid_batch_keeps_acks(tmp_path):
    """Shutdown while a claimed batch is half-processed must not cause
    redelivery of the messages already handled (review regression)."""
    # short claim lease so the interrupted tail becomes visible again
    # quickly after the restart below
    broker = SqliteBroker("b", tmp_path / "b.db", poll_interval=0.01,
                          claim_lease=0.3)
    await broker.ensure_group("t", "g")
    for n in range(6):
        await broker.publish("t", {"n": n})

    handled = []
    block = asyncio.Event()

    async def slow(msg):
        handled.append(msg.data["n"])
        if len(handled) == 3:
            block.set()          # signal: cancel me now
            await asyncio.sleep(30)
        return True

    await broker.subscribe("t", "g", slow)
    await asyncio.wait_for(block.wait(), timeout=5)
    # hard shutdown mid-message-3 (aclose force-cancels the poll task;
    # sub.cancel() would drain gracefully instead)
    await broker.aclose()

    # reopen: the two fully handled messages must NOT come back;
    # message 3 (interrupted) and the unprocessed tail must.
    broker2 = SqliteBroker("b", tmp_path / "b.db", poll_interval=0.01)
    redelivered = []

    async def h(msg):
        redelivered.append(msg.data["n"])
        return True

    await broker2.subscribe("t", "g", h)
    deadline = asyncio.get_running_loop().time() + 5
    while len(redelivered) < 4:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.02)
    assert sorted(redelivered) == [2, 3, 4, 5]
    await broker2.aclose()


def test_pubsub_drivers_registered():
    from tasksrunner.component.registry import registered_types
    types = registered_types()
    assert "pubsub.azure.servicebus" in types  # reference file loads unchanged
    assert "pubsub.redis" in types
    assert "pubsub.in-memory" in types


async def test_sqlite_publish_after_close_raises(tmp_path):
    """Publish after aclose must fail fast, not hang on an unflushed
    future (the group-commit queue has no flusher once the executor is
    shut down)."""
    broker = make_sqlite(tmp_path)
    await broker.publish("t", {"n": 1})
    await broker.aclose()
    with pytest.raises(RuntimeError):
        await asyncio.wait_for(broker.publish("t", {"n": 2}), timeout=2)
    # and again: the failed attempt must not wedge the queue flag
    with pytest.raises(RuntimeError):
        await asyncio.wait_for(broker.publish("t", {"n": 3}), timeout=2)


async def test_sqlite_concurrent_publish_batches(tmp_path):
    """Group-commit: a concurrent burst lands every message exactly
    once per group, in the broker, with futures all resolved."""
    broker = make_sqlite(tmp_path)
    got = []
    done = asyncio.Event()

    async def h(msg):
        got.append(msg.data["n"])
        if len(got) >= 200:
            done.set()
        return True

    await broker.subscribe("t", "g", h)
    await asyncio.gather(*(broker.publish("t", {"n": i}) for i in range(200)))
    await asyncio.wait_for(done.wait(), timeout=10)
    assert sorted(got) == list(range(200))
    await broker.aclose()


async def test_dead_letter_detail_and_requeue(tmp_path):
    """DLQ operator surface: exhausted messages are inspectable with
    full payloads and can be returned to the queue with a fresh
    attempt budget (Service Bus dead-letter resubmission)."""
    broker = make_sqlite(tmp_path)
    calls = []
    healthy = False

    async def handler(msg):
        calls.append(msg.data["n"])
        return healthy

    await broker.subscribe("t", "g", handler)
    await broker.publish("t", {"n": 1})
    await wait_until(lambda: broker.dead_letters("t", "g") != [])

    detail = broker.dead_letter_detail("t", "g")
    assert len(detail) == 1
    assert detail[0]["data"] == {"n": 1}
    assert detail[0]["attempts"] == broker.max_attempts

    # selective requeue with a wrong id touches nothing
    assert broker.requeue_dead_letters("t", "g", msg_ids=["nope"]) == 0
    assert broker.requeue_dead_letters("t", "g", msg_ids=[]) == 0

    healthy = True
    seen = len(calls)
    assert broker.requeue_dead_letters("t", "g") == 1
    await wait_until(lambda: len(calls) > seen)
    assert broker.dead_letters("t", "g") == []
    await broker.aclose()


async def test_open_for_inspection_mirrors_driver_choice(tmp_path):
    """The inspection guard must agree with the redis driver: empty
    redisHost → sqlite fallback is the live store (inspectable);
    non-empty → Redis streams (refused)."""
    from tasksrunner.component.spec import parse_component
    from tasksrunner.errors import ComponentError
    from tasksrunner.pubsub.sqlite import open_for_inspection

    sqlite_backed = parse_component({
        "componentType": "pubsub.redis",
        "metadata": [{"name": "redisHost", "value": ""},
                     {"name": "brokerPath", "value": str(tmp_path / "b.db")}],
    }, default_name="ps")
    broker = open_for_inspection(sqlite_backed, tmp_path, must_exist=False)
    broker.close_sync()

    redis_backed = parse_component({
        "componentType": "pubsub.redis",
        "metadata": [{"name": "redisHost", "value": "localhost:6379"}],
    }, default_name="ps")
    with pytest.raises(ComponentError, match="Redis streams"):
        open_for_inspection(redis_backed, tmp_path)


async def test_broker_janitor_gc(tmp_path):
    """Settled messages are dropped by the janitor so the shared file
    never grows without bound (broker retention)."""
    broker = SqliteBroker("b", tmp_path / "b.db", poll_interval=0.01,
                          gc_interval=0.1, gc_retention=0.0)

    async def h(msg):
        return True

    await broker.subscribe("t", "g", h)
    for i in range(10):
        await broker.publish("t", {"n": i})
    await wait_until(lambda: broker.backlog("t", "g") == 0)

    def rows():
        return broker._conn.execute(
            "SELECT COUNT(*) FROM messages").fetchone()[0]

    await wait_until(lambda: rows() == 0, timeout=5)
    # a message with no subscribing group is undeliverable: gc-able
    await broker.publish("t2", {"n": 99})
    # a pending delivery pins its message
    await broker.ensure_group("t3", "g3")
    await broker.publish("t3", {"n": 100})
    await asyncio.sleep(0.3)
    remaining = {r[0] for r in broker._conn.execute(
        "SELECT topic FROM messages").fetchall()}
    assert "t3" in remaining, "pending messages must never be dropped"
    assert "t2" not in remaining, "undeliverable messages are gc-able"
    await broker.aclose()


async def test_janitor_retains_dead_letters_until_purged(tmp_path):
    """The janitor must NEVER destroy dead letters — the DLQ keeps
    payloads until an operator requeues or purges (Service Bus
    semantics); purge makes them gc-able."""
    broker = SqliteBroker("b", tmp_path / "b.db", poll_interval=0.01,
                          max_attempts=1, retry_delay=0.01,
                          gc_interval=0.1, gc_retention=0.0)

    async def never(msg):
        return False

    await broker.subscribe("t", "g", never)
    await broker.publish("t", {"n": 1})
    await wait_until(lambda: broker.dead_letters("t", "g") != [])
    await asyncio.sleep(0.3)  # several janitor cycles
    detail = broker.dead_letter_detail("t", "g")
    assert detail and detail[0]["data"] == {"n": 1}, \
        "dead letters survived gc with full payload"

    assert broker.purge_dead_letters("t", "g") == 1
    assert broker.dead_letters("t", "g") == []
    await wait_until(
        lambda: broker._conn.execute(
            "SELECT COUNT(*) FROM messages").fetchone()[0] == 0,
        timeout=5)
    await broker.aclose()
