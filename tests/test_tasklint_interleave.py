"""Interleave tasklint: atomic sections, fenced lanes + mechanics.

Same two-layer shape as the program/dataflow test files: seeded-bad
fixtures prove each interleave rule fires, healthy twins prove the
guards and precision filters stay quiet — the asyncio-lock guard, the
etag-threaded CAS write, the monotone epoch fence, the re-check-after-
await fix, the teardown/join idiom, except-handler writes, constructor
rivals, and awaits inside early-exit branches (the shape that
originally false-positived on ``_maybe_promote``). Mechanics tests pin
the v4 labelled-chain contracts (chain-aware suppression, the SARIF
codeFlow round trip), the mtime-proof tree digest behind the
``--changed`` empty-delta short-circuit, the zero-findings regression
over the real tree, and the four-phase wall-time budget.
"""

import io
import json
import os
import pathlib
import subprocess
import sys
import textwrap
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tasksrunner.analysis import engine
from tasksrunner.analysis.cache import _digest_memo, tree_digest
from tasksrunner.analysis.core import INTERLEAVE_RULES, Finding
from tasksrunner.analysis.engine import (
    DEFAULT_TARGET, _program_suppressed, known_rule_ids, run,
)
from tasksrunner.analysis.interleave import InterleaveAnalysis
from tasksrunner.analysis.program import ProgramGraph

INTERLEAVE_ONLY = tuple(sorted(INTERLEAVE_RULES))


def _interleave(tmp_path, sources, rules=INTERLEAVE_ONLY):
    """Run the interleave rules over ``sources`` ({relpath: code})
    with controlled relpaths, through the real suppression filter."""
    files = []
    for name, src in sources.items():
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
        files.append((path, name))
    graph = ProgramGraph.build(files)
    ia = InterleaveAnalysis(graph)
    raw = []
    for rid in rules:
        raw.extend(INTERLEAVE_RULES[rid].check(ia))
    findings = sorted(f for f in raw if not _program_suppressed(graph, f))
    return findings, len(raw) - len(findings)


# -- interleave-check-act -----------------------------------------------


CHECK_ACT_BAD = """\
class Cache:
    def __init__(self):
        self._items = None

    async def refresh(self):
        if self._items is None:
            fresh = await load()
            self._items = fresh

    async def invalidate(self):
        self._items = None


async def load():
    return {}
"""


def test_check_act_across_await_fires(tmp_path):
    findings, _ = _interleave(tmp_path, {"mod.py": CHECK_ACT_BAD},
                              rules=("interleave-check-act",))
    (f,) = findings
    assert f.rule == "interleave-check-act"
    assert (f.path, f.line) == ("mod.py", 6)  # the stale check
    assert "self._items" in f.message
    assert "Cache.invalidate" in f.message  # the rival writer
    # v4 labelled chain: check -> await -> write -> rival
    assert f.chain[0].startswith("mod.py:6 [checks")
    assert "[await opens window]" in f.chain[1]
    assert f.chain[2].startswith("mod.py:8 [writes")
    assert any("Cache.invalidate" in fr for fr in f.chain)


def test_check_act_no_rival_writer_is_quiet(tmp_path):
    # drop the rival: only __init__ and the checker itself write it —
    # constructor writes happen-before any method call, cannot race
    src = CHECK_ACT_BAD.replace(
        "    async def invalidate(self):\n"
        "        self._items = None\n", "")
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    assert findings == []


def test_check_act_guarded_by_asyncio_lock(tmp_path):
    src = """\
    import asyncio


    class Cache:
        def __init__(self):
            self._items = None
            self._lock = asyncio.Lock()

        async def refresh(self):
            async with self._lock:
                if self._items is None:
                    fresh = await load()
                    self._items = fresh

        async def invalidate(self):
            self._items = None


    async def load():
        return {}
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    assert findings == []


def test_check_act_etag_threaded_write_is_quiet(tmp_path):
    src = """\
    class Doc:
        def __init__(self, store):
            self.store = store
            self._cached = None

        async def refresh(self):
            item = await self.store.get("k")
            if self._cached is None:
                doc = await compute()
                self._cached = await self.store.set(
                    "k", doc, etag=item.etag)

        async def drop(self):
            self._cached = None


    async def compute():
        return {}
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    assert findings == []


def test_check_act_monotone_epoch_check_is_quiet(tmp_path):
    src = """\
    class Log:
        def __init__(self):
            self._epoch = 0

        async def fence(self, epoch):
            if epoch >= self._epoch:
                await persist(epoch)
                self._epoch = epoch

        async def reset(self):
            self._epoch = 0


    async def persist(epoch):
        pass
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    assert findings == []


def test_check_act_recheck_after_await_is_the_fix(tmp_path):
    # re-testing the location in the write's own atomic section is the
    # fix the rule recommends — it must recognise it
    src = CHECK_ACT_BAD.replace(
        "            fresh = await load()\n"
        "            self._items = fresh\n",
        "            fresh = await load()\n"
        "            if self._items is None:\n"
        "                self._items = fresh\n")
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    assert findings == []


def test_check_act_join_teardown_idiom_is_quiet(tmp_path):
    src = """\
    class Worker:
        def __init__(self):
            self._task = None

        async def stop(self):
            if self._task is not None:
                await self._task
                self._task = None

        async def start(self):
            self._task = spawn()


    def spawn():
        return None
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    assert findings == []


def test_check_act_except_handler_write_is_quiet(tmp_path):
    # the except-body write acts on the just-caught exception (fresh
    # information), not on the stale branch test
    src = """\
    class Link:
        def __init__(self):
            self._open = True

        async def ship(self, rec):
            if self._open:
                try:
                    await send(rec)
                except OSError:
                    self._open = False

        async def close(self):
            self._open = False


    async def send(rec):
        pass
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    assert findings == []


def test_check_act_await_in_early_exit_branch_is_quiet(tmp_path):
    # the _maybe_promote shape: the re-check's early-exit body itself
    # awaits (surrendering a lease) — that await is NOT a suspension on
    # the fall-through path, so the write right after stays guarded
    src = """\
    class Node:
        def __init__(self):
            self._busy = False

        async def promote(self):
            if self._busy:
                return
            token = await acquire()
            if self._busy:
                await release(token)
                return
            self._busy = True

        async def fence(self):
            self._busy = True


    async def acquire():
        return 1


    async def release(token):
        pass
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    assert findings == []


def test_check_act_cross_function_write_via_callee(tmp_path):
    src = """\
    class Pool:
        def __init__(self):
            self._conn = None

        async def ensure(self):
            if self._conn is None:
                await probe()
                await self._connect()

        async def _connect(self):
            self._conn = await dial()

        async def reset(self):
            self._conn = None


    async def probe():
        pass


    async def dial():
        return object()
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("interleave-check-act",))
    (f,) = findings
    assert (f.path, f.line) == ("mod.py", 6)
    assert any("[write inside callee]" in fr for fr in f.chain)
    assert "also writes" in f.message  # a rival (reset or the callee)


def test_check_act_suppression_on_chain_frame(tmp_path):
    # labelled v4 frames must still resolve for chain-aware
    # suppression — disable on the WRITE line, report is on the check
    src = CHECK_ACT_BAD.replace(
        "            self._items = fresh",
        "            self._items = fresh"
        "  # tasklint: disable=interleave-check-act")
    findings, suppressed = _interleave(tmp_path, {"mod.py": src},
                                       rules=("interleave-check-act",))
    assert findings == [] and suppressed == 1


# -- fenced-etag-origin -------------------------------------------------


def test_fenced_etag_cached_token_fires(tmp_path):
    src = """\
    class Lane:
        def __init__(self, store):
            self.store = store
            self._etag = None

        async def commit(self, doc):  # tasklint: fenced-lane
            await self.store.set("k", doc, etag=self._etag)
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("fenced-etag-origin",))
    (f,) = findings
    assert f.rule == "fenced-etag-origin"
    assert "same atomic scope" in f.message or "cached" in f.message
    assert any("[fenced lane]" in fr for fr in f.chain)


def test_fenced_etag_constant_token_fires(tmp_path):
    src = """\
    class Lane:
        def __init__(self, store):
            self.store = store

        async def commit(self, doc):  # tasklint: fenced-lane
            await self.store.set("k", doc, etag="42")
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("fenced-etag-origin",))
    (f,) = findings
    assert "constant" in f.message


def test_fenced_etag_threaded_from_read_is_quiet(tmp_path):
    src = """\
    class Lane:
        def __init__(self, store):
            self.store = store

        async def commit(self, doc):  # tasklint: fenced-lane
            item = await self.store.get("k")
            await self.store.set("k", doc, etag=item.etag)
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("fenced-etag-origin",))
    assert findings == []


def test_fenced_etag_unmarked_lane_is_out_of_scope(tmp_path):
    src = """\
    class Lane:
        def __init__(self, store):
            self.store = store

        async def commit(self, doc):
            await self.store.set("k", doc, etag=None)
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("fenced-etag-origin",))
    assert findings == []


# -- fenced-epoch-monotone ----------------------------------------------


def test_fenced_epoch_equality_fires(tmp_path):
    src = """\
    class Lane:
        def __init__(self):
            self._epoch = 0

        async def append(self, rec, epoch):  # tasklint: fenced-lane
            if epoch == self._epoch:
                await write(rec)


    async def write(rec):
        pass
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("fenced-epoch-monotone",))
    (f,) = findings
    assert f.rule == "fenced-epoch-monotone"
    assert "Eq" in f.message
    assert any("non-monotone" in fr for fr in f.chain)


def test_fenced_epoch_monotone_is_quiet(tmp_path):
    src = """\
    class Lane:
        def __init__(self):
            self._epoch = 0

        async def append(self, rec, epoch):  # tasklint: fenced-lane
            if epoch >= self._epoch:
                await write(rec)


    async def write(rec):
        pass
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("fenced-epoch-monotone",))
    assert findings == []


def test_fenced_epoch_word_boundary(tmp_path):
    # "terminate" contains "term"; a method-name dispatch compare is
    # not an epoch fence
    src = """\
    class Lane:
        async def handle(self, method):  # tasklint: fenced-lane
            if method == "terminate":
                await stop()


    async def stop():
        pass
    """
    findings, _ = _interleave(tmp_path, {"mod.py": src},
                              rules=("fenced-epoch-monotone",))
    assert findings == []


# -- mechanics ----------------------------------------------------------


def test_sarif_codeflow_parses_labelled_frames():
    from tasksrunner.analysis.sarif import to_sarif
    f = Finding(path="a.py", line=4, col=1, rule="interleave-check-act",
                message="m",
                chain=("a.py:4 [checks self._x]",
                       "a.py:5 [await opens window]",
                       "b.py:9 [also written by C.w]"))
    doc = to_sarif([f], {"interleave-check-act": "doc"})
    (result,) = doc["runs"][0]["results"]
    steps = result["codeFlows"][0]["threadFlows"][0]["locations"]
    locs = [(s["location"]["physicalLocation"]["artifactLocation"]["uri"],
             s["location"]["physicalLocation"]["region"]["startLine"])
            for s in steps]
    assert locs == [("a.py", 4), ("a.py", 5), ("b.py", 9)]
    # the label survives as the step message
    assert steps[0]["location"]["message"]["text"].endswith(
        "[checks self._x]")


def test_tree_digest_is_mtime_proof(tmp_path):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("x = 1\n")
    b.write_text("y = 2\n")
    before = tree_digest([a, b])
    os.utime(a, ns=(1, 1))  # touch: mtime churn, identical bytes
    os.utime(b, ns=(2, 2))
    _digest_memo.clear()  # a fresh process has no per-run memo
    assert tree_digest([a, b]) == before
    a.write_text("x = 3\n")
    _digest_memo.clear()
    assert tree_digest([a, b]) != before


def test_changed_empty_delta_short_circuits_to_cache(
        tmp_path, monkeypatch, capfd):
    """`lint --changed` with an empty git delta must not rebuild the
    whole-tree phases: the content-only tree digest survives the mtime
    churn of a branch switch, so the second run is pure cache."""
    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(["git", "-C", str(repo), *args],
                       check=True, capture_output=True)

    git("init", "-q")
    git("symbolic-ref", "HEAD", "refs/heads/main")
    git("config", "user.email", "t@example.com")
    git("config", "user.name", "t")
    (repo / "mod.py").write_text(textwrap.dedent(CHECK_ACT_BAD))
    git("add", ".")
    git("commit", "-qm", "seed")

    monkeypatch.setattr(engine, "REPO_ROOT", repo)
    cache = tmp_path / "cache.json"
    base = tmp_path / "baseline.json"
    argv = ["--changed", "--cache", str(cache), "--baseline", str(base),
            str(repo)]
    rc = engine.main(argv)
    capfd.readouterr()
    assert rc == 1  # the seeded window is a real finding

    # branch-switch simulation: every mtime churns, bytes identical
    for p in repo.rglob("*.py"):
        os.utime(p, ns=(7, 7))
    _digest_memo.clear()

    def bomb(files):
        raise AssertionError("whole-tree phase rebuilt on empty delta")

    monkeypatch.setattr(engine, "build_graph", bomb)
    rc = engine.main(argv)
    text = capfd.readouterr().out
    assert rc == 1
    assert "cached" in text


def test_real_tree_has_zero_interleave_findings(tmp_path):
    """The runtime itself must satisfy its own interleaving rules with
    an empty baseline — genuine windows get fixed, not suppressed."""
    rc = run([DEFAULT_TARGET], INTERLEAVE_ONLY,
             cache_path=None, out=io.StringIO())
    assert rc == 0


def test_four_phase_wall_time_budget(tmp_path):
    """`make test`'s lint leg must stay usable interactively with the
    interleave phase aboard: cold under 40s, warm (tree digest
    unchanged) under 6s for all four phases over the whole package."""
    all_rules = tuple(sorted(known_rule_ids()))
    cache_file = tmp_path / "cache.json"
    t0 = time.perf_counter()
    rc = run([DEFAULT_TARGET], all_rules, cache_path=cache_file,
             out=io.StringIO())
    cold = time.perf_counter() - t0
    assert rc == 0
    t0 = time.perf_counter()
    rc = run([DEFAULT_TARGET], all_rules, cache_path=cache_file,
             out=io.StringIO())
    warm = time.perf_counter() - t0
    assert rc == 0
    assert cold < 40.0, f"cold four-phase lint took {cold:.1f}s"
    assert warm < 6.0, f"warm four-phase lint took {warm:.1f}s"


def test_json_schema_v4(tmp_path):
    out = io.StringIO()
    rc = run([DEFAULT_TARGET], INTERLEAVE_ONLY, cache_path=None,
             json_out=True, out=out)
    assert rc == 0
    doc = json.loads(out.getvalue())
    assert doc["version"] == 4
    assert doc["findings"] == []
