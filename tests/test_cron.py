"""Cron schedule engine tests (reference schedule: `5 0 * * *`,
components/dapr-scheduled-cron.yaml)."""

import asyncio
import datetime as dt

import pytest

from tasksrunner.bindings.cron import CronBinding, CronSchedule
from tasksrunner.errors import BindingError


def d(*args):
    return dt.datetime(*args)


def test_reference_schedule_daily_0005():
    s = CronSchedule("5 0 * * *")
    assert s.next_after(d(2026, 7, 29, 12, 0)) == d(2026, 7, 30, 0, 5)
    assert s.next_after(d(2026, 7, 29, 0, 4)) == d(2026, 7, 29, 0, 5)
    assert s.next_after(d(2026, 7, 29, 0, 5)) == d(2026, 7, 30, 0, 5)  # strictly after


def test_steps_ranges_lists():
    s = CronSchedule("*/15 * * * *")
    assert s.next_after(d(2026, 1, 1, 10, 0)) == d(2026, 1, 1, 10, 15)
    assert s.next_after(d(2026, 1, 1, 10, 50)) == d(2026, 1, 1, 11, 0)
    s = CronSchedule("0 9-17 * * *")
    assert s.next_after(d(2026, 1, 1, 18, 0)) == d(2026, 1, 2, 9, 0)
    s = CronSchedule("0 0 1,15 * *")
    assert s.next_after(d(2026, 1, 2, 0, 0)) == d(2026, 1, 15, 0, 0)


def test_month_and_dow_names():
    s = CronSchedule("0 0 * jan *")
    assert s.next_after(d(2026, 2, 1, 0, 0)) == d(2027, 1, 1, 0, 0)
    s = CronSchedule("30 8 * * mon")
    nxt = s.next_after(d(2026, 7, 29, 9, 0))  # Wednesday
    assert nxt == d(2026, 8, 3, 8, 30)  # next Monday
    assert nxt.weekday() == 0


def test_dow_sunday_as_0_and_7():
    for expr in ("0 0 * * 0", "0 0 * * 7", "0 0 * * sun"):
        nxt = CronSchedule(expr).next_after(d(2026, 7, 29, 0, 0))
        assert nxt.weekday() == 6  # python Sunday


def test_dom_dow_or_rule():
    # standard cron: if both dom and dow are restricted, either matches
    s = CronSchedule("0 0 13 * fri")
    nxt = s.next_after(d(2026, 7, 29, 0, 0))
    # July 31 2026 is a Friday, before Aug 13
    assert nxt == d(2026, 7, 31, 0, 0)


def test_six_field_form_accepted():
    s = CronSchedule("0 5 0 * * *")
    assert s.next_after(d(2026, 7, 29, 12, 0)) == d(2026, 7, 30, 0, 5)


def test_every_shorthand():
    s = CronSchedule("@every 5s")
    assert s.interval == 5.0
    assert CronSchedule("@every 500ms").interval == 0.5
    assert CronSchedule("@every 2m").interval == 120.0


@pytest.mark.parametrize("bad", [
    "* * * *",              # 4 fields
    "61 * * * *",           # out of range
    "* * 0 * *",            # dom 0
    "a b c d e",
    "@every 5parsecs",
    "*/0 * * * *",          # zero step
    "0 0 30-10 * *",        # inverted range
])
def test_malformed_rejected(bad):
    with pytest.raises(BindingError):
        CronSchedule(bad)


@pytest.mark.asyncio
async def test_cron_binding_fires_and_stops():
    fired = []
    binding = CronBinding("ScheduledTasksManager", "@every 30ms")

    async def sink(event):
        fired.append(event)
        return True

    await binding.start(sink)
    await asyncio.sleep(0.2)
    await binding.stop()
    count = len(fired)
    assert count >= 3
    assert fired[0].binding == "ScheduledTasksManager"
    await asyncio.sleep(0.1)
    assert len(fired) == count  # nothing after stop
