"""Durable replay-based workflows (docs module 21).

The multi-replica tests mirror tests/test_actors.py: several
``Runtime`` objects built by hand around ONE shared durable store, so
N replicas of the same app are modeled without OS processes, and
failover is deterministic (``simulate_crash`` + short leases + explicit
``sweep()`` calls). The two acceptance drills are at the bottom:

* ``crashEveryN`` chaos felling the workflow owner mid-activity on an
  RF≥2 replicated store — replay converges on the adopting replica,
  every activity effect lands exactly once, compensations fire exactly
  once in reverse order, and no acked effect is lost even after the
  store's shard leader is itself crashed (``lost_acked_keys == []``).
* a cross-process ``kill -9`` of the workflow owner's OS process on a
  shared sqlite store, with history continuity proven on the replica
  that adopts the instance.
"""

import asyncio
import os
import random as random_mod
import sys
import time
import uuid as uuid_mod

import pytest

from tasksrunner.app import App
from tasksrunner.chaos.engine import ChaosPolicies
from tasksrunner.chaos.spec import parse_chaos
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import (
    ValidationError,
    WorkflowError,
    WorkflowNotFound,
)
from tasksrunner.observability.metrics import metrics
from tasksrunner.resiliency.policy import RetrySpec
from tasksrunner.runtime import InProcAppChannel, Runtime
from tasksrunner.state.memory import InMemoryStateStore
from tasksrunner.state.replication import build_replicated_store
from tasksrunner.workflows import WORKFLOW_ACTOR_TYPE

LEASE = 0.25
#: fast cadence for tests — the production default (2 s) would make
#: every adoption-driven step crawl
DRIVE = 0.1


@pytest.fixture
def wf_env(monkeypatch):
    monkeypatch.setenv("TASKSRUNNER_WORKFLOWS", "1")
    monkeypatch.setenv("TASKSRUNNER_ACTOR_LEASE_SECONDS", "5")
    # background sweep effectively disabled: every sweep in a test is
    # an explicit, deterministic sweep() call
    monkeypatch.setenv("TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS", "30")


def build_app(app_id="svc", log=None):
    """One app with every scenario workflow; ``log`` collects activity
    body executions as (kind, payload) tuples across ALL replicas."""
    app = App(app_id)
    log = log if log is not None else []

    @app.workflow("simple")
    async def simple(ctx, inp):
        a = await ctx.call_activity("add", {"x": inp, "y": 1})
        b = await ctx.call_activity("add", {"x": a, "y": 10})
        return b

    @app.activity("add")
    async def add(actx, data):
        log.append(("add", actx.seq))
        actx.stage_effect(f"eff||{actx.instance}||{actx.seq}", data)
        return data["x"] + data["y"]

    @app.workflow("fanout")
    async def fanout(ctx, n):
        tasks = [ctx.call_activity("add", {"x": i, "y": 0})
                 for i in range(n)]
        return sum(await ctx.when_all(tasks))

    @app.workflow("order")
    async def order(ctx, n):
        for i in range(n):
            await ctx.call_activity("reserve", {"i": i})
            ctx.register_compensation("release", {"i": i})
        await ctx.call_activity("charge", None)
        return "paid"

    @app.activity("reserve")
    async def reserve(actx, data):
        log.append(("reserve", data["i"]))
        actx.stage_effect(f"res||{actx.instance}||{data['i']}", data)
        return data["i"]

    @app.activity("release")
    async def release(actx, data):
        log.append(("release", data["i"]))
        actx.stage_effect(f"rel||{actx.instance}||{data['i']}", data)
        return data["i"]

    @app.activity("charge", retry=RetrySpec(duration=0.01, max_retries=1))
    async def charge(actx, data):
        log.append(("charge", actx.attempt))
        raise RuntimeError("card declined")

    @app.workflow("fallback")
    async def fallback(ctx, inp):
        from tasksrunner.errors import ActivityError
        try:
            return await ctx.call_activity("charge", None)
        except ActivityError as exc:
            return {"fallback": True, "cause": str(exc)}

    @app.workflow("parent")
    async def parent(ctx, inp):
        c1 = ctx.call_child("simple", 5)
        c2 = ctx.call_child("simple", 50)
        return await ctx.when_all([c1, c2])

    @app.workflow("waiter")
    async def waiter(ctx, inp):
        data = await ctx.wait_event("go")
        return {"got": data}

    @app.workflow("timed")
    async def timed(ctx, inp):
        log.append(("orchestrate", "timed"))
        u1 = ctx.uuid4()
        await ctx.sleep(0.15)
        return [u1, ctx.uuid4(), ctx.now()]

    @app.workflow("racer")
    async def racer(ctx, inp):
        winner = await ctx.when_any(
            [ctx.wait_event("a"), ctx.wait_event("b")])
        return winner.value

    @app.workflow("rogue")
    async def rogue(ctx, inp):
        await asyncio.sleep(0.01)  # forbidden: a foreign awaitable
        return "never"

    @app.workflow("lost")
    async def lost(ctx, inp):
        return await ctx.call_activity("no-such-activity", None)

    app.state["log"] = log
    return app


def make_runtime(shared, *, app_id="svc", chaos=None, crash_on_chaos=False,
                 lease=LEASE, log=None):
    spec = ComponentSpec(name="statestore", type="state.in-memory")
    reg = ComponentRegistry([spec], app_id=app_id)
    reg._instances["statestore"] = shared
    rt = Runtime(app_id, reg,
                 app_channel=InProcAppChannel(build_app(app_id, log)),
                 chaos=chaos)
    if crash_on_chaos:
        rt._actor_crash_on_chaos = True
    rt._test_lease = lease
    return rt


async def start_all(*rts):
    for rt in rts:
        await rt.start()
        assert rt.actors is not None and rt.workflows is not None
        if rt._test_lease is not None:
            rt.actors.lease_seconds = rt._test_lease
        rt.app_channel.app.workflow_engine.drive_period = DRIVE


async def shutdown(*rts):
    for rt in rts:
        if rt.actors is not None:
            if rt.workflows is not None:
                rt.workflows.detach()
                rt.workflows = None
            await rt.actors.stop()
            rt.actors = None
    for rt in rts:
        await rt.stop()


async def adopt_until(rt, instance, *, timeout=8.0):
    """Sweep-driven convergence: what a real cluster's periodic sweep
    does, compressed into an explicit loop."""
    deadline = time.monotonic() + timeout
    while True:
        await rt.actors.sweep()
        status = await rt.workflows.status(instance)
        if status["status"] in ("completed", "failed", "terminated"):
            return status
        assert time.monotonic() < deadline, \
            f"instance {instance} never converged: {status}"
        await asyncio.sleep(0.05)


# -- registration ----------------------------------------------------------


def test_workflow_decorator_rejects_sync_orchestrators():
    app = App("svc")
    with pytest.raises(ValidationError):
        @app.workflow("bad")
        def bad(ctx, inp):  # noqa: ARG001 - shape under test
            return None


def test_activity_decorator_rejects_sync_handlers():
    app = App("svc")
    with pytest.raises(ValidationError):
        @app.activity("bad")
        def bad(actx, data):  # noqa: ARG001 - shape under test
            return None


def test_duplicate_registration_rejected():
    app = App("svc")

    @app.workflow("dup")
    async def one(ctx, inp):
        return None

    with pytest.raises(WorkflowError):
        @app.workflow("dup")
        async def two(ctx, inp):
            return None

    @app.activity("dup-act")
    async def act_one(actx, data):
        return None

    with pytest.raises(WorkflowError):
        @app.activity("dup-act")
        async def act_two(actx, data):
            return None


# -- the basic scenarios ---------------------------------------------------


async def test_sequential_workflow_exact_once_effects(wf_env):
    shared = InMemoryStateStore("statestore")
    log = []
    rt = make_runtime(shared, log=log)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("simple", 100)
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "completed" and status["result"] == 111
        # each activity body ran once; each staged effect landed once
        assert log == [("add", 1), ("add", 2)]
        for seq in (1, 2):
            item = await shared.get(f"svc||eff||{inst}||{seq}")
            assert item is not None, f"missing effect for seq {seq}"
        history = await rt.workflows.history(inst)
        assert [e["t"] for e in history] == [
            "started", "activity_completed", "activity_completed",
            "completed"]
    finally:
        await shutdown(rt)


async def test_fanout_fanin(wf_env):
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("fanout", 7)
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "completed" and status["result"] == 21
    finally:
        await shutdown(rt)


async def test_saga_compensates_reverse_order(wf_env):
    shared = InMemoryStateStore("statestore")
    log = []
    rt = make_runtime(shared, log=log)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("order", 3)
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "failed"
        assert "card declined" in status["error"]
        # compensations: exactly once each, reverse registration order
        releases = [p for kind, p in log if kind == "release"]
        assert releases == [2, 1, 0]
        history = await rt.workflows.history(inst)
        comp = [e for e in history if e["t"] == "compensated"]
        assert [e["idx"] for e in comp] == [2, 1, 0]
        assert all("error" not in e for e in comp)
    finally:
        await shutdown(rt)


async def test_orchestrator_can_catch_activity_error(wf_env):
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("fallback", None)
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "completed"
        assert status["result"]["fallback"] is True
        assert "card declined" in status["result"]["cause"]
    finally:
        await shutdown(rt)


async def test_child_workflows_fan_out(wf_env):
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("parent", None)
        status = await rt.workflows.wait(inst, timeout=10)
        assert status["status"] == "completed"
        assert status["result"] == [16, 61]
        # deterministic child ids: idempotent restarts re-find them
        child = await rt.workflows.status(f"{inst}::c1")
        assert child["status"] == "completed" and child["parent"] == inst
    finally:
        await shutdown(rt)


async def test_external_event_and_duplicate_delivery(wf_env):
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("waiter", None)
        assert (await rt.workflows.status(inst))["status"] == "running"
        await rt.workflows.raise_event(inst, "go", data={"n": 1}, id="e-1")
        # duplicate delivery by id: dropped, not buffered twice
        await rt.workflows.raise_event(inst, "go", data={"n": 1}, id="e-1")
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "completed"
        assert status["result"] == {"got": {"n": 1}}
        history = await rt.workflows.history(inst)
        assert len([e for e in history if e["t"] == "event_raised"]) == 1
    finally:
        await shutdown(rt)


async def test_when_any_winner_is_replay_stable(wf_env):
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("racer", None)
        await rt.workflows.raise_event(inst, "b", data="b wins")
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "completed"
        assert status["result"] == "b wins"
        # the loser landing later must not flip the recorded verdict
        await rt.workflows.raise_event(inst, "a", data="a late")
        assert (await rt.workflows.status(inst))["result"] == "b wins"
    finally:
        await shutdown(rt)


async def test_durable_timer_and_deterministic_randomness(wf_env):
    """A timer suspends the instance across turns, so the orchestrator
    provably replays (it runs more than once) — yet the pre-timer
    uuid survives replay unchanged because ctx randomness is seeded
    from the instance identity, and ctx.now() comes from history."""
    shared = InMemoryStateStore("statestore")
    log = []
    rt = make_runtime(shared, log=log)
    await start_all(rt)
    try:
        t0 = time.time()
        inst = await rt.workflows.start("timed", "x")
        assert (await rt.workflows.status(inst))["status"] == "running"
        status = await adopt_until(rt, inst)
        assert status["status"] == "completed"
        u1, u2, wf_now = status["result"]
        rng = random_mod.Random(f"wf:timed:{inst}")
        assert u1 == str(uuid_mod.UUID(int=rng.getrandbits(128), version=4))
        assert u2 == str(uuid_mod.UUID(int=rng.getrandbits(128), version=4))
        assert t0 <= wf_now <= time.time()
        # replay happened: the orchestrator body ran at least twice
        replays = [p for kind, p in log if kind == "orchestrate"]
        assert len(replays) >= 2
        # and the durable timer left exactly one fired event
        history = await rt.workflows.history(inst)
        assert len([e for e in history if e["t"] == "timer_fired"]) == 1
    finally:
        await shutdown(rt)


async def test_nondeterminism_foreign_await_fails_cleanly(wf_env):
    shared = InMemoryStateStore("statestore")
    log = []
    rt = make_runtime(shared, log=log)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("rogue", None)
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "failed"
        assert "foreign awaitable" in status["error"]
        # fail-fast, not compensate: no activity ever ran
        assert log == []
    finally:
        await shutdown(rt)


async def test_unregistered_activity_fails_workflow(wf_env):
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("lost", None)
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "failed"
        assert "no-such-activity" in status["error"]
    finally:
        await shutdown(rt)


async def test_terminate_and_listing(wf_env):
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        inst = await rt.workflows.start("waiter", None)
        await rt.workflows.terminate(inst, reason="operator said no")
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "terminated"
        assert status["error"] == "operator said no"
        rows = await rt.workflows.list()
        assert [r["instance"] for r in rows] == [inst]
        with pytest.raises(WorkflowNotFound):
            await rt.workflows.status("no-such-instance")
    finally:
        await shutdown(rt)


async def test_history_gc_truncates_terminal_instances(wf_env):
    shared = InMemoryStateStore("statestore")
    rt = make_runtime(shared)
    await start_all(rt)
    try:
        rt.app_channel.app.workflow_engine.retain_seconds = 0.1
        inst = await rt.workflows.start("simple", 1)
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "completed" and status["events"] == 4
        await asyncio.sleep(0.15)
        await rt.actors.sweep()  # fires the one-shot GC reminder
        status = await rt.workflows.status(inst)
        assert status["events"] == 1  # only the terminal stub remains
        assert status["status"] == "completed" and status["result"] == 12
        history = await rt.workflows.history(inst)
        assert history[0]["t"] == "completed"
    finally:
        await shutdown(rt)


# -- surfacing: sidecar routes ---------------------------------------------


async def test_sidecar_workflow_routes_gated_off(monkeypatch):
    monkeypatch.delenv("TASKSRUNNER_WORKFLOWS", raising=False)
    from tasksrunner.sidecar import build_sidecar_app

    app = build_sidecar_app(make_runtime(InMemoryStateStore("statestore")),
                            api_token=None, peer_tokens=set())
    assert not any("/v1.0/workflows" in str(r.resource.canonical)
                   for r in app.router.routes() if r.resource is not None)


async def test_sidecar_registers_actor_routes_under_workflows_flag(wf_env):
    """Workflow instances are actors, and a non-owning replica forwards
    turns to the owner through the /v1.0/actors routes — so the
    workflows flag alone must open the actor route gate, or every
    cross-replica workflow operation 404s at the owner's sidecar."""
    from tasksrunner.sidecar import build_sidecar_app

    app = build_sidecar_app(make_runtime(InMemoryStateStore("statestore")),
                            api_token=None, peer_tokens=set())
    assert any("/v1.0/actors" in str(r.resource.canonical)
               for r in app.router.routes() if r.resource is not None)


async def test_sidecar_workflow_api_end_to_end(wf_env):
    import aiohttp

    from tasksrunner.sidecar import Sidecar

    rt = make_runtime(InMemoryStateStore("statestore"))
    sc = Sidecar(rt, port=0)
    await sc.start()
    rt.app_channel.app.workflow_engine.drive_period = DRIVE
    try:
        base = f"http://127.0.0.1:{sc.port}"
        async with aiohttp.ClientSession() as session:
            resp = await session.post(
                f"{base}/v1.0/workflows/engine/simple/start",
                params={"instanceID": "http-1"}, json=100)
            assert resp.status == 200
            assert (await resp.json())["instanceID"] == "http-1"
            await rt.workflows.wait("http-1", timeout=5)
            resp = await session.get(f"{base}/v1.0/workflows/engine/http-1")
            doc = await resp.json()
            assert doc["status"] == "completed" and doc["result"] == 111
            resp = await session.get(
                f"{base}/v1.0/workflows/engine/http-1/history")
            assert [e["t"] for e in (await resp.json())["history"]][0] \
                == "started"

            resp = await session.post(
                f"{base}/v1.0/workflows/engine/waiter/start",
                params={"instanceID": "http-2"}, json=None)
            assert resp.status == 200
            resp = await session.post(
                f"{base}/v1.0/workflows/engine/http-2/raiseEvent/go",
                params={"eventID": "e1"}, json={"n": 2})
            assert resp.status == 202
            status = await rt.workflows.wait("http-2", timeout=5)
            assert status["result"] == {"got": {"n": 2}}

            resp = await session.post(
                f"{base}/v1.0/workflows/engine/waiter/start",
                params={"instanceID": "http-3"}, json=None)
            resp = await session.post(
                f"{base}/v1.0/workflows/engine/http-3/terminate",
                json={"reason": "done testing"})
            assert resp.status == 202
            status = await rt.workflows.wait("http-3", timeout=5)
            assert status["status"] == "terminated"

            resp = await session.get(f"{base}/v1.0/workflows")
            rows = (await resp.json())["instances"]
            assert {r["instance"] for r in rows} == \
                {"http-1", "http-2", "http-3"}

            resp = await session.get(f"{base}/v1.0/workflows/engine/ghost")
            assert resp.status == 404
    finally:
        await sc.stop()


# -- failover (in-proc replicas) -------------------------------------------


async def test_owner_crash_mid_run_replica_adopts(wf_env):
    """Plain simulate_crash (no chaos): the owner dies between turns,
    the survivor adopts via sweep and finishes the run — effects from
    the committed prefix are not re-applied."""
    shared = InMemoryStateStore("statestore")
    log = []
    r1 = make_runtime(shared, log=log)
    r2 = make_runtime(shared, log=log)
    await start_all(r1, r2)
    try:
        inst = await r1.workflows.start("fanout", 5)
        # fanout completes within the start pump — use a timer-blocked
        # one instead for a genuine mid-run crash
        inst2 = await r1.workflows.start("timed", None)
        assert (await r1.workflows.status(inst2))["status"] == "running"
        r1.actors.simulate_crash()
        await asyncio.sleep(LEASE + 0.1)
        status = await adopt_until(r2, inst2)
        assert status["status"] == "completed"
        assert (await r2.workflows.status(inst))["status"] == "completed"
    finally:
        await shutdown(r2, r1)


# -- THE chaos acceptance drill --------------------------------------------

CHAOS_YAML_DOC = {
    "apiVersion": "tasksrunner/v1alpha1",
    "kind": "Chaos",
    "metadata": {"name": "wf-drill"},
    "spec": {
        "seed": 7,
        "faults": {"fell-owner": {"crashEveryN": {"n": 2,
                                                  "raise": "OSError"}}},
        "targets": {"workflows": {"order/reserve": ["fell-owner"]}},
    },
}


async def test_chaos_crash_mid_activity_rf2_exactly_once(wf_env, tmp_path):
    """THE acceptance drill: a declarative ``crashEveryN`` rule on
    ``workflows.order/reserve`` fells the owning replica mid-activity,
    on an RF=2 replicated store. The surviving replica adopts the
    instance, replay converges from the committed prefix, every forward
    effect lands exactly once, compensations fire exactly once in
    reverse order — and after the store's own shard leader is crashed,
    every acked effect is still present (lost_acked_keys == [])."""
    store = build_replicated_store(
        "statestore", tmp_path / "wf.db", replicas=2, ack_quorum=2,
        lease_seconds=0.4)
    log = []
    chaos = ChaosPolicies([parse_chaos(CHAOS_YAML_DOC)], app_id="svc")
    r1 = make_runtime(store, chaos=chaos, crash_on_chaos=True, log=log)
    r2 = make_runtime(store, log=log)
    await start_all(r1, r2)
    started0 = metrics.get("workflow_started_total", workflow="order")
    comp0 = metrics.get("workflow_compensation_total", workflow="order")
    inst = "drill-1"
    try:
        # reserve attempt #2 crashes the owner: the start call dies
        # mid-pump with the turn uncommitted, like SIGKILL would
        with pytest.raises(BaseException) as crashed:
            await r1.workflows.start("order", 3, instance=inst)
        assert "chaos crash" in str(crashed.value)
        assert r1.actors.crashed

        # the committed prefix survived: exactly one reserve completed
        status = await r2.workflows.status(inst)
        assert status["status"] == "running"
        history = await r2.workflows.history(inst)
        assert [e["seq"] for e in history
                if e["t"] == "activity_completed"] == [1]

        # survivor adopts after lease expiry and converges the saga
        await asyncio.sleep(LEASE + 0.1)
        status = await adopt_until(r2, inst)
        assert status["status"] == "failed"
        assert "card declined" in status["error"]

        # every forward effect exactly once (bodies too: the chaos
        # fault fires before the body, so no reserve double-ran)
        assert sorted(p for k, p in log if k == "reserve") == [0, 1, 2]
        # compensations exactly once, reverse order
        assert [p for k, p in log if k == "release"] == [2, 1, 0]
        history = await r2.workflows.history(inst)
        comp = [e for e in history if e["t"] == "compensated"]
        assert [e["idx"] for e in comp] == [2, 1, 0]

        # workflow_* metrics moved
        assert metrics.get("workflow_started_total",
                           workflow="order") == started0 + 1
        assert metrics.get("workflow_compensation_total",
                           workflow="order") == comp0 + 3

        # host loss on the store itself: crash the shard leader; RF=2
        # with quorum acks means the follower has every committed write
        leader = next(n for n in store.nodes
                      if n.node_id == store.leader_member())
        leader.crash()
        lost = []
        for i in range(3):
            for prefix in ("res", "rel"):
                if await store.get(f"svc||{prefix}||{inst}||{i}") is None:
                    lost.append(f"{prefix}||{i}")
        assert lost == [], f"acked effects lost after leader crash: {lost}"
        # history is intact on the promoted follower too
        history = await r2.workflows.history(inst)
        assert [e["seq"] for e in history
                if e["t"] == "activity_completed"] == [1, 2, 3]
        assert history[-1]["t"] == "failed"
    finally:
        await shutdown(r2, r1)


# -- cross-process kill -9 drill -------------------------------------------

_KILL9_CHILD = '''
import asyncio, os, sys

os.environ["TASKSRUNNER_WORKFLOWS"] = "1"
os.environ["TASKSRUNNER_ACTOR_LEASE_SECONDS"] = "0.5"
os.environ["TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS"] = "30"

from tasksrunner.app import App
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.runtime import InProcAppChannel, Runtime


def build():
    app = App("svc")

    @app.workflow("steps")
    async def steps(ctx, n):
        total = 0
        for i in range(n):
            total += await ctx.call_activity("slowstep", {"i": i})
        return total

    @app.activity("slowstep")
    async def slowstep(actx, data):
        actx.stage_effect(f"eff||{actx.instance}||{actx.seq}", data)
        print(f"STEP {actx.seq}", flush=True)
        await asyncio.sleep(0.05)
        return 1

    return app


async def main():
    spec = ComponentSpec(name="statestore", type="state.sqlite",
                         metadata={"databasePath": sys.argv[1]})
    reg = ComponentRegistry([spec], app_id="svc")
    rt = Runtime("svc", reg, app_channel=InProcAppChannel(build()))
    await rt.start()
    rt.actors.lease_seconds = 0.5
    rt.app_channel.app.workflow_engine.drive_period = 0.2
    print("READY", flush=True)
    await rt.workflows.start("steps", 12, instance="xproc-1")
    await asyncio.sleep(60)  # the parent kills us long before this


asyncio.run(main())
'''


async def test_kill9_workflow_owner_history_continuity(wf_env, tmp_path):
    """Cross-process acceptance drill: ``kill -9`` the OS process that
    owns a running workflow, mid-activity, on a shared sqlite store.
    This replica adopts the instance and finishes it; the history shows
    one contiguous, duplicate-free run — the committed prefix from the
    dead process plus this replica's continuation."""
    db = tmp_path / "wf.db"
    script = tmp_path / "owner_child.py"
    script.write_text(_KILL9_CHILD)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    child = await asyncio.create_subprocess_exec(
        sys.executable, str(script), str(db),
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT,
        env=env)
    try:
        # let the child commit a few steps, then SIGKILL it mid-run
        steps_seen = 0
        deadline = asyncio.get_running_loop().time() + 30
        while steps_seen < 3:
            assert asyncio.get_running_loop().time() < deadline, \
                f"child never progressed (saw {steps_seen} steps)"
            line = (await asyncio.wait_for(child.stdout.readline(), 30)
                    ).decode().strip()
            if line.startswith("STEP "):
                steps_seen = int(line.split()[1])
        child.kill()
        await child.wait()

        spec = ComponentSpec(name="statestore", type="state.sqlite",
                             metadata={"databasePath": str(db)})
        reg = ComponentRegistry([spec], app_id="svc")
        rt = Runtime("svc", reg, app_channel=InProcAppChannel(build_app()))
        await rt.start()
        rt.actors.lease_seconds = LEASE
        rt.app_channel.app.workflow_engine.drive_period = DRIVE

        # the adopting replica doesn't know "steps"/"slowstep" — prove
        # continuity with the same app shape instead
        @rt.app_channel.app.workflow("steps")
        async def steps(ctx, n):
            total = 0
            for i in range(n):
                total += await ctx.call_activity("slowstep", {"i": i})
            return total

        @rt.app_channel.app.activity("slowstep")
        async def slowstep(actx, data):
            actx.stage_effect(f"eff||{actx.instance}||{actx.seq}", data)
            return 1

        try:
            status = await adopt_until(rt, "xproc-1", timeout=15.0)
            assert status["status"] == "completed"
            assert status["result"] == 12

            history = await rt.workflows.history("xproc-1")
            seqs = [e["seq"] for e in history
                    if e["t"] == "activity_completed"]
            # continuity: one contiguous run, no duplicates, no gaps —
            # the dead owner's committed prefix flowed straight into
            # the adopter's continuation
            assert seqs == list(range(1, 13)), seqs
            assert len([e for e in history if e["t"] == "started"]) == 1
            store = reg.get("statestore")
            for seq in range(1, 13):
                item = await store.get(f"svc||eff||xproc-1||{seq}")
                assert item is not None, f"missing effect for seq {seq}"
            # the adopter fenced above the dead owner's epoch
            record = await store.get(
                f"svc||actor-rec||{WORKFLOW_ACTOR_TYPE}||xproc-1")
            assert int(record.value["epoch"]) >= 2
        finally:
            await shutdown(rt)
    finally:
        if child.returncode is None:
            child.kill()
            await child.wait()


# -- the tasks-tracker sample scenarios ------------------------------------


async def test_sample_tasks_tracker_scenarios(wf_env):
    """The three shipped sample workflows, end to end on the fake
    manager: checkout saga (success and declined-with-compensation),
    reminder-driven overdue escalation, and the fan-out/fan-in sweep."""
    import datetime as dt

    from samples.tasks_tracker.backend_api.app import APP_ID, make_app
    from samples.tasks_tracker.backend_api.managers import FakeTasksManager
    from samples.tasks_tracker.backend_api.models import format_dt

    manager = FakeTasksManager(seed_count=0)
    app = make_app(manager=manager)
    await app.startup()
    assert app.state["tasks"] is manager

    shared = InMemoryStateStore("statestore")
    spec = ComponentSpec(name="statestore", type="state.in-memory")
    reg = ComponentRegistry([spec], app_id=APP_ID)
    reg._instances["statestore"] = shared
    rt = Runtime(APP_ID, reg, app_channel=InProcAppChannel(app))
    await rt.start()
    rt.actors.lease_seconds = LEASE
    app.workflow_engine.drive_period = DRIVE
    try:
        # 1. checkout saga, happy path: every stage_effect landed
        inst = await rt.workflows.start(
            "checkout", {"items": ["tea", "mug"], "amount": 42.0},
            instance="ord-ok")
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "completed"
        order_id = status["result"]["orderId"]
        assert status["result"]["receipt"]["amount"] == 42.0
        for item in ("tea", "mug"):
            key = f"{APP_ID}||checkout||{order_id}||reserved||{item}"
            assert await shared.get(key) is not None
        assert await shared.get(
            f"{APP_ID}||checkout||{order_id}||charge") is not None
        assert await shared.get(
            f"{APP_ID}||checkout||{order_id}||confirmation") is not None

        # 2. checkout saga, declined card: reservations compensated
        # away (staged deletes), no charge, no confirmation
        inst = await rt.workflows.start(
            "checkout", {"items": ["tv"], "amount": 9000.0,
                         "orderId": "bigspender"},
            instance="ord-declined")
        status = await rt.workflows.wait(inst, timeout=5)
        assert status["status"] == "failed"
        assert "card declined" in status["error"]
        assert await shared.get(
            f"{APP_ID}||checkout||bigspender||reserved||tv") is None
        assert await shared.get(
            f"{APP_ID}||checkout||bigspender||charge") is None
        history = await rt.workflows.history(inst)
        comp = [e for e in history if e["t"] == "compensated"]
        assert [c["name"] for c in comp] == ["release-stock"]

        # 3. overdue escalation: never completed -> nags then overdue
        task_id = await manager.create_new_task(
            {"taskName": "file taxes", "taskCreatedBy": "sam@tasks.dev"})
        inst = await rt.workflows.start(
            "overdue-escalation",
            {"taskId": task_id, "intervalSeconds": 0.05, "maxLevels": 2},
            instance="esc-1")
        status = await adopt_until(rt, inst)
        assert status["status"] == "completed"
        assert status["result"] == {"taskId": task_id,
                                    "outcome": "overdue", "nags": 2}
        task = await manager.get_task_by_id(task_id)
        assert task.is_over_due
        for level in (1, 2):
            assert await shared.get(
                f"{APP_ID}||escalation||{task_id}||{level}") is not None

        # 4. escalation stands down when the task completes in time
        task2 = await manager.create_new_task(
            {"taskName": "water plants", "taskCreatedBy": "sam@tasks.dev"})
        await manager.mark_task_completed(task2)
        inst = await rt.workflows.start(
            "overdue-escalation",
            {"taskId": task2, "intervalSeconds": 0.05, "maxLevels": 3},
            instance="esc-2")
        status = await adopt_until(rt, inst)
        assert status["status"] == "completed"
        assert status["result"]["outcome"] == "completed"
        assert status["result"]["nags"] == 0

        # 5. fan-out/fan-in sweep over yesterday's due tasks
        yesterday = format_dt(
            (dt.datetime.now() - dt.timedelta(days=1)).replace(
                hour=0, minute=0, second=0, microsecond=0))
        due_ids = []
        for i in range(3):
            due_ids.append(await manager.create_new_task(
                {"taskName": f"due-{i}", "taskCreatedBy": "sam@tasks.dev",
                 "taskDueDate": yesterday}))
        inst = await rt.workflows.start("overdue-sweep", None,
                                        instance="sweep-1")
        status = await rt.workflows.wait(inst, timeout=8)
        assert status["status"] == "completed"
        assert status["result"]["swept"] == 3
        assert sorted(status["result"]["taskIds"]) == sorted(due_ids)
        for tid in due_ids:
            task = await manager.get_task_by_id(tid)
            assert task.is_over_due
            assert await shared.get(f"{APP_ID}||overdue||{tid}") is not None
    finally:
        await shutdown(rt)
