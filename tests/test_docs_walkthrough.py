"""Docs-as-tests: replay the workshop's own command blocks.

The reference workshop's only QA is manual verification checkpoints in
its module docs (SURVEY.md §4.1). This suite exceeds that the way §4
prescribes: the checkpoints are *executable*. Each test extracts the
```bash blocks from a module page (docs/modules/*.md), replays them in
document order against a scratch directory, and asserts the outputs
the page itself promises. A module whose commands or expected outputs
rot fails here instead of in front of a reader.

Covered end-to-end — every module, 1 through 15: module 1 (host + both
front doors + CRUD + the decoupled two-process layout), module 2 (the
configured-URL path breaking on a port move vs the app-id path
surviving it, plus the full browser CRUD loop via curl), module 3 (the
sidecar as a separate program: attach, kill each side in both orders,
metadata introspection), module 4 (store swap, durability across
restart, queries, etag 409, transactions, raw probes), module 5
(orchestrator, invoke → broker → processor delivery, metrics, raw
publish), module 6 (external-queue ingest chain: input binding →
invoke → blob archive → email outbox, every hop in metrics), module 7
(overdue task → manual cron fire → isOverDue flip), module 8 (the
happy transaction with its async consumer tail, the poison event's
redelivery story as one trace, the service map in text and mermaid,
counters with status labels), module 9 (the KEDA-style flood: 1→5→1
in the scaler log, empty DLQ), module 10 (the
secret chain: granted reader resolves, ungranted reader refused with
its missing grant named), module 11 (the
four deploy verbs: validate, first-run create, empty diff, the exact
touched path after an edit, boot from generated artifacts), module 13
(the staged outage: concurrent burst trips the breaker, millisecond
fast-fails while open, automatic recovery closing it), module 14
(revisions from env updates, rolling restart, and the staged DLQ
incident: poison → dead-letter → diagnose → purge), and module 15
(the secure baseline: fail-closed apply, per-app identities refusing
even the operator on the data plane, token-gated control plane, and
the untouched app with its integration gated off) — plus module 11b
(the GitHub Actions pipeline rehearsed job by job from the page text,
including the smoke write through the public frontend and the 401
data-plane fence the page's warning box promises), module 11c (the
broken-manifest rehearsal that proves the ADO stage gate), and module
12's daemonless footprint measurement, its >=50% payload-saving
claim, and the real OCI image artifacts (build, digest-walk
verification, layer dedup, reproducibility, corrupted-blob failure).
The appendices replay too: session variables (save / fresh-shell
restore / update-in-place / direct-execution warning / the restored
environment booting the full sample) and the debugging appendix's
one-terminal forensic loop (ps, logs, the traces pivot, the
deliberate restart and the re-resolve recovery that follows).

Mechanics: commands run with the scratch dir as cwd (so `.tasksrunner/`
state lands there) with `samples/` and `run.yaml` reachable, exactly as
a reader at the repo root. Long-running server blocks are backgrounded;
placeholders the docs tell the reader to fill (`<the id you got back>`)
are filled the same way the reader would.
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
DOCS = REPO / "docs" / "modules"

API = "tasksmanager-backend-api"


def bash_blocks(doc_name: str) -> list[str]:
    text = (DOCS / doc_name).read_text()
    return re.findall(r"```bash\n(.*?)```", text, re.S)


def block_with(blocks: list[str], needle: str) -> str:
    """The first ```bash block containing `needle` — failing loudly when
    the doc no longer contains the command the walkthrough promises."""
    for b in blocks:
        if needle in b:
            return b
    raise AssertionError(
        f"no bash block containing {needle!r} — the doc changed; "
        f"update this walkthrough test with it")


class Scratch:
    """A reader's terminal: scratch cwd wired like the repo root."""

    def __init__(self, tmp: Path):
        self.dir = tmp
        (tmp / "samples").symlink_to(REPO / "samples")
        (tmp / "run.yaml").write_text((REPO / "run.yaml").read_text())
        self.env = {**os.environ, "PYTHONPATH": str(REPO)}
        self.env.pop("TASKSRUNNER_API_TOKEN", None)
        self.procs: list[subprocess.Popen] = []

    def run(self, script: str, timeout: float = 60, check: bool = True,
            extra_env: dict | None = None) -> str:
        p = subprocess.run(
            ["bash", "-c", script], cwd=self.dir,
            env={**self.env, **(extra_env or {})},
            capture_output=True, text=True, timeout=timeout)
        if check:
            assert p.returncode == 0, (
                f"block failed rc={p.returncode}\n--- script\n{script}\n"
                f"--- stdout\n{p.stdout}\n--- stderr\n{p.stderr}")
        return p.stdout + p.stderr

    def spawn(self, script: str,
              extra_env: dict | None = None) -> subprocess.Popen:
        p = subprocess.Popen(
            ["bash", "-c", script], cwd=self.dir,
            env={**self.env, **(extra_env or {})},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            start_new_session=True)
        # remember the process GROUP at spawn time: cleanup must kill
        # the whole tree even after the bash leader has already exited
        # (a dead leader with live orphans was observed leaking servers
        # on the fixed workshop ports, poisoning every later run)
        p.pgid = os.getpgid(p.pid)
        # drain stdout continuously: a chatty topology (orchestrator
        # multiplexing every replica) would otherwise fill the 64 KB
        # pipe and BLOCK on its next write, stalling the whole test
        p.output = []

        def _drain(proc=p):
            for line in proc.stdout:
                proc.output.append(line)

        import threading
        t = threading.Thread(target=_drain, daemon=True)
        t.start()
        self.procs.append(p)
        return p

    def wait_port(self, port: int, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            proc_dead = self.procs and self.procs[-1].poll() is not None
            try:
                with socket.create_connection(("127.0.0.1", port), 0.25):
                    return
            except OSError:
                if proc_dead:
                    out = "".join(self.procs[-1].output[-50:])
                    raise AssertionError(
                        f"server exited before opening :{port}\n{out}")
                time.sleep(0.1)
        raise AssertionError(f"port {port} never opened")

    @staticmethod
    def _killpg(pgid: int, sig) -> None:
        try:
            os.killpg(pgid, sig)
        except ProcessLookupError:
            pass

    def stop_proc(self, p: subprocess.Popen, sig=signal.SIGTERM) -> None:
        # signal the GROUP unconditionally: children may outlive the
        # bash leader, so p.poll() saying the leader exited proves
        # nothing about the tree
        self._killpg(p.pgid, sig)
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        self._killpg(p.pgid, signal.SIGKILL)
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass

    def materialize_samples(self) -> None:
        """Swap the samples symlink for a real copy (tests that APPLY
        deployments need a writable .tasksrunner dir under samples/)."""
        import shutil
        (self.dir / "samples").unlink()
        shutil.copytree(REPO / "samples", self.dir / "samples",
                        ignore=shutil.ignore_patterns(".tasksrunner"))

    def close(self) -> None:
        for p in self.procs:
            self.stop_proc(p, signal.SIGKILL)


WORKSHOP_PORTS = (5103, 5189, 5217, 3500, 3501, 3502)


def _port_open(port: int) -> bool:
    try:
        with socket.create_connection(("127.0.0.1", port), 0.2):
            return True
    except OSError:
        return False


@pytest.fixture
def scratch(tmp_path):
    # fail LOUDLY if a stale server holds the workshop's fixed ports —
    # silently probing someone else's process produces nonsense
    # assertions (a store-backed API answering the fake-mode test).
    # Give the PREVIOUS test's just-killed tree a few seconds to vanish
    # first: between back-to-back tests the kernel may still be tearing
    # a listener down.
    for port in WORKSHOP_PORTS:
        deadline = time.monotonic() + 5.0
        while _port_open(port):
            if time.monotonic() > deadline:
                pytest.fail(
                    f"port {port} still in use after 5s — a stale "
                    f"tasksrunner process is running; kill it before "
                    f"this suite")
            time.sleep(0.2)
    s = Scratch(tmp_path)
    yield s
    s.close()


def test_module_01_run_a_service(scratch):
    blocks = bash_blocks("01-run-a-service.md")

    # §2.1 start the host exactly as the doc says; leave it "running in
    # this terminal"
    host_cmd = block_with(blocks, "tasksrunner host")
    assert "TASKS_MANAGER=fake" in host_cmd
    host = scratch.spawn(host_cmd)
    scratch.wait_port(5103)
    scratch.wait_port(3500)

    # §2.2 direct front door: ten seeded tasks for tempuser@mail.com
    direct = scratch.run(block_with(blocks, "http://127.0.0.1:5103/api/tasks?createdBy"))
    seeded = json.loads(direct)
    assert len(seeded) == 10
    assert all(t["taskCreatedBy"] == "tempuser@mail.com" for t in seeded)

    # §2.3 sidecar front door: same list through the invoke address
    via_sidecar = scratch.run(block_with(blocks, "/v1.0/invoke/tasksmanager-backend-api/method/api/tasks?createdBy"))
    assert {t["taskId"] for t in json.loads(via_sidecar)} == \
        {t["taskId"] for t in seeded}

    # §3 CRUD through the sidecar: create...
    created = scratch.run(block_with(blocks, '"taskName":"My first task"'))
    task_id = json.loads(created)["taskId"]
    # ...then the TASK_ID=<the id you got back> block, filled as the
    # reader fills it
    crud = block_with(blocks, "$TASK_ID/markcomplete")
    crud = crud.replace("TASK_ID=<the id you got back>", f"TASK_ID={task_id}")
    out = scratch.run(crud)
    assert '"isCompleted": true' in out
    assert out.count("200") >= 2  # markcomplete and delete both answer 200

    # §4 the fully decoupled two-process layout, then the §2.3 re-probe
    scratch.stop_proc(host)
    two_proc = block_with(blocks, "tasksrunner sidecar")
    assert "tasksrunner serve" in two_proc  # app process backgrounded with &
    scratch.spawn(two_proc)
    scratch.wait_port(3500)
    re_probe = scratch.run(block_with(blocks, "/v1.0/invoke/tasksmanager-backend-api/method/api/tasks?createdBy"))
    assert len(json.loads(re_probe)) == 10  # fake reseeded: identical behavior


def test_module_04_state(scratch):
    blocks = bash_blocks("04-state.md")

    host_cmd = block_with(blocks, "TASKS_MANAGER=store")
    host = scratch.spawn(host_cmd)
    scratch.wait_port(5103)
    scratch.wait_port(3500)

    # §2.2 create a durable task
    created = scratch.run(block_with(blocks, '"taskName":"Durable now"'))
    task_id = json.loads(created[created.index("{"):])["taskId"]

    # "kill the host, start it again with the same command, and list"
    scratch.stop_proc(host)
    scratch.spawn(host_cmd)
    scratch.wait_port(5103)
    listed = scratch.run(block_with(blocks, "api/tasks?createdBy=me@mail.com"))
    tasks = json.loads(listed)
    assert [t["taskId"] for t in tasks] == [task_id], \
        "task must survive the restart (and no fake seeds may appear)"

    # §3 key prefixing: the raw probe, with the reader's task id
    probe = block_with(blocks, "state get statestore").replace(
        "<your-task-id>", task_id)
    out = scratch.run(probe)
    assert "Durable now" in out

    # §4 the EQ query through the sidecar returns the task with an etag
    q = scratch.run(block_with(blocks, '"filter": {"EQ": {"taskCreatedBy"'))
    results = json.loads(q)["results"]
    assert results and results[0]["data"]["taskName"] == "Durable now"
    assert results[0]["etag"]

    # §5 stale etag bounces: the doc's two-step probe block
    etag_block = block_with(blocks, '"etag": "0"')
    out = scratch.run(etag_block)
    assert "etag mismatch" in out

    # §6 transaction: both ops or neither
    scratch.run(block_with(blocks, '"operation": "upsert"'))
    # probe key was deleted by the transaction; t1 exists
    get_t1 = scratch.run(
        f"curl -s http://127.0.0.1:3500/v1.0/state/statestore/t1")
    assert json.loads(get_t1) == {"a": 1}
    get_probe = scratch.run(
        "curl -s -o /dev/null -w '%{http_code}' "
        "http://127.0.0.1:3500/v1.0/state/statestore/probe")
    assert get_probe.strip() == "204"  # gone

    # §7 the reference's own raw probes
    raw = scratch.run(block_with(blocks, '"key": "rawkey"'))
    assert "204" in raw and "written raw" in raw


def test_module_05_pubsub(scratch):
    blocks = bash_blocks("05-pubsub.md")

    # §3 one command runs the whole topology
    orch = scratch.spawn(block_with(blocks, "tasksrunner run run.yaml"))
    for port in (5103, 5189, 5217, 3500, 3502):
        scratch.wait_port(port)
    # registration is async after ports open; ps exits non-zero until
    # all three registered
    deadline = time.monotonic() + 30
    while True:
        ps = scratch.run(block_with(blocks, "tasksrunner ps"), check=False)
        if ps.count("ok") >= 3:
            break
        assert time.monotonic() < deadline, f"apps never healthy:\n{ps}"
        time.sleep(0.5)
    assert "tasksmanager-backend-processor" in ps

    # §4.1 create a task through the sidecar
    created = scratch.run(block_with(blocks, '"taskName":"Ship module 5"'))
    assert "taskId" in created

    # §4.2 the processor logs the delivery
    logs_cmd = block_with(blocks, "tasksrunner logs tasksmanager-backend-processor")
    deadline = time.monotonic() + 20
    while True:
        logs = scratch.run(logs_cmd, check=False)
        if "Started processing message with task name 'Ship module 5'" in logs:
            break
        assert time.monotonic() < deadline, f"delivery never logged:\n{logs}"
        time.sleep(0.5)

    # §4.3 counted in metrics
    metrics = scratch.run(block_with(blocks, "tasksrunner metrics"))
    assert re.search(r"pubsub_delivery\{.*status=200\}\s+\d", metrics)

    # §6 the reference-style raw publish probe answers 200 and delivers
    raw = scratch.run(block_with(blocks, "v1.0/publish/dapr-pubsub-servicebus"))
    assert "200" in raw
    deadline = time.monotonic() + 20
    while True:
        logs = scratch.run(logs_cmd, check=False)
        if "raw publish" in logs:
            break
        assert time.monotonic() < deadline, "raw-published event never delivered"
        time.sleep(0.5)

    scratch.stop_proc(orch)


def _boot_topology(scratch):
    """Module 5's one-command topology, reused by modules 6-7 ('leave
    the orchestrator running — module 6 continues on this topology').
    The simulated slow-processing delay (the reference's load-test
    posture when the email integration is off) is shortened so floods
    drain in test time while consumers stay the bottleneck."""
    blocks = bash_blocks("05-pubsub.md")
    orch = scratch.spawn(block_with(blocks, "tasksrunner run run.yaml"),
                         extra_env={"SENDGRID__SIMULATED_WORK_MS": "100"})
    for port in (5103, 5189, 5217, 3500, 3502):
        scratch.wait_port(port)
    deadline = time.monotonic() + 30
    while True:
        ps = scratch.run(block_with(blocks, "tasksrunner ps"), check=False)
        if ps.count("ok") >= 3:
            return orch
        assert time.monotonic() < deadline, f"apps never healthy:\n{ps}"
        time.sleep(0.5)


def _poll_logs(scratch, logs_cmd, needle, timeout=20):
    deadline = time.monotonic() + timeout
    while True:
        logs = scratch.run(logs_cmd, check=False)
        if needle in logs:
            return logs
        assert time.monotonic() < deadline, \
            f"{needle!r} never appeared in:\n{logs}"
        time.sleep(0.5)


def test_module_06_bindings(scratch):
    blocks = bash_blocks("06-bindings.md")
    orch = _boot_topology(scratch)

    # §3.1 drop a message in as an external system would
    out = scratch.run(block_with(blocks, "SqliteQueue"))
    assert "sent" in out

    # §3.2 the chain executes under one trace, visible in the logs
    logs_cmd = block_with(blocks, "tasksrunner logs tasksmanager-backend-processor")
    _poll_logs(scratch, logs_cmd,
               "Started processing message with task name 'Pay electricity bill'")
    _poll_logs(scratch, logs_cmd, 'pubsub delivery "POST /api/tasksnotifier/tasksaved" 200')

    # §3.3 the blob archive holds the payload under the stored id
    blob = scratch.run(block_with(blocks, "externaltaskscontainer"))
    assert '"taskName": "Pay electricity bill"' in blob

    # §3.4 every hop counted in metrics
    metrics = scratch.run(block_with(blocks, "tasksrunner metrics"))
    for needle in ("binding_delivery{binding=externaltasksmanager,status=200}",
                   "binding_invoke{binding=externaltasksblobstore,operation=create}",
                   "binding_invoke{binding=sendgrid,operation=create}",
                   "pubsub_delivery{route=/api/tasksnotifier/tasksaved,status=200}"):
        assert needle in metrics, metrics

    # §1.3 the outbox holds the notification email
    outbox = scratch.run(block_with(blocks, ".tasksrunner/outbox"))
    assert '"subject": "Tasks assigned to you"' in outbox
    assert '"to": "ops@mail.com"' in outbox

    scratch.stop_proc(orch)


def test_module_07_cron(scratch):
    blocks = bash_blocks("07-cron.md")
    orch = _boot_topology(scratch)

    # §3.1 create a task due yesterday (the doc computes Y itself)
    created = scratch.run(block_with(blocks, "date -d yesterday"))
    assert "taskId" in created

    # §3.2 fire the job route exactly as the runtime would
    fired = scratch.run(block_with(blocks, "method/ScheduledTasksManager"))
    assert "HTTP 200" in fired

    # §3.3 the flip is visible through the API...
    deadline = time.monotonic() + 10
    while True:
        listed = scratch.run(block_with(blocks, "api/tasks?createdBy=me@mail.com"))
        if '"isOverDue": true' in listed:
            break
        assert time.monotonic() < deadline, listed
        time.sleep(0.5)
    # ...and the job's own log lines confirm the 3-step flow (poll: the
    # flip is visible through the API before the handler's lines flush)
    logs_cmd = block_with(blocks, "tasksrunner logs tasksmanager-backend-processor")
    logs = _poll_logs(scratch, logs_cmd, "ScheduledTasksManager executed at")
    if "Marking 1 tasks overdue" not in logs:
        logs = _poll_logs(scratch, logs_cmd, "Marking 1 tasks overdue")

    scratch.stop_proc(orch)


def test_module_14_operations(scratch):
    """The operations drill: revisions from env updates, live scale
    bounds, and the full staged DLQ incident (poison → dead-letter →
    diagnose → purge) — each command straight from the doc."""
    blocks = bash_blocks("14-operations.md")
    orch = _boot_topology(scratch)

    # ps (the doc's replica-status block)
    ps = scratch.run(block_with(blocks, "tasksrunner ps"))
    assert ps.count("ok") >= 3

    # env change → revision 2; scale bounds; history lists both
    rev_block = block_with(blocks, "--set-env LOG_LEVEL=debug")
    out = scratch.run(rev_block, timeout=120)
    assert "revision 2" in out
    history = out  # the block ends with `revisions`
    assert re.search(r"\b1\b.*initial deploy", history)
    assert "env update" in history

    # rolling restart, not a crash
    out = scratch.run(block_with(blocks, "tasksrunner restart"))
    assert "restarted tasksmanager-backend-api" in out

    # stage the DLQ incident exactly as the doc does
    poison = scratch.run(block_with(blocks, '"poison-1"'))
    assert "messageId" in poison

    dlq_list = block_with(blocks, "dlq list")
    deadline = time.monotonic() + 30
    while True:
        parked = scratch.run(dlq_list)
        m = re.search(r"^([0-9a-f]{32})\s+\d", parked, re.M)
        if m:
            msg_id = m.group(1)
            break
        assert time.monotonic() < deadline, parked
        time.sleep(0.5)

    shown = scratch.run(block_with(blocks, "dlq show"))
    assert '"taskName": "malformed event' in shown

    purge = block_with(blocks, "dlq purge").replace(
        "84b02210b8599299f3c5c4d946a9aeef", msg_id)
    out = scratch.run(purge)
    assert "purged 1 message(s)" in out
    assert "no dead letters" in scratch.run(dlq_list)

    scratch.stop_proc(orch)


def test_module_13_resiliency_episode(scratch):
    """The staged outage: kill the API mid-flight, watch retries give
    way to the open circuit's fast-fails, then automatic recovery on
    both sides — latencies and log lines as the doc promises."""
    blocks = bash_blocks("13-resiliency.md")
    orch = _boot_topology(scratch)

    # the doc's curls assume a signed-in session (cookies.txt from the
    # earlier modules); establish it the way the reader did
    scratch.run("curl -s -c cookies.txt -X POST http://127.0.0.1:5189/ "
                "-d 'email=resil@x.com' -o /dev/null")

    # §1 note the API's pid, then a crash (not a clean stop). The doc's
    # block contains both the ps and the kill with the <api-pid>
    # placeholder the reader fills — fill it the same way first.
    ps = scratch.run("python -m tasksrunner ps")
    api_pid = re.search(r"tasksmanager-backend-api\s+(\d+)", ps).group(1)
    kill_block = block_with(blocks, "kill -9").replace("<api-pid>", api_pid)
    scratch.run(kill_block)

    # §2 a concurrent burst trips the shared breaker: everyone 503s
    # fast instead of burning a full retry budget
    burst = block_with(blocks, "seq 1 8")
    out = scratch.run(burst, check=False, timeout=60)
    codes = re.findall(r"burst: (\d{3}) in ([0-9.]+)s", out)
    assert len(codes) == 8, out
    assert all(c == "503" for c, _ in codes), out
    assert all(float(t) < 2.0 for _, t in codes), out

    # while open, a sequential probe fast-fails in milliseconds
    probe = block_with(blocks, '"open: %{http_code}')
    saw_fast_fail = False
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not saw_fast_fail:
        m = re.search(r"open: (\d{3}) in ([0-9.]+)s",
                      scratch.run(probe, check=False, timeout=15))
        if m and m.group(1) == "503" and float(m.group(2)) < 0.05:
            saw_fast_fail = True
    assert saw_fast_fail, "circuit never produced a millisecond fast-fail"
    logs = scratch.run(
        "python -m tasksrunner logs tasksmanager-frontend-webapp --tail 60",
        check=False)
    assert "circuit api-breaker[tasksmanager-backend-api] OPEN" in logs

    # §3 recovery is automatic on both sides: the orchestrator restarts
    # the replica, a probe closes the breaker, traffic flows again
    recovered = block_with(blocks, '"recovered: %{http_code}')
    deadline = time.monotonic() + 60
    while True:
        try:
            out = scratch.run(recovered, check=False, timeout=15)
        except subprocess.TimeoutExpired:
            out = ""
        if "recovered: 200" in out:
            break
        assert time.monotonic() < deadline, f"never recovered: {out}"
        time.sleep(1)
    logs = scratch.run(
        "python -m tasksrunner logs tasksmanager-frontend-webapp --tail 60",
        check=False)
    assert "closed" in logs and "half-open" in logs

    scratch.stop_proc(orch)


def test_module_15_production_baseline(scratch):
    """The secure-baseline drill: fail-closed apply without a token,
    hardened deploy with one, data plane refusing even the operator,
    control plane obeying the operator token — each fence pushed with
    the doc's own commands."""
    import shutil

    # deploy writes its state beside the manifest; replace the samples
    # SYMLINK with a real copy so the scratch run cannot touch the repo
    (scratch.dir / "samples").unlink()
    shutil.copytree(REPO / "samples", scratch.dir / "samples",
                    ignore=shutil.ignore_patterns(".tasksrunner"))

    blocks = bash_blocks("15-production-baseline.md")
    token = {"TASKSRUNNER_API_TOKEN": "walkthrough-prod-tok"}

    # the workshop reaches module 15 with module 11's dev environment
    # applied — the prod what-if below diffs against that recorded state
    scratch.run("python -m tasksrunner deploy apply "
                "samples/tasks_tracker/environment.yaml")

    # §2 fail closed: apply without a token is a hard error
    out = scratch.run(block_with(blocks, "unset TASKSRUNNER_API_TOKEN"),
                      check=False)
    assert "requires an API token" in out

    # §3 deploy with the token: the what-if diff IS the hardening list
    diff = scratch.run(block_with(blocks, "deploy what-if"), extra_env=token)
    assert "SENDGRID__INTEGRATIONENABLED" in diff
    out = scratch.run("python -m tasksrunner deploy apply "
                      "samples/tasks_tracker/environment.prod.yaml",
                      extra_env=token)
    assert "applied" in out
    orch = scratch.spawn(
        "python -m tasksrunner run "
        "samples/tasks_tracker/.tasksrunner/tasks-tracker-prod-run.yaml",
        extra_env=token)
    for port in (5103, 5189, 5217):
        scratch.wait_port(port)

    reg = "samples/tasks_tracker/.tasksrunner/apps.json"
    ps_cmd = f"python -m tasksrunner ps --registry-file {reg}"
    deadline = time.monotonic() + 30
    while True:
        ps = scratch.run(ps_cmd, check=False, extra_env=token)
        if ps.count("ok") >= 3:
            break
        assert time.monotonic() < deadline, ps
        time.sleep(0.5)
    # §4.1 health visible, inventory token-gated (per-app identities)
    assert "auth" in ps

    # §4.2 the data plane refuses even the operator's token
    state_probe = block_with(blocks, "state get statestore")
    out = scratch.run(state_probe, check=False, extra_env=token)
    assert "401" in out

    # §4.3 control plane obeys exactly the operator token
    out = scratch.run(block_with(blocks, "tasksrunner restart"),
                      extra_env=token)
    assert "restarted tasksmanager-frontend-webapp" in out
    out = scratch.run(block_with(blocks, "tasksrunner restart"), check=False)
    assert "401" in out  # tokenless shell refused

    # §4.3b the orchestrator played sentry: CA + one workload cert per
    # app, and the cert's SAN is the app-id (the pinned identity)
    out = scratch.run(block_with(blocks, "pki/"))
    assert "ca.pem" in out
    assert "subject=CN = tasksmanager-backend-api" in out
    assert "DNS:tasksmanager-backend-api" in out

    # §4.5 the app itself is untouched: full CRUD through the frontend,
    # and the prod env gates the email integration off (empty outbox)
    scratch.run(
        "curl -s -c c.txt -X POST http://127.0.0.1:5189/ -d email=p@x.com "
        "-o /dev/null && "
        "curl -s -b c.txt -X POST http://127.0.0.1:5189/tasks/create "
        "-d 'taskName=prod-ok&taskAssignedTo=a@b.com&taskDueDate=2026-12-01' "
        "-o /dev/null")
    listed = scratch.run("curl -s -b c.txt http://127.0.0.1:5189/tasks")
    assert "prod-ok" in listed
    outbox = scratch.dir / ".tasksrunner" / "outbox"
    assert not outbox.exists() or not any(outbox.iterdir())

    scratch.stop_proc(orch)


def test_module_11_declarative_deploys(scratch):
    """The four verbs with the doc's own outputs: validate, the
    first-run create, apply's artifacts, the empty diff, the exact
    touched path after an edit, and booting from generated artifacts."""
    scratch.materialize_samples()
    blocks = bash_blocks("11-deploy.md")

    out = scratch.run(block_with(blocks, "deploy validate"))
    assert "manifest 'tasks-tracker-env' is valid (3 apps, 7 components)" in out

    whatif = block_with(blocks, "deploy what-if")
    assert "+ tasks-tracker-env" in scratch.run(whatif)   # first run: create

    out = scratch.run(block_with(blocks, "deploy apply"))
    assert "applied 1 change(s)" in out
    assert "no changes" in scratch.run(whatif)            # recorded == manifest

    # edit the manifest: what-if names exactly the touched path
    env_yaml = scratch.dir / "samples/tasks_tracker/environment.yaml"
    env_yaml.write_text(env_yaml.read_text().replace(
        "app_port: 5103", "app_port: 5104"))
    diff = scratch.run(whatif)
    assert "~ apps.tasksmanager-backend-api.app_port: 5103 -> 5104" in diff
    env_yaml.write_text(env_yaml.read_text().replace(
        "app_port: 5104", "app_port: 5103"))
    assert "no changes" in scratch.run(whatif)

    # boot the environment from the generated artifacts (the doc's
    # block includes module 10's SENDGRID_API_KEY export — the
    # cloud-dialect components resolve secretRefs from the env)
    orch = scratch.spawn(block_with(blocks, "tasks-tracker-env-run.yaml"))
    for port in (5103, 5189, 5217):
        scratch.wait_port(port)
    reg = "samples/tasks_tracker/.tasksrunner/apps.json"
    deadline = time.monotonic() + 30
    while True:
        ps = scratch.run(f"python -m tasksrunner ps --registry-file {reg}",
                         check=False)
        if ps.count("ok") >= 3:
            break
        assert time.monotonic() < deadline, ps
        time.sleep(0.5)
    scratch.stop_proc(orch)


def test_module_11b_github_pipeline_rehearsal(scratch):
    """Module 11b replayed: the pipeline's own commands — validate,
    what-if, apply, the smoke step's two probes, teardown — run from
    the page text in job order, printing the outputs the page quotes.
    This is the CI pipeline executed locally, which is the page's
    whole thesis."""
    scratch.materialize_samples()
    blocks = bash_blocks("11-deploy-ci-github.md")

    # job 1: lint-validate
    out = scratch.run(block_with(blocks, "deploy validate"))
    assert "manifest 'tasks-tracker-env' is valid (3 apps, 7 components)" in out
    # job 2: what-if — first run shows the full create
    out = scratch.run(block_with(blocks, "deploy what-if"))
    assert "+ tasks-tracker-env" in out
    # job 3: apply
    out = scratch.run(block_with(blocks, "deploy apply"))
    assert "applied 1 change(s)" in out

    # the smoke step: boot from the generated run config, then drive
    # one real write through the frontend — the public door — exactly
    # as the page's block does (the page backgrounds with `&` +
    # kill %1; the test manages the process itself)
    smoke = block_with(blocks, "ci-smoke")
    lines = smoke.strip().splitlines()
    # the boot prefix = everything up to the backgrounded run command;
    # fail loudly if the page's block shape changes
    boot_end = next(i for i, l in enumerate(lines) if l.rstrip().endswith("&"))
    boot_lines = lines[:boot_end + 1]
    assert boot_lines[0].startswith("export SENDGRID_API_KEY"), boot_lines
    assert any("tasksrunner run" in l for l in boot_lines), boot_lines
    assert len(boot_lines) == 3, boot_lines
    boot = "\n".join(boot_lines).replace("timeout 30 ", "")
    orch = scratch.spawn(boot.rstrip("& \n"))
    for port in (5189, 3500):
        scratch.wait_port(port)
    jar = scratch.dir / "jar"
    out = scratch.run(
        f"curl -sf -c {jar} -b {jar} http://127.0.0.1:5189/ -o /dev/null "
        f"&& echo frontend-ok")
    assert "frontend-ok" in out
    # the page's warning box, enforced: the token-fenced data plane
    # refuses the runner's direct sidecar curl
    out = scratch.run(
        "curl -s -o /dev/null -w '%{http_code}' -X POST "
        "http://127.0.0.1:3500/v1.0/invoke/tasksmanager-backend-api"
        "/method/api/tasks -H 'content-type: application/json' "
        "-d '{\"taskName\":\"x\"}'")
    assert "401" in out
    scratch.run(f"curl -sf -c {jar} -b {jar} -X POST "
                f"http://127.0.0.1:5189/ -d 'email=ci@x.com' -o /dev/null")
    scratch.run(
        f"curl -sf -c {jar} -b {jar} -X POST "
        f"http://127.0.0.1:5189/tasks/create "
        f"-d 'taskName=ci-smoke&taskAssignedTo=ci@x.com"
        f"&taskDueDate=2026-12-31' -o /dev/null")
    out = scratch.run(
        f"curl -sf -c {jar} -b {jar} http://127.0.0.1:5189/tasks "
        f"| grep ci-smoke")
    assert "ci-smoke" in out
    scratch.stop_proc(orch)

    # teardown path: down removes state; what-if shows the create again
    out = scratch.run(block_with(blocks, "deploy down"))
    assert "environment 'tasks-tracker-env' state removed" in out
    out = scratch.run(block_with(blocks, "deploy what-if"))
    assert "+ tasks-tracker-env" in out


def test_module_11c_azdo_stage_gating(scratch):
    """Module 11c §3 replayed: the broken-manifest rehearsal — the
    duplicated app_id fails `validate` non-zero with the duplicate
    named, the gate that stops both CI systems' later stages."""
    blocks = bash_blocks("11-deploy-ci-azdo.md")
    block = block_with(blocks, "broken-env.yaml")
    # the page writes to /tmp; keep the rehearsal inside the scratch dir
    block = block.replace("/tmp/broken-env.yaml",
                          str(scratch.dir / "broken-env.yaml"))
    out = scratch.run(block, check=False)
    assert "tasksmanager-backend-api" in out
    assert "duplicate" in out.lower()
    # and the verb really exited non-zero (the stage gate)
    rc = scratch.run(
        f"python -m tasksrunner deploy validate "
        f"{scratch.dir / 'broken-env.yaml'} >/dev/null 2>&1; echo rc=$?")
    assert "rc=0" not in rc


def test_module_10_secrets(scratch):
    """The secret chain through the sidecar: the granted reader gets
    the value, the ungranted reader gets the error naming its missing
    grant — both straight from the doc's blocks."""
    blocks = bash_blocks("10-secrets.md")

    orch = scratch.spawn(block_with(blocks, "SENDGRID_API_KEY=sg-local-123"))
    for port in (5103, 5189, 5217, 3502):
        scratch.wait_port(port)
    deadline = time.monotonic() + 30
    while True:
        ps = scratch.run("python -m tasksrunner ps", check=False)
        if ps.count("ok") >= 3:
            break
        assert time.monotonic() < deadline, ps
        time.sleep(0.5)

    # §2 the granted reader resolves the env-backed secret
    out = scratch.run(block_with(blocks, "tasksmanager-backend-processor"))
    assert '"sendgrid-api-key": "sg-local-123"' in out
    # raw curl against the processor's sidecar
    raw = scratch.run(block_with(blocks, "v1.0/secrets/secretstoreakv"))
    assert '"sendgrid-api-key": "sg-local-123"' in raw

    # §3 the wrong reader is refused with the grant named
    out = scratch.run(block_with(blocks, "tasksmanager-frontend-webapp"),
                      check=False)
    assert "has no 'read' grant on component 'secretstoreakv'" in out

    scratch.stop_proc(orch)


def test_module_12_footprint_measurement(scratch):
    """The daemonless container measurement prints the breakdown and a
    payload saving >= 50%, as the module's checkpoint promises."""
    blocks = bash_blocks("12-optimize-containers.md")
    out = scratch.run("cd " + str(REPO) + " && " +
                      block_with(blocks, "measure_footprint"))
    assert "installed-footprint" in out
    m = re.search(r"payload saving, default -> optimized: ([0-9.]+)%", out)
    assert m and float(m.group(1)) >= 50.0, out


def test_module_12_oci_image_build(scratch, tmp_path):
    """§4 replayed on a real artifact: the builder writes OCI image
    layouts, the optimized payload layers are >=50% smaller than the
    default's (the reference's measured-image claim, module 12
    :318-326), the shared runtime layer dedups by digest, and the
    layout survives the same digest walk skopeo would do."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "build_oci_image", REPO / "scripts" / "build_oci_image.py")
    oci = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(oci)

    out_dir = tmp_path / "oci"
    shared: dict = {}
    default = oci.build_image("backend-api", "default", out_dir, shared)
    optimized = oci.build_image("backend-api", "optimized", out_dir, shared)

    # a real, inspectable artifact: index -> manifest -> config ->
    # layers, every digest/size/diff_id re-derived
    oci.verify_layout(out_dir / "backend-api-default")
    oci.verify_layout(out_dir / "backend-api-optimized")

    # the measured saving on the variant-controlled layers
    saving = 1 - (optimized["payload_uncompressed"]
                  / default["payload_uncompressed"])
    assert saving >= 0.50, f"payload saving {saving:.1%} < 50%"

    # base-layer dedup: identical runtime blob in both images
    runtime_digest = default["layers"][0]["digest"]
    assert optimized["layers"][0]["digest"] == runtime_digest
    blob = runtime_digest.split(":", 1)[1]
    assert (out_dir / "backend-api-default" / "blobs" / "sha256" / blob).is_file()
    assert (out_dir / "backend-api-optimized" / "blobs" / "sha256" / blob).is_file()

    # reproducibility: rebuilding yields byte-identical digests
    rebuilt = oci.build_image("backend-api", "optimized", out_dir, {})
    assert [l["digest"] for l in rebuilt["layers"]] == \
        [l["digest"] for l in optimized["layers"]]

    # a corrupted blob must fail verification
    victim = out_dir / "backend-api-default" / "blobs" / "sha256" / blob
    data = victim.read_bytes()
    victim.write_bytes(data[:-1] + bytes([data[-1] ^ 1]))
    with pytest.raises(oci.LayoutError, match="corrupt"):
        oci.verify_layout(out_dir / "backend-api-default")


def test_module_02_communication(scratch):
    """The module's whole argument, replayed: the configured-URL path
    breaks when the API moves ports; the app-id path survives the
    identical move with zero reconfiguration."""
    blocks = bash_blocks("02-communication.md")

    def spawn_each(block: str) -> list:
        """The doc backgrounds both hosts in one block with `&`; spawn
        each as its own process so the test can kill the API alone the
        way the reader's `kill %1` does."""
        procs, acc = [], []
        for line in block.strip().splitlines():
            acc.append(line)
            if re.search(r"&\s*(#.*)?$", line):  # command ends backgrounded
                cmd = re.sub(r"\s*&\s*(#.*)?$", "", "\n".join(acc).strip())
                procs.append(scratch.spawn(cmd))
                acc = []
        assert not acc, acc
        return procs

    def restart_cmd(block: str) -> str:
        """The §2.3/§3.3 blocks pair `kill %1` (job control the test
        does itself via stop_proc) with the restart command."""
        lines = [l for l in block.strip().splitlines()
                 if not l.startswith("kill")]
        return re.sub(r"\s*&\s*(#.*)?$", "", "\n".join(lines).strip())

    # ---- §2 the wrong way first: a configured base URL --------------
    api, fe = spawn_each(
        block_with(blocks, "BACKENDAPICONFIG__BASEURLEXTERNALHTTP"))
    for port in (5103, 3500, 5189, 3501):
        scratch.wait_port(port)

    # §2.2 sign in, land on the ten seeded tasks (browser walk via curl)
    scratch.run("curl -s -c cookies.txt -X POST http://127.0.0.1:5189/ "
                "-d 'email=tempuser@mail.com' -o /dev/null")
    listed = scratch.run("curl -s -b cookies.txt http://127.0.0.1:5189/tasks")
    assert listed.count("/tasks/edit/") == 10, listed

    # §2.3 move the API to another port; the pinned URL goes stale and
    # the page says so
    scratch.stop_proc(api)
    api = scratch.spawn(restart_cmd(block_with(blocks, "--app-port 5104")))
    scratch.wait_port(5104)
    broken = scratch.run(
        "curl -s -b cookies.txt -w '\\nHTTP %{http_code}' "
        "http://127.0.0.1:5189/tasks")
    assert "HTTP 502" in broken, broken
    assert "The backend API is unreachable." in broken, broken

    # "Kill both hosts before continuing"
    scratch.stop_proc(api)
    scratch.stop_proc(fe)

    # ---- §3 the right way: invocation by app id ---------------------
    plain = [b for b in blocks
             if "frontend_ui" in b and "BACKENDAPICONFIG" not in b]
    assert plain, ("no un-pinned two-host block — the doc changed; "
                   "update this walkthrough test with it")
    api, fe = spawn_each(plain[0])
    for port in (5103, 3500, 5189, 3501):
        scratch.wait_port(port)

    # §3.2 the full CRUD loop the doc walks in the browser
    scratch.run("curl -s -c c2.txt -X POST http://127.0.0.1:5189/ "
                "-d 'email=tempuser@mail.com' -o /dev/null")
    listed = scratch.run("curl -s -b c2.txt http://127.0.0.1:5189/tasks")
    assert listed.count("/tasks/edit/") == 10

    # create → the list shows it
    scratch.run("curl -s -b c2.txt -X POST http://127.0.0.1:5189/tasks/create "
                "-d 'taskName=Module 2 task&taskDueDate=2026-12-01"
                "&taskAssignedTo=peer@mail.com' -o /dev/null")
    listed = scratch.run("curl -s -b c2.txt http://127.0.0.1:5189/tasks")
    assert "Module 2 task" in listed
    tid = re.search(r'/tasks/edit/([0-9a-f-]+)"[^>]*>Module 2 task', listed).group(1)

    # empty name → per-field message in the reference's wording, HTTP 400
    invalid = scratch.run(
        "curl -s -b c2.txt -w '\\nHTTP %{http_code}' "
        "-X POST http://127.0.0.1:5189/tasks/create "
        "-d 'taskName=&taskDueDate=2026-12-01&taskAssignedTo=peer@mail.com'")
    assert "The Task Name field is required." in invalid
    assert "HTTP 400" in invalid

    # edit: change the assignee, save
    scratch.run(f"curl -s -b c2.txt -X POST http://127.0.0.1:5189/tasks/edit/{tid} "
                "-d 'taskName=Module 2 task&taskDueDate=2026-12-01"
                "&taskAssignedTo=other@mail.com' -o /dev/null")
    listed = scratch.run("curl -s -b c2.txt http://127.0.0.1:5189/tasks")
    assert "other@mail.com" in listed

    # complete, then delete
    scratch.run(f"curl -s -b c2.txt -X POST "
                f"http://127.0.0.1:5189/tasks/complete/{tid} -o /dev/null")
    listed = scratch.run("curl -s -b c2.txt http://127.0.0.1:5189/tasks")
    assert re.search(r'class="done">completed</span>', listed)
    scratch.run(f"curl -s -b c2.txt -X POST "
                f"http://127.0.0.1:5189/tasks/delete/{tid} -o /dev/null")
    listed = scratch.run("curl -s -b c2.txt http://127.0.0.1:5189/tasks")
    assert "Module 2 task" not in listed

    # §3.3 the resilience proof: same port move, zero reconfiguration
    scratch.stop_proc(api)
    scratch.spawn(restart_cmd(block_with(blocks, "different app port again")))
    scratch.wait_port(5104)
    deadline = time.monotonic() + 30
    while True:
        listed = scratch.run(
            "curl -s -b c2.txt -w '\\nHTTP %{http_code}' "
            "http://127.0.0.1:5189/tasks", check=False)
        if "HTTP 200" in listed and listed.count("/tasks/edit/") == 10:
            break  # fake manager reseeded: identical behavior, new port
        assert time.monotonic() < deadline, listed
        time.sleep(0.5)


def test_module_03_sidecar(scratch):
    """The sidecar as a separate program: attach it to a running app,
    kill each side in both orders, read the metadata introspection —
    the checkpoint curl answering after every recovery."""
    blocks = bash_blocks("03-sidecar.md")
    probe = ("curl -s 'http://127.0.0.1:3500/v1.0/invoke/"
             "tasksmanager-backend-api/method/api/tasks?createdBy="
             "tempuser@mail.com'")

    def wait_probe(timeout=30.0):
        deadline = time.monotonic() + timeout
        while True:
            out = scratch.run(probe, check=False)
            try:
                tasks = json.loads(out)
                if len(tasks) == 10:
                    return
            except ValueError:
                pass
            assert time.monotonic() < deadline, out
            time.sleep(0.5)

    # §2.1 the app alone: up, but no distributed capabilities
    serve_cmd = block_with(blocks, "tasksrunner serve")
    app = scratch.spawn(serve_cmd)
    scratch.wait_port(5103)

    # §2.2 attach the sidecar; the doc's expected ready line appears
    sidecar_cmd = block_with(blocks, "tasksrunner sidecar")
    sc = scratch.spawn(sidecar_cmd)
    scratch.wait_port(3500)
    deadline = time.monotonic() + 20
    while "listening on 127.0.0.1:3500" not in "".join(sc.output):
        assert time.monotonic() < deadline, "".join(sc.output)
        time.sleep(0.2)
    wait_probe()

    # §2.3 order independence, first order: kill the APP, sidecar stays
    scratch.stop_proc(app)
    assert sc.poll() is None
    assert _port_open(3500)
    app = scratch.spawn(serve_cmd)
    scratch.wait_port(5103)
    wait_probe()  # sidecar re-probes, service resumes

    # reverse order: kill the SIDECAR under a running app
    scratch.stop_proc(sc)
    assert app.poll() is None
    direct = scratch.run("curl -s 'http://127.0.0.1:5103/api/tasks?"
                         "createdBy=tempuser@mail.com'")
    assert len(json.loads(direct)) == 10  # the app never noticed
    sc = scratch.spawn(sidecar_cmd)
    scratch.wait_port(3500)
    wait_probe()

    # §4 introspection: scoped components, no subscriptions for the API
    meta = scratch.run(block_with(blocks, "v1.0/metadata"))
    parsed = json.loads(re.search(r"\{.*\}", meta, re.S).group(0))
    assert parsed["id"] == "tasksmanager-backend-api"
    names = {c["name"] for c in parsed["components"]}
    assert "statestore" in names
    assert parsed.get("subscriptions") == []


def test_module_08_observability(scratch):
    """Logs, traces, metrics from one terminal: the happy transaction
    with its async consumer tail, the poison event's redelivery story
    as ONE trace, the service map (text and mermaid), and the counters
    — every command from the doc."""
    blocks = bash_blocks("08-observability.md")
    orch = _boot_topology(scratch)

    # §2.1 produce a transaction (module 5's invoke, as the doc says)
    scratch.run(block_with(bash_blocks("05-pubsub.md"),
                           '"taskName":"Ship module 5"'))
    logs_cmd = "python -m tasksrunner logs tasksmanager-backend-processor --tail 40"
    _poll_logs(scratch, logs_cmd,
               "Started processing message with task name 'Ship module 5'")

    # §1 role-tagged, trace-tagged structured logs
    logs = scratch.run(block_with(blocks, "--tail 20").splitlines()[0])
    assert "trace=" in logs

    # §2.2 transaction search: find the write transaction, drill in
    listed = scratch.run(block_with(blocks, "traces list --limit 5"))
    m = re.search(r"^([0-9a-f]{16})\s.*api/tasks", listed, re.M)
    assert m, listed
    trace_id = m.group(1)
    show_cmd = block_with(blocks, "traces show").replace(
        "53d22b80e13c0278", trace_id)
    deadline = time.monotonic() + 20
    while True:  # the async consumer tail lands after the HTTP response
        shown = scratch.run(show_cmd)
        if "consumer" in shown and "/api/tasksnotifier/tasksaved" in shown:
            break
        assert time.monotonic() < deadline, shown
        time.sleep(0.5)
    assert "[tasksmanager-backend-api]" in shown
    assert "producer" in shown and "server" in shown

    # §2.3 the poison event: publish succeeds, then the redelivery
    # attempts fail visibly inside the SAME trace
    scratch.run(block_with(blocks, '"poison-1"'))
    deadline = time.monotonic() + 30
    while True:
        listed = scratch.run(block_with(blocks, "traces list --limit 1"))
        p = re.search(r"^([0-9a-f]{16})\s.*publish dapr-pubsub-servicebus",
                      listed, re.M)
        if p:
            poison_shown = scratch.run(show_cmd.replace(trace_id, p.group(1)))
            if poison_shown.count("(500)") >= 3:
                break
        assert time.monotonic() < deadline, listed
        time.sleep(0.5)
    assert "producer publish dapr-pubsub-servicebus/tasksavedtopic (200)" \
        in poison_shown

    # §2.4 the service map, text and mermaid
    the_map = scratch.run(block_with(blocks, "traces map\n"))
    assert re.search(r"--producer-->\s+dapr-pubsub-servicebus/tasksavedtopic",
                     the_map)
    assert "avg" in the_map
    mermaid = scratch.run(block_with(blocks, "traces map --mermaid"))
    assert "graph LR" in mermaid
    assert "-.->" in mermaid  # dashed publish edge

    # §3 metrics: delivery counters with status labels, incl. the 500s
    metrics = scratch.run(block_with(blocks, "tasksrunner metrics"))
    assert re.search(
        r"pubsub_delivery\{route=/api/tasksnotifier/tasksaved,status=200\}\s+\d",
        metrics)
    assert "status=500" in metrics  # the redelivery-loop early warning
    assert "uptime_seconds" in metrics

    # the raw feed behind ps/metrics
    meta = scratch.run(block_with(blocks, "v1.0/metadata"))
    assert '"id"' in meta and '"components"' in meta

    # §3b the local Log-Analytics pane: every example query from the
    # page runs over the live span store
    out = scratch.run(block_with(blocks, "GROUP BY role"))
    assert out.splitlines()[0] == "role\tn\tavg_ms"
    assert "tasksmanager-backend-api" in out
    out = scratch.run(block_with(blocks, "wall_ms DESC"))
    assert out.splitlines()[0] == "trace_id\twall_ms\tspans"
    assert re.search(r"^[0-9a-f]{32}\t", out.splitlines()[1]), out
    out = scratch.run(block_with(blocks, "kind='consumer'"))
    assert "/api/tasksnotifier/tasksaved" in out
    # and a query drilling into the poison route's errors shows them
    # read-only: a mutating query must fail without touching telemetry
    out = scratch.run("python -m tasksrunner traces query "
                      "'DELETE FROM spans'", check=False)
    assert "query failed" in out and "readonly" in out.lower()

    scratch.stop_proc(orch)


def test_module_09_autoscale_flood(scratch):
    """The KEDA-style load test: gate the email integration off (the
    reference's own load-test instruction), flood 200 events, watch the
    scaler breathe 1→5→1 in the orchestrator's log, and finish with an
    empty DLQ — all from the doc's blocks."""
    blocks = bash_blocks("09-autoscale.md")
    orch = _boot_topology(scratch)

    out = scratch.run(block_with(blocks, "SENDGRID__INTEGRATIONENABLED=false"))
    assert "revision 2" in out

    out = scratch.run(block_with(blocks, "--count 200"))
    assert "published 200/200" in out

    def orch_log() -> str:
        return "".join(orch.output)

    # generous deadlines: on a loaded host the scaler's first sighting
    # of the backlog can lag several poll intervals
    deadline = time.monotonic() + 90
    while not re.search(
            r"scaling tasksmanager-backend-processor out: \d+ -> 5", orch_log()):
        assert time.monotonic() < deadline, orch_log()[-2000:]
        time.sleep(0.5)
    deadline = time.monotonic() + 120
    while not re.search(
            r"scaling tasksmanager-backend-processor in: \d+ -> 1", orch_log()):
        assert time.monotonic() < deadline, orch_log()[-2000:]
        time.sleep(0.5)

    # §3.4 exactly-once evidence: an empty DLQ after the episode
    out = scratch.run(block_with(blocks, "dlq list"))
    assert "no dead letters" in out

    scratch.stop_proc(orch)


def test_appendix_variables(scratch):
    """The session-variables appendix replayed as the two sittings it
    describes: save at the end of one shell, restore in a fresh one,
    update in place, and the direct-execution warning."""
    (scratch.dir / "scripts").symlink_to(REPO / "scripts")
    blocks = bash_blocks("31-appendix-variables.md")

    # sitting 1 ends: export + save (one shell, the page's block)
    out = scratch.run(block_with(blocks, "set_variables.sh save"))
    assert "saved 3 variable(s)" in out
    out = scratch.run(block_with(blocks, "set_variables.sh show"))
    assert out.splitlines()[:3] == [
        "SENDGRID_API_KEY=sg-123",
        "TASKSRUNNER_API_TOKEN=tok-1",
        "TASKS_MANAGER=store",
    ]

    # sitting 2: a FRESH shell restores and the state is back
    out = scratch.run(block_with(blocks, "manager=$TASKS_MANAGER"))
    assert "restored 3 variable(s)" in out
    assert "manager=store key=sg-123" in out

    # §4 update-in-place: changed value, still THREE lines (the doc's
    # checkpoint 3 — an update must not shrink the snapshot)
    out = scratch.run(block_with(blocks, "TASKS_MANAGER=fake"))
    assert "TASKS_MANAGER=fake" in out
    show = scratch.run("source scripts/set_variables.sh show")
    assert show.count("TASKS_MANAGER=fake") == 1
    assert len([l for l in show.splitlines() if "=" in l]) == 3, show
    # put the store value back for the boot below
    scratch.run("source scripts/set_variables.sh restore && "
                "export TASKS_MANAGER=store && "
                "source scripts/set_variables.sh save")

    # checkpoint 4: executed directly, restore warns and fails
    out = scratch.run("bash scripts/set_variables.sh restore; echo rc=$?")
    assert "die" in out or "source" in out
    assert "rc=1" in out

    # §2's proof: the restored environment boots the full sample
    # (sendgrid secretRef resolves from the restored shell)
    orch = scratch.spawn(
        "source scripts/set_variables.sh restore && "
        "python -m tasksrunner run run.yaml")
    for port in (5103, 5189, 5217):
        scratch.wait_port(port)
    deadline = time.monotonic() + 30
    while True:
        ps = scratch.run("python -m tasksrunner ps", check=False)
        if ps.count("ok") >= 3:
            break
        assert time.monotonic() < deadline, ps
        time.sleep(0.5)
    scratch.stop_proc(orch)


def test_appendix_debugging_forensic_loop(scratch):
    """The debugging appendix's one-terminal altitude, replayed: boot
    the topology, run the forensic commands the page lists (ps, logs
    --tail, traces list/show/map), then the deliberate-kill move and
    the recovery the page promises."""
    blocks = bash_blocks("30-appendix-debugging.md")
    orch = scratch.spawn(block_with(blocks, "tasksrunner run run.yaml"),
                         extra_env={"SENDGRID_API_KEY": "sg-dbg"})
    for port in (5103, 5189, 5217):
        scratch.wait_port(port)
    deadline = time.monotonic() + 30
    while True:
        ps = scratch.run(block_with(blocks, "tasksrunner ps"), check=False)
        if ps.count("ok") >= 3:
            break
        assert time.monotonic() < deadline, ps
        time.sleep(0.5)

    # make one transaction to have something to inspect
    scratch.run("curl -sf -X POST http://127.0.0.1:3500/v1.0/invoke/"
                "tasksmanager-backend-api/method/api/tasks "
                "-H 'content-type: application/json' "
                "-d '{\"taskName\":\"dbg\",\"taskCreatedBy\":\"d@x.com\"}'")

    out = scratch.run(block_with(blocks, "logs tasksmanager-backend-api"))
    assert "role=tasksmanager-backend-api" in out
    # the three pivot commands share one block; run line by line,
    # filling the <trace-id> placeholder the way the reader would
    pivot = [l.split("#")[0].strip()
             for l in block_with(blocks, "traces list").splitlines()
             if l.strip()]
    assert len(pivot) == 3, pivot
    out = scratch.run(pivot[0])                       # traces list
    trace_id = out.split()[0]
    out = scratch.run(pivot[1].replace("<trace-id>", trace_id))
    assert "invoke" in out or "POST" in out
    out = scratch.run(pivot[2])                       # traces map --mermaid
    assert "graph" in out or "-->" in out  # mermaid output

    # the deliberate kill: staged restart, then recovery
    out = scratch.run(block_with(blocks, "tasksrunner restart"))
    deadline = time.monotonic() + 30
    while True:
        ps = scratch.run("python -m tasksrunner ps", check=False)
        if ps.count("ok") >= 3:
            break
        assert time.monotonic() < deadline, ps
        time.sleep(0.5)
    # the re-resolve argument: the same invoke works after the restart
    out = scratch.run("curl -sf -X POST http://127.0.0.1:3500/v1.0/invoke/"
                      "tasksmanager-backend-api/method/api/tasks "
                      "-H 'content-type: application/json' "
                      "-d '{\"taskName\":\"dbg2\",\"taskCreatedBy\":\"d@x.com\"}'")
    assert "taskId" in out
    scratch.stop_proc(orch)


def test_docs_mermaid_blocks_are_wellformed():
    """Every mermaid fence in the docs opens with a known diagram type
    and closes — the strict mkdocs build renders them client-side, so
    a truncated block would fail silently at read time, not build
    time. (The three load-bearing diagrams: scenario architecture,
    module-5 pub/sub topology, module-15 production topology.)"""
    import pathlib
    docs = pathlib.Path(__file__).resolve().parents[1] / "docs"
    known = ("flowchart", "sequenceDiagram", "graph", "stateDiagram")
    found = []
    for md in sorted(docs.rglob("*.md")):
        lines = md.read_text().splitlines()
        open_at = None
        for i, line in enumerate(lines):
            if line.strip() == "```mermaid":
                assert open_at is None, f"{md}:{i+1}: nested mermaid fence"
                open_at = i
                first = next((l.strip() for l in lines[i + 1:]
                              if l.strip()), "")
                assert first.startswith(known), \
                    f"{md}:{i+2}: unknown mermaid type {first[:30]!r}"
            elif line.strip().startswith("```") and open_at is not None:
                found.append(md.name)
                open_at = None
        assert open_at is None, f"{md}: unclosed mermaid fence"
    # the three diagrams the round-4 verdict called load-bearing
    assert "00-intro-2-scenario-architecture.md" in found
    assert "05-pubsub.md" in found
    assert "15-production-baseline.md" in found


def test_appendix_snippets_commands_are_real():
    """The command-snippets appendix (module 35) is a copy-paste
    surface: every `python -m tasksrunner <sub>` it shows must be a
    registered CLI subcommand, and the OCI builder flags must match
    the script's argparse choices — the page may never rot ahead of
    the tools it quotes."""
    import pathlib
    import re

    from tasksrunner.cli import build_parser

    page = (pathlib.Path(__file__).resolve().parents[1]
            / "docs/modules/35-appendix-snippets.md").read_text()
    subs = set(re.findall(r"python -m tasksrunner (\w+)", page))
    assert {"host", "serve", "sidecar", "run", "state"} <= subs
    parser = build_parser()
    known = set()
    for action in parser._subparsers._group_actions:
        known |= set(action.choices)
    unknown = subs - known
    assert not unknown, f"snippets page quotes unknown subcommands: {unknown}"
    # the OCI builder flags quoted on the page
    assert "--service backend-api --variant optimized" in page
