"""Whole-program tasklint: ProgramGraph rules + engine mechanics.

Same two-layer shape as test_tasklint.py: seeded-bad-code fixtures
prove each interprocedural rule fires (and stays quiet on the healthy
variant), and the mechanics tests pin the program-phase contracts —
chain-aware suppression, the tree-digest cache, ``--changed`` keeping
the program phase whole-tree, the v2 JSON schema, and the wall-time
budget that keeps `make lint` usable as a pre-commit step.
"""

import io
import json
import os
import pathlib
import subprocess
import sys
import textwrap
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tasksrunner.analysis import engine
from tasksrunner.analysis.cache import (
    ResultCache, _digest_memo, ruleset_signature,
)
from tasksrunner.analysis.core import PROGRAM_RULES, known_rule_ids
from tasksrunner.analysis.engine import (
    DEFAULT_TARGET, _program_suppressed, changed_paths, run,
)
from tasksrunner.analysis.program import ProgramGraph

ALL_RULES = tuple(sorted(known_rule_ids()))
PROGRAM_ONLY = tuple(sorted(PROGRAM_RULES))


def _program(tmp_path, sources, rules=PROGRAM_ONLY):
    """Build a ProgramGraph over ``sources`` ({relpath: code}) with
    controlled relpaths (so cross-module imports resolve) and run the
    program rules through the real suppression filter."""
    files = []
    for name, src in sources.items():
        path = tmp_path / name
        path.write_text(textwrap.dedent(src))
        files.append((path, name))
    graph = ProgramGraph.build(files)
    raw = []
    for rid in rules:
        raw.extend(PROGRAM_RULES[rid].check(graph))
    findings = sorted(f for f in raw if not _program_suppressed(graph, f))
    return findings, len(raw) - len(findings)


# -- transitive-blocking ------------------------------------------------


ENTRY = """\
from b import helper


async def entry():
    helper()
"""

HELPERS = """\
import time


def helper():
    deeper()


def deeper():
    time.sleep(1)
"""


def test_transitive_blocking_reports_cross_module_chain(tmp_path):
    findings, _ = _program(tmp_path, {"a.py": ENTRY, "b.py": HELPERS},
                           rules=("transitive-blocking",))
    (f,) = findings
    assert f.rule == "transitive-blocking"
    assert (f.path, f.line) == ("a.py", 5)  # the entry call site
    assert "entry" in f.message and "deeper" in f.message
    assert "time.sleep" in f.message and "off-loop dispatch" in f.message
    # full path: entry call -> helper's call -> the blocking leaf
    assert [frame.split(":")[0] for frame in f.chain] == \
        ["a.py", "b.py", "b.py"]
    assert f.chain == ("a.py:5", "b.py:5", "b.py:9")


def test_transitive_blocking_stops_at_dispatch_and_off_loop(tmp_path):
    dispatched = """\
        import asyncio

        from b import helper


        async def entry():
            await asyncio.to_thread(helper)
        """
    findings, _ = _program(tmp_path, {"a.py": dispatched, "b.py": HELPERS},
                           rules=("transitive-blocking",))
    assert findings == []

    declared = HELPERS.replace("def helper():",
                               "def helper():  # tasklint: off-loop")
    findings, _ = _program(tmp_path, {"a.py": ENTRY, "b.py": declared},
                           rules=("transitive-blocking",))
    assert findings == []


def test_transitive_suppressable_at_entry_or_leaf(tmp_path):
    at_entry = ENTRY.replace(
        "    helper()",
        "    helper()  # tasklint: disable=transitive-blocking")
    findings, suppressed = _program(
        tmp_path, {"a.py": at_entry, "b.py": HELPERS},
        rules=("transitive-blocking",))
    assert (findings, suppressed) == ([], 1)

    at_leaf = HELPERS.replace(
        "    time.sleep(1)",
        "    time.sleep(1)  # tasklint: disable=transitive-blocking")
    findings, suppressed = _program(
        tmp_path, {"a.py": ENTRY, "b.py": at_leaf},
        rules=("transitive-blocking",))
    assert (findings, suppressed) == ([], 1)


# -- held-lock-across-await ---------------------------------------------


def test_held_lock_across_await_fires_with_chain(tmp_path):
    findings, _ = _program(tmp_path, {"m.py": """\
        import asyncio
        import threading

        L = threading.Lock()


        async def bad():
            with L:
                await asyncio.sleep(0)
        """}, rules=("held-lock-across-await",))
    (f,) = findings
    assert f.line == 8
    assert "L is held" in f.message and "await" in f.message
    assert f.chain == ("m.py:8", "m.py:9")  # acquire, then the await


def test_held_lock_not_spanning_await_is_clean(tmp_path):
    findings, _ = _program(tmp_path, {"m.py": """\
        import asyncio
        import threading

        L = threading.Lock()
        A = asyncio.Lock()  # not a threading lock: fine across awaits


        async def ok():
            with L:
                x = 1
            await asyncio.sleep(0)
            async with A:
                await asyncio.sleep(0)
        """}, rules=("held-lock-across-await",))
    assert findings == []


def test_held_lock_suppressable_on_acquire_line(tmp_path):
    findings, suppressed = _program(tmp_path, {"m.py": """\
        import asyncio
        import threading

        L = threading.Lock()


        async def bad():
            with L:  # tasklint: disable=held-lock-across-await
                await asyncio.sleep(0)
        """}, rules=("held-lock-across-await",))
    assert (findings, suppressed) == ([], 1)


# -- lock-order-cycle ---------------------------------------------------


def test_lock_order_cycle_nested_with(tmp_path):
    findings, _ = _program(tmp_path, {"m.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()


        def one():
            with A:
                with B:
                    pass


        def two():
            with B:
                with A:
                    pass
        """}, rules=("lock-order-cycle",))
    (f,) = findings  # the mirror-image cycle is deduplicated
    assert "lock order cycle" in f.message
    assert "A -> B -> A" in f.message or "B -> A -> B" in f.message
    assert len(f.chain) == 2  # one witness frame per edge


def test_lock_order_cycle_interprocedural(tmp_path):
    findings, _ = _program(tmp_path, {"m.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()


        def one():
            with A:
                grab()


        def grab():
            with B:
                pass


        def two():
            with B:
                with A:
                    pass
        """}, rules=("lock-order-cycle",))
    (f,) = findings
    assert "calls grab" in f.message  # the A→B edge goes through a call


def test_lock_order_consistent_is_clean(tmp_path):
    findings, _ = _program(tmp_path, {"m.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()


        def one():
            with A:
                with B:
                    pass


        def two():
            with A:
                with B:
                    pass
        """}, rules=("lock-order-cycle",))
    assert findings == []


def test_lock_order_cycle_suppressable_on_witness_frame(tmp_path):
    """The finding spans two witness sites; a disable on either chain
    frame (here: one()'s outer acquisition) silences it."""
    findings, suppressed = _program(tmp_path, {"m.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()


        def one():
            with A:  # tasklint: disable=lock-order-cycle
                with B:
                    pass


        def two():
            with B:
                with A:
                    pass
        """}, rules=("lock-order-cycle",))
    assert (findings, suppressed) == ([], 1)


# -- thread-shared-state ------------------------------------------------


RACY = """\
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        threading.Thread(target=self._work).start()

    def _work(self):
        self.count = 1

    async def bump(self):
        self.count = 2
"""


def test_thread_shared_state_fires_across_boundary(tmp_path):
    findings, _ = _program(tmp_path, {"m.py": RACY},
                           rules=("thread-shared-state",))
    (f,) = findings
    assert "Box.count" in f.message and "no common lock" in f.message
    assert f.line == 11  # the thread-side write
    assert len(f.chain) == 2  # thread-side frame, loop-side frame


def test_thread_shared_state_common_lock_is_clean(tmp_path):
    guarded = RACY.replace(
        "        self.count = 1",
        "        with self._lock:\n            self.count = 1").replace(
        "        self.count = 2",
        "        with self._lock:\n            self.count = 2")
    findings, _ = _program(tmp_path, {"m.py": guarded},
                           rules=("thread-shared-state",))
    assert findings == []


def test_thread_shared_state_init_only_writes_are_clean(tmp_path):
    findings, _ = _program(tmp_path, {"m.py": """\
        import threading


        class Box:
            def __init__(self):
                self.count = 0
                threading.Thread(target=self._work).start()

            def _work(self):
                self.count = 1
        """}, rules=("thread-shared-state",))
    assert findings == []  # __init__ runs before the object is shared


def test_thread_shared_state_suppression_on_write_line(tmp_path):
    quiet = RACY.replace(
        "        self.count = 1",
        "        self.count = 1  # tasklint: disable=thread-shared-state")
    findings, suppressed = _program(tmp_path, {"m.py": quiet},
                                    rules=("thread-shared-state",))
    assert (findings, suppressed) == ([], 1)


# -- route-conformance --------------------------------------------------


ROUTE_TABLE = """\
@routes.get("/v1.0/state/{store}/{key}")
async def get_state(request):
    pass


@routes.post("/v1.0/state/{store}")
async def save_state(request):
    pass


def register(app):
    app.router.add_get("/admin/apps", list_apps)
"""


def test_route_conformance_flags_drifted_path(tmp_path):
    findings, _ = _program(tmp_path, {"app.py": ROUTE_TABLE,
                                      "client.py": """\
        async def drifted(session, store):
            await session.get(f"/v1.0/states/{store}/x")
        """}, rules=("route-conformance",))
    (f,) = findings
    assert (f.path, f.line) == ("client.py", 2)
    assert "matches no declared route" in f.message
    assert "closest route: GET /v1.0/state/{store}/{key}" in f.message
    assert len(f.chain) == 2  # the site, then the closest route


def test_route_conformance_flags_method_mismatch(tmp_path):
    findings, _ = _program(tmp_path, {"app.py": ROUTE_TABLE,
                                      "client.py": """\
        async def wrong_verb(session):
            await session.post("/admin/apps")
        """}, rules=("route-conformance",))
    (f,) = findings
    assert "POST /admin/apps" in f.message


def test_route_conformance_matching_sites_are_clean(tmp_path):
    findings, _ = _program(tmp_path, {"app.py": ROUTE_TABLE,
                                      "client.py": """\
        async def fetch(session, store, key):
            await session.get(f"/v1.0/state/{store}/{key}")


        async def save(sidecar, store):
            await _sidecar_request(sidecar, "POST", f"state/{store}")


        async def external(session):
            await session.get("http://example.com/metrics")
        """}, rules=("route-conformance",))
    assert findings == []


def test_route_conformance_suppressable_on_site_line(tmp_path):
    findings, suppressed = _program(tmp_path, {"app.py": ROUTE_TABLE,
                                               "client.py": """\
        async def legacy(session, store):
            # the old spelling, kept for a deprecated peer
            await session.get(f"/v1.0/states/{store}/x")  # tasklint: disable=route-conformance
        """}, rules=("route-conformance",))
    assert (findings, suppressed) == ([], 1)


# -- engine mechanics: program phase ------------------------------------


PROG_BAD = """\
import time


async def entry():
    helper()


def helper():
    deeper()


def deeper():
    time.sleep(1)
"""

GOOD = """\
import asyncio


async def handler():
    await asyncio.sleep(0.1)
"""


def test_run_emits_program_findings_with_chain_in_json(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(PROG_BAD)
    out = io.StringIO()
    rc = run([target], ("transitive-blocking",), json_out=True, out=out)
    assert rc == 1
    doc = json.loads(out.getvalue())
    assert doc["version"] == 4
    (finding,) = doc["findings"]
    assert finding["rule"] == "transitive-blocking"
    assert len(finding["chain"]) == 3
    assert all(frame.rsplit(":", 1)[1].isdigit()
               for frame in finding["chain"])


def test_program_phase_uses_tree_digest_cache(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(PROG_BAD)
    cache_file = tmp_path / "cache.json"

    def _run():
        out = io.StringIO()
        rc = run([target], ALL_RULES, cache_path=cache_file, out=out)
        return rc, out.getvalue()

    rc1, text1 = _run()
    rc2, text2 = _run()
    assert (rc1, rc2) == (1, 1)
    assert "cached" not in text1
    # one per-file hit + the program, dataflow, and interleave entries
    assert "4 cached" in text2

    # any content change invalidates the tree digest
    target.write_text(PROG_BAD + "# trailing comment\n")
    rc3, text3 = _run()
    assert rc3 == 1 and "cached" not in text3


def test_bad_suppression_fires_for_unknown_id_on_chain_line(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(PROG_BAD.replace(
        "    helper()",
        "    helper()  # tasklint: disable=transitive-blocked"))  # typo
    out = io.StringIO()
    rc = run([target], ALL_RULES, out=out)
    assert rc == 1
    text = out.getvalue()
    # the typo is reported AND the chain it meant to silence still fires
    assert "bad-suppression" in text and "transitive-blocked" in text
    assert "transitive-blocking" in text.replace("transitive-blocked", "")


def test_content_hash_invalidates_same_size_touch_r(tmp_path):
    """``touch -r`` style edits: same byte count, restored mtime. The
    mtime+size proxy is blind to this; the persisted sha1 is not."""
    bad1 = "import time\n\nasync def handler():\n    time.sleep(1)\n"
    bad2 = "import time\n\nasync def handler():\n    time.sleep(2)\n"
    assert len(bad1) == len(bad2)
    target = tmp_path / "mod.py"
    target.write_text(bad1)
    stat = target.stat()

    sig = ruleset_signature(("blocking-call-in-async",))
    cache_file = tmp_path / "cache.json"
    cache = ResultCache(cache_file, sig)
    findings, _ = engine.lint_file(target, ("blocking-call-in-async",))
    cache.put(target, findings)
    cache.save()
    assert ResultCache(cache_file, sig).get(target) == (findings, 0)

    target.write_text(bad2)
    os.utime(target, ns=(stat.st_atime_ns, stat.st_mtime_ns))
    _digest_memo.clear()  # a fresh process has no per-run memo
    assert ResultCache(cache_file, sig).get(target) is None


def test_changed_narrows_files_but_program_phase_stays_whole_tree(
        tmp_path, monkeypatch, capfd):
    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(["git", "-C", str(repo), *args],
                       check=True, capture_output=True)

    git("init", "-q")
    git("symbolic-ref", "HEAD", "refs/heads/main")
    git("config", "user.email", "t@example.com")
    git("config", "user.name", "t")
    (repo / "legacy.py").write_text(PROG_BAD)
    (repo / "notes.txt").write_text("not python\n")
    git("add", ".")
    git("commit", "-qm", "seed")
    (repo / "fresh.py").write_text(GOOD)  # untracked

    monkeypatch.setattr(engine, "REPO_ROOT", repo)
    changed = changed_paths([repo])
    assert changed == [(repo / "fresh.py").resolve()]

    rc = engine.main(["--changed", "--no-cache",
                      "--baseline", str(tmp_path / "baseline.json"),
                      str(repo)])
    text = capfd.readouterr().out
    assert rc == 1
    assert "1 file(s)" in text  # per-file phase: fresh.py only
    # legacy.py was skipped per-file (its direct-blocking finding is
    # absent) but the whole-tree program phase still walked its chain
    assert "transitive-blocking" in text
    assert "blocking-call-in-async" not in text


def test_whole_tree_wall_time_budget(tmp_path):
    """`make lint` must stay usable interactively: cold under 20s,
    warm (tree digest unchanged) under 3s for the whole package."""
    cache_file = tmp_path / "cache.json"
    t0 = time.perf_counter()
    rc = run([DEFAULT_TARGET], ALL_RULES, cache_path=cache_file,
             out=io.StringIO())
    cold = time.perf_counter() - t0
    assert rc == 0
    t0 = time.perf_counter()
    rc = run([DEFAULT_TARGET], ALL_RULES, cache_path=cache_file,
             out=io.StringIO())
    warm = time.perf_counter() - t0
    assert rc == 0
    assert cold < 20.0, f"cold whole-tree lint took {cold:.1f}s"
    assert warm < 3.0, f"warm whole-tree lint took {warm:.1f}s"
