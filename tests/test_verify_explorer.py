"""`tasksrunner verify`: the protocol kernels under every schedule.

Drills: the correct kernels survive exhaustive interleavings including
crash schedules; the seeded-bug twins are caught and minimised to a
readable repro; the explorer itself is deterministic (same tree, same
counts) and its preemption-bounded search really returns a minimal
schedule.
"""

import io
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tasksrunner.analysis.explore import (
    KERNELS,
    InvariantViolation,
    LeaseTakeoverModel,
    QuorumAppendModel,
    TurnCommitModel,
    explore,
    format_repro,
    shortest_repro,
    verify,
)


def test_correct_kernels_survive_every_schedule():
    for name, kernel in KERNELS.items():
        res = explore(lambda: kernel(False), stop_on_violation=True)
        assert res.violation is None, \
            f"{name} violated:\n{format_repro(res.violation)}"
        assert res.runs > 1
        # crash points were actually exercised, not just enumerated
        assert res.crash_runs > 0, f"{name} explored no crash schedule"


def test_exploration_is_deterministic():
    a = explore(lambda: LeaseTakeoverModel(False), stop_on_violation=False)
    b = explore(lambda: LeaseTakeoverModel(False), stop_on_violation=False)
    assert (a.runs, a.crash_runs) == (b.runs, b.crash_runs)


def test_seeded_lease_bug_is_caught_and_minimised():
    repro = shortest_repro(lambda: LeaseTakeoverModel(True))
    assert repro is not None
    assert "two owners committed at epoch" in repro.violation
    # the classic double-acquire needs exactly one preemption: node-b
    # peeks before node-a's CAS lands
    assert repro.preemptions() == 1
    text = format_repro(repro)
    assert "schedule" in text and "peek lease" in text


def test_seeded_quorum_bug_needs_a_crash():
    repro = shortest_repro(lambda: QuorumAppendModel(True))
    assert repro is not None
    assert "lost" in repro.violation
    # a premature ack only loses data when the leader dies before
    # shipping — the minimal repro must include the crash choice
    assert any("CRASH" in step for step in repro.trace)
    assert any("resync ladder" in step for step in repro.trace)


def test_seeded_turn_commit_bug_is_caught():
    repro = shortest_repro(lambda: TurnCommitModel(True))
    assert repro is not None
    assert "acked event" in repro.violation


def test_crash_recovery_converges_on_correct_kernels():
    # force a specific crash schedule by exhaustive search: every
    # quorum-append schedule with a crash still ends with equal logs
    res = explore(lambda: QuorumAppendModel(False), stop_on_violation=True)
    assert res.violation is None and res.crash_runs > 0


def test_invariant_raised_mid_step_is_reported():
    from tasksrunner.analysis.explore import Model, _execute

    class Boom(Model):
        name = "boom"

        def procs(self):
            def proc():
                yield "step"
                raise InvariantViolation("mid-step failure")
            return [("p", proc())]

    run = _execute(Boom, ())
    assert run.violation == "mid-step failure"


def test_verify_reports_ok_and_self_test(capsys=None):
    out = io.StringIO()
    rc = verify(out=out)
    text = out.getvalue()
    assert rc == 0
    # one "invariants hold" + one "seeded bug caught" per kernel
    assert text.count("invariants hold") == len(KERNELS)
    assert text.count("seeded bug caught") == len(KERNELS)
    assert "minimal" in text and "FAIL" not in text


def test_verify_single_kernel():
    out = io.StringIO()
    rc = verify(["turn-commit"], out=out)
    assert rc == 0
    assert "turn-commit" in out.getvalue()
    assert "lease-takeover" not in out.getvalue()
