"""tasklint engine mechanics + one seeded-bad-code fixture per rule.

Two layers: the fixtures prove each rule actually fires (a rule that
never fires is worse than none — it certifies invariants it doesn't
check), and the mechanics tests pin the suppression / baseline / cache
/ JSON contracts the workflow depends on. The final test runs the real
engine over the real package and asserts zero non-baselined findings —
CI is green-by-construction, and any future regression fails here even
if `make lint` is skipped.
"""

import io
import json
import pathlib
import sys
import textwrap

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tasksrunner.analysis.cache import ResultCache, ruleset_signature
from tasksrunner.analysis.core import RULES, known_rule_ids
from tasksrunner.analysis.engine import (
    DEFAULT_BASELINE, DEFAULT_TARGET, lint_file, run,
)

#: per-file rules only — what lint_file accepts; the program rules are
#: exercised in test_tasklint_program.py
ALL_RULES = tuple(sorted(RULES))
EVERY_RULE = tuple(sorted(known_rule_ids()))


def _lint_source(tmp_path, source, rules=ALL_RULES, name="fixture.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    findings, suppressed = lint_file(path, rules)
    return findings, suppressed


def _rules_fired(findings):
    return {f.rule for f in findings}


# -- per-rule seeded-bad-code fixtures ----------------------------------


def test_blocking_rule_fires_on_async_sleep_sqlite_and_open(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import sqlite3
        import time

        async def handler():
            time.sleep(0.1)
            conn = sqlite3.connect("x.db")
            data = open("f").read()
        """, rules=("blocking-call-in-async",))
    assert len(findings) == 3
    assert _rules_fired(findings) == {"blocking-call-in-async"}
    assert [f.line for f in findings] == [5, 6, 7]


def test_blocking_rule_fires_on_sync_sleep_without_offloop_declaration(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import time

        def busy_backoff():
            time.sleep(0.001)
        """, rules=("blocking-call-in-async",))
    assert len(findings) == 1
    assert "off-loop" in findings[0].message


def test_blocking_rule_honors_offloop_marker_and_awaited_calls(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import time

        def busy_backoff():  # tasklint: off-loop
            time.sleep(0.001)

        async def ok():
            await policy.execute(fn)
        """, rules=("blocking-call-in-async",))
    assert findings == []


def test_unawaited_rule_fires_on_discarded_coroutine_and_orphan_task(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import asyncio

        async def work():
            return 1

        async def main():
            work()
            asyncio.create_task(work())
        """, rules=("unawaited-coroutine",))
    assert len(findings) == 2
    assert "without await" in findings[0].message or \
        "without await" in findings[1].message


def test_unawaited_rule_allows_awaited_and_retained(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import asyncio

        async def work():
            return 1

        async def main(self):
            await work()
            self._task = asyncio.create_task(work())
        """, rules=("unawaited-coroutine",))
    assert findings == []


def test_lock_rule_fires_on_unguarded_cross_context_write(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []
                threading.Thread(target=self._drain).start()

            def _drain(self):
                self._pending = []

            async def submit(self, item):
                with self._lock:
                    self._pending = [item]
        """, rules=("lock-discipline",))
    assert len(findings) == 1
    assert "_pending" in findings[0].message
    assert findings[0].line == 10  # the unguarded thread-side write


def test_lock_rule_fires_on_inconsistent_lock_ordering(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """, rules=("lock-discipline",))
    assert len(findings) == 1
    assert "lock order conflict" in findings[0].message


def test_envflag_rule_fires_on_raw_bool_read_and_undeclared_flag(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import os

        FLAG = "TASKSRUNNER_MESH"

        gate = os.environ.get("TASKSRUNNER_CHAOS")
        undeclared = os.getenv("TASKSRUNNER_NOT_A_FLAG")
        via_const = os.environ[FLAG]
        """, rules=("env-flag-discipline",))
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 3
    assert "TASKSRUNNER_CHAOS" in msgs and "env_flag" in msgs
    assert "TASKSRUNNER_NOT_A_FLAG" in msgs and "inventory" in msgs
    assert "TASKSRUNNER_MESH" in msgs  # resolved through the constant


def test_envflag_rule_fires_on_env_flag_call_with_undeclared_name(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        from tasksrunner.envflag import env_flag

        gate = env_flag("TASKSRUNNER_BRAND_NEW_KNOB", default=False)
        """, rules=("env-flag-discipline",))
    assert len(findings) == 1
    assert "TASKSRUNNER_BRAND_NEW_KNOB" in findings[0].message


def test_taxonomy_rule_fires_on_generic_raise_swallow_and_adhoc_class(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        class AdHocError(Exception):
            pass

        def validate(doc):
            raise ValueError("bad doc")

        async def deliver():
            try:
                pass
            except Exception:
                pass

        def cleanup():
            try:
                pass
            except:
                raise
        """, rules=("error-taxonomy",))
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 4
    assert "raise ValueError" in msgs
    assert "swallows" in msgs
    assert "AdHocError" in msgs
    assert "bare 'except:'" in msgs


def test_metric_names_rule_fires_on_typo_and_kind_mismatch(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        def instrument(metrics):
            metrics.inc("not_a_declared_metric")
            metrics.observe("state_save", 1.0)
        """, rules=("metric-names",))
    assert len(findings) == 2
    assert "not declared" in findings[0].message
    assert "different" in findings[1].message  # counter used as histogram


def test_workflow_determinism_rule_fires_on_ambient_and_effect_calls(
        tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import os
        import random
        import time
        import uuid
        from datetime import datetime

        def build(app, client):
            @app.workflow("checkout")
            async def checkout(ctx, order):
                started = time.time()
                when = datetime.now()
                pick = random.choice(order["items"])
                order_id = uuid.uuid4()
                region = os.environ["REGION"]
                fallback = os.getenv("REGION")
                await client.publish("pubsub", "orders", order)
                await client.save_state("store", "k", order)
                return started
        """, rules=("workflow-determinism",))
    assert _rules_fired(findings) == {"workflow-determinism"}
    assert len(findings) == 8
    messages = " ".join(f.message for f in findings)
    assert "ctx.now()" in messages
    assert "ctx.random()" in messages
    assert "ctx.uuid4()" in messages
    assert "activity" in messages


def test_workflow_determinism_rule_allows_ctx_and_activities(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import time

        def build(app, client):
            @app.workflow("checkout")
            async def checkout(ctx, order):
                paid = await ctx.call_activity("charge", order)
                ctx.register_compensation("refund", paid)
                await ctx.sleep(ctx.random())
                return {"id": ctx.uuid4(), "at": ctx.now()}

            @app.activity("charge")
            async def charge(actx, order):
                # the effectful half may do anything a turn may do
                actx.stage_effect(f"charge||{actx.instance}", order)
                await client.publish("pubsub", "charged", order)
                return time.time()

            async def helper():  # undecorated: out of the rule's scope
                return time.time()
        """, rules=("workflow-determinism",))
    assert findings == []


def test_workflow_determinism_rule_honors_suppression(tmp_path):
    findings, suppressed = _lint_source(tmp_path, """\
        import time

        def build(app):
            @app.workflow("w")
            async def w(ctx, inp):
                return time.time()  # tasklint: disable=workflow-determinism
        """, rules=("workflow-determinism",))
    assert findings == []
    assert suppressed == 1


# -- engine mechanics ---------------------------------------------------


def test_inline_suppression_is_honored_and_counted(tmp_path):
    findings, suppressed = _lint_source(tmp_path, """\
        import time

        async def handler():
            time.sleep(0.1)  # tasklint: disable=blocking-call-in-async
        """)
    assert findings == []
    assert suppressed == 1


def test_disable_file_suppresses_everywhere(tmp_path):
    findings, suppressed = _lint_source(tmp_path, """\
        # tasklint: disable-file=blocking-call-in-async
        import time

        async def a():
            time.sleep(1)

        async def b():
            time.sleep(2)
        """)
    assert findings == []
    assert suppressed == 2


def test_unknown_rule_in_suppression_is_rejected(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        x = 1  # tasklint: disable=not-a-rule
        """)
    assert len(findings) == 1
    assert findings[0].rule == "bad-suppression"
    assert "not-a-rule" in findings[0].message
    # the known-rule list is printed so the typo is a one-edit fix
    assert "blocking-call-in-async" in findings[0].message


def test_syntax_error_becomes_parse_error_finding(tmp_path):
    findings, _ = _lint_source(tmp_path, "def broken(:\n")
    assert [f.rule for f in findings] == ["parse-error"]


BAD = """\
import time

async def handler():
    time.sleep(0.1)
"""

GOOD = """\
import asyncio

async def handler():
    await asyncio.sleep(0.1)
"""


def _run(paths, **kw):
    out = io.StringIO()
    rc = run(paths, kw.pop("rules", ALL_RULES), out=out, **kw)
    return rc, out.getvalue()


def test_baseline_add_then_expire(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(BAD)
    baseline = tmp_path / "baseline.json"

    # no baseline: fails
    rc, _ = _run([target], baseline_path=baseline)
    assert rc == 1

    # --update-baseline grandfathers the finding...
    rc, text = _run([target], baseline_path=baseline, update_baseline=True)
    assert rc == 0
    doc = json.loads(baseline.read_text())
    assert doc["version"] == 1 and len(doc["findings"]) == 1

    # ...so the next run is green, with the match reported
    rc, text = _run([target], baseline_path=baseline)
    assert rc == 0
    assert "1 baselined" in text

    # the finding is fixed: entry goes stale (noted, still green)...
    target.write_text(GOOD)
    rc, text = _run([target], baseline_path=baseline)
    assert rc == 0
    assert "no longer matches" in text

    # ...and --update-baseline expires it
    rc, _ = _run([target], baseline_path=baseline, update_baseline=True)
    assert rc == 0
    assert json.loads(baseline.read_text())["findings"] == {}


def test_baseline_matches_by_count(tmp_path):
    """Two identical findings share a fingerprint; baselining one
    occurrence must not grandfather a second one."""
    target = tmp_path / "mod.py"
    target.write_text(BAD)
    baseline = tmp_path / "baseline.json"
    _run([target], baseline_path=baseline, update_baseline=True)

    target.write_text(BAD + "\n\nasync def handler2():\n    time.sleep(0.1)\n")
    rc, text = _run([target], baseline_path=baseline)
    assert rc == 1  # the new duplicate is NOT covered


def test_json_output_schema(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(BAD)
    out = io.StringIO()
    rc = run([target], ALL_RULES, json_out=True, out=out)
    assert rc == 1
    doc = json.loads(out.getvalue())
    assert doc["version"] == 4
    assert doc["files"] == 1
    assert isinstance(doc["suppressed"], int)
    assert isinstance(doc["baselined"], int)
    assert doc["stale_baseline"] == []
    (finding,) = doc["findings"]
    assert finding["rule"] == "blocking-call-in-async"
    assert finding["path"].endswith("mod.py")
    assert finding["line"] == 4 and finding["col"] >= 1
    assert "time.sleep" in finding["message"]
    assert finding["fingerprint"]
    assert finding["chain"] == []  # per-file findings carry no chain


def test_cache_roundtrip_and_invalidation(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(BAD)
    sig = ruleset_signature(ALL_RULES)

    cache_file = tmp_path / "cache.json"
    cache = ResultCache(cache_file, sig)
    assert cache.get(target) is None
    findings, nsup = lint_file(target, ALL_RULES)
    cache.put(target, findings, nsup)
    cache.save()

    # fresh instance: hit, identical findings + suppressed count (the
    # summary line must not drift between cold and warm runs)
    cache2 = ResultCache(cache_file, sig)
    assert cache2.get(target) == (findings, nsup)
    assert cache2.hits == 1

    # content change invalidates (the sha1 is authoritative; see
    # test_tasklint_program.py for the same-size touch -r case)
    target.write_text(GOOD)
    assert ResultCache(cache_file, sig).get(target) is None

    # ruleset change invalidates
    target.write_text(BAD)
    cache3 = ResultCache(cache_file, sig)
    cache3.put(target, findings)
    cache3.save()
    assert ResultCache(cache_file, "other-signature").get(target) is None


def test_engine_uses_cache_across_runs(tmp_path):
    target = tmp_path / "mod.py"
    target.write_text(BAD)
    cache_file = tmp_path / "cache.json"
    rc1, _ = _run([target], cache_path=cache_file)
    rc2, text = _run([target], cache_path=cache_file)
    assert (rc1, rc2) == (1, 1)
    assert "1 cached" in text


def test_rules_filter_limits_what_fires(tmp_path):
    findings, _ = _lint_source(tmp_path, """\
        import time

        async def handler(metrics):
            time.sleep(0.1)
            metrics.inc("not_a_declared_metric")
        """, rules=("metric-names",))
    assert _rules_fired(findings) == {"metric-names"}


# -- the tree itself ----------------------------------------------------


def test_package_has_zero_nonbaselined_findings():
    """Green-by-construction: the shipped baseline is EMPTY and the
    whole package passes every rule — per-file AND whole-program. Any
    new finding fails this test even if `make lint` is skipped."""
    out = io.StringIO()
    rc = run([DEFAULT_TARGET], EVERY_RULE,
             baseline_path=DEFAULT_BASELINE, cache_path=None, out=out)
    assert rc == 0, out.getvalue()
    baseline = json.loads(DEFAULT_BASELINE.read_text())
    assert baseline["findings"] == {}, \
        "the shipped baseline must stay empty — fix or suppress inline"


def test_cli_wiring_runs_tasklint(capsys):
    from tasksrunner.cli import main as cli_main
    with pytest.raises(SystemExit) as exc:
        cli_main(["lint", "--", "--list-rules"])
    assert exc.value.code == 0
    assert "blocking-call-in-async" in capsys.readouterr().out
