"""Orchestrator + autoscaler tests.

Covers the KEDA-analog scaling math and cooldown (SURVEY.md §5.8), the
run-config parser, process supervision with restart-on-crash, and a
real multi-process launch of the Tasks Tracker config.
"""

import asyncio
import pathlib
import sys
import textwrap
import time

import pytest

from tasksrunner.component.spec import parse_component
from tasksrunner.errors import ComponentError
from tasksrunner.orchestrator import (
    AppSpec,
    AutoscaleController,
    load_run_config,
    read_backlog,
)
from tasksrunner.orchestrator.config import ScaleRule, ScaleSpec
from tasksrunner.pubsub.sqlite import SqliteBroker

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_run_config_parse(tmp_path):
    cfg = tmp_path / "run.yaml"
    cfg.write_text(textwrap.dedent("""
        resources_path: ./components
        apps:
          - app_id: api
            module: pkg.mod:make_app
            app_port: 5103
            sidecar_port: 3500
            env: { A: "1" }
          - app_id: worker
            module: pkg.worker:make_app
            scale:
              min_replicas: 2
              max_replicas: 5
              rules:
                - type: pubsub-backlog
                  metadata: { component: ps, topic: t, messageCount: 10 }
    """))
    config = load_run_config(cfg)
    assert [a.app_id for a in config.apps] == ["api", "worker"]
    assert config.apps[0].env == {"A": "1"}
    assert config.resources_path == str(tmp_path / "components")
    worker = config.apps[1]
    assert worker.scale.min_replicas == 2
    assert worker.scale.rules[0].metadata["messageCount"] == "10"

    (tmp_path / "empty.yaml").write_text("apps: []")
    with pytest.raises(ComponentError):
        load_run_config(tmp_path / "empty.yaml")


@pytest.mark.asyncio
async def test_read_backlog_pubsub(tmp_path):
    spec = parse_component({
        "componentType": "pubsub.sqlite",
        "metadata": [{"name": "brokerPath", "value": str(tmp_path / "b.db")}],
    }, default_name="ps")
    broker = SqliteBroker("ps", tmp_path / "b.db")
    await broker.ensure_group("t", "worker")
    for _ in range(25):
        await broker.publish("t", {})
    rule = ScaleRule(type="pubsub-backlog",
                     metadata={"component": "ps", "topic": "t", "group": "worker"})
    assert read_backlog(rule, app_id="worker", components=[spec],
                        base_dir=tmp_path) == 25
    await broker.aclose()


@pytest.mark.asyncio
async def test_autoscaler_formula_and_cooldown(tmp_path):
    """+1 replica per messageCount, clamp to [min,max]; scale-out is
    immediate, scale-in waits for the cooldown."""
    spec = parse_component({
        "componentType": "pubsub.sqlite",
        "metadata": [{"name": "brokerPath", "value": str(tmp_path / "b.db")}],
    }, default_name="ps")
    broker = SqliteBroker("ps", tmp_path / "b.db")
    await broker.ensure_group("tasksavedtopic", "worker")

    calls = []
    app = AppSpec(
        app_id="worker", module="x:y",
        scale=ScaleSpec(min_replicas=1, max_replicas=5, cooldown_seconds=0.2,
                        rules=[ScaleRule(type="pubsub-backlog", metadata={
                            "component": "ps", "topic": "tasksavedtopic",
                            "messageCount": "10"})]),
    )
    scaler = AutoscaleController(app, [spec], calls.append, base_dir=tmp_path)

    assert await scaler.step() == 1 and calls == []

    for _ in range(35):
        await broker.publish("tasksavedtopic", {})
    assert await scaler.step() == 4  # ceil(35/10)
    assert calls == [4]

    for _ in range(100):
        await broker.publish("tasksavedtopic", {})
    assert await scaler.step() == 5  # clamped at max
    assert calls == [4, 5]

    # drain the backlog; scale-in must wait for cooldown
    broker._conn.execute("UPDATE deliveries SET done = 1")
    broker._conn.commit()
    assert await scaler.step() == 5  # cooldown not yet elapsed
    await asyncio.sleep(0.25)
    assert await scaler.step() == 1
    assert calls == [4, 5, 1]
    await broker.aclose()


def test_unknown_rule_type_rejected(tmp_path):
    with pytest.raises(ComponentError):
        read_backlog(ScaleRule(type="cpu", metadata={}), app_id="x",
                     components=[], base_dir=tmp_path)


@pytest.mark.asyncio
async def test_orchestrator_multiprocess_tasks_tracker(tmp_path):
    """Launch the real run.yaml shape as subprocesses and drive the
    write path across three OS processes (≙ the three-terminal local
    milestone, SURVEY.md §7.3)."""
    import aiohttp
    from tasksrunner.orchestrator.config import RunConfig
    from tasksrunner.orchestrator.run import Orchestrator

    config = RunConfig(
        apps=[
            AppSpec(app_id="tasksmanager-backend-api",
                    module="samples.tasks_tracker.backend_api:make_app",
                    env={"TASKS_MANAGER": "store"}),
            AppSpec(app_id="tasksmanager-frontend-webapp",
                    module="samples.tasks_tracker.frontend_ui:make_app"),
            AppSpec(app_id="tasksmanager-backend-processor",
                    module="samples.tasks_tracker.processor:make_app"),
        ],
        resources_path=str(REPO / "samples" / "tasks_tracker" / "components"),
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
    )
    orch = Orchestrator(config)
    await orch.start()
    try:
        registry = tmp_path / "apps.json"

        async def all_ready():
            if not registry.is_file():
                return False
            import json
            entries = json.loads(registry.read_text() or "{}")
            return len(entries) == 3

        deadline = asyncio.get_running_loop().time() + 30
        while not await all_ready():
            assert asyncio.get_running_loop().time() < deadline, "apps never registered"
            await asyncio.sleep(0.2)

        import json
        entries = json.loads(registry.read_text())
        # registry entries are replica LISTS since multi-replica ingress
        frontend_port = entries["tasksmanager-frontend-webapp"][0]["app_port"]

        jar = aiohttp.CookieJar(unsafe=True)
        async with aiohttp.ClientSession(cookie_jar=jar) as browser:
            async with browser.post(f"http://127.0.0.1:{frontend_port}/",
                                    data={"email": "mp@x.com"}) as r:
                assert r.status == 200
            async with browser.post(
                f"http://127.0.0.1:{frontend_port}/tasks/create",
                data={"taskName": "multiproc", "taskDueDate": "2026-08-09",
                      "taskAssignedTo": "z@x.com"}) as r:
                assert "multiproc" in await r.text()

        # the processor (third OS process) must receive the event:
        # observable via its sendgrid outbox on disk
        outbox = tmp_path / ".tasksrunner" / "outbox"
        deadline = asyncio.get_running_loop().time() + 15
        while not (outbox.is_dir() and list(outbox.glob("*.json"))):
            assert asyncio.get_running_loop().time() < deadline, "no email archived"
            await asyncio.sleep(0.2)
    finally:
        await orch.stop()


@pytest.mark.asyncio
async def test_replica_restart_on_crash(tmp_path):
    """≙ ACA restart-on-crash (SURVEY.md §5.3)."""
    from tasksrunner.orchestrator.config import RunConfig
    from tasksrunner.orchestrator.run import Orchestrator

    # an app whose process dies right after starting
    pkg = tmp_path / "crashpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "boom.py").write_text(textwrap.dedent("""
        import os, asyncio
        from tasksrunner import App

        def make_app():
            app = App("crasher")

            @app.on_startup
            async def die():
                asyncio.get_running_loop().call_later(0.3, os._exit, 17)

            return app
    """))
    config = RunConfig(
        apps=[AppSpec(app_id="crasher", module="crashpkg.boom:make_app")],
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
    )
    import os
    os.environ["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO}"
    try:
        orch = Orchestrator(config)
        await orch.start()
        replica = orch.replicas["crasher"][0]
        deadline = asyncio.get_running_loop().time() + 20
        while replica.restarts < 2:
            assert asyncio.get_running_loop().time() < deadline, "no restarts happened"
            await asyncio.sleep(0.1)
    finally:
        del os.environ["PYTHONPATH"]
        await orch.stop()


@pytest.mark.asyncio
async def test_liveness_probe_restarts_unhealthy_replica(tmp_path):
    """≙ ACA liveness probes: a replica whose /healthz starts failing
    (process alive, app sick) is killed and restarted; the restarted
    incarnation is healthy again."""
    import aiohttp

    from tasksrunner.orchestrator.config import HealthSpec, RunConfig
    from tasksrunner.orchestrator.run import Orchestrator

    pkg = tmp_path / "sickpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "sick.py").write_text(textwrap.dedent("""
        from tasksrunner import App, Response

        def make_app():
            app = App("sickapp")
            state = {"sick": False}

            @app.post("/poison")
            async def poison(req):
                state["sick"] = True
                return 200

            @app.get("/healthz")
            async def healthz(req):
                return Response(status=503 if state["sick"] else 204)

            return app
    """))
    config = RunConfig(
        apps=[AppSpec(
            app_id="sickapp", module="sickpkg.sick:make_app",
            app_port=0, sidecar_port=0,
            health=HealthSpec(interval_seconds=0.15, failure_threshold=2,
                              initial_delay_seconds=0.1, timeout_seconds=1.0),
        )],
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
    )
    import os
    os.environ["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO}"
    orch = Orchestrator(config)
    try:
        await orch.start()
        replica = orch.replicas["sickapp"][0]
        await asyncio.wait_for(replica.ready.wait(), timeout=30)
        app_port = replica.ports[0]

        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{app_port}/poison") as r:
                assert r.status == 200

        deadline = asyncio.get_running_loop().time() + 20
        while replica.health_restarts < 1:
            assert asyncio.get_running_loop().time() < deadline, \
                "liveness probe never restarted the replica"
            await asyncio.sleep(0.1)

        # the new incarnation comes up healthy on (possibly) new ports
        deadline = asyncio.get_running_loop().time() + 20
        while True:
            assert asyncio.get_running_loop().time() < deadline
            if replica.ready.is_set() and replica.ports is not None:
                try:
                    async with aiohttp.ClientSession() as s:
                        async with s.get(
                            f"http://127.0.0.1:{replica.ports[0]}/healthz") as r:
                            if r.status == 204:
                                break
                except OSError:
                    pass
            await asyncio.sleep(0.1)
    finally:
        del os.environ["PYTHONPATH"]
        await orch.stop()


def test_health_config_variants(tmp_path):
    from tasksrunner.orchestrator.config import load_run_config

    cfg = tmp_path / "run.yaml"
    cfg.write_text(textwrap.dedent("""
        apps:
          - app_id: a
            module: m:make_app
            health: true
          - app_id: b
            module: m:make_app
            health: false
          - app_id: c
            module: m:make_app
            health:
          - app_id: d
            module: m:make_app
            health:
              interval_seconds: 0.5
              failure_threshold: 7
    """))
    apps = {a.app_id: a for a in load_run_config(cfg).apps}
    assert apps["a"].health.enabled and apps["a"].health.failure_threshold == 3
    assert not apps["b"].health.enabled
    assert apps["c"].health.enabled
    assert apps["d"].health.interval_seconds == 0.5
    assert apps["d"].health.failure_threshold == 7


@pytest.mark.asyncio
async def test_custom_unhealthy_healthz_does_not_block_startup():
    """An app may report 503 on its own /healthz from the first moment
    (not yet warm) — the sidecar's startup handshake must still finish,
    because it checks liveness, not app health."""
    from tasksrunner import App, InProcCluster, Response

    app = App("coldstart")

    @app.get("/healthz")
    async def healthz(req):
        return Response(status=503)

    @app.get("/work")
    async def work(req):
        return {"ok": True}

    cluster = InProcCluster([])
    cluster.add_app(app)
    await cluster.start()  # previously would hang/raise on the handshake
    try:
        resp = await cluster.client("coldstart").invoke_method(
            "coldstart", "work", http_method="GET")
        assert resp.status == 200
        health = await cluster.client("coldstart").invoke_method(
            "coldstart", "healthz", http_method="GET")
        assert health.status == 503  # the custom route is really served
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_no_message_loss_across_replica_crash(tmp_path):
    """SURVEY §5.3 end-to-end: flood the broker, SIGKILL the consumer
    replica mid-consumption, let the supervisor restart it, and assert
    every message is eventually processed exactly the at-least-once
    way (no loss; duplicates allowed)."""
    import json
    import os
    import signal as sig

    from tasksrunner.orchestrator.config import RunConfig
    from tasksrunner.orchestrator.run import Orchestrator
    from tasksrunner.pubsub.sqlite import SqliteBroker

    N = 120
    pkg = tmp_path / "crashconsumer"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "worker.py").write_text(textwrap.dedent("""
        import json, os, pathlib
        from tasksrunner import App

        OUT = pathlib.Path(os.environ["SEEN_FILE"])

        def make_app():
            app = App("crashworker")

            @app.subscribe(pubsub="bus", topic="jobs", route="/on-job")
            async def on_job(req):
                import asyncio
                n = req.data["n"]
                # slow enough that claims are in flight at kill time
                await asyncio.sleep(0.01)
                with open(OUT, "a") as f:
                    f.write(f"{n}\\n")
                return 200

            return app
    """))
    components = tmp_path / "components"
    components.mkdir()
    (components / "bus.yaml").write_text(json.dumps({
        "componentType": "pubsub.sqlite",
        "metadata": [
            {"name": "brokerPath", "value": str(tmp_path / "bus.db")},
            {"name": "pollIntervalSeconds", "value": "0.01"},
            # short lock duration: the killed replica's claims expire
            # into redelivery quickly (≙ Service Bus lock duration)
            {"name": "claimLeaseSeconds", "value": "2"},
        ],
    }))
    seen_file = tmp_path / "seen.txt"
    seen_file.write_text("")

    config = RunConfig(
        apps=[AppSpec(app_id="crashworker", module="crashconsumer.worker:make_app",
                      env={"SEEN_FILE": str(seen_file)})],
        resources_path=str(components),
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
    )
    os.environ["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO}"

    # publisher side: a broker handle on the shared file
    broker = SqliteBroker("bus", tmp_path / "bus.db", poll_interval=0.01,
                          claim_lease=2.0)
    orch = Orchestrator(config)
    await orch.start()
    try:
        replica = orch.replicas["crashworker"][0]
        await asyncio.wait_for(replica.ready.wait(), timeout=30)

        for i in range(N):
            # raw payload straight onto the broker: mark it as plain
            # JSON so delivery skips the CloudEvents unwrap
            await broker.publish("jobs", {"n": i},
                                 metadata={"content-type": "application/json"})

        # wait until consumption is clearly underway, then SIGKILL.
        # SIGKILL can tear a buffered write mid-line, concatenating two
        # numbers — keep only in-range values (the torn ones are
        # redelivered anyway, which is the property under test)
        def seen() -> set[int]:
            if not seen_file.exists():
                return set()
            return {int(x) for x in seen_file.read_text().split()
                    if x.isdigit() and int(x) < N}

        deadline = asyncio.get_running_loop().time() + 30
        while len(seen()) < 5:
            assert asyncio.get_running_loop().time() < deadline, "consumption never started"
            await asyncio.sleep(0.02)
        os.kill(replica.proc.pid, sig.SIGKILL)

        # supervisor restarts the replica; claimed-but-unacked messages
        # are redelivered after their lease expires — nothing is lost
        deadline = asyncio.get_running_loop().time() + 90
        while not seen() >= set(range(N)):
            assert asyncio.get_running_loop().time() < deadline, \
                f"lost messages: {sorted(set(range(N)) - seen())[:10]}"
            await asyncio.sleep(0.1)
        assert replica.restarts >= 1, "the crash must go through supervise()"
    finally:
        del os.environ["PYTHONPATH"]
        await orch.stop()
        await broker.aclose()


@pytest.mark.asyncio
async def test_http_concurrency_rule_scales_out_and_back(tmp_path, monkeypatch):
    """The ACA HTTP scale rule analog end-to-end
    (docs/aca/09-aca-autoscale-keda/index.md:27-35): flood a slow app
    with concurrent requests, watch replicas scale out to max, stop
    the flood, watch them scale back within bounds."""
    import aiohttp

    from tasksrunner.orchestrator.config import RunConfig, ScaleSpec, ScaleRule
    from tasksrunner.orchestrator.run import Orchestrator

    pkg = tmp_path / "slowpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "slow.py").write_text(textwrap.dedent("""
        import asyncio
        from tasksrunner import App

        def make_app():
            app = App("slowapp")

            @app.post("/work")
            async def work(req):
                await asyncio.sleep(0.25)
                return 200, {"ok": True}

            return app
    """))
    config = RunConfig(
        apps=[AppSpec(
            app_id="slowapp", module="slowpkg.slow:make_app",
            scale=ScaleSpec(
                min_replicas=1, max_replicas=3, cooldown_seconds=0.5,
                rules=[ScaleRule(type="http-concurrency",
                                 metadata={"concurrentRequests": "2"})]),
        )],
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
    )
    import os
    monkeypatch.setenv("PYTHONPATH", f"{tmp_path}{os.pathsep}{REPO}")
    orch = Orchestrator(config)
    try:
        await orch.start()
        replica = orch.replicas["slowapp"][0]
        await asyncio.wait_for(replica.ready.wait(), timeout=30)
        app_port = replica.ports[0]

        stop_flood = asyncio.Event()

        async def flood_worker(session):
            while not stop_flood.is_set():
                try:
                    async with session.post(
                        f"http://127.0.0.1:{app_port}/work") as resp:
                        await resp.read()
                except (OSError, aiohttp.ClientError):
                    await asyncio.sleep(0.05)

        async with aiohttp.ClientSession() as session:
            flood = [asyncio.create_task(flood_worker(session))
                     for _ in range(12)]
            try:
                deadline = asyncio.get_running_loop().time() + 30
                while orch.replica_count("slowapp") < 3:
                    assert asyncio.get_running_loop().time() < deadline, (
                        "never scaled out to max under sustained "
                        f"concurrency (at {orch.replica_count('slowapp')})")
                    await asyncio.sleep(0.1)

                # round-4 ingress: the ADDED replicas joined the
                # registry (they serve invokes, ≙ ACA ingress
                # balancing) and resolve() rotates across the fleet
                from tasksrunner.invoke.resolver import NameResolver
                resolver = NameResolver(registry_file=config.registry_file)
                deadline = asyncio.get_running_loop().time() + 15
                while len(resolver.resolve_all("slowapp")) < 3:
                    assert asyncio.get_running_loop().time() < deadline, (
                        f"scale-out replicas never registered: "
                        f"{resolver.resolve_all('slowapp')}")
                    await asyncio.sleep(0.2)
                    resolver = NameResolver(
                        registry_file=config.registry_file)
                fleet = {a.sidecar_port
                         for a in resolver.resolve_all("slowapp")}
                assert len(fleet) == 3
                rotated = {resolver.resolve("slowapp").sidecar_port
                           for _ in range(6)}
                assert rotated == fleet  # every replica is in rotation
            finally:
                stop_flood.set()
                for t in flood:
                    t.cancel()
                await asyncio.gather(*flood, return_exceptions=True)

        # flood over: after the cooldown the app returns to min
        deadline = asyncio.get_running_loop().time() + 30
        while orch.replica_count("slowapp") > 1:
            assert asyncio.get_running_loop().time() < deadline, \
                "never scaled back in after the flood stopped"
            await asyncio.sleep(0.1)
    finally:
        await orch.stop()


@pytest.mark.asyncio
async def test_cpu_and_memory_rules_measure_real_processes(tmp_path):
    """The cpu/memory rules read real /proc numbers: memory of THIS
    process trips a tiny threshold; cpu's first sample reports 0 (a
    delta needs two polls) and never goes negative."""
    import os

    from tasksrunner.orchestrator.config import ScaleSpec, ScaleRule

    me = [{"pid": os.getpid(), "app_port": None, "host": "127.0.0.1"}]
    app = AppSpec(
        app_id="w", module="x:y",
        scale=ScaleSpec(min_replicas=1, max_replicas=9, rules=[
            ScaleRule(type="memory", metadata={"megabytes": "1"}),
        ]))
    scaler = AutoscaleController(app, [], lambda n: None,
                                 base_dir=tmp_path, replica_info=lambda: me)
    # this test process holds far more than 2 MB RSS
    assert scaler.desired_replicas() >= 2

    app.scale.rules = [ScaleRule(type="cpu", metadata={"utilization": "50"})]
    assert scaler._rule_desired(app.scale.rules[0]) == 0  # first sample
    # burn some CPU so the second delta is visibly >= 0
    t0 = time.monotonic()
    while time.monotonic() - t0 < 0.05:
        sum(i * i for i in range(1000))
    assert scaler._rule_desired(app.scale.rules[0]) >= 0


@pytest.mark.asyncio
async def test_memory_rule_is_stable_for_both_memory_shapes(tmp_path, monkeypatch):
    """The composite memory formula must neither ratchet (fixed
    per-replica baseline above the budget must not ask for ever more
    replicas) nor flip-flop (load-proportional memory must not argue
    for scale-in the moment scale-out has halved the mean)."""
    from tasksrunner.orchestrator import autoscale
    from tasksrunner.orchestrator.config import ScaleSpec, ScaleRule

    rss_by_pid = {}
    monkeypatch.setattr(autoscale, "_read_proc_rss_mb",
                        lambda pid: rss_by_pid[pid])

    def fleet(*pids):
        return [{"pid": p, "app_port": None, "host": "127.0.0.1"}
                for p in pids]

    app = AppSpec(
        app_id="w", module="x:y",
        scale=ScaleSpec(min_replicas=1, max_replicas=50, rules=[
            ScaleRule(type="memory", metadata={"megabytes": "512"}),
        ]))
    replicas = fleet(1)
    scaler = AutoscaleController(app, [], lambda n: None,
                                 base_dir=tmp_path,
                                 replica_info=lambda: replicas)
    rule = app.scale.rules[0]

    # fixed baseline OVER budget (misconfigured threshold): one step
    # out is allowed, then stable — never a ratchet toward max
    rss_by_pid.update({1: 600.0, 2: 600.0, 3: 600.0})
    assert scaler._rule_desired(rule) == 2
    replicas = fleet(1, 2)
    assert scaler._rule_desired(rule) == 2   # stable at 2
    replicas = fleet(1, 2, 3)
    assert scaler._rule_desired(rule) == 3   # never ABOVE current count

    # load-proportional memory: 900 MB of working set on one replica
    # scales out to two; the halved per-replica mean must NOT argue
    # for scale-in while the total footprint still needs two replicas
    rss_by_pid.update({1: 900.0})
    replicas = fleet(1)
    assert scaler._rule_desired(rule) == 2
    rss_by_pid.update({1: 450.0, 2: 450.0})
    replicas = fleet(1, 2)
    assert scaler._rule_desired(rule) == 2   # stable, no flip-flop
    # load actually drops -> scale-in follows
    rss_by_pid.update({1: 100.0, 2: 100.0})
    assert scaler._rule_desired(rule) == 1


@pytest.mark.asyncio
async def test_memory_rule_does_not_ratchet_with_replica_count(tmp_path):
    """Memory scaling reads the per-replica AVERAGE, not the sum: a
    fleet where every replica sits at the same baseline RSS must want
    the same replica count whether one or three replicas are running —
    otherwise each added replica feeds the signal and a threshold below
    the baseline ratchets to max_replicas and never scales in."""
    import os

    from tasksrunner.orchestrator.config import ScaleSpec, ScaleRule

    def fleet_of(n):
        return [{"pid": os.getpid(), "app_port": None, "host": "127.0.0.1"}
                for _ in range(n)]

    app = AppSpec(
        app_id="w", module="x:y",
        scale=ScaleSpec(min_replicas=1, max_replicas=50, rules=[
            ScaleRule(type="memory", metadata={"megabytes": "1"}),
        ]))
    replicas = fleet_of(1)
    scaler = AutoscaleController(app, [], lambda n: None,
                                 base_dir=tmp_path,
                                 replica_info=lambda: replicas)
    desired_one = scaler.desired_replicas()
    assert desired_one >= 2  # this process holds far more than 1 MB

    replicas = fleet_of(3)
    assert scaler.desired_replicas() == desired_one, (
        "same per-replica RSS must not ask for more replicas "
        "just because more replicas exist")


def _telemetry_scaler(tmp_path, rules, *, max_replicas=10,
                      cooldown_seconds=5.0, calls=None):
    app = AppSpec(
        app_id="w", module="x:y",
        scale=ScaleSpec(min_replicas=1, max_replicas=max_replicas,
                        cooldown_seconds=cooldown_seconds, rules=rules))
    return AutoscaleController(
        app, [], (calls.append if calls is not None else lambda n: None),
        base_dir=tmp_path)


def _p99_doc(counts, *, bounds=(0.1, 0.5, 1.0),
             metric="state_op_latency_seconds"):
    """A fake sidecar /v1.0/metadata doc with one histogram series."""
    return {"histograms": {metric: {
        "bounds": list(bounds),
        "series": [{"labels": {}, "counts": list(counts),
                    "sum": 0.0, "count": sum(counts)}],
    }}}


@pytest.mark.asyncio
async def test_target_p99_rule_windows_deltas(tmp_path):
    """target-p99 sizes the fleet from the p99 of the WINDOW between
    evaluations, not all-time cumulative counts — otherwise one past
    overload would argue for a big fleet forever."""
    rule = ScaleRule(type="target-p99", metadata={
        "metric": "state_op_latency_seconds",
        "targetSeconds": "0.25", "minSamples": "5"})
    scaler = _telemetry_scaler(tmp_path, [rule])

    # 20 observations in the (0.5, 1.0] bucket: p99 ~= 0.995, nearly
    # 4x the 0.25s target, 1 live replica -> ceil(1 * p99/0.25) = 4
    docs = [_p99_doc([0, 0, 20, 0])]
    scaler._replica_metadata = lambda: docs
    assert scaler._rule_desired(rule) == 4

    # same cumulative counts next evaluation: the window is empty,
    # under minSamples -> no verdict, the overload is NOT remembered
    assert scaler._rule_desired(rule) == 0

    # fresh fast traffic: 30 new observations under 0.1s -> p99 under
    # target -> no pressure
    docs = [_p99_doc([30, 0, 20, 0])]
    assert scaler._rule_desired(rule) == 0

    # replica restart shrinks the cumulative counts; negative deltas
    # clamp to 0 instead of poisoning the window
    docs = [_p99_doc([1, 0, 0, 0])]
    assert scaler._rule_desired(rule) == 0

    # metric gone entirely (no traffic yet on a fresh fleet): silence
    # is not pressure
    docs = [{"histograms": {}}]
    assert scaler._rule_desired(rule) == 0


@pytest.mark.asyncio
async def test_loop_lag_rule_adds_one_while_any_loop_lags(tmp_path):
    rule = ScaleRule(type="loop-lag", metadata={"maxLagSeconds": "0.5"})
    scaler = _telemetry_scaler(tmp_path, [rule])

    # worst lag across replicas and label sets decides — one healthy
    # replica must not mask a saturated one
    docs = [
        {"metrics": {"event_loop_lag_seconds": 0.05}},
        {"metrics": {'event_loop_lag_seconds{replica="1"}': 0.8,
                     "other_metric": 99.0}},
    ]
    scaler._replica_metadata = lambda: docs
    assert scaler._rule_desired(rule) == scaler.current + 1

    # incremental, not proportional: from a bigger fleet it still asks
    # for just one more
    scaler.current = 3
    assert scaler._rule_desired(rule) == 4

    docs = [{"metrics": {"event_loop_lag_seconds": 0.1}}]
    assert scaler._rule_desired(rule) == 0


@pytest.mark.asyncio
async def test_rule_failure_isolation_and_desired_gauge(tmp_path):
    """One broken rule is logged + skipped, the healthy rule's verdict
    still drives scaling, and the decision lands in the
    autoscale_desired_replicas gauge; only an all-rules blackout holds
    the current count."""
    from tasksrunner.observability.metrics import metrics

    bad = ScaleRule(type="pubsub-backlog", metadata={
        "component": "no-such-broker", "topic": "t"})  # raises
    lag = ScaleRule(type="loop-lag", metadata={"maxLagSeconds": "0.5"})
    scaler = _telemetry_scaler(tmp_path, [bad, lag], max_replicas=5)
    scaler._replica_metadata = lambda: [
        {"metrics": {"event_loop_lag_seconds": 2.0}}]

    # bad rule raises ComponentError; lag rule still argues 1 -> 2
    assert scaler.desired_replicas() == 2
    assert metrics.get("autoscale_desired_replicas", app="w") == 2.0

    # every rule failing = telemetry blackout: hold, don't scale in
    scaler.app.scale.rules = [bad]
    scaler.current = 3
    assert scaler.desired_replicas() == 3
    assert metrics.get("autoscale_desired_replicas", app="w") == 3.0


@pytest.mark.asyncio
async def test_autoscale_cooldown_resets_when_load_returns(tmp_path):
    """Scale-out is immediate; scale-in needs the backlog low for the
    WHOLE cooldown — load returning mid-cooldown resets the clock, so
    a sawtooth load never causes a scale-in at its trough."""
    calls = []
    scaler = _telemetry_scaler(tmp_path, [ScaleRule(type="loop-lag")],
                               cooldown_seconds=0.3, calls=calls)
    box = {"n": 1}
    scaler.desired_replicas = lambda: box["n"]

    box["n"] = 3
    assert await scaler.step() == 3 and calls == [3]  # out: immediate

    box["n"] = 1
    assert await scaler.step() == 3      # low observed, clock starts
    await asyncio.sleep(0.2)
    box["n"] = 3
    assert await scaler.step() == 3      # load is back: clock must reset
    box["n"] = 1
    assert await scaler.step() == 3      # clock restarts here
    await asyncio.sleep(0.2)
    # 0.4s since the FIRST low sample but only 0.2s since the reset:
    # a non-reset clock would (wrongly) scale in now
    assert await scaler.step() == 3
    await asyncio.sleep(0.15)
    assert await scaler.step() == 1      # full quiet cooldown elapsed
    assert calls == [3, 1]


# -- restartable control plane (replication PR) ------------------------------

def _survivor_config(tmp_path, **kw):
    from tasksrunner.orchestrator.config import RunConfig

    pkg = tmp_path / "hapkg"
    if not pkg.is_dir():
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "survivor.py").write_text(textwrap.dedent("""
            from tasksrunner import App

            def make_app():
                return App("survivor")
        """))
    return RunConfig(
        apps=[AppSpec(app_id="survivor", module="hapkg.survivor:make_app")],
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
        **kw,
    )


async def _wait_registered(tmp_path, *, app_id="survivor", timeout=20):
    import json
    registry = tmp_path / "apps.json"
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        if registry.is_file():
            entries = json.loads(registry.read_text() or "{}").get(app_id, [])
            if entries and entries[0].get("pid"):
                return entries[0]["pid"]
        assert asyncio.get_running_loop().time() < deadline, \
            f"{app_id} never registered"
        await asyncio.sleep(0.1)


@pytest.mark.asyncio
async def test_orchestrator_restart_adopts_running_replicas(tmp_path,
                                                            monkeypatch):
    """kill -9 the orchestrator (abandon() is its in-process test
    double): the data plane keeps running, and a successor — here a
    standby waiting on the control-plane lease — re-adopts the live
    replicas instead of respawning them. Same pids, no restart."""
    import os

    from tasksrunner.orchestrator.run import Orchestrator

    monkeypatch.setenv("TASKSRUNNER_REPL_LEASE_SECONDS", "0.5")
    monkeypatch.setenv("PYTHONPATH", f"{tmp_path}{os.pathsep}{REPO}")
    orch_a = Orchestrator(_survivor_config(tmp_path))
    await orch_a.start()
    try:
        pid = await _wait_registered(tmp_path)
        # the orchestrator process "dies": no lease release, no
        # registry cleanup, replicas keep running unsupervised
        await orch_a.abandon()

        orch_b = Orchestrator(_survivor_config(tmp_path, standby=True))
        await orch_b.start()  # waits out the dead holder's lease
        try:
            adopted = orch_b.replicas["survivor"]
            assert [r.proc.pid for r in adopted] == [pid], \
                "the successor should adopt, not respawn"
            assert adopted[0].restarts == 0
            reasons = [r["reason"] for r in orch_b.revisions["survivor"]]
            assert any("adopted" in r for r in reasons), reasons
            # the adopted process is genuinely supervised: it is alive
            # and its exit would be noticed (returncode still None)
            assert adopted[0].proc.returncode is None
        finally:
            await orch_b.stop()
    finally:
        await orch_a.abandon()  # idempotent if already abandoned


@pytest.mark.asyncio
async def test_second_orchestrator_is_fenced_out(tmp_path):
    """Two orchestrators over one registry dir would fight for ports
    and entries: the second (non-standby) start must refuse, naming
    the holder and the --standby escape hatch."""
    from tasksrunner.orchestrator.config import RunConfig
    from tasksrunner.orchestrator.run import Orchestrator

    config = RunConfig(apps=[], registry_file=str(tmp_path / "apps.json"),
                       base_dir=tmp_path)
    orch_a = Orchestrator(config)
    await orch_a.start()
    try:
        orch_b = Orchestrator(RunConfig(
            apps=[], registry_file=str(tmp_path / "apps.json"),
            base_dir=tmp_path))
        with pytest.raises(SystemExit, match="--standby"):
            await orch_b.start()
    finally:
        await orch_a.stop()


def test_cli_heals_torn_orchestrator_info_file(tmp_path):
    """A torn/garbage orchestrator.json (crash debris — live writes
    are atomic rename) is removed by the CLI reader instead of
    wedging every admin verb until someone deletes it by hand."""
    from tasksrunner import cli
    from tasksrunner.orchestrator.admin import info_path

    registry_file = str(tmp_path / "apps.json")
    info_file = info_path(registry_file)
    info_file.write_text('{"admin_url": truncated-mid-wri')
    with pytest.raises(SystemExit, match="unreadable"):
        cli._admin_request(registry_file, "GET", "/apps")
    assert not info_file.exists(), "crash debris should be healed away"
