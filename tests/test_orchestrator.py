"""Orchestrator + autoscaler tests.

Covers the KEDA-analog scaling math and cooldown (SURVEY.md §5.8), the
run-config parser, process supervision with restart-on-crash, and a
real multi-process launch of the Tasks Tracker config.
"""

import asyncio
import pathlib
import sys
import textwrap

import pytest

from tasksrunner.component.spec import parse_component
from tasksrunner.errors import ComponentError
from tasksrunner.orchestrator import (
    AppSpec,
    AutoscaleController,
    load_run_config,
    read_backlog,
)
from tasksrunner.orchestrator.config import ScaleRule, ScaleSpec
from tasksrunner.pubsub.sqlite import SqliteBroker

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_run_config_parse(tmp_path):
    cfg = tmp_path / "run.yaml"
    cfg.write_text(textwrap.dedent("""
        resources_path: ./components
        apps:
          - app_id: api
            module: pkg.mod:make_app
            app_port: 5103
            sidecar_port: 3500
            env: { A: "1" }
          - app_id: worker
            module: pkg.worker:make_app
            scale:
              min_replicas: 2
              max_replicas: 5
              rules:
                - type: pubsub-backlog
                  metadata: { component: ps, topic: t, messageCount: 10 }
    """))
    config = load_run_config(cfg)
    assert [a.app_id for a in config.apps] == ["api", "worker"]
    assert config.apps[0].env == {"A": "1"}
    assert config.resources_path == str(tmp_path / "components")
    worker = config.apps[1]
    assert worker.scale.min_replicas == 2
    assert worker.scale.rules[0].metadata["messageCount"] == "10"

    (tmp_path / "empty.yaml").write_text("apps: []")
    with pytest.raises(ComponentError):
        load_run_config(tmp_path / "empty.yaml")


@pytest.mark.asyncio
async def test_read_backlog_pubsub(tmp_path):
    spec = parse_component({
        "componentType": "pubsub.sqlite",
        "metadata": [{"name": "brokerPath", "value": str(tmp_path / "b.db")}],
    }, default_name="ps")
    broker = SqliteBroker("ps", tmp_path / "b.db")
    await broker.ensure_group("t", "worker")
    for _ in range(25):
        await broker.publish("t", {})
    rule = ScaleRule(type="pubsub-backlog",
                     metadata={"component": "ps", "topic": "t", "group": "worker"})
    assert read_backlog(rule, app_id="worker", components=[spec],
                        base_dir=tmp_path) == 25
    await broker.aclose()


@pytest.mark.asyncio
async def test_autoscaler_formula_and_cooldown(tmp_path):
    """+1 replica per messageCount, clamp to [min,max]; scale-out is
    immediate, scale-in waits for the cooldown."""
    spec = parse_component({
        "componentType": "pubsub.sqlite",
        "metadata": [{"name": "brokerPath", "value": str(tmp_path / "b.db")}],
    }, default_name="ps")
    broker = SqliteBroker("ps", tmp_path / "b.db")
    await broker.ensure_group("tasksavedtopic", "worker")

    calls = []
    app = AppSpec(
        app_id="worker", module="x:y",
        scale=ScaleSpec(min_replicas=1, max_replicas=5, cooldown_seconds=0.2,
                        rules=[ScaleRule(type="pubsub-backlog", metadata={
                            "component": "ps", "topic": "tasksavedtopic",
                            "messageCount": "10"})]),
    )
    scaler = AutoscaleController(app, [spec], calls.append, base_dir=tmp_path)

    assert await scaler.step() == 1 and calls == []

    for _ in range(35):
        await broker.publish("tasksavedtopic", {})
    assert await scaler.step() == 4  # ceil(35/10)
    assert calls == [4]

    for _ in range(100):
        await broker.publish("tasksavedtopic", {})
    assert await scaler.step() == 5  # clamped at max
    assert calls == [4, 5]

    # drain the backlog; scale-in must wait for cooldown
    broker._conn.execute("UPDATE deliveries SET done = 1")
    broker._conn.commit()
    assert await scaler.step() == 5  # cooldown not yet elapsed
    await asyncio.sleep(0.25)
    assert await scaler.step() == 1
    assert calls == [4, 5, 1]
    await broker.aclose()


def test_unknown_rule_type_rejected(tmp_path):
    with pytest.raises(ComponentError):
        read_backlog(ScaleRule(type="cpu", metadata={}), app_id="x",
                     components=[], base_dir=tmp_path)


@pytest.mark.asyncio
async def test_orchestrator_multiprocess_tasks_tracker(tmp_path):
    """Launch the real run.yaml shape as subprocesses and drive the
    write path across three OS processes (≙ the three-terminal local
    milestone, SURVEY.md §7.3)."""
    import aiohttp
    from tasksrunner.orchestrator.config import RunConfig
    from tasksrunner.orchestrator.run import Orchestrator

    config = RunConfig(
        apps=[
            AppSpec(app_id="tasksmanager-backend-api",
                    module="samples.tasks_tracker.backend_api:make_app",
                    env={"TASKS_MANAGER": "store"}),
            AppSpec(app_id="tasksmanager-frontend-webapp",
                    module="samples.tasks_tracker.frontend_ui:make_app"),
            AppSpec(app_id="tasksmanager-backend-processor",
                    module="samples.tasks_tracker.processor:make_app"),
        ],
        resources_path=str(REPO / "samples" / "tasks_tracker" / "components"),
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
    )
    orch = Orchestrator(config)
    await orch.start()
    try:
        registry = tmp_path / "apps.json"

        async def all_ready():
            if not registry.is_file():
                return False
            import json
            entries = json.loads(registry.read_text() or "{}")
            return len(entries) == 3

        deadline = asyncio.get_running_loop().time() + 30
        while not await all_ready():
            assert asyncio.get_running_loop().time() < deadline, "apps never registered"
            await asyncio.sleep(0.2)

        import json
        entries = json.loads(registry.read_text())
        frontend_port = entries["tasksmanager-frontend-webapp"]["app_port"]

        jar = aiohttp.CookieJar(unsafe=True)
        async with aiohttp.ClientSession(cookie_jar=jar) as browser:
            async with browser.post(f"http://127.0.0.1:{frontend_port}/",
                                    data={"email": "mp@x.com"}) as r:
                assert r.status == 200
            async with browser.post(
                f"http://127.0.0.1:{frontend_port}/tasks/create",
                data={"taskName": "multiproc", "taskDueDate": "2026-08-09",
                      "taskAssignedTo": "z@x.com"}) as r:
                assert "multiproc" in await r.text()

        # the processor (third OS process) must receive the event:
        # observable via its sendgrid outbox on disk
        outbox = tmp_path / ".tasksrunner" / "outbox"
        deadline = asyncio.get_running_loop().time() + 15
        while not (outbox.is_dir() and list(outbox.glob("*.json"))):
            assert asyncio.get_running_loop().time() < deadline, "no email archived"
            await asyncio.sleep(0.2)
    finally:
        await orch.stop()


@pytest.mark.asyncio
async def test_replica_restart_on_crash(tmp_path):
    """≙ ACA restart-on-crash (SURVEY.md §5.3)."""
    from tasksrunner.orchestrator.config import RunConfig
    from tasksrunner.orchestrator.run import Orchestrator

    # an app whose process dies right after starting
    pkg = tmp_path / "crashpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "boom.py").write_text(textwrap.dedent("""
        import os, asyncio
        from tasksrunner import App

        def make_app():
            app = App("crasher")

            @app.on_startup
            async def die():
                asyncio.get_running_loop().call_later(0.3, os._exit, 17)

            return app
    """))
    config = RunConfig(
        apps=[AppSpec(app_id="crasher", module="crashpkg.boom:make_app")],
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
    )
    import os
    os.environ["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO}"
    try:
        orch = Orchestrator(config)
        await orch.start()
        replica = orch.replicas["crasher"][0]
        deadline = asyncio.get_running_loop().time() + 20
        while replica.restarts < 2:
            assert asyncio.get_running_loop().time() < deadline, "no restarts happened"
            await asyncio.sleep(0.1)
    finally:
        del os.environ["PYTHONPATH"]
        await orch.stop()
