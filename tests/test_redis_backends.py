"""Redis-protocol backends over a real TCP socket.

Server side is the hermetic RedisLite double (tasksrunner/testing/
redislite.py); the drivers under test are the same ones a live Redis
would get. Contract coverage mirrors the reference's semantics:
etag CAS (SURVEY.md §5.2), no-query-on-plain-redis
(docs/aca/04-aca-dapr-stateapi/index.md:166-168), durable groups +
competing consumers + at-least-once (docs module 5, SURVEY.md §5.8).
"""

import asyncio

import pytest

from tasksrunner.component.registry import resolve_driver
from tasksrunner.component.spec import parse_component
from tasksrunner.errors import EtagMismatch, QueryError
from tasksrunner.pubsub.redis import RedisStreamsBroker
from tasksrunner.pubsub.sqlite import SqliteBroker
from tasksrunner.redisproto import RedisClient, RedisReplyError
from tasksrunner.state.redis import RedisStateStore
from tasksrunner.testing import RedisLiteServer


async def wait_until(predicate, timeout=5.0, interval=0.01):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return predicate()


# ------------------------------------------------------------- protocol


@pytest.mark.asyncio
async def test_resp_roundtrip_and_errors():
    async with RedisLiteServer() as srv:
        client = RedisClient("127.0.0.1", srv.port)
        try:
            assert await client.ping()
            assert await client.execute("SET", "k", "v") == "OK"
            assert await client.execute("GET", "k") == b"v"
            assert await client.execute("GET", "missing") is None
            assert await client.execute("DEL", "k", "missing") == 1
            with pytest.raises(RedisReplyError):
                await client.execute("NOPE")
            # concurrent commands share the pool without interleaving
            await client.execute("SET", "n", "0")
            replies = await asyncio.gather(
                *[client.execute("SET", f"k{i}", str(i)) for i in range(20)])
            assert replies == ["OK"] * 20
            got = await client.execute("MGET", *[f"k{i}" for i in range(20)])
            assert got == [str(i).encode() for i in range(20)]
        finally:
            await client.aclose()


@pytest.mark.asyncio
async def test_watch_multi_exec_conflict_detection():
    async with RedisLiteServer() as srv:
        c1 = RedisClient("127.0.0.1", srv.port)
        c2 = RedisClient("127.0.0.1", srv.port)
        try:
            await c1.execute("SET", "key", "a")
            async with c1.acquire() as conn:
                await conn.execute("WATCH", "key")
                assert await conn.execute("GET", "key") == b"a"
                # interloper writes between WATCH and EXEC
                await c2.execute("SET", "key", "b")
                await conn.execute("MULTI")
                await conn.execute("SET", "key", "c")
                assert await conn.execute("EXEC") is None  # aborted
            assert await c1.execute("GET", "key") == b"b"
        finally:
            await c1.aclose()
            await c2.aclose()


# ------------------------------------------------------------- state


@pytest.mark.asyncio
async def test_redis_state_crud_and_etags():
    async with RedisLiteServer() as srv:
        store = RedisStateStore("statestore", f"127.0.0.1:{srv.port}")
        try:
            assert await store.get("t1") is None
            etag = await store.set("t1", {"taskName": "wash car"})
            item = await store.get("t1")
            assert item.value == {"taskName": "wash car"}
            assert item.etag == etag

            # matching etag wins, returns a fresh etag
            etag2 = await store.set("t1", {"taskName": "updated"}, etag=etag)
            assert etag2 != etag
            # stale etag loses
            with pytest.raises(EtagMismatch):
                await store.set("t1", {"taskName": "stale"}, etag=etag)
            with pytest.raises(EtagMismatch):
                await store.delete("t1", etag=etag)
            assert await store.delete("t1", etag=etag2) is True
            assert await store.get("t1") is None
            assert await store.delete("t1") is False
        finally:
            await store.aclose()


@pytest.mark.asyncio
async def test_redis_state_bulk_keys_and_query_refusal():
    async with RedisLiteServer() as srv:
        store = RedisStateStore("statestore", f"127.0.0.1:{srv.port}")
        try:
            for i in range(5):
                await store.set(f"app||{i}", {"n": i})
            items = await store.bulk_get(["app||0", "nope", "app||4"])
            assert [it.value if it else None for it in items] == \
                [{"n": 0}, None, {"n": 4}]
            assert await store.keys(prefix="app||") == \
                [f"app||{i}" for i in range(5)]
            # the reference's documented limitation: plain redis can't query
            assert store.supports_query is False
            with pytest.raises(QueryError):
                await store.query({"filter": {"EQ": {"taskCreatedBy": "x"}}})
        finally:
            await store.aclose()


@pytest.mark.asyncio
async def test_redis_state_concurrent_cas_single_winner():
    """N racers CAS from the same etag; exactly one must win."""
    async with RedisLiteServer() as srv:
        store = RedisStateStore("statestore", f"127.0.0.1:{srv.port}")
        try:
            etag = await store.set("slot", {"owner": None})

            async def racer(i):
                try:
                    await store.set("slot", {"owner": i}, etag=etag)
                    return True
                except EtagMismatch:
                    return False

            results = await asyncio.gather(*[racer(i) for i in range(8)])
            assert sum(results) == 1
        finally:
            await store.aclose()


# ------------------------------------------------------------- pub/sub


@pytest.mark.asyncio
async def test_redis_pubsub_publish_subscribe_ack():
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "taskspubsub", f"127.0.0.1:{srv.port}",
            redeliver_interval=0.05, block_ms=30)
        try:
            got = []

            async def handler(msg):
                got.append(msg)
                return True

            await broker.subscribe("tasksavedtopic", "processor", handler)
            mid = await broker.publish(
                "tasksavedtopic", {"taskName": "t"}, metadata={"k": "v"})
            assert await wait_until(lambda: len(got) == 1)
            assert got[0].id == mid
            assert got[0].data == {"taskName": "t"}
            assert got[0].metadata == {"k": "v"}
            assert got[0].attempt == 1
            # acked: nothing pending, no redelivery
            await asyncio.sleep(0.2)
            assert len(got) == 1
        finally:
            await broker.aclose()


@pytest.mark.asyncio
async def test_redis_pubsub_durable_group_delivers_offline_messages():
    """≙ docs/aca/05-aca-dapr-pubsubapi/index.md:27-29: consumers need
    not be up when messages arrive."""
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "taskspubsub", f"127.0.0.1:{srv.port}",
            redeliver_interval=0.05, block_ms=30)
        try:
            await broker.ensure_group("topic", "app")
            await broker.publish("topic", {"n": 1})
            await broker.publish("topic", {"n": 2})
            got = []

            async def handler(msg):
                got.append(msg.data["n"])
                return True

            await broker.subscribe("topic", "app", handler)
            assert await wait_until(lambda: sorted(got) == [1, 2])
        finally:
            await broker.aclose()


@pytest.mark.asyncio
async def test_redis_pubsub_competing_consumers_split_work():
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "taskspubsub", f"127.0.0.1:{srv.port}",
            redeliver_interval=0.2, block_ms=30)
        try:
            seen_a, seen_b = [], []

            async def mk(bucket):
                async def handler(msg):
                    bucket.append(msg.data["n"])
                    return True
                return handler

            await broker.subscribe("topic", "app", await mk(seen_a))
            await broker.subscribe("topic", "app", await mk(seen_b))
            for i in range(12):
                await broker.publish("topic", {"n": i})
            assert await wait_until(
                lambda: len(seen_a) + len(seen_b) == 12)
            # each message delivered exactly once across the group
            assert sorted(seen_a + seen_b) == list(range(12))
        finally:
            await broker.aclose()


@pytest.mark.asyncio
async def test_redis_pubsub_fanout_to_independent_groups():
    """Two app-ids (groups) each get every message — the Service Bus
    subscription-per-app model (bicep/modules/service-bus.bicep:55-57)."""
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "p", f"127.0.0.1:{srv.port}", redeliver_interval=0.2, block_ms=30)
        try:
            a, b = [], []

            async def ha(msg):
                a.append(msg.data["n"]); return True

            async def hb(msg):
                b.append(msg.data["n"]); return True

            await broker.subscribe("topic", "app-a", ha)
            await broker.subscribe("topic", "app-b", hb)
            for i in range(5):
                await broker.publish("topic", {"n": i})
            assert await wait_until(
                lambda: sorted(a) == list(range(5)) and sorted(b) == list(range(5)))
        finally:
            await broker.aclose()


@pytest.mark.asyncio
async def test_redis_pubsub_nack_redelivers_with_attempt_count():
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "p", f"127.0.0.1:{srv.port}",
            max_attempts=5, redeliver_interval=0.05, block_ms=30)
        try:
            attempts = []

            async def flaky(msg):
                attempts.append(msg.attempt)
                return msg.attempt >= 3  # fail twice, then ack

            await broker.subscribe("topic", "app", flaky)
            await broker.publish("topic", {"n": 1})
            assert await wait_until(lambda: 3 in attempts)
            assert attempts[:3] == [1, 2, 3]
            await asyncio.sleep(0.2)  # no further redelivery after ack
            assert len(attempts) == 3
        finally:
            await broker.aclose()


@pytest.mark.asyncio
async def test_redis_pubsub_poison_message_parks_on_dead_letter():
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "p", f"127.0.0.1:{srv.port}",
            max_attempts=2, redeliver_interval=0.05, block_ms=30)
        try:
            calls = []

            async def poison(msg):
                calls.append(msg.attempt)
                raise RuntimeError("boom")

            await broker.subscribe("topic", "app", poison)
            await broker.publish("topic", {"bad": True})
            assert await wait_until(lambda: len(calls) >= 2)
            # parked: the dead-letter stream holds it, group drained
            assert await wait_until(lambda: bool(
                srv.streams.get(b"tasksrunner:topic:topic:dead")))
            await asyncio.sleep(0.2)
            assert len(calls) == 2
        finally:
            await broker.aclose()


# ------------------------------------------------------------- wiring


def test_driver_dispatch_follows_the_yaml(tmp_path):
    """Reference invariant: the YAML (not code) picks the backend."""
    with_host = parse_component({
        "componentType": "pubsub.redis",
        "metadata": [{"name": "redisHost", "value": "localhost:6399"}],
    }, default_name="taskspubsub")
    without_host = parse_component({
        "componentType": "pubsub.redis",
        "metadata": [{"name": "brokerPath",
                      "value": str(tmp_path / "b.db")}],
    }, default_name="taskspubsub")
    factory = resolve_driver("pubsub.redis")
    real = factory(with_host, {"redisHost": "localhost:6399"})
    local = factory(without_host, {"brokerPath": str(tmp_path / "b.db")})
    assert isinstance(real, RedisStreamsBroker)
    assert isinstance(local, SqliteBroker)

    state_factory = resolve_driver("state.redis")
    store = state_factory(with_host, {"redisHost": "localhost:6399"})
    assert isinstance(store, RedisStateStore)


@pytest.mark.asyncio
async def test_redis_state_keys_with_glob_metacharacters():
    """MATCH metacharacters in an app-id prefix must stay literal."""
    async with RedisLiteServer() as srv:
        store = RedisStateStore("s", f"127.0.0.1:{srv.port}")
        try:
            await store.set("app[1]||x", {"n": 1})
            await store.set("app1||y", {"n": 2})
            assert await store.keys(prefix="app[1]||") == ["app[1]||x"]
        finally:
            await store.aclose()


@pytest.mark.asyncio
async def test_redis_pubsub_stream_capped_by_maxlen():
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "p", f"127.0.0.1:{srv.port}", max_stream_len=5, block_ms=30)
        try:
            for i in range(20):
                await broker.publish("topic", {"n": i})
            stream = srv.streams[b"tasksrunner:topic:topic"]
            assert len(stream.entries) <= 5
        finally:
            await broker.aclose()


@pytest.mark.asyncio
async def test_redis_pubsub_cancel_does_not_poison_pool():
    """Tearing down a blocked consumer must retire its socket, not
    return it to the pool with an unread XREADGROUP reply in flight."""
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "p", f"127.0.0.1:{srv.port}", block_ms=5_000)
        try:
            async def handler(msg):
                return True

            sub = await broker.subscribe("topic", "app", handler)
            await asyncio.sleep(0.05)  # consumer is now blocked in XREADGROUP
            await sub.cancel()
            # a poisoned pool would hand back the stale reply here
            for i in range(5):
                mid = await broker.publish("topic", {"n": i})
                assert "-" in mid, mid  # well-formed stream id
        finally:
            await broker.aclose()


@pytest.mark.asyncio
async def test_redis_pubsub_many_subscriptions_do_not_starve_pool():
    """20 subscriptions exceed the client pool size; publishes must
    still flow because read loops own dedicated sockets."""
    async with RedisLiteServer() as srv:
        broker = RedisStreamsBroker(
            "p", f"127.0.0.1:{srv.port}", block_ms=5_000)
        try:
            got = {}

            def mk(i):
                async def handler(msg):
                    got.setdefault(i, []).append(msg.data["n"])
                    return True
                return handler

            for i in range(20):
                await broker.subscribe(f"topic-{i}", "app", mk(i))
            for i in range(20):
                await broker.publish(f"topic-{i}", {"n": i})
            assert await wait_until(
                lambda: sum(len(v) for v in got.values()) == 20)
            assert all(got[i] == [i] for i in range(20))
        finally:
            await broker.aclose()


@pytest.mark.asyncio
async def test_redis_cas_conflict_reuses_pooled_connection():
    """An etag mismatch is an application outcome, not a transport
    fault: the pooled socket must survive it."""
    async with RedisLiteServer() as srv:
        store = RedisStateStore("s", f"127.0.0.1:{srv.port}")
        try:
            etag = await store.set("k", {"v": 1})
            await store.set("k", {"v": 2})  # invalidates etag
            for _ in range(5):
                with pytest.raises(EtagMismatch):
                    await store.set("k", {"v": 3}, etag=etag)
            # one reusable connection in the pool, not five corpses
            assert len(store.client._all) == 1
            assert len(store.client._free) == 1
        finally:
            await store.aclose()
