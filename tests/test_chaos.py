"""Chaos verification harness (`tasksrunner/chaos`).

This file is the second half of the chaos tentpole: the spec layer is
tested the way the Resiliency spec is (round-trip + load-time
validation), and the engine is tested for the property the whole
subsystem exists to provide — a *deterministic* adversary that lets us
assert the resiliency guarantees we advertise actually hold:

* seeded injection is bit-for-bit reproducible across two invocations;
* retries recover from sub-threshold error rates with **no lost
  writes**;
* sustained failure walks the breaker open → half-open → closed on the
  documented schedule (and the `resiliency_breaker_state` gauge tracks
  it);
* poisoned deliveries exhaust redelivery, land in the DLQ, and
  ``requeue_dead_letters`` drains them once the fault clears;
* with the gate off (the default) components are NOT wrapped — the
  production path allocates nothing.
"""

import asyncio
import json
import time

import pytest

from tasksrunner.chaos import (
    ChaosPolicies,
    chaos_enabled,
    load_chaos,
    parse_chaos,
)
from tasksrunner.chaos.wrappers import (
    ChaosOutputBinding,
    ChaosPubSubBroker,
    ChaosStateStore,
    wrap_component,
)
from tasksrunner.component.loader import load_components
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import parse_component
from tasksrunner.errors import (
    ChaosInjectedError,
    CircuitOpenError,
    ComponentError,
    PubSubError,
)
from tasksrunner.observability.metrics import metrics
from tasksrunner.pubsub.base import Message
from tasksrunner.pubsub.sqlite import SqliteBroker
from tasksrunner.resiliency import ResiliencyPolicies, parse_resiliency
from tasksrunner.runtime import Runtime
from tasksrunner.state.memory import InMemoryStateStore


def chaos_doc(**spec) -> dict:
    return {
        "apiVersion": "tasksrunner/v1alpha1",
        "kind": "Chaos",
        "metadata": {"name": "test-chaos"},
        "spec": spec,
    }


# ---------------------------------------------------------------------------
# spec: round-trip + load-time validation
# ---------------------------------------------------------------------------


def test_parse_roundtrip_all_fault_kinds():
    doc = chaos_doc(
        seed=42,
        faults={
            "slow": {"latency": {"duration": "20ms", "jitter": "10ms"}},
            "flaky": {"error": {"probability": 0.1, "raise": "OSError"}},
            "fivehundred": {"error": {"status": 503}},
            "dead": {"blackhole": {"deadline": "2s"}},
            "poison": {"crashEveryN": {"n": 5, "raise": "PubSubError"}},
        },
        targets={
            "apps": {"backend": ["dead"]},
            "components": {
                "statestore": {"outbound": ["slow", "flaky"]},
                "taskspubsub": {"inbound": "poison", "outbound": ["fivehundred"]},
            },
        },
    )
    doc["scopes"] = ["backend"]
    spec = parse_chaos(doc)
    assert spec.name == "test-chaos" and spec.seed == 42
    assert spec.scopes == ["backend"]
    assert set(spec.rules) == {"slow", "flaky", "fivehundred", "dead", "poison"}
    assert spec.rules["slow"].fault.duration == pytest.approx(0.02)
    assert spec.rules["slow"].fault.jitter == pytest.approx(0.01)
    assert spec.rules["flaky"].fault.probability == pytest.approx(0.1)
    assert spec.rules["fivehundred"].fault.status == 503
    assert spec.rules["dead"].fault.deadline == pytest.approx(2.0)
    assert spec.rules["poison"].fault.n == 5
    assert spec.app_targets == {"backend": ("dead",)}
    assert spec.component_targets["statestore"]["outbound"] == ("slow", "flaky")
    # single rule name normalizes to a tuple
    assert spec.component_targets["taskspubsub"]["inbound"] == ("poison",)
    assert spec.in_scope("backend") and not spec.in_scope("other")


@pytest.mark.parametrize("faults,targets,fragment", [
    # dangling rule reference must fail startup, not inject nothing
    ({"f": {"error": {"raise": "OSError"}}},
     {"components": {"s": {"outbound": ["typo"]}}}, "unknown fault rule"),
    ({"f": {"error": {"raise": "NoSuchError"}}}, {}, "unknown fault error class"),
    ({"f": {"error": {"probability": 1.5, "raise": "OSError"}}}, {},
     "probability"),
    ({"f": {"error": {"raise": "OSError", "status": 500}}}, {}, "exactly one"),
    ({"f": {"error": {"status": 77}}}, {}, "not an HTTP status"),
    ({"f": {"crashEveryN": {"n": 0}}}, {}, "n >= 1"),
    ({"f": {"teleport": {}}}, {}, "unknown fault kind"),
    ({"f": {"latency": {"duration": "1s"}, "error": {"status": 500}}}, {},
     "exactly one"),
])
def test_validation_fails_at_load_time(faults, targets, fragment):
    with pytest.raises(ComponentError, match=fragment):
        parse_chaos(chaos_doc(faults=faults, targets=targets))


def test_loader_skips_chaos_docs_and_load_chaos_collects(tmp_path):
    (tmp_path / "all.yaml").write_text(
        "\n".join([
            "componentType: state.in-memory",
            "metadata: []",
            "---",
            "kind: Chaos",
            "metadata: {name: c1}",
            "spec:",
            "  faults:",
            "    f: {error: {raise: OSError}}",
            "  targets:",
            "    components:",
            "      all: {outbound: [f]}",
        ]))
    comps = load_components(tmp_path)
    assert [c.type for c in comps] == ["state.in-memory"]
    specs = load_chaos(tmp_path)
    assert [s.name for s in specs] == ["c1"]
    # and a missing dir is simply no chaos
    assert load_chaos(tmp_path / "nope") == []


# ---------------------------------------------------------------------------
# engine: determinism, toggles, metrics
# ---------------------------------------------------------------------------


def _flaky_spec(probability=0.4, seed=7):
    return parse_chaos(chaos_doc(
        seed=seed,
        faults={"flaky": {"error": {"probability": probability,
                                    "raise": "OSError"}}},
        targets={"components": {"statestore": {"outbound": ["flaky"]}}},
    ))


async def _verdict_sequence(spec, n=40):
    """Drive the statestore injector n times, recording inject/pass."""
    policies = ChaosPolicies([spec])
    store = ChaosStateStore(InMemoryStateStore("statestore"),
                            policies.for_component("statestore"))
    out = []
    for i in range(n):
        try:
            await store.set(f"k{i}", i)
            out.append(0)
        except OSError:
            out.append(1)
    return out


@pytest.mark.asyncio
async def test_seeded_injection_bit_for_bit_reproducible():
    """The acceptance bar: two invocations of the same seeded scenario
    produce the identical fault sequence (string seeding is sha512-based
    in CPython, so this holds across processes too, independent of
    PYTHONHASHSEED)."""
    spec = _flaky_spec()
    first = await _verdict_sequence(spec)
    second = await _verdict_sequence(_flaky_spec())
    assert first == second
    assert 0 < sum(first) < len(first)  # actually probabilistic, not const
    # a different seed produces a different (but equally stable) run
    assert first != await _verdict_sequence(_flaky_spec(seed=8))


@pytest.mark.asyncio
async def test_injection_counts_into_metrics():
    spec = _flaky_spec(probability=1.0)
    policies = ChaosPolicies([spec])
    store = ChaosStateStore(InMemoryStateStore("statestore"),
                            policies.for_component("statestore"))
    before = metrics.get("chaos_injected_total",
                         target="components/statestore/outbound", fault="flaky")
    for _ in range(3):
        with pytest.raises(OSError):
            await store.get("k")
    after = metrics.get("chaos_injected_total",
                        target="components/statestore/outbound", fault="flaky")
    assert after - before == 3


@pytest.mark.asyncio
async def test_disable_enable_toggle_and_describe():
    spec = _flaky_spec(probability=1.0)
    policies = ChaosPolicies([spec])
    store = ChaosStateStore(InMemoryStateStore("statestore"),
                            policies.for_component("statestore"))
    with pytest.raises(OSError):
        await store.get("k")
    policies.disable("flaky")
    assert (await store.get("k")) is None  # fault switched off mid-run
    assert policies.describe()[0]["disabled"] is True
    policies.enable("flaky")
    with pytest.raises(OSError):
        await store.get("k")
    desc = policies.describe()
    assert desc[0]["rule"] == "flaky"
    assert desc[0]["targets"] == ["components/statestore/outbound"]


@pytest.mark.asyncio
async def test_status_fault_raises_chaos_injected_on_component_seam():
    spec = parse_chaos(chaos_doc(
        faults={"fivehundred": {"error": {"status": 503}}},
        targets={"components": {"statestore": {"outbound": ["fivehundred"]}}},
    ))
    policies = ChaosPolicies([spec])
    store = ChaosStateStore(InMemoryStateStore("statestore"),
                            policies.for_component("statestore"))
    with pytest.raises(ChaosInjectedError) as err:
        await store.get("k")
    assert err.value.status == 503


@pytest.mark.asyncio
async def test_blackhole_hangs_then_times_out():
    spec = parse_chaos(chaos_doc(
        faults={"dead": {"blackhole": {"deadline": "50ms"}}},
        targets={"components": {"statestore": {"outbound": ["dead"]}}},
    ))
    policies = ChaosPolicies([spec])
    store = ChaosStateStore(InMemoryStateStore("statestore"),
                            policies.for_component("statestore"))
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        await store.get("k")
    assert time.monotonic() - t0 >= 0.05


@pytest.mark.asyncio
async def test_crash_every_n_is_exact():
    spec = parse_chaos(chaos_doc(
        faults={"poison": {"crashEveryN": {"n": 3}}},
        targets={"components": {"statestore": {"outbound": ["poison"]}}},
    ))
    policies = ChaosPolicies([spec])
    store = ChaosStateStore(InMemoryStateStore("statestore"),
                            policies.for_component("statestore"))
    outcomes = []
    for i in range(9):
        try:
            await store.set(f"k{i}", i)
            outcomes.append("ok")
        except OSError:
            outcomes.append("boom")
    assert outcomes == ["ok", "ok", "boom"] * 3


def test_workflow_targets_parse_roundtrip_and_validate():
    """``targets.workflows`` keys are ``<workflow>`` or
    ``<workflow>/<activity>``; single rule names normalize to tuples
    and dangling refs fail at load time like every other target kind."""
    spec = parse_chaos(chaos_doc(
        seed=3,
        faults={
            "slow": {"latency": {"duration": "10ms"}},
            "fell": {"crashEveryN": {"n": 2, "raise": "OSError"}},
        },
        targets={"workflows": {"checkout": "slow",
                               "checkout/charge": ["fell"]}},
    ))
    assert spec.workflow_targets == {"checkout": ("slow",),
                                     "checkout/charge": ("fell",)}
    with pytest.raises(ComponentError, match="unknown fault rule"):
        parse_chaos(chaos_doc(
            faults={"f": {"error": {"raise": "OSError"}}},
            targets={"workflows": {"checkout": ["typo"]}}))


def test_for_workflow_resolves_most_specific_first():
    spec = parse_chaos(chaos_doc(
        faults={
            "wide": {"latency": {"duration": "10ms"}},
            "narrow": {"crashEveryN": {"n": 2, "raise": "OSError"}},
        },
        targets={"workflows": {"checkout": ["wide"],
                               "checkout/charge": ["narrow"]}},
    ))
    policies = ChaosPolicies([spec])
    # exact <workflow>/<activity> binding beats the workflow-wide one
    charge = policies.for_workflow("checkout", "charge")
    assert [i.rule.name for i in charge.injectors] == ["narrow"]
    # other activities of the workflow fall back to the wide binding
    ship = policies.for_workflow("checkout", "ship")
    assert [i.rule.name for i in ship.injectors] == ["wide"]
    # no-activity resolution (compensations use the workflow key too)
    assert [i.rule.name
            for i in policies.for_workflow("checkout").injectors] == ["wide"]
    assert policies.for_workflow("other", "charge") is None
    bound = {t for d in policies.describe() for t in d["targets"]}
    assert bound == {"workflows/checkout/activity",
                     "workflows/checkout/charge/activity"}


def test_placement_targets_parse_roundtrip_and_validate():
    """``targets.placement`` keys are ``<store>`` or
    ``<store>/<shard>`` — the elastic-migration catch-up lane; single
    rule names normalize to tuples and dangling refs fail at load
    time like every other target kind."""
    spec = parse_chaos(chaos_doc(
        seed=3,
        faults={
            "slow": {"latency": {"duration": "10ms"}},
            "dead": {"blackhole": {"deadline": "50ms"}},
        },
        targets={"placement": {"statestore": "slow",
                               "statestore/2": ["dead"]}},
    ))
    assert spec.placement_targets == {"statestore": ("slow",),
                                      "statestore/2": ("dead",)}
    with pytest.raises(ComponentError, match="unknown fault rule"):
        parse_chaos(chaos_doc(
            faults={"f": {"error": {"raise": "OSError"}}},
            targets={"placement": {"statestore": ["typo"]}}))


def test_for_placement_resolves_most_specific_first():
    spec = parse_chaos(chaos_doc(
        faults={
            "wide": {"latency": {"duration": "10ms"}},
            "narrow": {"blackhole": {"deadline": "50ms"}},
        },
        targets={"placement": {"statestore": ["wide"],
                               "statestore/2": ["narrow"]}},
    ))
    policies = ChaosPolicies([spec])
    # exact <store>/<shard> binding beats the store-wide one
    shard2 = policies.for_placement("statestore", 2)
    assert [i.rule.name for i in shard2.injectors] == ["narrow"]
    # other shards of the store fall back to the wide binding
    shard0 = policies.for_placement("statestore", 0)
    assert [i.rule.name for i in shard0.injectors] == ["wide"]
    # no-shard resolution (store-wide drills)
    assert [i.rule.name
            for i in policies.for_placement("statestore").injectors] \
        == ["wide"]
    assert policies.for_placement("other", 2) is None
    bound = {t for d in policies.describe() for t in d["targets"]}
    assert bound == {"placement/statestore/migration",
                     "placement/statestore/2/migration"}


def test_scoping_filters_specs():
    spec = _flaky_spec()
    spec.scopes = ["backend"]
    assert ChaosPolicies([spec], app_id="frontend").for_component(
        "statestore") is None
    assert ChaosPolicies([spec], app_id="backend").for_component(
        "statestore") is not None


# ---------------------------------------------------------------------------
# wiring: the gate and the wrap-at-build seam
# ---------------------------------------------------------------------------


def test_gate_is_off_by_default(monkeypatch):
    monkeypatch.delenv("TASKSRUNNER_CHAOS", raising=False)
    assert chaos_enabled() is False
    monkeypatch.setenv("TASKSRUNNER_CHAOS", "1")
    assert chaos_enabled() is True


def test_registry_wraps_only_targeted_components():
    specs = [
        parse_component({"componentType": "state.in-memory"},
                        default_name="statestore"),
        parse_component({"componentType": "state.in-memory"},
                        default_name="other"),
    ]
    # no chaos at all → bare instances (the production path)
    bare = ComponentRegistry(specs, app_id="app")
    assert type(bare.get("statestore")) is InMemoryStateStore
    # chaos naming one component → only that one is wrapped
    chaotic = ComponentRegistry(specs, app_id="app",
                                chaos=ChaosPolicies([_flaky_spec()]))
    assert isinstance(chaotic.get("statestore"), ChaosStateStore)
    assert type(chaotic.get("other")) is InMemoryStateStore


def test_wrap_component_dispatches_by_block():
    from tasksrunner.bindings.base import BindingResponse, OutputBinding

    class NoopOut(OutputBinding):
        async def invoke(self, operation, data, metadata=None):
            return BindingResponse(data=None)

    spec = parse_chaos(chaos_doc(
        faults={"f": {"error": {"raise": "BindingError"}}},
        targets={"components": {"outb": {"outbound": ["f"]}}},
    ))
    chaos = ChaosPolicies([spec])
    cspec = parse_component({"componentType": "bindings.noop"},
                            default_name="outb")
    wrapped = wrap_component(NoopOut("outb"), cspec, chaos)
    assert isinstance(wrapped, ChaosOutputBinding)
    # an untargeted sibling stays bare
    other = parse_component({"componentType": "bindings.noop"},
                            default_name="other")
    inner = NoopOut("other")
    assert wrap_component(inner, other, chaos) is inner


# ---------------------------------------------------------------------------
# the guarantees: retries, breaker schedule, DLQ drain
# ---------------------------------------------------------------------------

RETRY_DOC = {
    "kind": "Resiliency",
    "metadata": {"name": "r"},
    "spec": {
        "policies": {"retries": {"fast": {"duration": "1ms", "maxRetries": 5}}},
        "targets": {"components": {"statestore": {"retry": "fast"}}},
    },
}


@pytest.mark.asyncio
async def test_retries_recover_sub_threshold_errors_no_lost_writes():
    """A 25% injected error rate sits well under what 5 retries absorb:
    every write must land, and the retry counters must show the faults
    were real (injected and retried), not absent."""
    policies = ChaosPolicies([_flaky_spec(probability=0.25, seed=7)])
    registry = ComponentRegistry(
        [parse_component({"componentType": "state.in-memory"},
                         default_name="statestore")],
        app_id="app", chaos=policies)
    runtime = Runtime(
        "app", registry,
        resiliency=ResiliencyPolicies([parse_resiliency(RETRY_DOC)],
                                      app_id="app"))
    for i in range(40):
        await runtime.save_state("statestore", [{"key": f"k{i}", "value": i}])
    injected = metrics.get("chaos_injected_total",
                           target="components/statestore/outbound",
                           fault="flaky")
    assert injected > 0  # the adversary really fired…
    for i in range(40):  # …and no write was lost
        item = await runtime.get_state("statestore", f"k{i}")
        assert item is not None and item.value == i


BREAKER_DOC = {
    "kind": "Resiliency",
    "metadata": {"name": "r"},
    "spec": {
        "policies": {"circuitBreakers": {
            "cb": {"timeout": "50ms", "trip": "consecutiveFailures >= 2"},
        }},
        "targets": {"components": {"statestore": {"circuitBreaker": "cb"}}},
    },
}


@pytest.mark.asyncio
async def test_breaker_open_half_open_closed_under_sustained_chaos():
    """Sustained 100% failure trips the breaker after exactly the trip
    threshold; while open, calls shed WITHOUT reaching the store; after
    the documented timeout one probe goes through (half-open) — failing
    re-opens, succeeding closes — and the state gauge tracks it."""
    policies = ChaosPolicies([_flaky_spec(probability=1.0)])
    registry = ComponentRegistry(
        [parse_component({"componentType": "state.in-memory"},
                         default_name="statestore")],
        app_id="app", chaos=policies)
    runtime = Runtime(
        "app", registry,
        resiliency=ResiliencyPolicies([parse_resiliency(BREAKER_DOC)],
                                      app_id="app"))
    policies.for_component("statestore")  # populate the lazy injector map
    injector = policies._injectors[("flaky", "components/statestore/outbound")]

    def gauge():
        return metrics.get("resiliency_breaker_state",
                           policy="cb", target="statestore")

    for _ in range(2):  # trip threshold
        with pytest.raises(OSError):
            await runtime.get_state("statestore", "k")
    assert gauge() == 2  # OPEN
    with pytest.raises(CircuitOpenError):
        await runtime.get_state("statestore", "k")
    assert injector.calls == 2  # the shed call never reached the store

    await asyncio.sleep(0.07)  # > breaker timeout → next call probes
    with pytest.raises(OSError):  # half-open probe fails → re-open
        await runtime.get_state("statestore", "k")
    assert injector.calls == 3  # the probe DID go through to the store
    assert gauge() == 2

    await asyncio.sleep(0.07)
    policies.disable("flaky")  # fault clears → probe succeeds → closed
    assert (await runtime.get_state("statestore", "k")) is None
    assert gauge() == 0


@pytest.mark.asyncio
async def test_poisoned_deliveries_reach_dlq_and_requeue_drains(tmp_path):
    """Inbound chaos raises in the delivery path, which the broker
    counts as a nack: redelivery runs, attempts exhaust, the messages
    dead-letter. Clearing the fault and requeueing drains the DLQ
    through the normal delivery machinery — nothing is lost."""
    spec = parse_chaos(chaos_doc(
        faults={"poison": {"error": {"raise": "PubSubError"}}},
        targets={"components": {"tp": {"inbound": ["poison"]}}},
    ))
    policies = ChaosPolicies([spec])
    inner = SqliteBroker("tp", tmp_path / "broker.db",
                         max_attempts=2, retry_delay=0.01, poll_interval=0.01)
    broker = ChaosPubSubBroker(
        inner,
        policies.for_component("tp", "outbound"),
        policies.for_component("tp", "inbound"))
    received = []

    async def handler(msg: Message) -> bool:
        received.append(msg.data["n"])
        return True

    try:
        sub = await broker.subscribe("t", "g", handler)
        for n in range(3):
            await broker.publish("t", {"n": n})
        for _ in range(500):
            if len(inner.dead_letters("t", "g")) == 3:
                break
            await asyncio.sleep(0.01)
        assert len(inner.dead_letters("t", "g")) == 3
        assert received == []  # chaos fired before the handler every time

        policies.disable("poison")
        # driver extras pass through the wrapper untouched
        assert broker.requeue_dead_letters("t", "g") == 3
        for _ in range(500):
            if len(received) == 3:
                break
            await asyncio.sleep(0.01)
        assert sorted(received) == [0, 1, 2]
        assert inner.dead_letters("t", "g") == []
        assert inner.backlog("t", "g") == 0
        await sub.cancel()
    finally:
        await broker.aclose()


# ---------------------------------------------------------------------------
# invoke seam: app-targeted rules run per attempt inside resiliency
# ---------------------------------------------------------------------------


class CountingChannel:
    def __init__(self, replies=None):
        self.calls = 0
        self.replies = replies

    async def request(self, method, path, *, query="", headers=None, body=b""):
        self.calls += 1
        if self.replies:
            reply = self.replies.pop(0)
            if isinstance(reply, Exception):
                raise reply
        return 200, {}, b"ok"


@pytest.mark.asyncio
async def test_invoke_status_fault_synthesizes_reply_without_reaching_peer():
    spec = parse_chaos(chaos_doc(
        faults={"down": {"error": {"status": 503}}},
        targets={"apps": {"backend": ["down"]}},
    ))
    channel = CountingChannel()
    runtime = Runtime("caller", ComponentRegistry([], app_id="caller"),
                      chaos=ChaosPolicies([spec], app_id="caller"))
    runtime.peers["backend"] = channel
    status, headers, body = await runtime.invoke("backend", "/api/x")
    assert status == 503
    assert headers["x-tasksrunner-chaos"] == "injected"
    assert json.loads(body)["message"].startswith("chaos")
    assert channel.calls == 0  # synthesized before the wire


@pytest.mark.asyncio
async def test_invoke_raised_fault_is_retried_by_resiliency():
    """An app-targeted raising fault looks like a transport failure, so
    the declarative retry policy absorbs it — chaos exercises the real
    resiliency machinery, per attempt."""
    spec = parse_chaos(chaos_doc(
        faults={"flaky": {"crashEveryN": {"n": 2, "raise": "OSError"}}},
        targets={"apps": {"backend": ["flaky"]}},
    ))
    doc = {
        "kind": "Resiliency", "metadata": {"name": "r"},
        "spec": {
            "policies": {"retries": {"fast": {"duration": "1ms",
                                              "maxRetries": 3}}},
            "targets": {"apps": {"backend": {"retry": "fast"}}},
        },
    }
    channel = CountingChannel()
    runtime = Runtime(
        "caller", ComponentRegistry([], app_id="caller"),
        resiliency=ResiliencyPolicies([parse_resiliency(doc)], app_id="caller"),
        chaos=ChaosPolicies([spec], app_id="caller"))
    runtime.peers["backend"] = channel
    # attempts 1,3 pass the injector (crash every 2nd), so each invoke
    # needs at most one retry and always lands
    for _ in range(4):
        status, _, _ = await runtime.invoke("backend", "/api/x")
        assert status == 200


# ---------------------------------------------------------------------------
# satellites: jitter, breaker gauge, timeoutPolicy, inbound delivery path
# ---------------------------------------------------------------------------


def test_retry_jitter_zero_preserves_exact_schedule():
    import itertools
    from tasksrunner.resiliency.policy import RetrySpec
    spec = RetrySpec(policy="exponential", duration=0.5, max_interval=4.0,
                     max_retries=5)
    assert list(spec.delays()) == [0.5, 1.0, 2.0, 4.0, 4.0]
    # jitter is opt-in: the default spec is bit-identical to before
    assert spec.jitter == 0.0


def test_retry_jitter_is_bounded_and_seedable():
    import random
    from tasksrunner.resiliency.policy import RetrySpec
    spec = RetrySpec(policy="exponential", duration=0.1, max_interval=2.0,
                     max_retries=50, jitter=1.0)
    a = list(spec.delays(random.Random(42)))
    b = list(spec.delays(random.Random(42)))
    assert a == b  # seedable → reproducible
    # fully-decorrelated delays stay inside [duration, maxInterval]
    assert all(0.1 <= d <= 2.0 for d in a)
    assert len(set(round(d, 6) for d in a)) > 5  # actually jittered
    # a 0.5 blend lands between the deterministic and jittered schedules
    blend = RetrySpec(policy="constant", duration=0.1, max_interval=2.0,
                      max_retries=20, jitter=0.5)
    for d in blend.delays(random.Random(1)):
        assert 0.1 * 0.5 + 0.1 * 0.5 <= d <= 0.5 * 0.1 + 0.5 * 2.0


def test_retry_jitter_parses_and_validates():
    doc = {
        "kind": "Resiliency", "metadata": {"name": "r"},
        "spec": {"policies": {"retries": {
            "j": {"duration": "100ms", "maxRetries": 3, "jitter": 0.8},
        }}},
    }
    assert parse_resiliency(doc).retries["j"].jitter == pytest.approx(0.8)
    doc["spec"]["policies"]["retries"]["j"]["jitter"] = 1.5
    with pytest.raises(ComponentError, match="jitter"):
        parse_resiliency(doc)


def test_breaker_state_gauge_tracks_transitions():
    from tasksrunner.resiliency.policy import CircuitBreaker, CircuitBreakerSpec
    cb = CircuitBreaker(
        CircuitBreakerSpec(name="g", trip_threshold=2, timeout=0.01),
        target="gauge-target")

    def gauge():
        return metrics.get("resiliency_breaker_state",
                           policy="g", target="gauge-target")

    assert gauge() == 0  # closed at birth
    cb.record_failure()
    cb.record_failure()
    assert gauge() == 2  # open
    time.sleep(0.02)
    cb.before_call()  # timeout elapsed → half-open probe admitted
    assert gauge() == 1
    cb.record_success()
    assert gauge() == 0


@pytest.mark.asyncio
async def test_retry_counters_from_execute():
    from tasksrunner.resiliency.policy import RetrySpec, TargetPolicy
    policy = TargetPolicy(target="ctr-target",
                          retry=RetrySpec(duration=0.001, max_retries=2))
    calls = 0

    async def flaky():
        nonlocal calls
        calls += 1
        if calls < 3:
            raise OSError("transient")
        return "ok"

    r0 = metrics.get("resiliency_retry_total", target="ctr-target")
    assert await policy.execute(flaky) == "ok"
    assert metrics.get("resiliency_retry_total", target="ctr-target") - r0 == 2

    calls = -100  # never recovers → retries exhaust
    e0 = metrics.get("resiliency_retry_exhausted_total", target="ctr-target")
    with pytest.raises(OSError):
        await policy.execute(flaky)
    assert metrics.get("resiliency_retry_exhausted_total",
                       target="ctr-target") - e0 == 1


def test_timeout_policy_parses_and_validates():
    doc = {
        "kind": "Resiliency", "metadata": {"name": "r"},
        "spec": {
            "policies": {"timeouts": {"slow": "200ms"}},
            "targets": {"components": {"s": {
                "outbound": {"timeout": "slow", "timeoutPolicy": "total"},
            }}},
        },
    }
    pol = ResiliencyPolicies([parse_resiliency(doc)]).for_component("s")
    assert pol.timeout_policy == "total"
    assert pol.timeout == pytest.approx(0.2)
    doc["spec"]["targets"]["components"]["s"]["outbound"]["timeoutPolicy"] = "sometimes"
    with pytest.raises(ComponentError, match="timeoutPolicy"):
        parse_resiliency(doc)


@pytest.mark.asyncio
async def test_timeout_policy_total_is_a_budget_across_attempts():
    """perAttempt (historical default) restarts the clock every try;
    total is an overall budget covering attempts AND backoff sleeps."""
    from tasksrunner.resiliency.policy import RetrySpec, TargetPolicy

    async def always_failing():
        await asyncio.sleep(0.02)
        raise OSError("down")

    total = TargetPolicy(
        target="t", timeout=0.08, timeout_policy="total",
        retry=RetrySpec(duration=0.02, max_retries=50))
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="total budget"):
        await total.execute(always_failing)
    elapsed = time.monotonic() - t0
    assert elapsed < 0.5  # ~50 attempts * 40ms would be ~2s without the budget

    # the same policy perAttempt keeps retrying well past 80ms
    per_attempt = TargetPolicy(
        target="t", timeout=0.08, timeout_policy="perAttempt",
        retry=RetrySpec(duration=0.02, max_retries=5))
    t0 = time.monotonic()
    with pytest.raises(OSError):
        await per_attempt.execute(always_failing)
    assert time.monotonic() - t0 > 0.12  # 6 attempts * 20ms + sleeps


@pytest.mark.asyncio
async def test_timeout_policy_total_caps_a_hanging_call():
    from tasksrunner.resiliency.policy import TargetPolicy

    async def hangs():
        await asyncio.sleep(60)

    policy = TargetPolicy(target="t", timeout=0.05, timeout_policy="total")
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        await policy.execute(hangs)
    assert time.monotonic() - t0 < 1.0


class FlakyThenOkChannel:
    """App channel that fails the first N deliveries with a transport
    error, then answers 200 — the shape of an app mid-restart."""

    def __init__(self, failures=2):
        self.calls = 0
        self.failures = failures

    async def request(self, method, path, *, query="", headers=None, body=b""):
        self.calls += 1
        if self.calls <= self.failures:
            raise OSError("app not up yet")
        return 200, {}, b"ok"


INBOUND_DOC = {
    "kind": "Resiliency", "metadata": {"name": "r"},
    "spec": {
        "policies": {"retries": {"fast": {"duration": "1ms", "maxRetries": 5}}},
        "targets": {"components": {"tp": {"inbound": {"retry": "fast"}}}},
    },
}


@pytest.mark.asyncio
async def test_inbound_policy_retries_subscription_delivery():
    """The inbound direction of a component target guards the
    sidecar→app hop: a transiently-failing handler is retried locally
    and the delivery still acks — it never counts as a nack."""
    channel = FlakyThenOkChannel(failures=2)
    resiliency = ResiliencyPolicies([parse_resiliency(INBOUND_DOC)],
                                    app_id="app")
    runtime = Runtime("app", ComponentRegistry([], app_id="app"),
                      app_channel=channel, resiliency=resiliency)
    # direction separation: inbound config must not leak outbound
    assert resiliency.for_component("tp", "outbound") is None
    assert resiliency.for_component("tp", "inbound") is not None

    deliver = runtime._make_subscription_handler("tp", "/on")
    ok = await deliver(Message(id="m1", topic="t", data={"n": 1}))
    assert ok is True
    assert channel.calls == 3  # two retries absorbed the failures


@pytest.mark.asyncio
async def test_inbound_policy_retries_binding_delivery():
    from tasksrunner.bindings.base import BindingEvent, InputBinding

    class Stub(InputBinding):
        async def start(self, sink):  # pragma: no cover - not started here
            pass

        async def stop(self):  # pragma: no cover
            pass

    doc = {
        "kind": "Resiliency", "metadata": {"name": "r"},
        "spec": {
            "policies": {"retries": {"fast": {"duration": "1ms",
                                              "maxRetries": 5}}},
            "targets": {"components": {"inq": {"inbound": {"retry": "fast"}}}},
        },
    }
    channel = FlakyThenOkChannel(failures=1)
    runtime = Runtime(
        "app", ComponentRegistry([], app_id="app"), app_channel=channel,
        resiliency=ResiliencyPolicies([parse_resiliency(doc)], app_id="app"))
    sink = runtime._make_binding_sink(Stub("inq"))
    ok = await sink(BindingEvent(binding="inq", data={"n": 1}, metadata={}))
    assert ok is True
    assert channel.calls == 2


@pytest.mark.asyncio
async def test_inbound_retries_exhausted_still_nacks():
    """When the app stays down past the retry budget the delivery must
    report False (nack) so the broker's redelivery/DLQ machinery — not
    the inbound policy — owns the message's fate."""
    channel = FlakyThenOkChannel(failures=99)
    runtime = Runtime(
        "app", ComponentRegistry([], app_id="app"), app_channel=channel,
        resiliency=ResiliencyPolicies([parse_resiliency(INBOUND_DOC)],
                                      app_id="app"))
    deliver = runtime._make_subscription_handler("tp", "/on")
    ok = await deliver(Message(id="m1", topic="t", data={"n": 1}))
    assert ok is False
    assert channel.calls == 6  # 1 + 5 retries, then gave up


# ---------------------------------------------------------------------------
# CLI admin surface
# ---------------------------------------------------------------------------

CHAOS_YAML = """\
kind: Chaos
metadata: {name: cli-chaos}
spec:
  seed: 9
  faults:
    flaky: {error: {probability: 0.2, raise: OSError}}
  targets:
    components:
      statestore: {outbound: [flaky]}
"""


def test_cli_chaos_status_gate_off_warns_and_exits_3(tmp_path, capsys,
                                                     monkeypatch):
    from tasksrunner.cli import main
    monkeypatch.delenv("TASKSRUNNER_CHAOS", raising=False)
    (tmp_path / "chaos.yaml").write_text(CHAOS_YAML)
    with pytest.raises(SystemExit) as err:
        main(["chaos", "status", "--resources", str(tmp_path)])
    assert err.value.code == 3  # scriptable "documents present but inert"
    out = capsys.readouterr().out
    assert "flaky" in out and "statestore" in out
    assert "TASKSRUNNER_CHAOS=1" in out


def test_cli_chaos_status_json(tmp_path, capsys, monkeypatch):
    from tasksrunner.cli import main
    monkeypatch.setenv("TASKSRUNNER_CHAOS", "1")
    (tmp_path / "chaos.yaml").write_text(CHAOS_YAML)
    main(["chaos", "status", "--resources", str(tmp_path), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert payload["enabled"] is True
    assert payload["documents"] == 1
    assert payload["rules"][0]["rule"] == "flaky"
    assert payload["rules"][0]["targets"] == ["components/statestore/outbound"]


def test_cli_chaos_status_rejects_malformed_documents(tmp_path, monkeypatch):
    from tasksrunner.cli import main
    (tmp_path / "chaos.yaml").write_text(
        "kind: Chaos\nspec:\n  targets:\n    components:\n"
        "      s: {outbound: [nope]}\n")
    with pytest.raises(SystemExit, match="unknown fault rule"):
        main(["chaos", "status", "--resources", str(tmp_path)])
