"""End-to-end integration: the SURVEY.md §7.3 minimum slice.

frontend → invoke → API → state store → publish → processor handler,
run identically through BOTH hosting modes:

* InProcCluster (direct channels, no sockets)
* AppHost pairs (real aiohttp app servers + sidecars on ephemeral
  ports, Dapr-shaped /v1.0 HTTP between them)

This mirrors the reference's end-of-module-5 local milestone: three
`dapr run` terminals, browser CRUD, consumer logging the event
(SURVEY.md §3.1 call stack).
"""

import asyncio
import textwrap
import uuid

import pytest

from tasksrunner import App, AppHost, InProcCluster, load_components
from tasksrunner.errors import TasksRunnerError

COMPONENTS_YAML = textwrap.dedent(
    """
    apiVersion: dapr.io/v1alpha1
    kind: Component
    metadata:
      name: statestore
    spec:
      type: state.in-memory
      version: v1
    scopes:
    - backend-api
    ---
    apiVersion: dapr.io/v1alpha1
    kind: Component
    metadata:
      name: taskspubsub
    spec:
      type: pubsub.sqlite
      version: v1
      metadata:
      - name: brokerPath
        value: "{broker_path}"
      - name: pollIntervalSeconds
        value: "0.01"
    """
)


def make_api_app() -> App:
    app = App("backend-api")

    @app.get("/api/tasks")
    async def list_tasks(req):
        created_by = req.query.get("createdBy", "")
        result = await app.client.query_state(
            "statestore", {"filter": {"EQ": {"taskCreatedBy": created_by}}})
        return [r["data"] for r in result["results"]]

    @app.post("/api/tasks")
    async def create_task(req):
        task = req.json()
        task_id = str(uuid.uuid4())
        task["taskId"] = task_id
        await app.client.save_state("statestore", task_id, task)
        await app.client.publish_event("taskspubsub", "tasksavedtopic", task)
        return 201, {"taskId": task_id}

    @app.get("/api/tasks/{task_id}")
    async def get_task(req):
        task = await app.client.get_state("statestore", req.path_params["task_id"])
        if task is None:
            return 404
        return task

    return app


def make_frontend_app() -> App:
    app = App("frontend")

    @app.post("/tasks/create")
    async def create(req):
        resp = await app.client.invoke_method(
            "backend-api", "api/tasks", http_method="POST", data=req.json())
        resp.raise_for_status()
        return {"taskId": resp.json()["taskId"]}

    @app.get("/tasks")
    async def list_tasks(req):
        return await app.client.invoke_json(
            "backend-api", "api/tasks",
            query=f"createdBy={req.query.get('createdBy', '')}")

    return app


def make_processor_app(received: list) -> App:
    app = App("processor")

    @app.subscribe(pubsub="taskspubsub", topic="tasksavedtopic",
                   route="/api/tasksnotifier/tasksaved")
    async def on_task_saved(req):
        received.append(req.data)  # CloudEvents-unwrapped payload
        return 200

    return app


def specs_for(tmp_path):
    text = COMPONENTS_YAML.format(broker_path=tmp_path / "broker.db")
    f = tmp_path / "components.yaml"
    f.write_text(text)
    return load_components(tmp_path)


async def wait_until(cond, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition not met in time")
        await asyncio.sleep(0.02)


async def run_slice(frontend_client, received):
    """The canonical write path + read path, driven from the frontend."""
    resp = await frontend_client.invoke_method(
        "frontend", "tasks/create", http_method="POST",
        data={"taskName": "demo", "taskCreatedBy": "a@x.com"})
    assert resp.ok, resp.body
    task_id = resp.json()["taskId"]

    tasks = await frontend_client.invoke_json(
        "frontend", "tasks", query="createdBy=a@x.com")
    assert [t["taskId"] for t in tasks] == [task_id]

    await wait_until(lambda: len(received) == 1)
    assert received[0]["taskId"] == task_id
    assert received[0]["taskName"] == "demo"
    return task_id


@pytest.mark.asyncio
async def test_end_to_end_in_proc(tmp_path):
    received: list = []
    cluster = InProcCluster(specs_for(tmp_path))
    cluster.add_app(make_api_app())
    cluster.add_app(make_frontend_app())
    cluster.add_app(make_processor_app(received))
    await cluster.start()
    try:
        await run_slice(cluster.client("frontend"), received)
        # scoping: frontend must NOT see the API-scoped state store
        with pytest.raises(TasksRunnerError):
            await cluster.client("frontend").get_state("statestore", "x")
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_end_to_end_http_sidecars(tmp_path):
    received: list = []
    specs = specs_for(tmp_path)
    registry_file = str(tmp_path / "apps.json")

    hosts = [
        AppHost(make_api_app(), specs=specs, registry_file=registry_file),
        AppHost(make_frontend_app(), specs=specs, registry_file=registry_file),
        AppHost(make_processor_app(received), specs=specs,
                registry_file=registry_file),
    ]
    for h in hosts:
        await h.start()
    try:
        task_id = await run_slice(hosts[1].client, received)

        # drive the sidecar API raw, as the workshop's manual probes do
        # (docs/aca/04-aca-dapr-stateapi/index.md:41-75)
        import aiohttp
        api_sidecar = hosts[0].sidecar_port
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{api_sidecar}/v1.0/state/statestore/{task_id}"
            ) as r:
                assert r.status == 200
                doc = await r.json()
                assert doc["taskName"] == "demo"
            async with s.get(
                f"http://127.0.0.1:{api_sidecar}/v1.0/metadata"
            ) as r:
                meta = await r.json()
                assert meta["id"] == "backend-api"
                assert any(c["name"] == "statestore" for c in meta["components"])
    finally:
        for h in hosts:
            await h.stop()


@pytest.mark.asyncio
async def test_invoke_unknown_app_id_404(tmp_path):
    cluster = InProcCluster(specs_for(tmp_path))
    cluster.add_app(make_frontend_app())
    await cluster.start()
    try:
        resp = await cluster.client("frontend").invoke_method(
            "nonexistent-app", "api/tasks", http_method="GET")
    except TasksRunnerError as exc:
        assert "no app registered" in str(exc)
    else:
        assert resp.status == 404
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_trace_propagates_across_invoke_and_pubsub(tmp_path):
    """One logical operation carries one trace id across all three
    services (SURVEY.md §5.1 App-Map capability)."""
    seen_traces: dict[str, str] = {}
    specs = specs_for(tmp_path)

    api = App("backend-api")

    @api.post("/api/tasks")
    async def create(req):
        seen_traces["api"] = req.headers.get("traceparent", "")
        await api.client.publish_event("taskspubsub", "tasksavedtopic", req.json())
        return 201, {"taskId": "t"}

    processor_traces: list[str] = []
    processor = App("processor")

    @processor.subscribe(pubsub="taskspubsub", topic="tasksavedtopic",
                         route="/on-saved")
    async def on_saved(req):
        processor_traces.append(req.headers.get("traceparent", ""))
        return 200

    frontend = make_frontend_app()

    cluster = InProcCluster(specs)
    for a in (api, frontend, processor):
        cluster.add_app(a)
    await cluster.start()
    try:
        root = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        resp = await cluster.client("frontend").invoke_method(
            "frontend", "tasks/create", http_method="POST",
            data={"taskName": "t"}, headers={"traceparent": root})
        # frontend route handler → invoke → api handler
        await wait_until(lambda: len(processor_traces) == 1)
        trace_id = "ab" * 16
        assert trace_id in seen_traces["api"]
        assert trace_id in processor_traces[0]
    finally:
        await cluster.stop()


async def test_route_precedence_is_first_registered_wins():
    """Dispatch order is strictly first-registered-wins: a literal
    route registered AFTER a parameterised or wildcard route that also
    matches must not shadow it via the O(1) exact-route table
    (regression for the fast-path dispatch optimisation)."""
    from tasksrunner.app import App

    app = App("prec")
    hits = []

    @app.route("/items/{item_id}", methods="GET")
    async def param_first(req):
        hits.append(("param", req.path_params.get("item_id")))
        return 200, {"via": "param"}

    @app.get("/items/special")
    async def literal_later(req):
        hits.append(("literal", None))
        return 200, {"via": "literal"}

    resp = await app.handle("GET", "/items/special")
    assert resp.encode()[0] == 200
    assert hits == [("param", "special")]

    # the reverse order: literal first, param later — literal wins and
    # still uses the O(1) table
    app2 = App("prec2")

    @app2.get("/items/special")
    async def literal_first(req):
        return 200, {"via": "literal"}

    @app2.route("/items/{item_id}", methods="GET")
    async def param_later(req):
        return 200, {"via": "param"}

    resp2 = await app2.handle("GET", "/items/special")
    import json as _json
    assert _json.loads(resp2.encode()[2])["via"] == "literal"
    assert ("GET", "/items/special") in app2._exact_routes


@pytest.mark.asyncio
async def test_etag_cas_over_http_sidecar(tmp_path):
    """Regression: the HTTP transport must round-trip etags.

    aiohttp reports response headers with wire casing ("Etag"); the
    transport once looked up "etag" against a case-preserving dict, so
    every StateItem read over a real sidecar carried etag="" and every
    etag-guarded save (the sample's CAS loop, the markoverdue path)
    failed deterministically with EtagMismatch — while the in-proc
    direct transport worked, hiding the bug from in-proc tests.
    """
    from tasksrunner.client import AppClient
    from tasksrunner.errors import EtagMismatch

    specs = specs_for(tmp_path)
    host = AppHost(make_api_app(), specs=specs,
                   registry_file=str(tmp_path / "apps.json"))
    await host.start()
    client = None
    try:
        client = AppClient.http(port=host.sidecar_port)
        await client.save_state("statestore", "cas-key", {"n": 0})

        item = await client.get_state_item("statestore", "cas-key")
        assert item is not None
        assert item.etag, "HTTP transport dropped the etag header"

        # fresh etag → CAS succeeds
        await client.save_state("statestore", "cas-key", {"n": 1},
                                etag=item.etag)
        # stale etag → CAS refused
        with pytest.raises(EtagMismatch):
            await client.save_state("statestore", "cas-key", {"n": 2},
                                    etag=item.etag)
        assert await client.get_state("statestore", "cas-key") == {"n": 1}
    finally:
        if client is not None:
            await client.close()
        await host.stop()
