"""Latency-histogram distribution layer (the App Insights percentile
charts analog): bucket math, cross-replica merging, Prometheus
exposition, saturation gauges, and the slow-call exemplar → trace
round trip."""

import argparse
import asyncio
import re

import pytest

from tasksrunner.observability.metrics import (
    DEFAULT_BOUNDS,
    FOLD_AT,
    MetricsRegistry,
    estimate_percentile,
    merge_flat_snapshots,
    merge_histogram_snapshots,
    render_prometheus,
    summarize_histograms,
)


# -- histogram core -------------------------------------------------------

def test_observe_lands_in_the_right_bucket():
    reg = MetricsRegistry()
    # bounds are 1e-4 * 2^i: 3e-4 falls in (2e-4, 4e-4] = index 2
    reg.observe("state_op_latency_seconds", 3e-4, store="s", op="save")
    snap = reg.snapshot_histograms()["state_op_latency_seconds"]
    (series,) = snap["series"]
    assert series["labels"] == {"store": "s", "op": "save"}
    assert series["counts"][2] == 1
    assert sum(series["counts"]) == series["count"] == 1
    assert series["sum"] == pytest.approx(3e-4)


def test_overflow_goes_to_inf_bucket_and_percentile_clamps():
    reg = MetricsRegistry()
    reg.observe("invoke_latency_seconds", 1e6, target="api")
    snap = reg.snapshot_histograms()["invoke_latency_seconds"]
    (series,) = snap["series"]
    assert series["counts"][len(DEFAULT_BOUNDS)] == 1
    assert estimate_percentile(
        snap["bounds"], series["counts"], 0.99) == DEFAULT_BOUNDS[-1]


def test_pending_folds_at_threshold_without_a_snapshot():
    reg = MetricsRegistry()
    for _ in range(FOLD_AT):
        reg.observe("invoke_latency_seconds", 1e-3, target="api")
    hist = reg._histograms["invoke_latency_seconds"]
    (series,) = hist._series.values()
    # the FOLD_AT-th observation triggered the inline fold
    assert series.count == FOLD_AT
    assert not series.pending


def test_recorder_closure_observes_and_honours_live_toggle():
    reg = MetricsRegistry()
    rec = reg.recorder("delivery_latency_seconds", route="/on-saved")
    rec(2e-4)
    reg.histograms_enabled = False
    rec(2e-4)  # dropped
    reg.histograms_enabled = True
    rec(9e-4)
    snap = reg.snapshot_histograms()["delivery_latency_seconds"]
    (series,) = snap["series"]
    assert series["count"] == 2
    assert series["labels"] == {"route": "/on-saved"}


def test_unused_recorder_series_is_hidden_from_snapshots():
    reg = MetricsRegistry()
    reg.recorder("sidecar_request_latency_seconds", route="healthz")
    snap = reg.snapshot_histograms()["sidecar_request_latency_seconds"]
    assert snap["series"] == []


def test_observe_many_counts_every_value():
    reg = MetricsRegistry()
    reg.observe_many("state_queue_wait_seconds",
                     [1e-4, 2e-4, 5e-2, 1e6], store="s")
    snap = reg.snapshot_histograms()["state_queue_wait_seconds"]
    (series,) = snap["series"]
    assert series["count"] == 4
    assert sum(series["counts"]) == 4
    assert series["sum"] == pytest.approx(1e-4 + 2e-4 + 5e-2 + 1e6)


def test_disabled_histograms_are_a_noop():
    reg = MetricsRegistry()
    reg.histograms_enabled = False
    reg.observe("invoke_latency_seconds", 0.5, target="api")
    reg.observe_many("state_queue_wait_seconds", [0.1], store="s")
    assert reg.snapshot_histograms() == {}


def test_percentile_estimates_are_bucket_accurate():
    reg = MetricsRegistry()
    # 90 fast (≤ bucket of 1ms) + 10 slow (~0.1s): p50 must sit in the
    # fast bucket, p99 in the slow one
    reg.observe_many("invoke_latency_seconds", [1e-3] * 90, target="api")
    reg.observe_many("invoke_latency_seconds", [0.1] * 10, target="api")
    rows = summarize_histograms(reg.snapshot_histograms())
    (row,) = rows
    assert row["count"] == 100
    assert row["p50"] <= 2e-3
    assert 0.05 <= row["p99"] <= 0.2


# -- kind collisions ------------------------------------------------------

def test_metric_kind_collision_raises():
    reg = MetricsRegistry()
    reg.inc("publish", pubsub="p", topic="t")
    with pytest.raises(ValueError, match="already registered as counter"):
        reg.observe("publish", 0.1, pubsub="p", topic="t")
    with pytest.raises(ValueError, match="already registered as counter"):
        reg.set_gauge("publish", 1.0)


def test_uptime_kind_is_claimed_up_front():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="already registered as gauge"):
        reg.inc("uptime_seconds")


# -- merging across replicas ----------------------------------------------

def _replica_payload(reg: MetricsRegistry) -> dict:
    """The /v1.0/metadata shape the CLI and admin merge."""
    return {
        "metrics": reg.snapshot(),
        "histograms": reg.snapshot_histograms(),
        "metric_kinds": reg.snapshot_kinds(),
    }


def test_histogram_merge_adds_bucket_arrays_elementwise():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.observe_many("invoke_latency_seconds", [1e-3] * 3, target="api")
    b.observe_many("invoke_latency_seconds", [1e-3] * 5, target="api")
    b.observe("invoke_latency_seconds", 1e-3, target="other")
    merged = merge_histogram_snapshots(
        [a.snapshot_histograms(), b.snapshot_histograms()])
    series = {tuple(sorted(s["labels"].items())): s
              for s in merged["invoke_latency_seconds"]["series"]}
    assert series[(("target", "api"),)]["count"] == 8
    assert sum(series[(("target", "api"),)]["counts"]) == 8
    assert series[(("target", "other"),)]["count"] == 1


def test_flat_merge_sums_counters_and_maxes_gauges():
    merged = merge_flat_snapshots(
        [{"publish{topic=t}": 2, "uptime_seconds": 10.0},
         {"publish{topic=t}": 3, "uptime_seconds": 99.0}],
        kinds={"publish": "counter", "uptime_seconds": "gauge"},
    )
    assert merged["publish{topic=t}"] == 5
    assert merged["uptime_seconds"] == 99.0


def test_cli_percentiles_merges_across_two_replicas(monkeypatch, capsys):
    """`tasksrunner metrics --percentiles` must aggregate EVERY replica
    of the app, not whichever one the resolver round-robins to."""
    import tasksrunner.cli as cli

    a, b = MetricsRegistry(), MetricsRegistry()
    a.observe_many("invoke_latency_seconds", [1e-3] * 40, target="api")
    b.observe_many("invoke_latency_seconds", [1e-3] * 60, target="api")
    payloads = [_replica_payload(a), _replica_payload(b)]
    monkeypatch.setattr(cli, "_fetch_all_replica_metadata",
                        lambda args: payloads)
    args = argparse.Namespace(app_id="api", json=False, percentiles=True,
                              slow=None)
    cli._metrics_percentiles(args)
    out = capsys.readouterr().out
    assert "# merged across 2 replica(s)" in out
    row = next(line for line in out.splitlines()
               if line.startswith("invoke_latency_seconds{target=api}"))
    assert re.search(r"\s100\s", row), row  # 40 + 60 merged


# -- Prometheus exposition -------------------------------------------------

def test_render_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.inc("pubsub_delivery", route="/on-saved", status="200")
    reg.set_gauge("broker_dlq_depth", 2.0, topic="t", group="g")
    reg.observe("invoke_latency_seconds", 3e-4, target="api")
    text = render_prometheus(reg)

    assert "# TYPE pubsub_delivery counter" in text
    assert "# TYPE broker_dlq_depth gauge" in text
    assert "# TYPE invoke_latency_seconds histogram" in text
    assert '# HELP invoke_latency_seconds' in text
    assert 'pubsub_delivery{route="/on-saved",status="200"} 1' in text
    assert 'broker_dlq_depth{group="g",topic="t"} 2' in text
    # cumulative buckets: the 3e-4 observation is inside every le ≥ 4e-4
    assert re.search(
        r'invoke_latency_seconds_bucket\{target="api",le="0\.0004"\} 1', text)
    assert 'invoke_latency_seconds_bucket{target="api",le="+Inf"} 1' in text
    assert 'invoke_latency_seconds_count{target="api"} 1' in text
    assert 'invoke_latency_seconds_sum{target="api"} 0.0003' in text
    assert re.search(r'uptime_seconds \d', text)
    assert text.endswith("\n")
    # buckets are cumulative and monotone
    cums = [int(m.group(1)) for m in re.finditer(
        r'invoke_latency_seconds_bucket\{[^}]*\} (\d+)', text)]
    assert cums == sorted(cums) and cums[-1] == 1


@pytest.mark.asyncio
async def test_sidecar_metrics_route_serves_prometheus_text(tmp_path):
    """GET /metrics on a live sidecar returns the exposition including
    histogram buckets (the acceptance scrape)."""
    import aiohttp

    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.observability.metrics import metrics
    from tasksrunner.runtime import Runtime
    from tasksrunner.sidecar import Sidecar

    class NullChannel:
        async def request(self, method, path, *, query="", headers=None,
                          body=b""):
            return 200, {}, b"{}"

        async def close(self):
            pass

    runtime = Runtime("metrics-app", ComponentRegistry([]),
                      app_channel=NullChannel())
    # exercise a real instrumented client path so the scrape has data
    runtime.peers["peer"] = NullChannel()
    await runtime.invoke("peer", "api/tasks", body=b"{}")
    sidecar = Sidecar(runtime, port=0)
    await sidecar.start()
    try:
        async with aiohttp.ClientSession() as session:
            resp = await session.get(
                f"http://127.0.0.1:{sidecar.port}/metrics")
            body = await resp.text()
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        assert re.search(
            r'invoke_latency_seconds_bucket\{target="peer",le="\+Inf"\} \d',
            body)
        assert "# TYPE invoke_latency_seconds histogram" in body
        # the uninstrumented scrape itself registered nothing weird
        assert metrics.snapshot_kinds()["invoke_latency_seconds"] == "histogram"
    finally:
        await sidecar.stop()


# -- saturation gauges -----------------------------------------------------

@pytest.mark.asyncio
async def test_event_loop_lag_probe_sets_gauge():
    from tasksrunner.observability.probes import EventLoopLagProbe

    reg = MetricsRegistry()
    probe = EventLoopLagProbe(interval=0.02, registry=reg)
    probe.start()
    await asyncio.sleep(0.08)
    await probe.stop()
    snap = reg.snapshot()
    assert "event_loop_lag_seconds" in snap
    assert snap["event_loop_lag_seconds"] >= 0.0


@pytest.mark.asyncio
async def test_state_write_queue_metrics_flow(tmp_path):
    """The group-commit store reports queue depth and the queue-wait /
    commit latency split."""
    from tasksrunner.observability.metrics import metrics
    from tasksrunner.state.sqlite import SqliteStateStore

    store = SqliteStateStore("qstore", tmp_path / "s.db")
    try:
        await asyncio.gather(*(store.set(f"k{i}", {"v": i})
                               for i in range(16)))
    finally:
        store.close()
    hists = metrics.snapshot_histograms()
    waits = [s for s in hists["state_queue_wait_seconds"]["series"]
             if s["labels"] == {"store": "qstore"}]
    commits = [s for s in hists["state_commit_seconds"]["series"]
               if s["labels"] == {"store": "qstore"}]
    assert waits and waits[0]["count"] >= 16
    assert commits and commits[0]["count"] >= 1
    assert "state_write_queue_depth{store=qstore}" in metrics.snapshot()


# -- exemplars → traces ----------------------------------------------------

def test_slow_observation_captures_trace_exemplar(monkeypatch):
    from tasksrunner.observability import tracing
    from tasksrunner.observability.tracing import TraceContext, trace_scope

    reg = MetricsRegistry()
    reg.slow_threshold = 0.05
    ctx = TraceContext.new()
    with trace_scope(ctx):
        reg.observe("invoke_latency_seconds", 0.2, target="api")
    # outside any trace: no exemplar (clear any context an earlier test
    # set without a scope)
    tracing._current.set(None)
    reg.observe("invoke_latency_seconds", 0.2, target="api")
    (series,) = reg.snapshot_histograms()["invoke_latency_seconds"]["series"]
    assert len(series["exemplars"]) == 1
    trace_id, value, when = series["exemplars"][0]
    assert trace_id == ctx.trace_id
    assert value == pytest.approx(0.2)
    assert series["count"] == 2  # slow observations still count in buckets


def test_exemplar_ring_keeps_newest(monkeypatch):
    from tasksrunner.observability.metrics import MAX_EXEMPLARS
    from tasksrunner.observability.tracing import TraceContext, trace_scope

    reg = MetricsRegistry()
    reg.slow_threshold = 0.0
    ids = []
    for _ in range(MAX_EXEMPLARS + 3):
        ctx = TraceContext.new()
        ids.append(ctx.trace_id)
        with trace_scope(ctx):
            reg.observe("invoke_latency_seconds", 0.1, target="api")
    (series,) = reg.snapshot_histograms()["invoke_latency_seconds"]["series"]
    kept = [e[0] for e in series["exemplars"]]
    assert kept == ids[-MAX_EXEMPLARS:]


@pytest.mark.asyncio
async def test_slow_invoke_exemplar_resolves_to_recorded_trace(
        tmp_path, monkeypatch, capsys):
    """The drill-down loop: a slow call inside a traced request leaves
    an exemplar whose trace id `metrics --slow` prints and the span
    store can resolve — percentile tail to full trace tree, no log
    spelunking."""
    import tasksrunner.cli as cli
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.observability import spans as spans_mod
    from tasksrunner.observability.metrics import metrics
    from tasksrunner.observability.tracing import TraceContext, trace_scope
    from tasksrunner.runtime import Runtime

    db = tmp_path / "traces.db"
    rec = spans_mod.configure_spans("api", db)
    monkeypatch.setattr(metrics, "slow_threshold", 0.01)

    class SlowChannel:
        async def request(self, method, path, *, query="", headers=None,
                          body=b""):
            await asyncio.sleep(0.03)
            return 200, {}, b"{}"

        async def close(self):
            pass

    runtime = Runtime("api", ComponentRegistry([]))
    runtime.peers["backend"] = SlowChannel()
    ctx = TraceContext.new()
    try:
        with trace_scope(ctx):
            status, _, _ = await runtime.invoke("backend", "api/tasks",
                                                body=b"{}")
        assert status == 200
    finally:
        await runtime.stop()
        rec.flush()
        rec.close()
        spans_mod._recorder = None

    # the exemplar carries the request's trace id
    series = [
        s for s in metrics.snapshot_histograms()
        ["invoke_latency_seconds"]["series"]
        if s["labels"] == {"target": "backend"}]
    exemplars = [e for s in series for e in s["exemplars"]]
    assert any(e[0] == ctx.trace_id for e in exemplars)

    # `tasksrunner metrics --slow` surfaces it with the drill-down hint
    payloads = [{"metrics": metrics.snapshot(),
                 "histograms": metrics.snapshot_histograms(),
                 "metric_kinds": metrics.snapshot_kinds()}]
    monkeypatch.setattr(cli, "_fetch_all_replica_metadata",
                        lambda args: payloads)
    cli._metrics_slow(argparse.Namespace(
        app_id="api", json=False, slow="invoke_latency"))
    out = capsys.readouterr().out
    assert f"trace {ctx.trace_id}" in out
    assert "tasksrunner traces show" in out

    # and the span store resolves that trace id to the recorded span
    spans = spans_mod.trace_spans(str(db), ctx.trace_id)
    assert any(s["name"] == "invoke backend/api/tasks" for s in spans)


# -- CLI ergonomics --------------------------------------------------------

def test_traces_cli_missing_db_exits_2(tmp_path, capsys):
    from tasksrunner.cli import _cmd_traces

    args = argparse.Namespace(action="list", db=str(tmp_path / "absent.db"),
                              trace_id=None, limit=5, mermaid=False)
    with pytest.raises(SystemExit) as exc:
        _cmd_traces(args)
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "no trace database" in err
    assert ".tasksrunner/traces.db" in err


def test_metric_name_lint_passes_on_the_tree():
    import subprocess
    import sys
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [sys.executable, str(repo / "scripts" / "check_metrics.py"),
         "--no-cache"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    # the script is now an alias for the tasklint metric-names rule
    assert "tasklint OK" in proc.stdout
