"""Continuous-batching engine + serving-plane wiring
(tasksrunner/ml/batching.py, service.py, and the Retry-After nack lane
through the brokers).

Covers the scheduling contract the bench relies on: flush on size OR
the oldest request's deadline, padding buckets that jit-compile exactly
once, per-request error isolation, queue-full shedding, the warmup
backoff (503+Retry-After → broker redelivery that doesn't burn the
attempt budget), the admission-controller signal hookup, and a burst
through the real service over sidecar invoke.
"""

import asyncio
import time

import numpy as np
import pytest

from tasksrunner.errors import SaturatedError
from tasksrunner.ml.batching import (
    BatcherConfig, DEFAULT_BUCKETS, MicroBatcher, parse_buckets,
)
from tasksrunner.observability.metrics import MetricsRegistry


def echo_batch(items, bucket):
    return list(items)


async def start_batcher(run_batch, **cfg):
    mb = MicroBatcher(run_batch, config=BatcherConfig(**cfg),
                      registry=MetricsRegistry())
    mb.start()
    return mb


# -- config parsing ------------------------------------------------------

def test_parse_buckets_sorts_dedups_and_survives_garbage():
    assert parse_buckets("8, 2,2, 4") == (2, 4, 8)
    assert parse_buckets("") == DEFAULT_BUCKETS
    assert parse_buckets("zero,-3") == DEFAULT_BUCKETS


def test_config_clamps_max_batch_to_top_bucket():
    cfg = BatcherConfig(max_batch=64, buckets=(1, 4, 2))
    assert cfg.buckets == (1, 2, 4)
    assert cfg.max_batch == 4
    serial = cfg.serial()
    assert serial.max_batch == 1 and serial.buckets == (1,)


def test_bucket_for_picks_smallest_fit():
    mb = MicroBatcher(echo_batch, config=BatcherConfig())
    assert [mb.bucket_for(n) for n in (1, 2, 3, 5, 9, 17, 32)] == \
        [1, 2, 4, 8, 16, 32, 32]


# -- flush discipline ----------------------------------------------------

@pytest.mark.asyncio
async def test_size_flush_does_not_wait_for_the_deadline():
    """A full batch goes to the device immediately even when the
    latency budget is far away."""
    mb = await start_batcher(echo_batch, max_batch=4, max_delay_ms=10_000)
    t0 = time.monotonic()
    results = await asyncio.wait_for(
        asyncio.gather(*(mb.submit(i) for i in range(4))), timeout=2.0)
    assert results == [0, 1, 2, 3]
    assert time.monotonic() - t0 < 2.0  # nowhere near the 10s budget
    assert mb.stats()["batches"] == {"4": 1}
    await mb.stop()


@pytest.mark.asyncio
async def test_deadline_flushes_a_partial_batch():
    """Short of max_batch, the batch leaves when the OLDEST request
    has waited max_delay_ms."""
    mb = await start_batcher(echo_batch, max_batch=32, max_delay_ms=50)
    t0 = time.monotonic()
    results = await asyncio.gather(*(mb.submit(i) for i in range(3)))
    waited = time.monotonic() - t0
    assert results == [0, 1, 2]
    assert 0.04 <= waited < 1.0  # the deadline, not the 32-size flush
    # 3 items pad up to the 4-bucket
    assert mb.stats()["batches"] == {"4": 1}
    await mb.stop()


@pytest.mark.asyncio
async def test_arrivals_during_execution_ride_the_next_batch():
    """The continuous part: whatever queued while a batch held the
    device is drained into the next batch without a fresh wait."""
    release = asyncio.Event()

    def slow_batch(items, bucket):
        if items[0] == 0:  # only the first batch blocks
            while not release.is_set():
                time.sleep(0.005)
        return list(items)

    mb = await start_batcher(slow_batch, max_batch=8, max_delay_ms=5)
    first = asyncio.ensure_future(mb.submit(0))
    await asyncio.sleep(0.05)  # batch 1 (just item 0) is on the device
    rest = [asyncio.ensure_future(mb.submit(i)) for i in range(1, 7)]
    await asyncio.sleep(0.05)  # they all queue behind the running batch
    release.set()
    assert await asyncio.wait_for(first, 2.0) == 0
    assert await asyncio.wait_for(asyncio.gather(*rest), 2.0) == \
        list(range(1, 7))
    stats = mb.stats()
    assert stats["batches"]["1"] == 1      # the blocker ran alone
    assert stats["batches"]["8"] == 1      # the six backlogged → one batch
    await mb.stop()


# -- padding buckets + jit cache ----------------------------------------

@pytest.mark.asyncio
async def test_buckets_jit_compile_once():
    """Every executed batch pads to a ladder shape, so the jit cache
    holds exactly one entry per bucket touched — zero recompiles on
    repeat traffic."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: x * 2.0)

    def run_batch(items, bucket):
        padded = np.zeros((bucket, 4), np.float32)
        for i, item in enumerate(items):
            padded[i] = item
        out = np.asarray(fn(jnp.asarray(padded)))
        return [out[i] for i in range(len(items))]

    mb = await start_batcher(run_batch, max_batch=8, max_delay_ms=5)
    for size in (1, 3, 3, 7, 2, 1):
        await asyncio.gather(*(mb.submit(np.full(4, i, np.float32))
                               for i in range(size)))
    touched = set(mb.stats()["batches"])
    assert touched <= {"1", "2", "4", "8"}
    assert fn._cache_size() == len(touched)
    before = fn._cache_size()
    for size in (3, 7, 1):  # repeat traffic: no new shapes
        await asyncio.gather(*(mb.submit(np.full(4, i, np.float32))
                               for i in range(size)))
    assert fn._cache_size() == before
    await mb.stop()


# -- error isolation -----------------------------------------------------

@pytest.mark.asyncio
async def test_bad_request_fails_alone():
    """run_batch may return an Exception per item; only that caller
    sees it, batchmates get their results."""

    def picky(items, bucket):
        return [ValueError(f"bad {i}") if i == "poison" else i
                for i in items]

    mb = await start_batcher(picky, max_batch=4, max_delay_ms=10_000)
    futures = [asyncio.ensure_future(mb.submit(x))
               for x in ("a", "poison", "c", "d")]
    done = await asyncio.gather(*futures, return_exceptions=True)
    assert done[0] == "a" and done[2] == "c" and done[3] == "d"
    assert isinstance(done[1], ValueError)
    await mb.stop()


@pytest.mark.asyncio
async def test_batch_crash_fails_only_that_batch():
    """run_batch raising fails the in-flight batch; the engine keeps
    serving the next one."""
    crash = {"armed": True}

    def flaky(items, bucket):
        if crash["armed"]:
            crash["armed"] = False
            raise RuntimeError("device fell over")
        return list(items)

    mb = await start_batcher(flaky, max_batch=2, max_delay_ms=10_000)
    first = await asyncio.gather(mb.submit(1), mb.submit(2),
                                 return_exceptions=True)
    assert all(isinstance(r, RuntimeError) for r in first)
    assert await asyncio.gather(mb.submit(3), mb.submit(4)) == [3, 4]
    await mb.stop()


# -- shedding + saturation ----------------------------------------------

@pytest.mark.asyncio
async def test_queue_full_sheds_with_retry_after():
    release = asyncio.Event()

    def gated(items, bucket):
        while not release.is_set():
            time.sleep(0.005)
        return list(items)

    mb = await start_batcher(gated, max_batch=1, max_delay_ms=0,
                             buckets=(1,), max_queue=2)
    first = asyncio.ensure_future(mb.submit("runs"))
    await asyncio.sleep(0.05)  # item 1 on the device; queue empty again
    queued = [asyncio.ensure_future(mb.submit(f"q{i}")) for i in range(2)]
    await asyncio.sleep(0)     # both enqueued: the queue is now full
    with pytest.raises(SaturatedError) as exc:
        await mb.submit("overflow")
    assert exc.value.retry_after >= 1
    assert mb.saturation() >= 1.0
    release.set()
    assert await asyncio.wait_for(first, 2.0) == "runs"
    assert await asyncio.wait_for(asyncio.gather(*queued), 2.0) == \
        ["q0", "q1"]
    assert mb.stats()["shed"] == 1
    await mb.stop()


@pytest.mark.asyncio
async def test_saturation_signal_reaches_the_admission_controller():
    """register_signal folds the batcher's worst ratio into the
    replica's saturation score — a token flood sheds at the front
    door, and unregister detaches it."""
    from tasksrunner.observability import admission
    from tasksrunner.observability.metrics import MetricsRegistry as Reg

    gate = admission.AdmissionController(registry=Reg())
    mb = MicroBatcher(echo_batch,
                      config=BatcherConfig(max_queue=4, max_tokens=100),
                      registry=MetricsRegistry())
    admission.register_signal("test_ml_tokens", mb.saturation)
    try:
        assert gate.sample() < 1.0
        mb._tokens_in_flight = 250   # 2.5x the token ceiling
        assert gate.sample() >= 1.0 and gate.shedding
    finally:
        admission.unregister_signal("test_ml_tokens")
    mb._tokens_in_flight = 250
    gate2 = admission.AdmissionController(registry=Reg())
    assert gate2.sample() < 1.0  # detached: the flood is invisible


# -- Retry-After nack lane (warmup backoff) ------------------------------

def test_nack_is_falsy_and_carries_the_hint():
    from tasksrunner.pubsub.base import Nack, retry_after_from_headers

    nack = Nack(2.5, counts_attempt=False)
    assert not nack and nack.retry_after == 2.5 and not nack.counts_attempt
    assert retry_after_from_headers({"Retry-After": "3"}) == 3.0
    assert retry_after_from_headers({"retry-after": "0"}) == 0.0
    assert retry_after_from_headers({"Retry-After": "soon"}) is None
    assert retry_after_from_headers({}) is None


@pytest.mark.asyncio
@pytest.mark.parametrize("kind", ["memory", "sqlite"])
async def test_backoff_nack_does_not_burn_the_attempt_budget(kind, tmp_path):
    """A Nack(counts_attempt=False) — the runtime's translation of
    503/429+Retry-After — redelivers MORE times than max_attempts
    without ever dead-lettering, and the attempt counter stays at 1
    the whole time (warmup is not a failure)."""
    from tasksrunner.pubsub import InMemoryBroker, SqliteBroker
    from tasksrunner.pubsub.base import Nack

    if kind == "memory":
        broker = InMemoryBroker("b", max_attempts=2, retry_delay=0.01)
    else:
        broker = SqliteBroker("b", tmp_path / "broker.db", max_attempts=2,
                              retry_delay=0.01, poll_interval=0.01)
    attempts = []

    async def warming(msg):
        attempts.append(msg.attempt)
        if len(attempts) <= 4:  # twice the attempt budget
            return Nack(retry_after=0.01, counts_attempt=False)
        return True

    await broker.subscribe("t", "g", warming)
    await broker.publish("t", {"x": 1})
    deadline = asyncio.get_running_loop().time() + 5
    while len(attempts) < 5:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.01)
    assert attempts == [1, 1, 1, 1, 1]  # the budget never moved
    await broker.aclose()


@pytest.mark.asyncio
async def test_runtime_turns_retry_after_responses_into_backoff(tmp_path):
    """End to end through the runtime: a subscription handler answering
    503+Retry-After (the serving app's warmup answer) gets the message
    back after the hinted delay, with no attempt burned — more 503
    rounds than maxRetries and it still completes instead of
    dead-lettering."""
    from tasksrunner import App, InProcCluster
    from tasksrunner.app import Response
    from tasksrunner.component.spec import parse_component

    specs = [parse_component(
        {"componentType": "pubsub.in-memory",
         "metadata": [{"name": "maxRetries", "value": "2"},
                      {"name": "retryDelaySeconds", "value": "0.01"}]},
        default_name="bus")]
    app = App("warming-worker")
    calls = []

    @app.subscribe(pubsub="bus", topic="jobs", route="/job")
    async def job(req):
        calls.append(req.data["n"])
        if len(calls) <= 4:  # twice the attempt budget
            return Response(503, {"error": "model loading"},
                            headers={"Retry-After": "0.01"})
        return 200

    cluster = InProcCluster(specs)
    cluster.add_app(app)
    cluster.add_app(App("sender"))
    await cluster.start()
    try:
        await cluster.client("sender").publish_event("bus", "jobs", {"n": 7})
        deadline = asyncio.get_running_loop().time() + 5
        while len(calls) < 5:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.01)
        assert calls == [7, 7, 7, 7, 7]
    finally:
        await cluster.stop()


# -- the real service under burst ---------------------------------------

@pytest.mark.asyncio
async def test_score_burst_over_the_sidecar(monkeypatch):
    """A concurrent /score burst through the real service on the
    runtime: every response matches its request's taskId, batches
    bigger than one actually formed, and the jit cache is exactly one
    entry per warmed bucket before AND after the burst."""
    from tasksrunner import App, InProcCluster
    from tasksrunner.component.spec import parse_component
    from tasksrunner.ml.service import PRIORITY_LABELS, make_app

    monkeypatch.setenv("TASKSRUNNER_ML_BUCKETS", "1,2,4,8")
    monkeypatch.setenv("TASKSRUNNER_ML_MAX_BATCH", "8")
    specs = [
        parse_component({"componentType": "state.in-memory"},
                        default_name="scores"),
        parse_component({"componentType": "pubsub.in-memory"},
                        default_name="taskspubsub"),
    ]
    cluster = InProcCluster(specs)
    cluster.add_app(make_app())
    cluster.add_app(App("burst-driver"))
    await cluster.start()
    try:
        client = cluster.client("burst-driver")
        stats = (await client.invoke_method(
            "priority-scorer", "ml/stats", http_method="GET")).json()
        assert stats["ready"]
        assert stats["jit_cache_size"] == 4  # one per bucket, warmed

        async def one(i: int):
            resp = await client.invoke_method(
                "priority-scorer", "score",
                data={"taskId": f"burst-{i}",
                      "taskName": f"task number {i} " + "pad " * (i % 5)})
            assert resp.status == 200
            doc = resp.json()
            assert doc["taskId"] == f"burst-{i}"
            assert doc["priority"] in PRIORITY_LABELS
            assert 0.0 < doc["confidence"] <= 1.0

        await asyncio.gather(*(one(i) for i in range(48)))
        stats = (await client.invoke_method(
            "priority-scorer", "ml/stats", http_method="GET")).json()
        assert stats["jit_cache_size"] == 4  # burst compiled nothing
        assert stats["submitted"] == 48 and stats["completed"] == 48
        # concurrency actually batched: fewer executions than requests
        assert sum(stats["batches"].values()) < 48
        assert any(int(b) > 1 for b in stats["batches"])
    finally:
        await cluster.stop()
