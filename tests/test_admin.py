"""Orchestrator control-plane tests: the `az containerapp` verbs the
workshop operates with (SURVEY.md §2.6 / docs modules 2, 8, 9) mapped
to the admin API — status, rolling restart, env update as a new
revision, live scale bounds, log tail, revision history.
"""

import asyncio
import json
import os
import pathlib
import textwrap
import urllib.error
import urllib.request

import pytest

from tasksrunner.orchestrator.config import AppSpec, RunConfig, ScaleSpec

REPO = pathlib.Path(__file__).resolve().parent.parent


def _write_env_echo_app(tmp_path):
    pkg = tmp_path / "envpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "echo.py").write_text(textwrap.dedent("""
        import os
        from tasksrunner import App

        def make_app():
            app = App("echo")

            @app.get("/greeting")
            async def greeting(req):
                return {"greeting": os.environ.get("GREETING", "unset"),
                        "pid": os.getpid()}

            return app
    """))


async def _admin(url, method="GET", body=None):
    def call():
        req = urllib.request.Request(
            url, method=method,
            headers={"content-type": "application/json"},
            data=json.dumps(body).encode() if body is not None else None)
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    return await asyncio.get_running_loop().run_in_executor(None, call)


async def _app_get(port, path):
    def call():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
            return json.loads(resp.read())
    return await asyncio.get_running_loop().run_in_executor(None, call)


@pytest.mark.asyncio
async def test_admin_api_full_lifecycle(tmp_path, monkeypatch):
    from tasksrunner.orchestrator.admin import info_path
    from tasksrunner.orchestrator.run import Orchestrator

    _write_env_echo_app(tmp_path)
    config = RunConfig(
        apps=[AppSpec(app_id="echo", module="envpkg.echo:make_app",
                      env={"GREETING": "hello"},
                      scale=ScaleSpec(min_replicas=1, max_replicas=3))],
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
    )
    # monkeypatch restores this after the test (a bare os.environ set
    # leaked into every later test in the session)
    monkeypatch.setenv("PYTHONPATH", f"{tmp_path}{os.pathsep}{REPO}")
    orch = Orchestrator(config)
    await orch.start()
    try:
        info_file = info_path(tmp_path / "apps.json")
        assert info_file.is_file(), "orchestrator.json must advertise the admin API"
        admin_url = json.loads(info_file.read_text())["admin_url"]

        replica = orch.replicas["echo"][0]
        await asyncio.wait_for(replica.ready.wait(), timeout=30)
        app_port = replica.ports[0]

        # -- status (az containerapp replica list analog)
        status, out = await _admin(f"{admin_url}/admin/apps")
        assert status == 200
        (app,) = out["apps"]
        assert app["app_id"] == "echo"
        assert app["revision"] == 1
        assert app["replicas"][0]["running"] is True
        first_pid = app["replicas"][0]["pid"]

        # the app really runs with its configured env
        doc = await _app_get(app_port, "/greeting")
        assert doc == {"greeting": "hello", "pid": first_pid}

        # -- unknown app → 404 with the known set
        with pytest.raises(urllib.error.HTTPError):
            await _admin(f"{admin_url}/admin/apps/nope/restart", "POST")

        # -- manual restart: new pid, same config, new revision, and
        # -- NOT counted as a crash
        status, out = await _admin(f"{admin_url}/admin/apps/echo/restart", "POST")
        assert status == 200 and out["revision"]["revision"] == 2
        await asyncio.wait_for(replica.ready.wait(), timeout=30)
        doc = await _app_get(replica.ports[0], "/greeting")
        assert doc["pid"] != first_pid
        assert doc["greeting"] == "hello"
        assert replica.restarts == 0, "manual restart must not count as crash"

        # -- env update: new revision, replicas restarted into new env
        status, out = await _admin(
            f"{admin_url}/admin/apps/echo/env", "POST",
            {"set": {"GREETING": "bonjour"}, "remove": []})
        assert status == 200 and out["revision"]["revision"] == 3
        await asyncio.wait_for(replica.ready.wait(), timeout=30)
        doc = await _app_get(replica.ports[0], "/greeting")
        assert doc["greeting"] == "bonjour"

        # -- scale up the floor: replicas appear without restart
        status, out = await _admin(
            f"{admin_url}/admin/apps/echo/scale", "POST", {"min_replicas": 2})
        assert status == 200
        assert len(orch.replicas["echo"]) == 2
        # scale-to-zero refused (workshop rejects it: starves bindings)
        with pytest.raises(urllib.error.HTTPError):
            await _admin(f"{admin_url}/admin/apps/echo/scale", "POST",
                         {"min_replicas": 0})
        # min above the current max refused (invariant min <= max)
        with pytest.raises(urllib.error.HTTPError):
            await _admin(f"{admin_url}/admin/apps/echo/scale", "POST",
                         {"min_replicas": 9})

        # -- revision history reflects every change, newest active
        status, out = await _admin(f"{admin_url}/admin/apps/echo/revisions")
        reasons = [r["reason"] for r in out["revisions"]]
        assert reasons == ["initial deploy", "manual restart",
                           "env update", "scale update"]
        actives = [r for r in out["revisions"] if r["active"]]
        assert len(actives) == 1 and actives[0]["revision"] == 4

        # -- logs: every replica's recent lines, tail-limited
        second = orch.replicas["echo"][1]
        await asyncio.wait_for(second.ready.wait(), timeout=30)
        status, out = await _admin(
            f"{admin_url}/admin/apps/echo/logs?tail=50")
        assert status == 200
        lines = out["lines"]
        assert any("ready app=" in e["line"] for e in lines)
        assert {e["replica"] for e in lines} == {0, 1}
    finally:
        await orch.stop()
    assert not info_path(tmp_path / "apps.json").is_file(), \
        "orchestrator.json must be cleaned up on stop"


def test_admin_cli_parser_wiring():
    from tasksrunner.cli import build_parser

    parser = build_parser()
    args = parser.parse_args(["restart", "echo"])
    assert args.app_id == "echo"
    args = parser.parse_args(["logs", "echo", "--tail", "5", "--replica", "1"])
    assert (args.tail, args.replica) == (5, 1)
    args = parser.parse_args(["scale", "echo", "--min-replicas", "2"])
    assert args.min_replicas == 2 and args.max_replicas is None
    args = parser.parse_args(
        ["update", "echo", "--set-env", "A=1", "--remove-env", "B"])
    assert args.set_env == ["A=1"] and args.remove_env == ["B"]
    args = parser.parse_args(["revisions", "echo"])
    assert args.fn is not None
    args = parser.parse_args(
        ["publish", "ps", "topic", "--app-id", "a", "--count", "50"])
    assert args.count == 50


def test_shards_cli_parser_wiring():
    from tasksrunner.cli import build_parser

    parser = build_parser()
    args = parser.parse_args(["shards", "--json"])
    assert args.json is True and args.fn is not None
    args = parser.parse_args(["shards", "--registry-file", "x/apps.json"])
    assert args.registry_file == "x/apps.json"


@pytest.mark.asyncio
async def test_admin_placement_one_shot_sweep(tmp_path, monkeypatch):
    """`/admin/placement` with TASKSRUNNER_RESHARD off (the default):
    the endpoint runs one on-demand sweep — sidecar metadata from each
    replica, merged per store — so `tasksrunner shards` always
    answers. The sharded store's routing epoch, per-shard ranking, and
    (quiet) plan must come through end-to-end."""
    import textwrap as _tw

    from tasksrunner.orchestrator.admin import info_path
    from tasksrunner.orchestrator.run import Orchestrator

    _write_env_echo_app(tmp_path)
    components = tmp_path / "components"
    components.mkdir()
    (components / "statestore.yaml").write_text(_tw.dedent(f"""
        apiVersion: dapr.io/v1alpha1
        kind: Component
        metadata:
          name: statestore
        spec:
          type: state.sqlite
          version: v1
          metadata:
          - name: databasePath
            value: {tmp_path / "state.db"}
          - name: shards
            value: "2"
    """))
    config = RunConfig(
        apps=[AppSpec(app_id="echo", module="envpkg.echo:make_app")],
        registry_file=str(tmp_path / "apps.json"),
        base_dir=tmp_path,
        resources_path=str(components),
    )
    monkeypatch.setenv("PYTHONPATH", f"{tmp_path}{os.pathsep}{REPO}")
    orch = Orchestrator(config)
    await orch.start()
    try:
        replica = orch.replicas["echo"][0]
        await asyncio.wait_for(replica.ready.wait(), timeout=30)
        sidecar_port = orch._replica_info("echo")[0]["sidecar_port"]
        # writes build the store and feed the heat tracker, so the
        # metadata sweep has a placement document to merge
        status, _ = await _admin(
            f"http://127.0.0.1:{sidecar_port}/v1.0/state/statestore",
            "POST",
            [{"key": f"k{i}", "value": {"v": i}} for i in range(10)])
        assert status in (200, 204)

        admin_url = json.loads(
            info_path(tmp_path / "apps.json").read_text())["admin_url"]
        status, out = await _admin(f"{admin_url}/admin/placement")
        assert status == 200
        assert out["reshard"] is False
        entry = out["apps"]["echo"]["stores"]["statestore"]
        assert entry["epoch"] == 1 and entry["shards"] == 2
        assert entry["replicas_reporting"] == 1
        assert len(entry["ranking"]) == 2
        assert {row["shard"] for row in entry["ranking"]} == {0, 1}
        assert entry["plan"] is None, "10 writes must not look hot"
        assert entry["migration"] is None
    finally:
        await orch.stop()
