"""OpenAPI generation, sidecar API-token auth, frontend direct-HTTP
fallback — the remaining SURVEY.md §2 inventory items."""

import asyncio
import os

import pytest

from tasksrunner import App, AppHost
from tasksrunner.component.spec import parse_component


@pytest.mark.asyncio
async def test_openapi_document():
    from samples.tasks_tracker.backend_api import make_app

    app = make_app("fake")
    resp = await app.handle("GET", "/openapi.json")
    assert resp.status == 200
    doc = resp.body
    assert doc["openapi"] == "3.1.0"
    assert doc["info"]["title"] == "tasksmanager-backend-api"
    assert "get" in doc["paths"]["/api/tasks"]
    assert "post" in doc["paths"]["/api/tasks"]
    byid = doc["paths"]["/api/tasks/{task_id}"]
    assert {"get", "put", "delete"} <= set(byid)
    assert byid["get"]["parameters"][0]["name"] == "task_id"
    # overdue controller surface (OverdueTasksController.cs:7-33)
    assert "/api/overduetasks" in doc["paths"]
    assert "/api/overduetasks/markoverdue" in doc["paths"]


@pytest.mark.asyncio
async def test_sidecar_api_token(tmp_path, monkeypatch):
    import aiohttp

    monkeypatch.setenv("TASKSRUNNER_API_TOKEN", "sekrit")
    app = App("secured")

    @app.get("/ping")
    async def ping(req):
        return {"ok": True}

    host = AppHost(app, specs=[parse_component(
        {"componentType": "state.in-memory"}, default_name="statestore")],
        registry_file=str(tmp_path / "apps.json"))
    await host.start()
    try:
        base = f"http://127.0.0.1:{host.sidecar_port}"
        async with aiohttp.ClientSession() as s:
            # no token -> 401
            async with s.get(f"{base}/v1.0/state/statestore/k") as r:
                assert r.status == 401
            # wrong token -> 401
            async with s.get(f"{base}/v1.0/state/statestore/k",
                             headers={"tr-api-token": "nope"}) as r:
                assert r.status == 401
            # right token -> through
            async with s.get(f"{base}/v1.0/state/statestore/k",
                             headers={"tr-api-token": "sekrit"}) as r:
                assert r.status == 204
            # healthz stays open for probes
            async with s.get(f"{base}/v1.0/healthz") as r:
                assert r.status == 204
            # metadata (component inventory, metrics) is token-gated too
            async with s.get(f"{base}/v1.0/metadata") as r:
                assert r.status == 401
            async with s.get(f"{base}/v1.0/metadata",
                             headers={"tr-api-token": "sekrit"}) as r:
                assert r.status == 200
        # the app's own client carries the token from env automatically
        result = await host.client.invoke_json("secured", "ping")
        assert result == {"ok": True}
    finally:
        await host.stop()
        monkeypatch.delenv("TASKSRUNNER_API_TOKEN")


@pytest.mark.asyncio
async def test_frontend_direct_http_fallback(tmp_path, monkeypatch):
    """≙ the reference frontend's BackendApiConfig:BaseUrlExternalHttp
    named-HttpClient path (Frontend Program.cs:15-27)."""
    from samples.tasks_tracker.backend_api import make_app as make_api
    from samples.tasks_tracker.frontend_ui import make_app as make_frontend

    registry_file = str(tmp_path / "apps.json")
    api_host = AppHost(make_api("fake"), registry_file=registry_file)
    frontend_host = AppHost(make_frontend(), registry_file=registry_file)
    await api_host.start()
    await frontend_host.start()
    try:
        monkeypatch.setenv("BACKENDAPICONFIG__BASEURLEXTERNALHTTP",
                           f"http://127.0.0.1:{api_host.app_port}")
        resp = await frontend_host.app.handle(
            "GET", "/tasks",
            headers={"cookie": "TasksCreatedByCookie=tempuser@mail.com"})
        assert resp.status == 200
        assert "Task number:" in resp.body  # seeded fake tasks rendered
    finally:
        monkeypatch.delenv("BACKENDAPICONFIG__BASEURLEXTERNALHTTP")
        await frontend_host.stop()
        await api_host.stop()


@pytest.mark.asyncio
async def test_ps_command(tmp_path):
    """`tasksrunner ps` reports live apps from the registry (health,
    ports, component counts) and flags dead registrations."""
    import asyncio as aio
    import json
    import sys

    registry = str(tmp_path / "apps.json")
    app = App("psapp")

    @app.get("/ping")
    async def ping(req):
        return {}

    host = AppHost(app, specs=[parse_component(
        {"componentType": "state.in-memory"}, default_name="statestore")],
        registry_file=registry)
    await host.start()
    try:
        proc = await aio.create_subprocess_exec(
            sys.executable, "-m", "tasksrunner", "ps",
            "--registry-file", registry, "--json",
            stdout=aio.subprocess.PIPE, stderr=aio.subprocess.PIPE)
        out, err = await proc.communicate()
        assert proc.returncode == 0, err.decode()
        rows = json.loads(out)
        assert len(rows) == 1
        row = rows[0]
        assert row["app_id"] == "psapp"
        assert row["health"] == "ok"
        assert row["components"] == 1
        assert row["sidecar_port"] == host.sidecar_port
    finally:
        await host.stop()

    # after the host is gone, re-register a dead address: ps exits 2
    from tasksrunner import AppAddress, NameResolver
    NameResolver(registry_file=registry).register(AppAddress(
        app_id="psapp", host="127.0.0.1",
        sidecar_port=host.sidecar_port, app_port=host.app_port))
    proc = await aio.create_subprocess_exec(
        sys.executable, "-m", "tasksrunner", "ps",
        "--registry-file", registry, "--json",
        stdout=aio.subprocess.PIPE, stderr=aio.subprocess.PIPE)
    out, _ = await proc.communicate()
    assert proc.returncode == 2
    assert json.loads(out)[0]["health"] == "down"


async def test_static_file_serving(tmp_path):
    """App.static (≙ UseStaticFiles over wwwroot): content-type by
    extension, 404 for missing files, traversal attempts blocked."""
    from tasksrunner import App

    (tmp_path / "site.css").write_text("body { color: red; }")
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "x.js").write_text("var a=1;")
    secret = tmp_path.parent / "secret.txt"
    secret.write_text("do not serve")

    app = App("static-test")
    app.static("/static", tmp_path)

    resp = await app.handle("GET", "/static/site.css")
    status, headers, body = resp.encode()
    assert status == 200
    assert headers["content-type"] == "text/css"
    assert b"color: red" in body

    resp = await app.handle("GET", "/static/sub/x.js")
    assert resp.status == 200

    resp = await app.handle("GET", "/static/missing.css")
    assert resp.status == 404

    resp = await app.handle("GET", "/static/../secret.txt")
    assert resp.status == 404

    # non-GET methods never reach the mount
    resp = await app.handle("POST", "/static/site.css")
    assert resp.status == 404

    # a miss falls through to routing (UseStaticFiles semantics):
    # routes under the mounted prefix stay reachable
    @app.get("/static/health")
    async def health_route(req):
        return {"ok": True}

    resp = await app.handle("GET", "/static/health")
    assert resp.status == 200 and resp.body == {"ok": True}

    # root mount works too
    root_app = App("root-static")
    root_app.static("/", tmp_path)
    resp = await root_app.handle("GET", "/site.css")
    assert resp.status == 200


async def test_frontend_serves_asset_tree():
    from samples.tasks_tracker.frontend_ui.app import make_app

    app = make_app()
    # the wwwroot tree (css/ + js/, ≙ the reference's wwwroot layout)
    for path in ("/static/css/site.css", "/static/js/site.js",
                 "/static/js/validation.js"):
        resp = await app.handle("GET", path)
        assert resp.status == 200, path
    resp = await app.handle("GET", "/")
    _, _, body = resp.encode()
    assert b'href="/static/css/site.css"' in body
    assert b'src="/static/js/validation.js"' in body


@pytest.mark.asyncio
async def test_port_in_use_raises_clean_error(tmp_path):
    """EADDRINUSE — the failure every attendee hits once — must
    surface as PortInUseError naming the port (the CLI maps it to one
    clean ERROR line), for both the app server and the sidecar bind."""
    import socket

    from tasksrunner import AppHost
    from tasksrunner.errors import PortInUseError

    squat = socket.socket()
    squat.bind(("127.0.0.1", 0))
    squat.listen()
    port = squat.getsockname()[1]
    try:
        app = App("clash")
        host = AppHost(app, specs=[], app_port=port,
                       registry_file=str(tmp_path / "apps.json"))
        with pytest.raises(PortInUseError, match=f"app port {port}"):
            await host.start()

        app2 = App("clash2")
        host2 = AppHost(app2, specs=[], sidecar_port=port,
                        registry_file=str(tmp_path / "apps.json"))
        try:
            with pytest.raises(PortInUseError, match=f"sidecar port {port}"):
                await host2.start()
        finally:
            # the app server bound before the sidecar failed — release it
            if host2._app_runner is not None:
                await host2._app_runner.cleanup()
    finally:
        squat.close()
