"""Admission control: saturation scoring, hysteresis, shed surface,
and the Retry-After contract through client + resiliency.

The overload drill (tests/test_overload_drill.py) proves the closed
loop end to end; this file pins the pieces in isolation.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from tasksrunner.app import App
from tasksrunner.errors import SaturatedError
from tasksrunner.observability.admission import AdmissionController
from tasksrunner.observability.metrics import MetricsRegistry, metrics
from tasksrunner.resiliency.policy import RetrySpec, TargetPolicy


# -- scoring + hysteresis ------------------------------------------------

def test_score_is_max_over_signals():
    reg = MetricsRegistry()
    reg.set_gauge("event_loop_lag_seconds", 0.05)
    reg.set_gauge("state_write_queue_depth", 256, store="s")
    box = {"inflight": 16}
    c = AdmissionController(
        max_lag_seconds=0.25, max_queue_depth=512, max_inflight=64,
        inflight=lambda: box["inflight"], registry=reg)
    # lag 0.2, queue 0.5, inflight 0.25 -> worst resource wins
    assert c.sample() == pytest.approx(0.5)
    assert not c.shedding


def test_queue_depth_uses_worst_series():
    reg = MetricsRegistry()
    reg.set_gauge("state_write_queue_depth", 10, store="s", shard="0")
    reg.set_gauge("broker_publish_queue_depth", 600, pubsub="bus")
    c = AdmissionController(
        max_lag_seconds=0, max_inflight=0, max_queue_depth=512, registry=reg)
    assert c.sample() > 1.0
    assert c.shedding


def test_zero_threshold_disables_signal():
    reg = MetricsRegistry()
    reg.set_gauge("event_loop_lag_seconds", 99.0)
    c = AdmissionController(
        max_lag_seconds=0, max_queue_depth=0, max_inflight=0, registry=reg)
    assert c.sample() == 0.0
    assert not c.shedding


def test_hysteresis_enter_at_one_exit_below_ratio():
    reg = MetricsRegistry()
    box = {"inflight": 0}
    c = AdmissionController(
        max_inflight=10, max_lag_seconds=0, max_queue_depth=0,
        inflight=lambda: box["inflight"], registry=reg)
    assert not c.shedding
    box["inflight"] = 10          # score 1.0: trip
    c.sample()
    assert c.shedding
    assert reg.get("admission_state") == 1.0
    box["inflight"] = 8           # 0.8 — inside the band: keep shedding
    c.sample()
    assert c.shedding, "exiting above exit_ratio would flap"
    box["inflight"] = 7           # 0.7 < 0.75: exit
    c.sample()
    assert not c.shedding
    assert reg.get("admission_state") == 0.0
    assert reg.get("admission_saturation") == pytest.approx(0.7)


def test_retry_after_tracks_score_with_clamps():
    reg = MetricsRegistry()
    c = AdmissionController(registry=reg)
    c.score = 0.0
    assert c.retry_after_seconds() == 1
    c.score = 3.2
    assert c.retry_after_seconds() == 4
    c.score = 1e6
    assert c.retry_after_seconds() == 30


def test_from_env_gate_and_thresholds(monkeypatch):
    monkeypatch.delenv("TASKSRUNNER_ADMISSION", raising=False)
    assert AdmissionController.from_env() is None
    monkeypatch.setenv("TASKSRUNNER_ADMISSION", "0")
    assert AdmissionController.from_env() is None
    monkeypatch.setenv("TASKSRUNNER_ADMISSION", "1")
    monkeypatch.setenv("TASKSRUNNER_ADMISSION_MAX_INFLIGHT", "7")
    monkeypatch.setenv("TASKSRUNNER_ADMISSION_MAX_LAG_SECONDS", "0.5")
    monkeypatch.setenv("TASKSRUNNER_ADMISSION_MAX_QUEUE_DEPTH", "100")
    c = AdmissionController.from_env(registry=MetricsRegistry())
    assert c is not None
    assert c.max_inflight == 7
    assert c.max_lag_seconds == 0.5
    assert c.max_queue_depth == 100


# -- shed surface: app server + sidecar ----------------------------------

@pytest.mark.asyncio
async def test_apphost_sheds_non_exempt_routes(tmp_path, monkeypatch):
    import aiohttp

    from tasksrunner.hosting import AppHost

    monkeypatch.setenv("TASKSRUNNER_ADMISSION", "1")
    app = App("admit-app")

    @app.post("/api/echo")
    async def echo(req):
        return {"ok": True}

    host = AppHost(app, specs=[], registry_file=str(tmp_path / "apps.json"))
    await host.start()
    try:
        assert host.admission is not None
        assert host.sidecar.admission is host.admission, \
            "app server and sidecar must shed on the same state"
        base_app = f"http://127.0.0.1:{host.app_port}"
        base_sc = f"http://127.0.0.1:{host.sidecar_port}"
        async with aiohttp.ClientSession() as s:
            # not saturated: everything flows
            async with s.post(f"{base_app}/api/echo", json={}) as r:
                assert r.status == 200

            host.admission.shedding = True
            host.admission.score = 3.0

            # app ingress shed with the Retry-After contract
            async with s.post(f"{base_app}/api/echo", json={}) as r:
                assert r.status == 429
                assert r.headers.get("Retry-After") == "3"
            # sidecar building-block route shed too
            async with s.get(f"{base_sc}/v1.0/state/foo/bar") as r:
                assert r.status == 429
                assert "Retry-After" in r.headers

            # exempt surfaces stay open while shedding: liveness,
            # scaler stats, sidecar health, the autoscaler's metadata
            # view, and the metrics scrape
            async with s.get(f"{base_app}/healthz") as r:
                assert r.status == 204
            async with s.get(f"{base_app}/tasksrunner/stats") as r:
                assert r.status == 200
            async with s.get(f"{base_sc}/v1.0/healthz") as r:
                assert r.status == 204
            async with s.get(f"{base_sc}/v1.0/metadata") as r:
                assert r.status == 200
            async with s.get(f"{base_sc}/metrics") as r:
                assert r.status == 200
                assert "admission_shed_total" in await r.text()
            assert metrics.get("admission_shed_total", route="app") >= 1

            # hysteresis exit: traffic flows again
            host.admission.shedding = False
            async with s.post(f"{base_app}/api/echo", json={}) as r:
                assert r.status == 200
    finally:
        await host.stop()


@pytest.mark.asyncio
async def test_apphost_gate_off_means_no_controller(tmp_path, monkeypatch):
    monkeypatch.delenv("TASKSRUNNER_ADMISSION", raising=False)
    from tasksrunner.hosting import AppHost

    app = App("no-admit-app")
    host = AppHost(app, specs=[], registry_file=str(tmp_path / "apps.json"))
    await host.start()
    try:
        assert host.admission is None
        assert host.sidecar.admission is None
    finally:
        await host.stop()


@pytest.mark.asyncio
async def test_sampler_task_trips_on_live_inflight(monkeypatch):
    """The controller's own loop (not a manual sample()) observes the
    in-flight callable and trips."""
    reg = MetricsRegistry()
    box = {"inflight": 0}
    c = AdmissionController(
        max_inflight=2, max_lag_seconds=0, max_queue_depth=0,
        inflight=lambda: box["inflight"], interval=0.02, registry=reg)
    c.start()
    try:
        box["inflight"] = 5
        deadline = time.monotonic() + 2
        while not c.shedding:
            assert time.monotonic() < deadline, "sampler never tripped"
            await asyncio.sleep(0.01)
        box["inflight"] = 0
        deadline = time.monotonic() + 2
        while c.shedding:
            assert time.monotonic() < deadline, "sampler never recovered"
            await asyncio.sleep(0.01)
    finally:
        await c.stop()


# -- Retry-After through the client and the retry loop -------------------

def test_client_maps_429_to_saturated_with_retry_after():
    from tasksrunner.client import _HTTPTransport

    with pytest.raises(SaturatedError) as ei:
        _HTTPTransport._raise(
            429, b'{"error": "replica saturated; retry later"}',
            context="save state s", headers={"retry-after": "7"})
    assert ei.value.http_status == 429
    assert ei.value.retry_after == 7.0


def test_client_attaches_retry_after_on_503_only_when_present():
    from tasksrunner.client import _HTTPTransport
    from tasksrunner.errors import TasksRunnerError

    with pytest.raises(TasksRunnerError) as ei:
        _HTTPTransport._raise(503, b"{}", context="publish p/t",
                              headers={"retry-after": "2.5"})
    assert ei.value.retry_after == 2.5
    with pytest.raises(TasksRunnerError) as ei:
        _HTTPTransport._raise(503, b"{}", context="publish p/t", headers={})
    assert getattr(ei.value, "retry_after", None) is None
    # a 400 never picks up the hint, even if a proxy added the header
    with pytest.raises(TasksRunnerError) as ei:
        _HTTPTransport._raise(400, b"{}", context="save state s",
                              headers={"retry-after": "9"})
    assert getattr(ei.value, "retry_after", None) is None


def test_invocation_response_carries_retry_after():
    from tasksrunner.client import InvocationResponse
    from tasksrunner.errors import InvocationStatusError

    resp = InvocationResponse(429, {"retry-after": "3"}, b"busy")
    with pytest.raises(InvocationStatusError) as ei:
        resp.raise_for_status()
    assert ei.value.status == 429
    assert ei.value.retry_after == 3.0


def test_retry_after_ignores_http_date_form():
    from tasksrunner.client import _retry_after_seconds

    assert _retry_after_seconds(
        {"retry-after": "Wed, 21 Oct 2026 07:28:00 GMT"}) is None
    assert _retry_after_seconds({"Retry-After": "4"}) == 4.0
    assert _retry_after_seconds({}) is None
    assert _retry_after_seconds(None) is None


@pytest.mark.asyncio
async def test_retry_loop_honors_retry_after_hint():
    policy = TargetPolicy(
        target="t", retry=RetrySpec(duration=0.001, max_retries=3))
    calls = []

    async def shed_then_ok():
        calls.append(time.monotonic())
        if len(calls) == 1:
            exc = SaturatedError("shed")
            exc.retry_after = 0.25
            raise exc
        return "ok"

    assert await policy.execute(
        shed_then_ok, retriable=(SaturatedError,)) == "ok"
    # the 0.001s schedule was stretched to honor the 0.25s hint
    assert calls[1] - calls[0] >= 0.25


@pytest.mark.asyncio
async def test_retry_after_hint_clamped_to_max_interval():
    policy = TargetPolicy(
        target="t",
        retry=RetrySpec(duration=0.001, max_retries=3, max_interval=0.05))
    calls = []

    async def shed_then_ok():
        calls.append(time.monotonic())
        if len(calls) == 1:
            exc = SaturatedError("shed")
            exc.retry_after = 30.0  # a pathological hint must not park us
            raise exc
        return "ok"

    t0 = time.monotonic()
    assert await policy.execute(
        shed_then_ok, retriable=(SaturatedError,)) == "ok"
    assert time.monotonic() - t0 < 5.0


@pytest.mark.asyncio
async def test_retry_after_hint_still_bounded_by_total_budget():
    policy = TargetPolicy(
        target="t", timeout=0.1, timeout_policy="total",
        retry=RetrySpec(duration=0.001, max_retries=10, max_interval=60))

    async def always_shed():
        exc = SaturatedError("shed")
        exc.retry_after = 30.0
        raise exc

    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="total budget"):
        await policy.execute(always_shed, retriable=(SaturatedError,))
    # surfaced immediately instead of sleeping 30s through the budget
    assert time.monotonic() - t0 < 2.0
