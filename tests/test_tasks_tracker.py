"""Tasks Tracker sample-app integration tests.

Automates the reference's manual verification checkpoints (SURVEY.md
§4): browser CRUD walkthrough, pub/sub consumer logs, cron overdue
job, external-queue ingest with blob archive — against the real
services on the real component files
(samples/tasks_tracker/components/).
"""

import asyncio
import datetime as dt
import json
import pathlib
import re

import pytest

from tasksrunner import AppHost, InProcCluster, load_components
from tasksrunner.bindings.localqueue import SqliteQueue

from samples.tasks_tracker.backend_api import make_app as make_api
from samples.tasks_tracker.backend_api.models import format_dt
from samples.tasks_tracker.frontend_ui import make_app as make_frontend
from samples.tasks_tracker.processor import make_app as make_processor

COMPONENTS_DIR = (
    pathlib.Path(__file__).resolve().parent.parent
    / "samples" / "tasks_tracker" / "components"
)

API = "tasksmanager-backend-api"
FRONTEND = "tasksmanager-frontend-webapp"
PROCESSOR = "tasksmanager-backend-processor"


async def wait_until(cond, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition not met in time")
        await asyncio.sleep(0.02)


@pytest.fixture
def isolated_cwd(tmp_path, monkeypatch):
    """Component files use relative .tasksrunner/ paths; isolate them."""
    monkeypatch.chdir(tmp_path)
    return tmp_path


def build_cluster():
    specs = load_components(COMPONENTS_DIR)
    cluster = InProcCluster(specs)
    api = make_api("store")
    frontend = make_frontend()
    processor = make_processor()
    for a in (api, frontend, processor):
        cluster.add_app(a)
    return cluster, api, frontend, processor


def cookie_from(resp) -> str:
    m = re.match(r"([^;]+)", resp.headers.get("set-cookie", ""))
    assert m, "no cookie set"
    return m.group(1)


@pytest.mark.asyncio
async def test_frontend_crud_walkthrough(isolated_cwd):
    """≙ the workshop's browser loop: sign in → create → list →
    reassign → complete → delete, with the processor notified."""
    cluster, api, frontend, processor = build_cluster()
    await cluster.start()
    try:
        # sign in: email → cookie → redirect
        resp = await frontend.handle("POST", "/", body=b"email=a%40x.com")
        assert resp.status == 303 and resp.headers["location"] == "/tasks"
        cookie = cookie_from(resp)
        assert cookie == "TasksCreatedByCookie=a@x.com"

        # empty list
        resp = await frontend.handle("GET", "/tasks", headers={"cookie": cookie})
        assert resp.status == 200 and "No tasks yet" in resp.body

        # create
        resp = await frontend.handle(
            "POST", "/tasks/create", headers={"cookie": cookie},
            body=b"taskName=Write+docs&taskDueDate=2026-08-01&taskAssignedTo=b%40x.com")
        assert resp.status == 303

        resp = await frontend.handle("GET", "/tasks", headers={"cookie": cookie})
        assert "Write docs" in resp.body and "b@x.com" in resp.body
        task_id = re.search(r"/tasks/edit/([0-9a-f-]{36})", resp.body).group(1)

        # processor got the TaskSaved event and "sent" the email
        await wait_until(lambda: len(processor.state["notified"]) == 1)
        assert processor.state["notified"][0]["taskName"] == "Write docs"
        outbox = list(pathlib.Path(".tasksrunner/outbox").glob("*.json"))
        assert len(outbox) == 1
        mail = json.loads(outbox[0].read_text())
        assert mail["to"] == "b@x.com"
        assert mail["subject"] == "Tasks assigned to you"

        # edit page prefilled
        resp = await frontend.handle("GET", f"/tasks/edit/{task_id}",
                                     headers={"cookie": cookie})
        assert 'value="Write docs"' in resp.body

        # reassign → second TaskSaved publish (TasksStoreManager.cs:95-98)
        resp = await frontend.handle(
            "POST", f"/tasks/edit/{task_id}", headers={"cookie": cookie},
            body=b"taskName=Write+docs&taskDueDate=2026-08-01&taskAssignedTo=c%40x.com")
        assert resp.status == 303
        await wait_until(lambda: len(processor.state["notified"]) == 2)

        # edit without reassignment → no extra publish
        await frontend.handle(
            "POST", f"/tasks/edit/{task_id}", headers={"cookie": cookie},
            body=b"taskName=Write+better+docs&taskDueDate=2026-08-01&taskAssignedTo=c%40x.com")
        await asyncio.sleep(0.2)
        assert len(processor.state["notified"]) == 2

        # complete
        await frontend.handle("POST", f"/tasks/complete/{task_id}",
                              headers={"cookie": cookie})
        resp = await frontend.handle("GET", "/tasks", headers={"cookie": cookie})
        assert "completed" in resp.body

        # delete
        await frontend.handle("POST", f"/tasks/delete/{task_id}",
                              headers={"cookie": cookie})
        resp = await frontend.handle("GET", "/tasks", headers={"cookie": cookie})
        assert "No tasks yet" in resp.body

        # no-cookie access redirects to sign-in
        resp = await frontend.handle("GET", "/tasks")
        assert resp.status == 303 and resp.headers["location"] == "/"
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_overdue_cron_job(isolated_cwd):
    """≙ SURVEY.md §3.3: cron fires → fetch overdue → mark overdue."""
    cluster, api, frontend, processor = build_cluster()
    await cluster.start()
    try:
        api_client = cluster.client(API)
        yesterday = format_dt((dt.datetime.now() - dt.timedelta(days=1)).replace(
            hour=0, minute=0, second=0, microsecond=0))
        # store a task due yesterday directly through the API surface
        resp = await api_client.invoke_method(
            API, "api/tasks", http_method="POST",
            data={"taskName": "stale", "taskCreatedBy": "a@x.com",
                  "taskDueDate": yesterday})
        task_id = resp.raise_for_status().json()["taskId"]
        # and one due tomorrow (must stay untouched)
        resp = await api_client.invoke_method(
            API, "api/tasks", http_method="POST",
            data={"taskName": "fresh", "taskCreatedBy": "a@x.com",
                  "taskDueDate": format_dt(dt.datetime.now() + dt.timedelta(days=1))})
        fresh_id = resp.raise_for_status().json()["taskId"]

        # fire the cron route exactly as the sidecar would
        resp = await cluster.client(PROCESSOR).invoke_method(
            PROCESSOR, "ScheduledTasksManager", http_method="POST")
        assert resp.ok

        stale = await api_client.invoke_json(API, f"api/tasks/{task_id}")
        fresh = await api_client.invoke_json(API, f"api/tasks/{fresh_id}")
        assert stale["isOverDue"] is True
        assert fresh["isOverDue"] is False
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_external_queue_ingest(isolated_cwd):
    """≙ SURVEY.md §3.4: queue message → input binding → invoke API →
    task stored → payload archived to blob store."""
    cluster, api, frontend, processor = build_cluster()
    await cluster.start()
    try:
        producer = SqliteQueue(
            pathlib.Path(".tasksrunner/queues/external-tasks-queue.db"))
        producer.send({"taskName": "external task",
                       "taskCreatedBy": "external@x.com",
                       "taskAssignedTo": "ops@x.com"})

        api_client = cluster.client(API)

        async def stored():
            tasks = await api_client.invoke_json(
                API, "api/tasks", query="createdBy=external@x.com")
            return tasks

        deadline = asyncio.get_running_loop().time() + 5
        tasks = []
        while not tasks:
            tasks = await stored()
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.05)
        assert tasks[0]["taskName"] == "external task"

        blob_dir = pathlib.Path(".tasksrunner/blobs/externaltaskscontainer")
        await wait_until(lambda: list(blob_dir.glob("*.json")))
        archived = json.loads(next(blob_dir.glob("*.json")).read_text())
        assert archived["taskName"] == "external task"
        producer.close()
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_fake_manager_mode_needs_no_components(isolated_cwd):
    """≙ module 1: FakeTasksManager ships first, no state store exists
    yet (Program.cs:13)."""
    cluster = InProcCluster([])  # zero components on purpose
    api = make_api("fake")
    cluster.add_app(api)
    await cluster.start()
    try:
        client = cluster.client(API)
        seeded = await client.invoke_json(
            API, "api/tasks", query="createdBy=tempuser@mail.com")
        assert len(seeded) == 10  # FakeTasksManager.GenerateRandomTasks

        resp = await client.invoke_method(
            API, "api/tasks", http_method="POST",
            data={"taskName": "t", "taskCreatedBy": "u@x.com"})
        task_id = resp.raise_for_status().json()["taskId"]
        assert (await client.invoke_json(API, f"api/tasks/{task_id}"))["taskName"] == "t"
        resp = await client.invoke_method(
            API, f"api/tasks/{task_id}/markcomplete", http_method="PUT")
        assert resp.ok
        resp = await client.invoke_method(
            API, f"api/tasks/{task_id}", http_method="DELETE")
        assert resp.ok
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_full_http_stack_with_real_browser_flow(isolated_cwd):
    """The same walkthrough over real sockets: app servers + sidecars
    + cookie-carrying HTTP client (≙ three `dapr run` terminals +
    a browser, SURVEY.md §4.3)."""
    import aiohttp

    specs = load_components(COMPONENTS_DIR)
    registry_file = str(isolated_cwd / "apps.json")
    hosts = [
        AppHost(make_api("store"), specs=specs, registry_file=registry_file),
        AppHost(make_frontend(), specs=specs, registry_file=registry_file),
        AppHost(make_processor(), specs=specs, registry_file=registry_file),
    ]
    for h in hosts:
        await h.start()
    try:
        base = f"http://127.0.0.1:{hosts[1].app_port}"
        jar = aiohttp.CookieJar(unsafe=True)
        async with aiohttp.ClientSession(cookie_jar=jar) as browser:
            async with browser.post(f"{base}/", data={"email": "web@x.com"}) as r:
                assert r.status == 200  # after redirect
                assert "No tasks yet" in await r.text()
            async with browser.post(f"{base}/tasks/create", data={
                "taskName": "via browser", "taskDueDate": "2026-08-02",
                "taskAssignedTo": "dev@x.com",
            }) as r:
                page = await r.text()
                assert "via browser" in page
        proc_app = hosts[2].app
        await wait_until(lambda: len(proc_app.state["notified"]) == 1)
        assert proc_app.state["notified"][0]["taskAssignedTo"] == "dev@x.com"
    finally:
        for h in hosts:
            await h.stop()


@pytest.mark.asyncio
async def test_concurrent_update_keeps_both_changes(isolated_cwd):
    """The lost-update race the reference HAS (TasksStoreManager.cs:
    84-101: get→modify→save, no etag) must not reproduce here: a
    rename racing a mark-completed keeps BOTH changes.

    Deterministic interleave: writer A (rename) reads the task, then —
    before A's save lands — writer B completes a full mark-completed.
    With last-write-wins, A's stale save erases isCompleted. With the
    etag CAS (managers.py TasksStoreManager._cas), A's save conflicts,
    retries against the fresh version, and both changes land.
    """
    from samples.tasks_tracker.backend_api.managers import TasksStoreManager

    cluster, api, frontend, processor = build_cluster()
    await cluster.start()
    try:
        raw_client = cluster.client(API)
        created = await raw_client.invoke_json(
            API, "api/tasks", http_method="POST",
            data={"taskName": "original", "taskCreatedBy": "race@x.com",
                  "taskAssignedTo": "a@x.com",
                  "taskDueDate": "2026-08-02T00:00:00"})
        task_id = created["taskId"]

        class RacingClient:
            """Delegates to the real client, but the FIRST save_state
            triggers a full competing write first."""

            def __init__(self, inner, race_once):
                self._inner = inner
                self._race = race_once
                self._raced = False

            def __getattr__(self, name):
                return getattr(self._inner, name)

            async def save_state(self, *args, **kwargs):
                if not self._raced:
                    self._raced = True
                    await self._race()
                return await self._inner.save_state(*args, **kwargs)

        async def competing_write():
            ok = await TasksStoreManager(raw_client).mark_task_completed(task_id)
            assert ok

        manager_a = TasksStoreManager(RacingClient(raw_client, competing_write))
        assert await manager_a.update_task(task_id, {"taskName": "renamed"})

        final = await raw_client.invoke_json(API, f"api/tasks/{task_id}")
        assert final["taskName"] == "renamed", "A's rename was lost"
        assert final["isCompleted"] is True, (
            "B's completion was erased by A's stale save — the "
            "reference's last-write-wins race reproduced")
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_frontend_per_field_validation(isolated_cwd):
    """≙ the [Required]/[Display] DataAnnotations on TaskAddModel
    (Pages/Tasks/Models/TasksModel.cs:6-49): an invalid submit
    re-renders the form with PER-FIELD messages in the reference's
    wording and the user's input preserved — not a redirect, not one
    generic error."""
    cluster, api, frontend, processor = build_cluster()
    await cluster.start()
    try:
        cookie = {"cookie": "TasksCreatedByCookie=val@x.com"}

        # missing name + bad email, valid date: two field errors
        resp2 = await cluster.apps[FRONTEND].handle(
            "POST", "/tasks/create",
            headers={**cookie,
                     "content-type": "application/x-www-form-urlencoded"},
            body=b"taskName=&taskDueDate=2026-08-02&taskAssignedTo=not-an-email")
        status, _, body = resp2.encode()
        page = body.decode()
        assert status == 400
        assert "The Task Name field is required." in page
        assert "not a valid e-mail address" in page
        # valid field's value is preserved in the re-rendered form
        assert 'value="2026-08-02"' in page
        assert "not-an-email" in page

        # a fully valid submit goes through and redirects
        ok = await cluster.apps[FRONTEND].handle(
            "POST", "/tasks/create",
            headers={**cookie,
                     "content-type": "application/x-www-form-urlencoded"},
            body=b"taskName=Valid&taskDueDate=2026-08-02&taskAssignedTo=a%40x.com")
        assert ok.status == 303
        tasks = await cluster.client(API).invoke_json(
            API, "api/tasks", query="createdBy=val@x.com")
        assert [t["taskName"] for t in tasks] == ["Valid"]
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_shared_layout_and_asset_tree(isolated_cwd):
    """VERDICT r2 item 5: the three pages render through ONE layout
    (header/nav/footer + stylesheet + script includes, ≙
    Pages/Shared/_Layout.cshtml:1-52) and every referenced asset
    resolves from the wwwroot tree (≙ wwwroot/ css+js)."""
    import re as _re

    cluster, api, frontend, processor = build_cluster()
    await cluster.start()
    try:
        signin = await frontend.handle("POST", "/", body=b"email=l%40x.com")
        cookie = {"cookie": cookie_from(signin)}
        pages = [
            await frontend.handle("GET", "/", headers=cookie),
            await frontend.handle("GET", "/tasks", headers=cookie),
            await frontend.handle("GET", "/tasks/create", headers=cookie),
        ]
        asset_refs: set[str] = set()
        for resp in pages:
            assert resp.status == 200
            doc = resp.body
            # one shared chrome on every page
            assert '<header class="site">' in doc
            assert "<nav>" in doc
            assert '<footer class="site">' in doc
            asset_refs.update(_re.findall(r'(?:href|src)="(/static/[^"]+)"', doc))
        assert '/static/css/site.css' in asset_refs
        assert '/static/js/validation.js' in asset_refs
        # every asset the layout references actually resolves
        for ref in sorted(asset_refs):
            resp = await frontend.handle("GET", ref)
            assert resp.status == 200, f"{ref} did not resolve"
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_client_validation_messages_mirror_server(isolated_cwd):
    """The client-side bundle must produce EXACTLY the server's
    per-field messages (≙ DataAnnotations text mirrored by the
    unobtrusive bundle) — a drift means users see one message before
    the round trip and a different one after."""
    import pathlib as _pathlib

    from samples.tasks_tracker.frontend_ui.app import _validate_task_form

    # isolated_cwd changed cwd; resolve from the repo instead
    js = _pathlib.Path(__file__).resolve().parent.parent / \
        "samples/tasks_tracker/frontend_ui/wwwroot/js/validation.js"
    source = js.read_text()
    server_errors = _validate_task_form(
        {"taskName": "", "taskDueDate": "not-a-date",
         "taskAssignedTo": "not-an-email"})
    # the three message TEMPLATES the server uses must appear verbatim
    # in the client bundle (modulo the display-name interpolation)
    assert '"The " + display + " field is required."' in source
    assert '"The " + display + " field is not a valid e-mail address."' in source
    assert '"The " + display + " field must be a valid date."' in source
    assert server_errors["taskName"] == "The Task Name field is required."
    assert server_errors["taskAssignedTo"] == \
        "The Task Assigned To field is not a valid e-mail address."
    assert server_errors["taskDueDate"] == \
        "The Task Due Date field must be a valid date."


@pytest.mark.asyncio
async def test_edit_form_surfaces_malformed_stored_date(isolated_cwd):
    """VERDICT r2 item 7: a malformed STORED due date must surface as
    a visible field error on the edit form, not render as a silently
    clipped plausible-looking date (the old value[:10] behavior)."""
    cluster, api, frontend, processor = build_cluster()
    await cluster.start()
    try:
        signin = await frontend.handle("POST", "/", body=b"email=d%40x.com")
        cookie = {"cookie": cookie_from(signin)}
        # plant a task with a corrupt stored date straight in the store
        await cluster.client(API).save_state("statestore", "bad-date-task", {
            "taskId": "bad-date-task", "taskName": "corrupt",
            "taskCreatedBy": "d@x.com", "taskCreatedOn": "2026-07-01T00:00:00",
            "taskDueDate": "07/29/2026 oops", "taskAssignedTo": "d@x.com",
            "isCompleted": False, "isOverDue": False,
        })
        resp = await frontend.handle("GET", "/tasks/edit/bad-date-task",
                                     headers=cookie)
        assert resp.status == 400  # invalid state renders, flagged
        assert "is not a valid date" in resp.body
        assert "07/29/2026 oops" in resp.body  # named, not hidden
        # and the good path still round-trips: a valid stored datetime
        # renders as the input's YYYY-MM-DD
        await cluster.client(API).save_state("statestore", "good-date-task", {
            "taskId": "good-date-task", "taskName": "fine",
            "taskCreatedBy": "d@x.com", "taskCreatedOn": "2026-07-01T00:00:00",
            "taskDueDate": "2026-08-15T00:00:00", "taskAssignedTo": "d@x.com",
            "isCompleted": False, "isOverDue": False,
        })
        resp = await frontend.handle("GET", "/tasks/edit/good-date-task",
                                     headers=cookie)
        assert resp.status == 200
        assert 'value="2026-08-15"' in resp.body
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_module5_code_snapshot_stays_runnable(isolated_cwd):
    """The docs' per-module code snapshot (the direct-SDK notifier the
    module-6 refactor replaces, ≙ the reference's
    TasksNotifierController-SendGrid.cs teaching copy) must stay
    importable and functional — a snapshot that rots teaches a bug."""
    import importlib.util
    import pathlib as _pathlib

    snippet = _pathlib.Path(__file__).resolve().parent.parent / \
        "docs/modules/snippets/notifier_direct_email.py"
    spec = importlib.util.spec_from_file_location("notifier_direct", snippet)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    sent = []

    class FakeClient:
        def send(self, *, to, subject, html):
            sent.append((to, subject, html))

    specs = load_components(COMPONENTS_DIR)
    cluster = InProcCluster(specs)
    api = make_api("store")
    old_processor = mod.make_app(email_client=FakeClient())
    cluster.add_app(api)
    cluster.add_app(old_processor)
    await cluster.start()
    try:
        await cluster.client(API).invoke_method(
            API, "api/tasks", http_method="POST",
            data={"taskName": "era-5 task", "taskCreatedBy": "s@x.com",
                  "taskDueDate": "2026-12-01T00:00:00",
                  "taskAssignedTo": "dev@x.com"})
        await wait_until(lambda: len(sent) == 1)
        to, subject, html = sent[0]
        assert to == "dev@x.com"
        assert "era-5 task" in html
    finally:
        await cluster.stop()
