"""State building-block contract suite, run against every engine.

Mirrors the reference's state usage: save/get/delete by key
(TasksStoreManager.cs:35,49,73), EQ filter query on a document field
:56-61, EQ on a serialized datetime :125-130, and the {app-id}||{key}
prefixing scheme (SURVEY.md §5.4). Both engines must behave
identically — the sqlite engine compiles the dialect to SQL and the
memory engine interprets it, so divergence here is a real bug.
"""

import asyncio

import pytest

from tasksrunner.errors import EtagMismatch, QueryError
from tasksrunner.state import (
    InMemoryStateStore,
    KeyPrefixer,
    SqliteStateStore,
    TransactionOp,
    build_sharded_store,
)

ENGINES = {
    "memory": lambda tmp_path: InMemoryStateStore("s"),
    "sqlite-mem": lambda tmp_path: SqliteStateStore("s"),
    "sqlite-file": lambda tmp_path: SqliteStateStore("s", tmp_path / "state.db"),
    # the rendezvous-sharded facade must be contract-identical to one
    # file: same CRUD/etag/transact/query semantics, merged across 3
    # independent shard engines (tests/test_state_sharding.py covers
    # the sharding-specific invariants on top)
    "sqlite-sharded": lambda tmp_path: build_sharded_store(
        "s", tmp_path / "state.db", shards=3, hash_seed="contract"),
}


@pytest.fixture(params=sorted(ENGINES))
def store(request, tmp_path):
    s = ENGINES[request.param](tmp_path)
    yield s
    s.close()


TASKS = [
    {"taskId": "t1", "taskName": "alpha", "taskCreatedBy": "a@x.com",
     "taskDueDate": "2026-07-28T00:00:00", "isCompleted": False, "priority": 3},
    {"taskId": "t2", "taskName": "beta", "taskCreatedBy": "b@x.com",
     "taskDueDate": "2026-07-29T00:00:00", "isCompleted": True, "priority": 1},
    {"taskId": "t3", "taskName": "gamma", "taskCreatedBy": "a@x.com",
     "taskDueDate": "2026-07-28T00:00:00", "isCompleted": False, "priority": 2},
]


async def seed(store, prefix=""):
    for t in TASKS:
        await store.set(prefix + t["taskId"], t)


@pytest.mark.asyncio
async def test_crud_roundtrip(store):
    etag = await store.set("k", {"a": 1})
    item = await store.get("k")
    assert item.value == {"a": 1} and item.etag == etag
    etag2 = await store.set("k", {"a": 2})
    assert etag2 != etag
    assert (await store.get("k")).value == {"a": 2}
    assert await store.delete("k") is True
    assert await store.get("k") is None
    assert await store.delete("k") is False


@pytest.mark.asyncio
async def test_etag_optimistic_concurrency(store):
    etag = await store.set("k", 1)
    with pytest.raises(EtagMismatch):
        await store.set("k", 2, etag="bogus")
    await store.set("k", 2, etag=etag)
    with pytest.raises(EtagMismatch):
        await store.delete("k", etag=etag)  # stale now
    with pytest.raises(EtagMismatch):
        await store.set("new-key", 1, etag="1")  # etag on missing key


@pytest.mark.asyncio
async def test_value_isolation(store):
    doc = {"nested": {"n": 1}}
    await store.set("k", doc)
    item = await store.get("k")
    item.value["nested"]["n"] = 99
    assert (await store.get("k")).value["nested"]["n"] == 1


@pytest.mark.asyncio
async def test_transact_atomic(store):
    await store.set("a", 1)
    with pytest.raises(EtagMismatch):
        await store.transact([
            TransactionOp("upsert", "b", 2),
            TransactionOp("delete", "a", etag="bogus"),
        ])
    # nothing from the failed transaction may be visible
    assert await store.get("b") is None
    await store.transact([
        TransactionOp("upsert", "b", 2),
        TransactionOp("delete", "a"),
    ])
    assert (await store.get("b")).value == 2
    assert await store.get("a") is None


@pytest.mark.asyncio
async def test_query_eq_by_creator(store):
    await seed(store)
    resp = await store.query({"filter": {"EQ": {"taskCreatedBy": "a@x.com"}}})
    assert {i.value["taskId"] for i in resp.items} == {"t1", "t3"}


@pytest.mark.asyncio
async def test_query_eq_serialized_datetime(store):
    """The DateTimeConverter trap: query matches the exact serialized
    string or nothing (reference TasksStoreManager.cs:104-130)."""
    await seed(store)
    hit = await store.query({"filter": {"EQ": {"taskDueDate": "2026-07-28T00:00:00"}}})
    assert len(hit.items) == 2
    miss = await store.query({"filter": {"EQ": {"taskDueDate": "07/28/2026 00:00:00"}}})
    assert miss.items == []


@pytest.mark.asyncio
async def test_query_eq_bool_and_missing_field(store):
    await seed(store)
    resp = await store.query({"filter": {"EQ": {"isCompleted": True}}})
    assert [i.value["taskId"] for i in resp.items] == ["t2"]
    resp = await store.query({"filter": {"EQ": {"noSuchField": None}}})
    assert len(resp.items) == 3  # missing field compares equal to null


@pytest.mark.asyncio
async def test_query_neq_in_and_or(store):
    await seed(store)
    resp = await store.query({"filter": {"NEQ": {"taskCreatedBy": "a@x.com"}}})
    assert [i.value["taskId"] for i in resp.items] == ["t2"]
    resp = await store.query({"filter": {"IN": {"taskName": ["alpha", "gamma"]}}})
    assert {i.value["taskId"] for i in resp.items} == {"t1", "t3"}
    resp = await store.query({"filter": {"AND": [
        {"EQ": {"taskCreatedBy": "a@x.com"}},
        {"EQ": {"isCompleted": False}},
        {"NEQ": {"taskName": "gamma"}},
    ]}})
    assert [i.value["taskId"] for i in resp.items] == ["t1"]
    resp = await store.query({"filter": {"OR": [
        {"EQ": {"taskName": "beta"}},
        {"EQ": {"taskName": "gamma"}},
    ]}})
    assert {i.value["taskId"] for i in resp.items} == {"t2", "t3"}


@pytest.mark.asyncio
async def test_query_in_with_null_candidate(store):
    await seed(store)
    resp = await store.query({"filter": {"IN": {"noField": [None]}}})
    assert len(resp.items) == 3
    resp = await store.query({"filter": {"IN": {"taskName": []}}})
    assert resp.items == []


@pytest.mark.asyncio
async def test_query_sort_and_page(store):
    await seed(store)
    resp = await store.query({"sort": [{"key": "priority", "order": "DESC"}]})
    assert [i.value["priority"] for i in resp.items] == [3, 2, 1]
    resp = await store.query({
        "sort": [{"key": "taskCreatedBy"}, {"key": "priority", "order": "DESC"}],
    })
    assert [i.value["taskId"] for i in resp.items] == ["t1", "t3", "t2"]
    # paging walks the full result set via tokens
    seen, token = [], None
    while True:
        page = {"limit": 2, **({"token": token} if token else {})}
        resp = await store.query({"sort": [{"key": "taskId"}], "page": page})
        seen += [i.value["taskId"] for i in resp.items]
        token = resp.token
        if token is None:
            break
    assert seen == ["t1", "t2", "t3"]


@pytest.mark.asyncio
async def test_query_key_prefix_isolation(store):
    await seed(store, prefix="appA||")
    await store.set("appB||t9", {"taskCreatedBy": "a@x.com"})
    resp = await store.query(
        {"filter": {"EQ": {"taskCreatedBy": "a@x.com"}}}, key_prefix="appA||"
    )
    assert {i.key for i in resp.items} == {"appA||t1", "appA||t3"}


@pytest.mark.asyncio
async def test_query_prefix_with_like_metacharacters(store):
    await store.set("app%_x||k", {"v": 1})
    await store.set("appZZxQQk", {"v": 2})
    resp = await store.query({}, key_prefix="app%_x||")
    assert [i.key for i in resp.items] == ["app%_x||k"]


@pytest.mark.asyncio
async def test_query_malformed_rejected(store):
    await seed(store)
    for bad in [
        {"filter": {"BOGUS": {"a": 1}}},
        {"filter": {"EQ": {"a": 1, "b": 2}}},
        {"filter": {"AND": []}},
        {"filter": {"IN": {"a": "not-a-list"}}},
        {"sort": [{"order": "ASC"}]},
        {"sort": [{"key": "a", "order": "SIDEWAYS"}]},
        {"page": {"limit": -1}},
        {"page": {"limit": 2, "token": "xyz"}},
    ]:
        with pytest.raises(QueryError):
            await store.query(bad)


@pytest.mark.asyncio
async def test_nested_path_query(store):
    await store.set("n1", {"address": {"city": "Athens"}})
    await store.set("n2", {"address": {"city": "Berlin"}})
    resp = await store.query({"filter": {"EQ": {"address.city": "Athens"}}})
    assert [i.key for i in resp.items] == ["n1"]


@pytest.mark.asyncio
async def test_bulk_get(store):
    await seed(store)
    items = await store.bulk_get(["t1", "missing", "t3"])
    assert items[0].value["taskId"] == "t1"
    assert items[1] is None
    assert items[2].value["taskId"] == "t3"


@pytest.mark.asyncio
async def test_sqlite_file_durability(tmp_path):
    path = tmp_path / "durable.db"
    s1 = SqliteStateStore("s", path)
    await s1.set("k", {"v": 42})
    s1.close()
    s2 = SqliteStateStore("s", path)
    assert (await s2.get("k")).value == {"v": 42}
    s2.close()


@pytest.mark.asyncio
async def test_etag_not_reused_after_delete(store):
    """A stale etag from a previous incarnation of a key must never
    validate against the recreated key (code-review finding)."""
    old_etag = await store.set("k", {"v": 1})
    await store.delete("k")
    await store.set("k", {"v": 2})
    with pytest.raises(EtagMismatch):
        await store.set("k", {"stale": True}, etag=old_etag)
    assert (await store.get("k")).value == {"v": 2}


@pytest.mark.asyncio
async def test_transact_etags_validate_against_pre_state(store):
    """Both engines: etags check pre-transaction state, then ops apply
    in order — multi-op-per-key transactions agree across engines."""
    etag = await store.set("a", 1)
    await store.transact([
        TransactionOp("upsert", "a", 2),
        TransactionOp("delete", "a", etag=etag),
    ])
    assert await store.get("a") is None


@pytest.mark.asyncio
async def test_sort_on_container_values_does_not_crash(store):
    await store.set("c1", {"address": {"city": "Athens"}})
    await store.set("c2", {"address": {"city": "Berlin"}})
    resp = await store.query({"sort": [{"key": "address"}]})
    assert len(resp.items) == 2


@pytest.mark.asyncio
async def test_nan_rejected_at_write_time(store):
    """NaN would poison json_extract in the sqlite engine; both engines
    must reject it at set() so queries can never break."""
    from tasksrunner.errors import StateError
    if isinstance(store, InMemoryStateStore):
        pytest.skip("memory engine stores Python objects; nothing to poison")
    with pytest.raises(StateError):
        await store.set("k", float("nan"))
    await seed(store)
    resp = await store.query({"filter": {"EQ": {"taskName": "alpha"}}})
    assert len(resp.items) == 1  # queries still work


@pytest.mark.asyncio
async def test_container_filter_operands_rejected(store):
    await seed(store)
    with pytest.raises(QueryError, match="scalar"):
        await store.query({"filter": {"EQ": {"tags": ["urgent"]}}})
    with pytest.raises(QueryError, match="scalar"):
        await store.query({"filter": {"IN": {"a": [{"x": 1}]}}})


@pytest.mark.asyncio
async def test_mixed_type_sort_rank_matches_sqlite_order(store):
    """NULL < numeric < text < container, both engines."""
    await store.set("a", {"v": "zeta"})
    await store.set("b", {"v": 5})
    await store.set("c", {"w": 1})          # v missing -> null
    await store.set("d", {"v": {"k": 1}})   # container
    resp = await store.query({"sort": [{"key": "v"}]})
    assert [i.key for i in resp.items] == ["c", "b", "a", "d"]


@pytest.mark.asyncio
async def test_negative_page_token_rejected(store):
    await seed(store)
    with pytest.raises(QueryError):
        await store.query({"page": {"limit": 2, "token": "-1"}})


# -- group-commit queue: concurrent-writer etag contention -----------------
# These run against EVERY engine: coalescing concurrent writes into one
# transaction (sqlite) must be observationally identical to the memory
# engine's lock-per-call — same winners, same per-key EtagMismatch.


@pytest.mark.asyncio
async def test_concurrent_stale_etag_contention(store):
    """N coroutines race a CAS on one key: exactly one wins, every
    other gets its own EtagMismatch, and the winner's etag is live."""
    etag = await store.set("k", 0)
    results = await asyncio.gather(
        *(store.set("k", i, etag=etag) for i in range(16)),
        return_exceptions=True)
    winners = [r for r in results if isinstance(r, str)]
    losers = [r for r in results if isinstance(r, EtagMismatch)]
    assert len(winners) == 1
    assert len(losers) == 15
    assert (await store.get("k")).etag == winners[0]


@pytest.mark.asyncio
async def test_mixed_outcomes_within_one_coalesced_flush(store):
    """A concurrent burst mixing successes, stale etags, deletes, and a
    miss: each caller gets its own outcome, untouched keys stay put."""
    etags = {k: await store.set(k, 0) for k in ("a", "b", "c", "d")}
    results = await asyncio.gather(
        store.set("a", 1, etag=etags["a"]),       # ok
        store.set("b", 1, etag="bogus"),          # per-key mismatch
        store.delete("c", etag=etags["c"]),       # ok
        store.delete("d", etag="bogus"),          # per-key mismatch
        store.set("e", 1),                        # ok, no etag
        store.delete("missing"),                  # False, not an error
        return_exceptions=True)
    assert isinstance(results[0], str)
    assert isinstance(results[1], EtagMismatch)
    assert results[2] is True
    assert isinstance(results[3], EtagMismatch)
    assert isinstance(results[4], str)
    assert results[5] is False
    assert (await store.get("a")).value == 1
    assert (await store.get("b")).value == 0      # refused write left b alone
    assert await store.get("c") is None
    assert (await store.get("d")).value == 0
    assert (await store.get("e")).value == 1


@pytest.mark.asyncio
async def test_transact_atomicity_survives_coalescing(store):
    """A failing transact inside a concurrent burst applies NOTHING,
    while its batch-mates commit normally."""
    await store.set("a", 1)
    results = await asyncio.gather(
        store.transact([TransactionOp("upsert", "x", 1),
                        TransactionOp("upsert", "y", 2)]),
        store.transact([TransactionOp("upsert", "z", 3),
                        TransactionOp("delete", "a", etag="bogus")]),
        store.set("w", 9),
        return_exceptions=True)
    assert results[0] is None
    assert isinstance(results[1], EtagMismatch)
    assert isinstance(results[2], str)
    assert (await store.get("x")).value == 1
    assert (await store.get("y")).value == 2
    assert await store.get("z") is None            # atomic: nothing leaked
    assert (await store.get("a")).value == 1
    assert (await store.get("w")).value == 9


@pytest.mark.asyncio
async def test_queued_writes_apply_in_submission_order(store):
    """Coalesced ops see the effects of ops queued before them, exactly
    as if each had committed alone (last submission wins)."""
    await asyncio.gather(*(store.set("k", i) for i in range(8)))
    assert (await store.get("k")).value == 7


@pytest.mark.slow
@pytest.mark.asyncio
async def test_group_commit_cas_soak(tmp_path):
    """Soak: 16 workers CAS-increment 8 shared counters through the
    group-commit queue; a single lost update fails the count."""
    s = SqliteStateStore("s", tmp_path / "soak.db")
    try:
        for k in range(8):
            await s.set(f"ctr{k}", 0)

        async def worker(wid: int) -> None:
            key = f"ctr{wid % 8}"
            for _ in range(25):
                while True:
                    item = await s.get(key)
                    try:
                        await s.set(key, item.value + 1, etag=item.etag)
                        break
                    except EtagMismatch:
                        continue

        await asyncio.gather(*(worker(w) for w in range(16)))
        for k in range(8):
            assert (await s.get(f"ctr{k}")).value == 50
    finally:
        s.close()


# -- read cache -------------------------------------------------------------


@pytest.mark.asyncio
async def test_read_cache_semantics(tmp_path):
    s = SqliteStateStore("s", tmp_path / "cache.db", cache_size=4)
    try:
        etag = await s.set("k", {"nested": {"n": 1}})
        item = await s.get("k")                  # hit (write-through)
        item.value["nested"]["n"] = 99           # isolation holds on hits
        assert (await s.get("k")).value["nested"]["n"] == 1
        # a refused write must not touch the cache
        with pytest.raises(EtagMismatch):
            await s.set("k", {"nested": {"n": 2}}, etag="bogus")
        assert (await s.get("k")).value["nested"]["n"] == 1
        # a successful CAS updates value AND etag in the cache
        etag2 = await s.set("k", {"nested": {"n": 2}}, etag=etag)
        got = await s.get("k")
        assert got.value["nested"]["n"] == 2 and got.etag == etag2
        # delete invalidates
        await s.delete("k")
        assert await s.get("k") is None
        # transact updates and invalidates its keys
        await s.set("t1", 1)
        await s.set("t2", 2)
        await s.transact([TransactionOp("upsert", "t1", 10),
                          TransactionOp("delete", "t2")])
        assert (await s.get("t1")).value == 10
        assert await s.get("t2") is None
    finally:
        s.close()


@pytest.mark.asyncio
async def test_read_cache_lru_bound_and_coherence(tmp_path):
    s = SqliteStateStore("s", tmp_path / "lru.db", cache_size=4)
    try:
        for i in range(32):
            await s.set(f"k{i}", i)
        assert len(s._cache) <= 4                # bound enforced
        # evicted keys still read correctly (SQL path)
        assert (await s.get("k0")).value == 0
    finally:
        s.close()
    # what the cache served matches what a fresh store reads from disk
    s2 = SqliteStateStore("s2", tmp_path / "lru.db")
    try:
        assert (await s2.get("k31")).value == 31
    finally:
        s2.close()


def test_sqlite_driver_metadata_knobs(tmp_path):
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import parse_component

    spec = parse_component({
        "componentType": "state.sqlite",
        "metadata": [
            {"name": "databasePath", "value": str(tmp_path / "s.db")},
            {"name": "readCacheSize", "value": "128"},
            {"name": "groupCommit", "value": "false"},
        ],
    }, default_name="st")
    store = ComponentRegistry([spec]).get("st")
    assert store.cache_size == 128
    assert store.group_commit is False
    store.close()


def test_sqlite_driver_metadata_knobs_rejected(tmp_path):
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import parse_component
    from tasksrunner.errors import ComponentError

    spec = parse_component({
        "componentType": "state.sqlite",
        "metadata": [{"name": "readCacheSize", "value": "lots"}],
    }, default_name="st")
    with pytest.raises(ComponentError, match="readCacheSize"):
        ComponentRegistry([spec]).get("st")


@pytest.mark.asyncio
async def test_group_commit_off_still_honors_contract(tmp_path):
    """The groupCommit=false comparison knob: per-op transactions, same
    observable semantics."""
    s = SqliteStateStore("s", tmp_path / "nogc.db", group_commit=False)
    try:
        etag = await s.set("k", 0)
        results = await asyncio.gather(
            *(s.set("k", i, etag=etag) for i in range(8)),
            return_exceptions=True)
        assert sum(isinstance(r, str) for r in results) == 1
        assert sum(isinstance(r, EtagMismatch) for r in results) == 7
    finally:
        s.close()


def test_state_drivers_registered_by_plain_import():
    import subprocess, sys
    out = subprocess.run(
        [sys.executable, "-c",
         "import tasksrunner; from tasksrunner.component.registry import registered_types; "
         "print('state.sqlite' in registered_types())"],
        capture_output=True, text=True, check=True,
    )
    assert out.stdout.strip() == "True"


def test_file_secret_store_malformed_content(tmp_path):
    from tasksrunner.secrets import FileSecretStore
    from tasksrunner.errors import SecretError

    f = tmp_path / "bad.json"
    f.write_text("{truncated")
    with pytest.raises(SecretError, match="cannot parse"):
        FileSecretStore("s", f)


def test_key_prefixer_strategies():
    assert KeyPrefixer("appid", app_id="api").apply("t1") == "api||t1"
    assert KeyPrefixer("appid", app_id=None).apply("t1") == "t1"
    assert KeyPrefixer("name", component_name="statestore").apply("t1") == "statestore||t1"
    assert KeyPrefixer("none", app_id="api").apply("t1") == "t1"
    assert KeyPrefixer("shared-ns", app_id="api").apply("t1") == "shared-ns||t1"
    p = KeyPrefixer("appid", app_id="api")
    assert p.strip("api||t1") == "t1"


def test_state_drivers_registered():
    from tasksrunner.component.registry import registered_types
    types = registered_types()
    assert "state.sqlite" in types
    assert "state.azure.cosmosdb" in types  # reference file loads unchanged
    assert "state.in-memory" in types
