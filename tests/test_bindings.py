"""Bindings building-block tests: queue in/out, blob, email outbox.

Contract source: SURVEY.md §3.4 (input→invoke→output chain) and the
component table §2.4.
"""

import asyncio
import json

import pytest

from tasksrunner.bindings import (
    EmailOutboxBinding,
    LocalBlobStoreBinding,
    LocalQueueBinding,
    SqliteQueue,
)
from tasksrunner.errors import BindingError


async def wait_until(cond, timeout=3.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition not met in time")
        await asyncio.sleep(0.01)


@pytest.mark.asyncio
async def test_queue_input_binding_ack_consumes(tmp_path):
    binding = LocalQueueBinding("externaltasksmanager", str(tmp_path / "q.db"),
                                route="/externaltasksprocessor/process",
                                poll_interval=0.01)
    assert binding.route == "/externaltasksprocessor/process"
    got = []

    async def sink(event):
        got.append(event)
        return True

    await binding.start(sink)
    binding.queue.send({"taskName": "external"})
    await wait_until(lambda: len(got) == 1)
    assert got[0].data == {"taskName": "external"}
    assert binding.queue.backlog() == 0
    await binding.stop()


@pytest.mark.asyncio
async def test_queue_nack_redelivers_then_dead_letters(tmp_path):
    binding = LocalQueueBinding("q", str(tmp_path / "q.db"),
                                poll_interval=0.01, max_attempts=2,
                                retry_delay=0.01)
    attempts = []

    async def sink(event):
        attempts.append(int(event.metadata["attempt"]))
        return False

    await binding.start(sink)
    binding.queue.send({"n": 1})
    await wait_until(lambda: len(attempts) >= 2)
    await asyncio.sleep(0.05)
    assert attempts == [1, 2]
    assert binding.queue.backlog() == 0  # dead-lettered, not pending
    await binding.stop()


@pytest.mark.asyncio
async def test_queue_output_binding_enqueues(tmp_path):
    binding = LocalQueueBinding("q", str(tmp_path / "q.db"), poll_interval=0.01)
    resp = await binding.invoke("create", {"external": True})
    assert resp.metadata["messageId"]
    assert binding.queue.backlog() == 1
    with pytest.raises(BindingError):
        await binding.invoke("get", None)
    await binding.stop()


@pytest.mark.asyncio
async def test_queue_cross_process_producer(tmp_path):
    """An external producer writes via a separate SqliteQueue handle
    (≙ Azure Storage Explorer dropping a message in the queue)."""
    path = tmp_path / "q.db"
    binding = LocalQueueBinding("q", str(path), poll_interval=0.01)
    got = []

    async def sink(event):
        got.append(event.data)
        return True

    await binding.start(sink)
    producer = SqliteQueue(path)
    producer.send({"from": "outside"})
    await wait_until(lambda: got == [{"from": "outside"}])
    producer.close()
    await binding.stop()


@pytest.mark.asyncio
async def test_blob_binding_crud(tmp_path):
    blob = LocalBlobStoreBinding("externaltasksblobstore", tmp_path)
    task = {"taskId": "abc", "taskName": "archived"}
    resp = await blob.invoke("create", task, {"blobName": "abc.json"})
    assert resp.metadata["blobName"] == "abc.json"

    got = await blob.invoke("get", None, {"blobName": "abc.json"})
    assert json.loads(got.data) == task

    listing = await blob.invoke("list", None)
    assert listing.data == ["abc.json"]

    await blob.invoke("delete", None, {"blobName": "abc.json"})
    assert (await blob.invoke("list", None)).data == []

    with pytest.raises(BindingError):
        await blob.invoke("get", None, {"blobName": "abc.json"})


@pytest.mark.asyncio
async def test_blob_binding_rejects_escape(tmp_path):
    blob = LocalBlobStoreBinding("b", tmp_path)
    with pytest.raises(BindingError, match="escapes"):
        await blob.invoke("create", "x", {"blobName": "../../etc/passwd"})


@pytest.mark.asyncio
async def test_email_outbox(tmp_path):
    mail = EmailOutboxBinding("sendgrid", tmp_path / "outbox",
                              default_from="noreply@tasksrunner.local")
    await mail.invoke("create", "<b>task assigned</b>", {
        "emailTo": "a@x.com", "emailToName": "A", "subject": "tasks assigned",
    })
    sent = mail.sent()
    assert len(sent) == 1
    assert sent[0]["to"] == "a@x.com"
    assert sent[0]["from"] == "noreply@tasksrunner.local"
    assert sent[0]["subject"] == "tasks assigned"

    with pytest.raises(BindingError, match="emailTo"):
        await mail.invoke("create", "x", {})


def test_binding_drivers_registered():
    from tasksrunner.component.registry import registered_types
    types = registered_types()
    # reference component types load unchanged
    assert "bindings.cron" in types
    assert "bindings.azure.storagequeues" in types
    assert "bindings.azure.blobstorage" in types
    assert "bindings.twilio.sendgrid" in types


async def test_queue_dead_letter_detail_and_requeue(tmp_path):
    """Queue-binding DLQ operator surface (Storage-queue poison-queue
    analog): inspect parked messages, resubmit with fresh attempts."""
    from tasksrunner.bindings.localqueue import (
        LocalQueueBinding, SqliteQueue, open_queue_for_inspection,
    )
    from tasksrunner.bindings.base import BindingEvent
    from tasksrunner.component.spec import parse_component

    binding = LocalQueueBinding(
        "extq", str(tmp_path / "queues" / "extq.db"),
        poll_interval=0.01, max_attempts=2, retry_delay=0.02)
    ok = False
    seen = []

    async def sink(event: BindingEvent) -> bool:
        seen.append(event.data)
        return ok

    await binding.start(sink)
    await binding.invoke("create", {"n": 9})
    deadline = asyncio.get_running_loop().time() + 5
    while not binding.queue.dead_letter_detail():
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.02)

    spec = parse_component({
        "componentType": "bindings.azure.storagequeues",
        "metadata": [{"name": "queuePath", "value": str(tmp_path / "queues")},
                     {"name": "queueName", "value": "extq"}],
    }, default_name="extq")
    queue = open_queue_for_inspection(spec, tmp_path)
    detail = queue.dead_letter_detail()
    assert detail and detail[0]["data"] == {"n": 9}
    assert queue.requeue_dead_letters(["bogus"]) == 0

    ok = True
    count = len(seen)
    assert queue.requeue_dead_letters() == 1
    queue.close()
    deadline = asyncio.get_running_loop().time() + 5
    while len(seen) <= count:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.02)
    assert binding.queue.dead_letter_detail() == []
    await binding.stop()
