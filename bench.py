"""Benchmark: end-to-end task-write throughput through the framework.

The reference publishes NO performance benchmarks (BASELINE.md: no
benchmarks directory, no throughput/latency numbers; `"published": {}`),
so there is no reference number to beat — ``vs_baseline`` is null.

The HEADLINE metric measures the framework's canonical write path in
the **faithful cross-process topology**: three OS processes, with
every PROCESS-BOUNDARY hop a real localhost transport. Since round 3,
app and sidecar inside one process dispatch directly (AppHost fuses
them — profiling showed 4 of 5 aiohttp round trips per request never
left a process; see BASELINE.md "where the time goes"); the [PB]
boundaries of SURVEY.md §3.1 — peer-to-peer invocation and the broker
— remain real:

    driver proc (≙ browser + frontend sidecar, fused)
      → api sidecar                 [PB: peer-sidecar localhost HTTP]
        → api app (direct dispatch, same process)
          → state write → durable sqlite
          → publish → durable sqlite broker file      [PB: shared file]
      ~ async ~
      broker → processor proc (sidecar+app, fused)    [PB: competing
                                                       consumer claim]

Each unit of work exercises invocation, state, pub/sub, and competing-
consumer delivery — the whole runtime in its production process model,
not a micro-op and not the flattering in-proc mode.

Also reported (in the final line's ``extras``):

* p50/p99 request latency under load in the same topology;
* ``state_ops_per_sec`` — the durable sqlite state engine measured
  alone: write-heavy concurrent upserts through the group-commit queue
  vs the seed one-commit-per-call path in the same run, plus read-heavy
  point gets with and without the write-through LRU read cache;
* ``histogram_overhead`` — the latency-histogram instrumentation
  measured on vs off (``TASKSRUNNER_HISTOGRAMS=0``) on the write-heavy
  state path and the publish/deliver path (must stay <3%);
* a 5-replica competing-consumer throughput figure (KEDA-style
  scale-out semantics, SURVEY.md §5.8);
* the in-process cluster number (continuity with round 1);
* the optional ML extension's train-step time / TFLOP/s / MFU measured
  on the real chip when one is attached (EXTENSION ONLY — the
  reference has no model, SURVEY.md §7.1).

Prints ONE JSON line to stdout:
{"metric", "value", "unit", "vs_baseline", "extras"}.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import pathlib
import signal
import sqlite3
import statistics
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

N_TASKS = 600
WARMUP = 50
CONCURRENCY = 64


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _component_specs(tmp: str):
    from tasksrunner.component.spec import parse_component
    return [
        parse_component({
            "componentType": "state.sqlite",
            "metadata": [{"name": "databasePath", "value": f"{tmp}/state.db"}],
            "scopes": ["bench-api"],
        }, default_name="statestore"),
        parse_component({
            "componentType": "pubsub.sqlite",
            "metadata": [
                {"name": "brokerPath", "value": f"{tmp}/broker.db"},
                {"name": "pollIntervalSeconds", "value": "0.002"},
                # scale-out runs shrink the claim batch so a backlog
                # spreads across competing replicas instead of one
                # replica prefetching everything
                {"name": "claimBatchSize",
                 "value": os.environ.get("BENCH_CLAIM_BATCH", "64")},
            ],
        }, default_name="pubsub"),
    ]


# ---------------------------------------------------------------------------
# worker processes (spawned as `python bench.py --worker ROLE --tmp DIR`)
# ---------------------------------------------------------------------------

def _make_api_app():
    from tasksrunner import App

    api = App("bench-api")

    @api.post("/api/tasks")
    async def create(req):
        doc = req.json()
        await api.client.save_state("statestore", doc["taskId"], doc)
        await api.client.publish_event("pubsub", "tasksavedtopic", doc)
        return 201, {"taskId": doc["taskId"]}

    return api


def _make_processor_app(tmp: str):
    from tasksrunner import App

    # each replica records unique deliveries in a shared sqlite table;
    # INSERT OR IGNORE dedupes at-least-once redelivery so the driver
    # counts completed tasks, not delivery attempts
    conn = sqlite3.connect(f"{tmp}/delivered.db", timeout=30)
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("PRAGMA busy_timeout=30000")
    # measurement table, not the system under test: the default
    # synchronous=FULL fsyncs every commit INSIDE the delivery handler
    # (~0.65 ms on this host), capping the whole pipeline at ~1.5k
    # deliveries/s of pure harness overhead. Durability of the counter
    # is irrelevant — crash-loss tests use the framework's own stores.
    conn.execute("PRAGMA synchronous=OFF")

    # simulated per-message work (≙ the reference processor's SendGrid
    # call) — this is what makes consumers the bottleneck so the
    # scale-out measurement exercises KEDA-style competing consumers
    work_s = float(os.environ.get("BENCH_WORK_MS", "0")) / 1000.0

    processor = App("bench-processor")

    @processor.subscribe(pubsub="pubsub", topic="tasksavedtopic",
                         route="/on-saved")
    async def on_saved(req):
        if work_s > 0:
            await asyncio.sleep(work_s)
        task_id = (req.data or {}).get("taskId")  # CloudEvents-unwrapped
        # a missing id means the envelope contract broke — fail delivery
        # (NULLs would dodge the PRIMARY KEY dedup and fake completions)
        assert task_id, f"delivery without taskId: {req.body[:200]!r}"
        conn.execute(
            "INSERT OR IGNORE INTO delivered(id) VALUES (?)", (task_id,))
        conn.commit()
        return 200

    return processor


async def _worker_main(role: str, tmp: str, idx: int) -> None:
    from tasksrunner.hosting import AppHost
    from tasksrunner.observability.spans import configure_spans

    # no-op unless TASKSRUNNER_TRACE_DB is set: lets a profiling run
    # attribute the write path hop-by-hop (BASELINE.md breakdown table)
    configure_spans(f"bench-{role}-{idx}")

    app = _make_api_app() if role == "api" else _make_processor_app(tmp)
    host = AppHost(
        app,
        specs=_component_specs(tmp),
        registry_file=f"{tmp}/registry.json",
        # scale-out processor replicas compete on the broker, they don't
        # serve invokes (hosting.py): only replica 0 registers
        register=(role == "api" or idx == 0),
    )
    await host.start()
    pathlib.Path(f"{tmp}/ready-{role}-{idx}").touch()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await host.stop()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

class _Workers:
    def __init__(self, tmp: str, n_processors: int, *, work_ms: float = 0.0,
                 pki: dict | None = None):
        self.tmp = tmp
        self.procs: list[subprocess.Popen] = []
        self.expected = ["api-0"] + [f"processor-{i}" for i in range(n_processors)]
        env = {**os.environ, "BENCH_WORK_MS": str(work_ms),
               "BENCH_CLAIM_BATCH": "4" if work_ms else "64",
               # production tuning, not a benchmark cheat: per-request
               # access-log formatting halves write-path throughput —
               # the A/B measurement is documented in BASELINE.md
               # ("Finding 2"); the workshop default keeps logs on,
               # the bench measures the tuned configuration
               "TASKSRUNNER_ACCESS_LOG": os.environ.get(
                   "TASKSRUNNER_ACCESS_LOG", "0")}
        self._logs = []
        for name in self.expected:
            role, idx = name.rsplit("-", 1)
            wenv = dict(env)
            if pki:
                # each worker process runs under its OWN workload
                # identity, as deployed — the mTLS variant must pay
                # real per-app certificate verification, not a shared
                # self-identity shortcut
                from tasksrunner.invoke.pki import CA_ENV, CERT_ENV, KEY_ENV
                p = pki["bench-api" if role == "api" else "bench-processor"]
                wenv.update({CA_ENV: p["ca"], CERT_ENV: p["cert"],
                             KEY_ENV: p["key"]})
            log = open(f"{tmp}/worker-{name}.log", "w")
            self._logs.append(log)
            self.procs.append(subprocess.Popen(
                [sys.executable, str(REPO / "bench.py"),
                 "--worker", role, "--tmp", tmp, "--idx", idx],
                cwd=str(REPO), env=wenv, stderr=log))

    def wait_ready(self, timeout: float = 60.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(pathlib.Path(f"{self.tmp}/ready-{n}").exists()
                   for n in self.expected):
                return
            for p in self.procs:
                if p.poll() is not None:
                    raise RuntimeError(f"bench worker exited rc={p.returncode}")
            time.sleep(0.05)
        raise RuntimeError("bench workers did not become ready in time")

    def stop(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for log in self._logs:
            log.close()


_count_conns: dict[str, sqlite3.Connection] = {}


def _delivered_count(tmp: str) -> int:
    """Poll completions over one long-lived read connection (a fresh
    connect per 10 ms poll would contend with the replicas' commits)."""
    conn = _count_conns.get(tmp)
    if conn is None:
        conn = _count_conns[tmp] = sqlite3.connect(
            f"{tmp}/delivered.db", timeout=5)
    try:
        return conn.execute("SELECT COUNT(*) FROM delivered").fetchone()[0]
    except sqlite3.OperationalError:
        return 0


async def run_xproc(n_tasks: int = N_TASKS, *, warmup: int = WARMUP,
                    n_processors: int = 1, rounds: int = 3,
                    concurrency: int = CONCURRENCY, work_ms: float = 0.0,
                    latency_probe: bool = False,
                    mesh_tls: bool = False) -> dict:
    """The faithful topology: separate api/processor OS processes, all
    hops over localhost HTTP, durable sqlite state + broker.

    Returns {"throughput"} where throughput counts full pipeline
    completion (all events delivered and acknowledged), plus
    {"p50_ms", "p99_ms"} when ``latency_probe`` — per-request write-path
    round trips measured in a separate low-concurrency (8) pass so the
    numbers reflect service time, not load-generator queueing.

    With ``mesh_tls`` an environment CA and per-app workload certs are
    provisioned and every peer-sidecar hop rides the authenticated TLS
    mesh lane (invoke/pki.py) — the production posture module 15
    recommends, measured instead of assumed.
    """
    from tasksrunner import App
    from tasksrunner.hosting import AppHost

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-")
    setup = sqlite3.connect(f"{tmp}/delivered.db")
    try:
        setup.execute("PRAGMA journal_mode=WAL")
        setup.execute("CREATE TABLE delivered (id TEXT PRIMARY KEY)")
        setup.commit()
    finally:
        setup.close()

    pki_paths = None
    pki_prev: dict[str, str | None] = {}
    if mesh_tls:
        from tasksrunner.invoke.pki import (CA_ENV, CERT_ENV, KEY_ENV,
                                            write_pki)
        pki_paths = write_pki(pathlib.Path(tmp) / "pki",
                              ["bench-frontend", "bench-api",
                               "bench-processor"])
        # the driver process plays the frontend: it dials under the
        # frontend's identity for the whole measurement (restored in
        # the outer finally — pytest reuses this interpreter)
        front = pki_paths["bench-frontend"]
        for var, val in ((CA_ENV, front["ca"]), (CERT_ENV, front["cert"]),
                         (KEY_ENV, front["key"])):
            pki_prev[var] = os.environ.get(var)
            os.environ[var] = val

    workers = None
    try:
        workers = _Workers(tmp, n_processors, work_ms=work_ms,
                           pki=pki_paths)
        workers.wait_ready()

        # the driver plays the frontend: its own app + sidecar so the
        # first hop is the same client→sidecar HTTP hop the reference's
        # frontend makes (Pages/Tasks/Create.cshtml.cs:46)
        from tasksrunner.observability.spans import configure_spans
        configure_spans("bench-frontend")  # no-op without TASKSRUNNER_TRACE_DB

        # same tuning as the workers (see _Workers): the driver hosts a
        # real frontend sidecar whose log formatting would distort the
        # measurement. Scoped to this host's startup only — run_xproc
        # must not leak config into the calling process (pytest runs
        # later tests in the same interpreter).
        prev_access_log = os.environ.get("TASKSRUNNER_ACCESS_LOG")
        os.environ.setdefault("TASKSRUNNER_ACCESS_LOG", "0")
        frontend = App("bench-frontend")
        fhost = AppHost(frontend, specs=_component_specs(tmp),
                        registry_file=f"{tmp}/registry.json")
        try:
            await fhost.start()
        finally:
            if prev_access_log is None:
                os.environ.pop("TASKSRUNNER_ACCESS_LOG", None)
            else:
                os.environ["TASKSRUNNER_ACCESS_LOG"] = prev_access_log
        try:
            client = frontend.client
            latencies: list[float] = []

            async def create_task(i: int, record: bool = False) -> None:
                t0 = time.perf_counter()
                resp = await client.invoke_method(
                    "bench-api", "api/tasks", http_method="POST",
                    data={"taskId": f"t{i}", "taskName": f"task {i}",
                          "taskCreatedBy": "bench@x.com",
                          "taskDueDate": "2026-08-01T00:00:00"})
                assert resp.status == 201, resp.body
                if record:
                    latencies.append(time.perf_counter() - t0)

            for i in range(warmup):
                await create_task(i)

            async def drain(target: int, timeout: float = 300.0) -> None:
                deadline = time.perf_counter() + timeout
                while _delivered_count(tmp) < target:
                    if time.perf_counter() > deadline:
                        raise RuntimeError(
                            f"delivery stalled: {_delivered_count(tmp)}"
                            f"/{target} events")
                    await asyncio.sleep(0.01)

            async def flood(start_id: int, n: int, conc: int,
                            record: bool = False) -> float:
                sem = asyncio.Semaphore(conc)

                async def bounded(i: int) -> None:
                    async with sem:
                        await create_task(i, record=record)

                t0 = time.perf_counter()
                await asyncio.gather(
                    *(bounded(i) for i in range(start_id, start_id + n)))
                await drain(start_id + n)
                return time.perf_counter() - t0

            # all rounds are reported: the headline is the MEDIAN (so
            # round-over-round comparisons aren't comparing luck on a
            # shared host), with min/max/best carried alongside
            # (BASELINE.md's variance-band table)
            round_rates: list[float] = []
            next_id = warmup
            # one full-size throwaway round first: the per-request
            # warmup above exercises the path, but the first *flood*
            # still pays cold costs (allocator growth, broker file
            # pages, branch-warm paths) — measured consistently ~20%
            # below steady state, which would skew the median low
            await drain(next_id)
            elapsed = await flood(next_id, n_tasks, concurrency)
            next_id += n_tasks
            for _ in range(rounds):
                await drain(next_id)
                elapsed = await flood(next_id, n_tasks, concurrency)
                next_id += n_tasks
                round_rates.append(n_tasks / elapsed)
            out = {
                "throughput": round(statistics.median(round_rates), 1),
                "throughput_runs": [round(r, 1) for r in round_rates],
                "throughput_min": round(min(round_rates), 1),
                "throughput_max": round(max(round_rates), 1),
            }

            if latency_probe:
                n_probe = max(200, n_tasks // 3)
                await drain(next_id)
                await flood(next_id, n_probe, 8, record=True)
                next_id += n_probe
                latencies.sort()
                out["p50_ms"] = round(
                    statistics.median(latencies) * 1000.0, 2)
                out["p99_ms"] = round(
                    latencies[min(len(latencies) - 1,
                                  int(0.99 * len(latencies)))] * 1000.0, 2)
                # the unloaded service-time companion: one request in
                # flight, so nothing queues behind the pipeline's own
                # delivery work. On a 1-core host the conc-8 figure
                # above is dominated by queueing (Little's law: ~8 /
                # pipeline-throughput), not by the transport — this
                # number is the actual frontend->api round trip
                latencies.clear()
                await drain(next_id)
                await flood(next_id, 200, 1, record=True)
                latencies.sort()
                out["p50_sequential_ms"] = round(
                    statistics.median(latencies) * 1000.0, 2)
            return out
        finally:
            await fhost.stop()
    finally:
        if workers is not None:
            workers.stop()
        for var, val in pki_prev.items():
            if val is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = val
        conn = _count_conns.pop(tmp, None)
        if conn is not None:
            conn.close()


async def run_inproc(n_tasks: int = N_TASKS, *, warmup: int = WARMUP,
                     rounds: int = 3) -> float:
    """Round-1 continuity metric: the same pipeline with every app in
    one event loop (InProcCluster) — the fast local-dev mode."""
    from tasksrunner import App, InProcCluster

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-inproc-")
    api = _make_api_app()

    received = 0
    done = asyncio.Event()
    done_at = [0]
    processor = App("bench-processor")

    @processor.subscribe(pubsub="pubsub", topic="tasksavedtopic",
                         route="/on-saved")
    async def on_saved(req):
        nonlocal received
        received += 1
        if received >= done_at[0]:
            done.set()
        return 200

    cluster = InProcCluster(_component_specs(tmp))
    cluster.add_app(api)
    cluster.add_app(processor)
    await cluster.start()
    try:
        client = cluster.client("bench-api")

        async def create_task(i: int) -> None:
            resp = await client.invoke_method(
                "bench-api", "api/tasks", http_method="POST",
                data={"taskId": f"t{i}", "taskName": f"task {i}",
                      "taskCreatedBy": "bench@x.com",
                      "taskDueDate": "2026-08-01T00:00:00"})
            assert resp.status == 201, resp.body

        for i in range(warmup):
            await create_task(i)

        sem = asyncio.Semaphore(CONCURRENCY)

        async def bounded(i: int) -> None:
            async with sem:
                await create_task(i)

        # median of measured rounds after one discarded warmup round,
        # matching the cross-process metric's reporting (noise-aware)
        rates: list[float] = []
        next_id = warmup
        for r in range(rounds + 1):
            deadline = time.perf_counter() + 120
            while received < next_id:
                if time.perf_counter() > deadline:
                    raise RuntimeError(
                        f"delivery stalled: {received}/{next_id} events")
                await asyncio.sleep(0.005)
            done.clear()
            done_at[0] = next_id + n_tasks
            start = time.perf_counter()
            await asyncio.gather(
                *(bounded(i) for i in range(next_id, next_id + n_tasks)))
            next_id += n_tasks
            await asyncio.wait_for(done.wait(), timeout=120)
            if r > 0:  # round 0 is the warmup
                rates.append(n_tasks / (time.perf_counter() - start))
        return round(statistics.median(rates), 1)
    finally:
        await cluster.stop()


# ---------------------------------------------------------------------------
# state-store micro-bench: the durable engine measured alone
# ---------------------------------------------------------------------------

class _SeedSqliteStore:
    """The PRE-change state write path, frozen as the bench comparator:
    one inline BEGIN IMMEDIATE…COMMIT per save, executed directly on
    the event loop (the seed tasksrunner/state/sqlite.py). The ≥2x
    acceptance gate for the group-commit store measures against THIS,
    same run, same host."""

    def __init__(self, path: str):
        from tasksrunner.state.sqlite import _SCHEMA
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.executescript(_SCHEMA)
        self._conn.commit()
        self._returning = sqlite3.sqlite_version_info >= (3, 35, 0)

    async def set(self, key, value, *, etag=None):
        cur = self._conn.cursor()
        try:
            cur.execute("BEGIN IMMEDIATE")
            if self._returning:
                (n,) = cur.execute(
                    "UPDATE etag_seq SET n = n + 1 WHERE id = 1 RETURNING n"
                ).fetchone()
            else:
                cur.execute("UPDATE etag_seq SET n = n + 1 WHERE id = 1")
                (n,) = cur.execute(
                    "SELECT n FROM etag_seq WHERE id = 1").fetchone()
            doc = json.dumps(value, separators=(",", ":"), allow_nan=False)
            cur.execute(
                "INSERT INTO state(key, value, etag) VALUES(?, ?, ?) "
                "ON CONFLICT(key) DO UPDATE SET value=excluded.value, "
                "etag=excluded.etag",
                (key, doc, str(n)))
            self._conn.commit()
            return str(n)
        except BaseException:
            self._conn.rollback()
            raise

    async def get(self, key):
        row = self._conn.execute(
            "SELECT value, etag FROM state WHERE key = ?", (key,)).fetchone()
        return None if row is None else json.loads(row[0])

    def close(self):
        self._conn.close()


async def _state_op_rate(store, mode: str, n_ops: int, concurrency: int,
                         keys: list) -> float:
    # fixed worker loops, not a semaphore over n_ops gathered tasks:
    # each worker issues its next op as soon as its last resolves —
    # the request-handler pattern — and the harness itself stays thin
    # enough that the measurement is the store, not task scheduling
    per_worker = n_ops // concurrency

    async def worker(w: int) -> None:
        base = w * per_worker
        for i in range(base, base + per_worker):
            if mode == "write":
                await store.set(keys[i % len(keys)],
                                {"taskId": f"t{i}", "n": i,
                                 "taskCreatedBy": "bench@x.com"})
            else:
                await store.get(keys[i % len(keys)])

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(w) for w in range(concurrency)))
    return (per_worker * concurrency) / (time.perf_counter() - t0)


async def run_state_bench(n_ops: int = 4000, *, concurrency: int = 64,
                          rounds: int = 3, n_keys: int = 512) -> dict:
    """``state_ops_per_sec``: the durable sqlite state engine alone, no
    HTTP hops — the component the e2e write path bottlenecks on.

    write-heavy: ``concurrency`` coroutines upserting over ``n_keys``
    keys (the bench hot path's save_state pattern), measured twice in
    the same run — the seed one-commit-per-call path, then the shipping
    group-commit store. read-heavy: point gets over the same keys (the
    frontend's read-per-render pattern), on the off-loop SQL path and
    with the write-through LRU read cache enabled. Medians of
    ``rounds`` after a warmup round, like every other section.
    """
    from tasksrunner.state.sqlite import SqliteStateStore

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-state-")
    keys = [f"k{i}" for i in range(n_keys)]

    async def measure(store, mode: str) -> float:
        rates = []
        await _state_op_rate(store, mode, max(200, n_ops // 4),
                             concurrency, keys)  # warmup round, discarded
        for _ in range(rounds):
            rates.append(await _state_op_rate(store, mode, n_ops,
                                              concurrency, keys))
        return statistics.median(rates)

    seed = _SeedSqliteStore(f"{tmp}/seed.db")
    try:
        seed_write = await measure(seed, "write")
    finally:
        seed.close()

    store = SqliteStateStore("bench-state", f"{tmp}/state.db")
    try:
        gc_write = await measure(store, "write")
        plain_read = await measure(store, "read")
    finally:
        store.close()

    cached = SqliteStateStore("bench-state-cache", f"{tmp}/state.db",
                              cache_size=n_keys)
    try:
        # write-through: the cache fills from writes, as in the serving
        # pattern (the API writes what the frontend then re-reads)
        for i, k in enumerate(keys):
            await cached.set(k, {"taskId": f"t{i}", "n": i,
                                 "taskCreatedBy": "bench@x.com"})
        cached_read = await measure(cached, "read")
    finally:
        cached.close()

    return {
        "write_heavy": {
            "ops_per_sec": round(gc_write, 1),
            "pre_change_ops_per_sec": round(seed_write, 1),
            "speedup": round(gc_write / seed_write, 2),
            "concurrency": concurrency,
        },
        "read_heavy": {
            "ops_per_sec": round(plain_read, 1),
            "cached_ops_per_sec": round(cached_read, 1),
            "cache_speedup": round(cached_read / plain_read, 2),
            "concurrency": concurrency,
        },
        "note": "durable sqlite state engine measured alone (no HTTP "
                "hops): write-heavy = concurrent upserts through the "
                "group-commit queue vs the seed one-commit-per-call "
                "path in the same run; read-heavy = off-loop point "
                "gets vs the write-through LRU cache (readCacheSize)",
    }


async def run_shard_scaling_bench(n_ops: int = 6000, *, concurrency: int = 64,
                                  rounds: int = 3, n_keys: int = 2048,
                                  shard_counts: tuple = (1, 2, 4, 8)) -> dict:
    """``state_shard_scaling``: write-heavy throughput vs shard count.

    The same write-heavy mix as ``state_ops_per_sec`` (concurrent
    upserts over a shared key set), swept across the ``shards``
    component knob. ``shards: 1`` is the exact code path a default
    component gets (a plain SqliteStateStore, no facade) so its lane
    doubles as the no-regression control; N > 1 lanes run the
    rendezvous-sharded facade — N write queues, N writer threads, N
    WALs. Keys spread ~uniformly, so N shards ≈ N independent
    group-commit engines; scaling is bounded by cores and by the
    shared event loop issuing the ops.
    """
    from tasksrunner.state.sqlite import SqliteStateStore, build_sharded_store

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-shard-")
    keys = [f"task-{i}" for i in range(n_keys)]

    async def measure(store) -> float:
        rates = []
        await _state_op_rate(store, "write", max(200, n_ops // 4),
                             concurrency, keys)  # warmup round, discarded
        for _ in range(rounds):
            rates.append(await _state_op_rate(store, "write", n_ops,
                                              concurrency, keys))
        return statistics.median(rates)

    lanes: dict[int, float] = {}
    for n in shard_counts:
        path = f"{tmp}/shards{n}/state.db"
        store = (SqliteStateStore(f"bench-shard{n}", path) if n == 1
                 else build_sharded_store(f"bench-shard{n}", path, shards=n))
        try:
            lanes[n] = await measure(store)
        finally:
            store.close()

    base = lanes[shard_counts[0]]
    return {
        "write_heavy": {
            str(n): {
                "ops_per_sec": round(rate, 1),
                "speedup_vs_shards1": round(rate / base, 2) if base else None,
            }
            for n, rate in lanes.items()
        },
        "concurrency": concurrency,
        "n_keys": n_keys,
        "host_cpus": os.cpu_count(),
        "note": "write-heavy mix (concurrent upserts) swept over the "
                "`shards` component knob; shards:1 is the plain "
                "single-file engine (no facade) and the control lane, "
                "N>1 is the rendezvous-sharded facade with N "
                "independent group-commit write queues. Scaling needs "
                "cores for the N writer threads: on a 1-core host the "
                "sweep measures the facade's routing/fan-out overhead, "
                "not the parallel-commit speedup",
    }


async def run_chaos_overhead_bench(n_ops: int = 12000, *, concurrency: int = 64,
                                   rounds: int = 5, n_keys: int = 512) -> dict:
    """``chaos_overhead``: the fault-injection subsystem's "free when
    off" claim, measured on the write-heavy state path the e2e bench
    bottlenecks on.

    Three configurations of the SAME durable sqlite engine:

    * ``baseline`` — the store constructed directly;
    * ``gate_off`` — the store built through a ComponentRegistry with no
      chaos wiring (TASKSRUNNER_CHAOS unset, the production path). The
      registry returns the bare instance — asserted structurally AND
      measured, because the acceptance bar is a number, not an argument;
    * ``wrapped_idle`` — the worst enabled-but-quiet case: the chaos
      wrapper installed with its only rule runtime-disabled, so every op
      pays the injector hook but no fault fires.

    baseline and gate_off alternate within each round so host noise
    lands on both sides of the comparison.
    """
    from tasksrunner.chaos.engine import ChaosPolicies
    from tasksrunner.chaos.spec import parse_chaos
    from tasksrunner.chaos.wrappers import ChaosStateStore
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import parse_component
    from tasksrunner.state.sqlite import SqliteStateStore

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-chaos-")
    keys = [f"k{i}" for i in range(n_keys)]

    baseline = SqliteStateStore("bench-chaos-base", f"{tmp}/base.db")
    registry = ComponentRegistry(
        [parse_component({
            "componentType": "state.sqlite",
            "metadata": [{"name": "databasePath", "value": f"{tmp}/off.db"}],
        }, default_name="statestore")],
        app_id="bench")  # no chaos kwarg: exactly what a disabled host builds
    gate_off = registry.get("statestore")
    assert not isinstance(gate_off, ChaosStateStore), \
        "gate-off registry must return the bare store"
    policies = ChaosPolicies([parse_chaos({
        "kind": "Chaos", "metadata": {"name": "bench"},
        "spec": {
            "faults": {"flaky": {"error": {"raise": "StateError"}}},
            "targets": {"components": {"statestore": {"outbound": ["flaky"]}}},
        },
    })])
    policies.disable("flaky")
    wrapped_idle = ChaosStateStore(
        SqliteStateStore("bench-chaos-idle", f"{tmp}/idle.db"),
        policies.for_component("statestore"))

    stores = [("baseline", baseline), ("gate_off", gate_off),
              ("wrapped_idle", wrapped_idle)]
    rates: dict[str, list[float]] = {name: [] for name, _ in stores}
    try:
        for _, store in stores:  # warmup round, discarded
            await _state_op_rate(store, "write", max(200, n_ops // 4),
                                 concurrency, keys)
        for r in range(rounds):
            # rotate the order each round so slot-position effects (GC
            # pauses, page-cache warmth, the 1-core host's scheduler)
            # land on every store equally, not always on the same one
            for name, store in stores[r % len(stores):] + stores[:r % len(stores)]:
                rates[name].append(await _state_op_rate(
                    store, "write", n_ops, concurrency, keys))
    finally:
        baseline.close()
        gate_off.close()
        wrapped_idle.close()

    med = {name: statistics.median(rs) for name, rs in rates.items()}

    def overhead_pct(name: str) -> float:
        # PAIRED comparison: each round's rate is divided by the SAME
        # round's baseline rate before taking the median, so host noise
        # that slows a whole round (the dominant noise mode on this
        # 1-core box) cancels out of the ratio instead of landing on
        # whichever store it happened to hit
        per_round = [1.0 - rates[name][r] / rates["baseline"][r]
                     for r in range(len(rates[name]))]
        return round(statistics.median(per_round) * 100.0, 2)

    return {
        "baseline_ops_per_sec": round(med["baseline"], 1),
        "gate_off_ops_per_sec": round(med["gate_off"], 1),
        "gate_off_overhead_pct": overhead_pct("gate_off"),
        "gate_off_is_bare_instance": True,
        "wrapped_idle_ops_per_sec": round(med["wrapped_idle"], 1),
        "wrapped_idle_overhead_pct": overhead_pct("wrapped_idle"),
        "concurrency": concurrency,
        "note": "write-heavy state path. gate_off is the production "
                "configuration (TASKSRUNNER_CHAOS unset): the registry "
                "returns the unwrapped store, so the measured delta vs "
                "baseline is pure host noise — the acceptance bar is "
                "<1% net of that noise. wrapped_idle is the enabled-"
                "but-quiet wrapper (rule disabled at runtime), the real "
                "per-op cost of an injector hook that fires nothing",
    }


async def run_histogram_overhead_bench(n_ops: int = 12000, *,
                                       concurrency: int = 64,
                                       rounds: int = 5, n_keys: int = 512,
                                       n_msgs: int = 3000) -> dict:
    """``histogram_overhead``: the latency-histogram instrumentation's
    hot-path cost, measured through the real instrumented layers.

    Two paths, each measured with ``TASKSRUNNER_HISTOGRAMS`` on and off
    (the flag ``metrics.observe`` gates on):

    * write-heavy state: ``Runtime.save_state`` through the group-commit
      sqlite store — pays the per-op ``state_op_latency_seconds``
      observe plus the per-row queue-wait / per-batch commit observes
      inside the store;
    * publish/deliver: ``Runtime.publish`` through the real broker write
      queue plus the subscription handler delivering to a null app
      channel — pays ``publish_latency_seconds`` and
      ``delivery_latency_seconds``.

    on/off alternate order each round and the overhead is the median of
    PAIRED per-round ratios (the chaos bench's methodology), so whole-
    round host noise cancels out of the number.
    """
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import parse_component
    from tasksrunner.observability.metrics import metrics
    from tasksrunner.pubsub.base import Message
    from tasksrunner.runtime import Runtime

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-hist-")
    keys = [f"k{i}" for i in range(n_keys)]

    class _NullChannel:
        async def request(self, method, path, query="", headers=None,
                          body=b""):
            return 200, {}, b"{}"

        async def close(self):
            pass

    registry = ComponentRegistry(
        [parse_component({
            "componentType": "state.sqlite",
            "metadata": [{"name": "databasePath",
                          "value": f"{tmp}/state.db"}],
        }, default_name="statestore"),
         parse_component({
            "componentType": "pubsub.sqlite",
            "metadata": [{"name": "brokerPath",
                          "value": f"{tmp}/broker.db"}],
        }, default_name="taskspubsub")],
        app_id="bench")
    runtime = Runtime("bench", registry, app_channel=_NullChannel())
    deliver = runtime._make_subscription_handler(
        "taskspubsub", "/api/bench/tasksaved")

    async def save_rate(n: int) -> float:
        per_worker = n // concurrency

        async def worker(w: int) -> None:
            base = w * per_worker
            for i in range(base, base + per_worker):
                await runtime.save_state("statestore", [
                    {"key": keys[i % len(keys)],
                     "value": {"taskId": f"t{i}", "n": i}}])

        t0 = time.perf_counter()
        await asyncio.gather(*(worker(w) for w in range(concurrency)))
        return (per_worker * concurrency) / (time.perf_counter() - t0)

    async def pubsub_rate(n: int) -> float:
        per_worker = n // concurrency

        async def worker(w: int) -> None:
            base = w * per_worker
            for i in range(base, base + per_worker):
                await runtime.publish("taskspubsub", "tasksaved", {"n": i})
                await deliver(Message(id=f"m{w}-{i}", topic="tasksaved",
                                      data={"n": i}))

        t0 = time.perf_counter()
        await asyncio.gather(*(worker(w) for w in range(concurrency)))
        # each iteration is one publish + one delivery
        return (2 * per_worker * concurrency) / (time.perf_counter() - t0)

    configs = [("hist_on", True), ("hist_off", False)]
    rates: dict[str, dict[str, list[float]]] = {
        "state": {name: [] for name, _ in configs},
        "pubsub": {name: [] for name, _ in configs},
    }
    was_enabled = metrics.histograms_enabled
    try:
        await save_rate(max(200, n_ops // 4))  # warmup round, discarded
        await pubsub_rate(max(200, n_msgs // 4))
        for r in range(rounds):
            for name, enabled in (configs if r % 2 == 0
                                  else list(reversed(configs))):
                metrics.histograms_enabled = enabled
                rates["state"][name].append(await save_rate(n_ops))
                rates["pubsub"][name].append(await pubsub_rate(n_msgs))
    finally:
        metrics.histograms_enabled = was_enabled
        await runtime.stop()

    def section(path: str) -> dict:
        med = {name: statistics.median(rs)
               for name, rs in rates[path].items()}
        per_round = [1.0 - rates[path]["hist_on"][r] / rates[path]["hist_off"][r]
                     for r in range(rounds)]
        return {
            "hist_on_ops_per_sec": round(med["hist_on"], 1),
            "hist_off_ops_per_sec": round(med["hist_off"], 1),
            "overhead_pct": round(statistics.median(per_round) * 100.0, 2),
        }

    return {
        "state_write": section("state"),
        "publish_deliver": section("pubsub"),
        "concurrency": concurrency,
        "note": "histograms-on vs TASKSRUNNER_HISTOGRAMS=0 through the "
                "real instrumented layers (Runtime + group-commit store "
                "+ broker write queue + subscription delivery); paired "
                "per-round ratios with alternating order, median of "
                f"{rounds} rounds — the acceptance bar is <3% on both "
                "paths",
    }


async def run_trace_overhead_bench(n_ops: int = 6000, *,
                                   concurrency: int = 64,
                                   rounds: int = 5, n_keys: int = 512,
                                   n_msgs: int = 2000,
                                   n_turns: int = 1200,
                                   n_notes: int = 200000) -> dict:
    """``trace_overhead``: causal tracing's hot-path cost, on vs off.

    Three instrumented paths, each measured with the span recorder
    configured (``TASKSRUNNER_TRACE_DB`` set — spans buffered and
    flushed off the hot path) and with it absent (the production
    default; every ``record_span`` / ``spans.active()`` site is one
    ``if``):

    * write-heavy state: ``Runtime.save_state`` through the
      group-commit sqlite store — pays the state-write span with
      queue-wait/service attrs per batch row;
    * publish/deliver: ``Runtime.publish`` + subscription delivery —
      pays the producer span and the delivery-side trace adoption;
    * actor turns: ``Runtime.invoke_actor`` on a local owner — pays
      the ACTOR server span plus the turn's state-commit span.

    All workers run inside an ambient trace scope in BOTH configs, so
    the measured delta is recording, not context management. on/off
    alternate order each round; overhead is the median of PAIRED
    per-round ratios (the chaos bench's methodology). The acceptance
    bar is <3% with tracing on and ~0% off.

    A fourth section times the flight recorder's ``note_request``
    (ring append) against the disabled path (``_flightrec is None`` —
    one ``if``), reported as ns/op for both.
    """
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import ComponentSpec
    from tasksrunner.app import App
    from tasksrunner.observability import flightrec as flightrec_mod
    from tasksrunner.observability import spans as spans_mod
    from tasksrunner.observability.tracing import ensure_trace, trace_scope
    from tasksrunner.pubsub.base import Message
    from tasksrunner.runtime import InProcAppChannel, Runtime

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-trace-")
    keys = [f"k{i}" for i in range(n_keys)]

    def build_app() -> App:
        app = App("bench-trace")

        @app.actor("Counter")
        async def counter(turn):
            turn.state["n"] = turn.state.get("n", 0) + 1
            return turn.state["n"]

        return app

    saved_env = {k: os.environ.get(k) for k in (
        "TASKSRUNNER_ACTORS", "TASKSRUNNER_ACTOR_LEASE_SECONDS",
        "TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS")}
    os.environ["TASKSRUNNER_ACTORS"] = "1"
    # leases must outlive the WHOLE bench: an expiry mid-run lets two
    # concurrent turns race the re-activation and one gets fenced
    os.environ["TASKSRUNNER_ACTOR_LEASE_SECONDS"] = "3600"
    os.environ["TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS"] = "3600"

    registry = ComponentRegistry(
        [ComponentSpec(name="statestore", type="state.sqlite",
                       metadata={"databasePath": f"{tmp}/state.db"}),
         ComponentSpec(name="taskspubsub", type="pubsub.sqlite",
                       metadata={"brokerPath": f"{tmp}/broker.db"})],
        app_id="bench-trace")
    runtime = Runtime("bench-trace", registry,
                      app_channel=InProcAppChannel(build_app()))
    await runtime.start()
    deliver = runtime._make_subscription_handler(
        "taskspubsub", "/api/bench/tasksaved")

    saved_recorder = spans_mod._recorder
    recorder = spans_mod.SpanRecorder("bench", f"{tmp}/traces.db")

    def set_tracing(on: bool) -> None:
        spans_mod._recorder = recorder if on else None

    actor_ids = [f"a{i}" for i in range(64)]

    async def save_rate(n: int) -> float:
        per_worker = n // concurrency

        async def worker(w: int) -> None:
            with trace_scope(ensure_trace()):
                base = w * per_worker
                for i in range(base, base + per_worker):
                    await runtime.save_state("statestore", [
                        {"key": keys[i % len(keys)],
                         "value": {"taskId": f"t{i}", "n": i}}])

        t0 = time.perf_counter()
        await asyncio.gather(*(worker(w) for w in range(concurrency)))
        return (per_worker * concurrency) / (time.perf_counter() - t0)

    async def pubsub_rate(n: int) -> float:
        per_worker = n // concurrency

        async def worker(w: int) -> None:
            with trace_scope(ensure_trace()):
                base = w * per_worker
                for i in range(base, base + per_worker):
                    await runtime.publish(
                        "taskspubsub", "tasksaved", {"n": i})
                    await deliver(Message(id=f"m{w}-{i}", topic="tasksaved",
                                          data={"n": i}))

        t0 = time.perf_counter()
        await asyncio.gather(*(worker(w) for w in range(concurrency)))
        return (2 * per_worker * concurrency) / (time.perf_counter() - t0)

    async def turn_rate(n: int) -> float:
        per_worker = n // concurrency

        async def worker(w: int) -> None:
            with trace_scope(ensure_trace()):
                for i in range(per_worker):
                    await runtime.invoke_actor(
                        "Counter", actor_ids[(w + i) % len(actor_ids)],
                        "bump")

        t0 = time.perf_counter()
        await asyncio.gather(*(worker(w) for w in range(concurrency)))
        return (per_worker * concurrency) / (time.perf_counter() - t0)

    paths = {"state": (save_rate, n_ops),
             "pubsub": (pubsub_rate, n_msgs),
             "actor": (turn_rate, n_turns)}
    configs = [("trace_on", True), ("trace_off", False)]
    rates: dict[str, dict[str, list[float]]] = {
        path: {name: [] for name, _ in configs} for path in paths}
    try:
        set_tracing(False)  # warmup round, discarded
        # activate every actor id serially first: two concurrent first
        # touches of one id race _activate and the loser gets fenced
        for aid in actor_ids:
            await runtime.invoke_actor("Counter", aid, "bump")
        for fn, n in paths.values():
            await fn(max(200, n // 4))
        for r in range(rounds):
            for name, on in (configs if r % 2 == 0
                             else list(reversed(configs))):
                set_tracing(on)
                for path, (fn, n) in paths.items():
                    rates[path][name].append(await fn(n))
    finally:
        spans_mod._recorder = saved_recorder
        recorder.close()
        if runtime.actors is not None:
            await runtime.actors.stop()
            runtime.actors = None
        await runtime.stop()
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value

    # -- flight recorder: ring append vs the disabled one-``if`` path ----
    saved_flightrec = flightrec_mod._flightrec
    note_ns: dict[str, float] = {}
    try:
        for name, rec in (("on", flightrec_mod.FlightRecorder(
                               "bench", out_dir=f"{tmp}/flightrec")),
                          ("off", None)):
            flightrec_mod._flightrec = rec
            t0 = time.perf_counter()
            for i in range(n_notes):
                flightrec_mod.note_request(
                    name="POST /bench", trace_id=None, status=200,
                    duration=0.001)
            note_ns[name] = ((time.perf_counter() - t0) / n_notes) * 1e9
    finally:
        flightrec_mod._flightrec = saved_flightrec

    def section(path: str) -> dict:
        med = {name: statistics.median(rs)
               for name, rs in rates[path].items()}
        per_round = [
            1.0 - rates[path]["trace_on"][r] / rates[path]["trace_off"][r]
            for r in range(rounds)]
        return {
            "trace_on_ops_per_sec": round(med["trace_on"], 1),
            "trace_off_ops_per_sec": round(med["trace_off"], 1),
            "overhead_pct": round(statistics.median(per_round) * 100.0, 2),
        }

    return {
        "state_write": section("state"),
        "publish_deliver": section("pubsub"),
        "actor_turn": section("actor"),
        "flightrec_note": {
            "on_ns_per_note": round(note_ns["on"], 1),
            "off_ns_per_note": round(note_ns["off"], 1),
            "delta_ns": round(note_ns["on"] - note_ns["off"], 1),
        },
        "concurrency": concurrency,
        "cpus": os.cpu_count(),
        "note": "span recorder configured vs absent (the "
                "TASKSRUNNER_TRACE_DB-unset default) through the real "
                "instrumented layers; ambient trace scope active in "
                "both configs so the delta is recording alone; paired "
                "per-round ratios with alternating order, median of "
                f"{rounds} rounds — the bar is <3% on, ~0% off, and it "
                "presumes the flush thread has a spare core: on a "
                "1-cpu host the ratio additionally charges the whole "
                "flush-thread share (json + sqlite for every span) to "
                "the hot path; the flight-recorder section is the ring "
                "append vs the disabled one-if path, in ns per note",
    }


async def run_admission_overhead_bench(n_ops: int = 3000, *,
                                       concurrency: int = 32,
                                       rounds: int = 5) -> dict:
    """``admission_overhead``: the admission controller's "free when
    off" claim, measured on the ingress path it guards.

    Three configurations of the SAME echo app behind the real aiohttp
    app server (``hosting.build_app_server``), flooded over localhost:

    * ``baseline`` — no controller (``admission=None``), the code path
      before this subsystem existed;
    * ``gate_off`` — the production default: ``TASKSRUNNER_ADMISSION``
      unset, ``from_env()`` returns None — asserted structurally AND
      measured, because the <1% acceptance bar is a number, not an
      argument;
    * ``attached_idle`` — the enabled-but-admitting worst quiet case:
      a live controller (sampler running) that never sheds, so every
      request pays the ``admission.shedding`` check and nothing else.

    Order rotates each round; the overhead is the median of PAIRED
    per-round ratios (the chaos bench's methodology).
    """
    import aiohttp
    from aiohttp import web

    from tasksrunner.app import App
    from tasksrunner.hosting import build_app_server
    from tasksrunner.observability.admission import AdmissionController
    from tasksrunner.observability.metrics import MetricsRegistry

    prev_flag = os.environ.pop("TASKSRUNNER_ADMISSION", None)
    controller = AdmissionController(
        max_lag_seconds=0.25, max_queue_depth=512, max_inflight=10 ** 9,
        registry=MetricsRegistry())

    def make_server(admission):
        app = App("bench-admission")

        @app.post("/api/echo")
        async def echo(req):
            return {"ok": True}

        return build_app_server(app, admission=admission)

    runners, ports = [], {}
    try:
        gate_off = AdmissionController.from_env()
        assert gate_off is None, \
            "gate-off from_env() must return no controller"
        configs = [("baseline", make_server(None)),
                   ("gate_off", make_server(gate_off)),
                   ("attached_idle", make_server(controller))]
        for name, server in configs:
            runner = web.AppRunner(server)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            ports[name] = runner.addresses[0][1]
        controller.start()

        rates: dict[str, list[float]] = {name: [] for name, _ in configs}
        per_worker = n_ops // concurrency

        async with aiohttp.ClientSession() as session:

            async def rate(name: str, n_per_worker: int) -> float:
                url = f"http://127.0.0.1:{ports[name]}/api/echo"

                async def worker() -> None:
                    for _ in range(n_per_worker):
                        async with session.post(url, json={}) as resp:
                            await resp.read()
                            assert resp.status == 200

                t0 = time.perf_counter()
                await asyncio.gather(*(worker() for _ in range(concurrency)))
                return (n_per_worker * concurrency) / (time.perf_counter() - t0)

            for name, _ in configs:  # warmup round, discarded
                await rate(name, max(2, per_worker // 4))
            for r in range(rounds):
                order = configs[r % len(configs):] + configs[:r % len(configs)]
                for name, _ in order:
                    rates[name].append(await rate(name, per_worker))
    finally:
        await controller.stop()
        for runner in runners:
            await runner.cleanup()
        if prev_flag is not None:
            os.environ["TASKSRUNNER_ADMISSION"] = prev_flag

    med = {name: statistics.median(rs) for name, rs in rates.items()}

    def overhead_pct(name: str) -> float:
        per_round = [1.0 - rates[name][r] / rates["baseline"][r]
                     for r in range(len(rates[name]))]
        return round(statistics.median(per_round) * 100.0, 2)

    return {
        "baseline_req_per_sec": round(med["baseline"], 1),
        "gate_off_req_per_sec": round(med["gate_off"], 1),
        "gate_off_overhead_pct": overhead_pct("gate_off"),
        "gate_off_is_none": True,
        "attached_idle_req_per_sec": round(med["attached_idle"], 1),
        "attached_idle_overhead_pct": overhead_pct("attached_idle"),
        "concurrency": concurrency,
        "note": "ingress path (real aiohttp app server, localhost "
                "flood). gate_off is the production default "
                "(TASKSRUNNER_ADMISSION unset -> no controller object "
                "at all), so its delta vs baseline is pure host noise "
                "— the acceptance bar is <1% net of that noise. "
                "attached_idle is the per-request cost of one attribute "
                "check plus a background sampler at 4 Hz",
    }


async def run_overload_drill_bench() -> dict:
    """``overload_drill``: the closed loop (shed → scale out → recover,
    zero lost acks) run end to end against real subprocess replicas and
    a chaos-slowed store; prints the measured trajectory. The test
    suite asserts this trajectory (tests/test_overload_drill.py); the
    bench records it next to the numbers docs module 09 quotes."""
    import pathlib

    from tasksrunner.testing.overload import run_overload_drill

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-overload-")
    return await run_overload_drill(pathlib.Path(tmp))


async def run_actor_bench(n_turns: int = 1600, *, concurrency: int = 32,
                          ingress_ops: int = 3000,
                          ingress_concurrency: int = 32,
                          rounds: int = 5) -> dict:
    """``actor_bench``: the virtual-actor subsystem's three numbers.

    * **turn throughput** — acked turns/s through the full path
      (placement lookup → per-actor lock → app handler → etag-guarded
      state commit → ack) over 64 actors, plus one actor alone (turns
      on a single id are serialized by design, so this is the per-actor
      ceiling, not a defect);
    * **failover drill** — two replicas over one store; the owner takes
      acked turns and holds a periodic reminder, then crashes WITHOUT
      releasing its lease (the hard case). Reported: time until the
      survivor completes a turn on the same actor (bounded by the lease
      TTL), time until the reminder fires again on the survivor, and
      the lost-acked-turns count (must be 0 — the next turn's counter
      value proves every pre-crash ack survived);
    * **gate-off ingress overhead** — the sidecar with
      ``TASKSRUNNER_ACTORS`` unset has a route table with NO actor
      routes (asserted structurally — byte-identical dispatch to the
      pre-actors sidecar), and the healthz flood measures that as a
      number vs an independently built baseline server; ``enabled`` is
      the route-table cost of the five actor routes on non-actor
      traffic. Order rotates each round; overhead is the median of
      PAIRED per-round ratios (the chaos bench's methodology).
    """
    import aiohttp
    from aiohttp import web

    from tasksrunner.app import App
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import ComponentSpec
    from tasksrunner.errors import TasksRunnerError
    from tasksrunner.runtime import InProcAppChannel, Runtime
    from tasksrunner.sidecar import build_sidecar_app
    from tasksrunner.state.memory import InMemoryStateStore

    saved = {k: os.environ.get(k) for k in (
        "TASKSRUNNER_ACTORS", "TASKSRUNNER_ACTOR_LEASE_SECONDS",
        "TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS")}

    def build_app() -> App:
        app = App("bench-actors")

        @app.actor("Counter")
        async def counter(turn):
            if turn.is_reminder:
                turn.state["reminded"] = turn.state.get("reminded", 0) + 1
                return None
            turn.state["n"] = turn.state.get("n", 0) + 1
            return turn.state["n"]

        return app

    def make_runtime(shared) -> Runtime:
        spec = ComponentSpec(name="statestore", type="state.in-memory")
        reg = ComponentRegistry([spec], app_id="bench-actors")
        reg._instances["statestore"] = shared
        return Runtime("bench-actors", reg,
                       app_channel=InProcAppChannel(build_app()))

    out: dict = {}
    lease_seconds = 0.4
    os.environ["TASKSRUNNER_ACTORS"] = "1"
    os.environ["TASKSRUNNER_ACTOR_LEASE_SECONDS"] = str(lease_seconds)
    os.environ["TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS"] = "0.05"
    try:
        # -- turn throughput ---------------------------------------------
        rt = make_runtime(InMemoryStateStore("statestore"))
        await rt.start()
        assert rt.actors is not None
        ids = [f"a{i}" for i in range(64)]
        per_worker = n_turns // concurrency

        async def turn_worker(w: int) -> None:
            for i in range(per_worker):
                await rt.invoke_actor(
                    "Counter", ids[(w + i) % len(ids)], "bump")

        t0 = time.perf_counter()
        await asyncio.gather(*(turn_worker(w) for w in range(concurrency)))
        many = (per_worker * concurrency) / (time.perf_counter() - t0)

        t0 = time.perf_counter()
        for _ in range(200):
            await rt.invoke_actor("Counter", "serial", "bump")
        serial = 200 / (time.perf_counter() - t0)
        await rt.stop()
        out["turns"] = {
            "turns_per_sec_64_actors": round(many, 1),
            "turns_per_sec_single_actor": round(serial, 1),
            "concurrency": concurrency,
            "note": "in-memory store; single-actor turns are serialized "
                    "by the turn-based concurrency contract, so that "
                    "figure is the per-actor ceiling",
        }

        # -- failover drill ----------------------------------------------
        shared = InMemoryStateStore("statestore")
        r1, r2 = make_runtime(shared), make_runtime(shared)
        await r1.start()
        await r2.start()
        acked = 0
        for _ in range(25):
            acked = await r1.invoke_actor("Counter", "fo", "bump")
        await r1.register_actor_reminder(
            "Counter", "fo", "tick", due_seconds=0.0, period_seconds=0.15)
        await r1.actors.sweep()  # the reminder fires once pre-crash
        r1.actors.simulate_crash()
        t0 = time.perf_counter()
        while True:
            try:
                v = await r2.invoke_actor("Counter", "fo", "bump")
                break
            except TasksRunnerError:
                await asyncio.sleep(0.02)
        failover_ms = (time.perf_counter() - t0) * 1000.0
        refire_ms = None
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 5.0:
            doc = await r2.get_actor_state("Counter", "fo")
            if doc["data"].get("reminded", 0) >= 2:
                refire_ms = round((time.perf_counter() - t0) * 1000.0, 1)
                break
            await asyncio.sleep(0.02)
        await r2.stop()
        r1.actors = None  # crashed replica: nothing to release
        await r1.stop()
        out["failover"] = {
            "acked_turns_before_crash": acked,
            "lost_acked_turns": (acked + 1) - v,
            "failover_ms": round(failover_ms, 1),
            "lease_seconds": lease_seconds,
            "reminder_refire_ms": refire_ms,
            "note": "crash WITHOUT lease release — failover is bounded "
                    "by the lease TTL; the survivor's first turn "
                    "returning acked+1 proves zero lost acked turns",
        }
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val

    # -- gate-off ingress overhead ---------------------------------------
    def make_sidecar(flag_on: bool) -> web.Application:
        prev = os.environ.pop("TASKSRUNNER_ACTORS", None)
        if flag_on:
            os.environ["TASKSRUNNER_ACTORS"] = "1"
        try:
            return build_sidecar_app(
                make_runtime(InMemoryStateStore("statestore")),
                api_token=None, peer_tokens=set())
        finally:
            if prev is None:
                os.environ.pop("TASKSRUNNER_ACTORS", None)
            else:
                os.environ["TASKSRUNNER_ACTORS"] = prev

    def has_actor_routes(webapp: web.Application) -> bool:
        return any("/v1.0/actors" in str(r.resource.canonical)
                   for r in webapp.router.routes()
                   if r.resource is not None)

    configs = [("baseline", make_sidecar(False)),
               ("gate_off", make_sidecar(False)),
               ("enabled", make_sidecar(True))]
    by_name = dict(configs)
    assert not has_actor_routes(by_name["gate_off"]), \
        "gate-off sidecar must not register actor routes"
    assert has_actor_routes(by_name["enabled"])

    runners, ports = [], {}
    rates: dict[str, list[float]] = {name: [] for name, _ in configs}
    try:
        for name, server in configs:
            runner = web.AppRunner(server)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            ports[name] = runner.addresses[0][1]

        per_worker = ingress_ops // ingress_concurrency
        async with aiohttp.ClientSession() as session:

            async def rate(name: str, n_per_worker: int) -> float:
                url = f"http://127.0.0.1:{ports[name]}/v1.0/healthz"

                async def worker() -> None:
                    for _ in range(n_per_worker):
                        async with session.get(url) as resp:
                            await resp.read()
                            assert resp.status == 204

                t0 = time.perf_counter()
                await asyncio.gather(
                    *(worker() for _ in range(ingress_concurrency)))
                return ((n_per_worker * ingress_concurrency)
                        / (time.perf_counter() - t0))

            for name, _ in configs:  # warmup round, discarded
                await rate(name, max(2, per_worker // 4))
            for r in range(rounds):
                order = configs[r % len(configs):] + configs[:r % len(configs)]
                for name, _ in order:
                    rates[name].append(await rate(name, per_worker))
    finally:
        for runner in runners:
            await runner.cleanup()

    med = {name: statistics.median(rs) for name, rs in rates.items()}

    def overhead_pct(name: str) -> float:
        per_round = [1.0 - rates[name][r] / rates["baseline"][r]
                     for r in range(len(rates[name]))]
        return round(statistics.median(per_round) * 100.0, 2)

    out["ingress"] = {
        "baseline_req_per_sec": round(med["baseline"], 1),
        "gate_off_req_per_sec": round(med["gate_off"], 1),
        "gate_off_overhead_pct": overhead_pct("gate_off"),
        "gate_off_route_table_has_actor_routes": False,
        "enabled_req_per_sec": round(med["enabled"], 1),
        "enabled_overhead_pct": overhead_pct("enabled"),
        "concurrency": ingress_concurrency,
        "note": "sidecar healthz flood (real aiohttp server, "
                "localhost). gate_off is the production default "
                "(TASKSRUNNER_ACTORS unset -> the actor routes are "
                "never registered, asserted structurally), so its "
                "delta vs baseline is pure host noise — the "
                "acceptance bar is <1% net of that noise. enabled "
                "measures the five extra routes' dispatch cost on "
                "non-actor traffic",
    }
    return out


async def run_workflow_bench(n_sagas: int = 160, *, concurrency: int = 16,
                             chain_instances: int = 40,
                             chain_steps: int = 5) -> dict:
    """``workflow_bench``: the durable-workflow subsystem's three numbers.

    * **saga throughput** — completed checkout-shaped sagas/s through
      the full path (start -> replay -> 5 activities with staged
      effects and registered compensations -> terminal commit), driven
      concurrently;
    * **replay-recovery drill** — two replicas over one store; the
      owner commits a prefix of a long sequential workflow and crashes
      WITHOUT releasing its lease. Reported: time until a survivor's
      sweep adopts the instance and replay runs it to completion, plus
      the effect audit (every activity's staged effect present exactly
      once — the committed prefix did NOT re-execute its effects);
    * **history-append overhead** — matched concurrent runs of
      workflow activity steps vs bare actor turns on the same store:
      a workflow step pays the actor turn plus orchestrator replay,
      history append, and effect staging, and the ratio prices that.
    """
    from tasksrunner.app import App
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.component.spec import ComponentSpec
    from tasksrunner.runtime import InProcAppChannel, Runtime
    from tasksrunner.state.memory import InMemoryStateStore

    saved = {k: os.environ.get(k) for k in (
        "TASKSRUNNER_WORKFLOWS", "TASKSRUNNER_ACTORS",
        "TASKSRUNNER_ACTOR_LEASE_SECONDS",
        "TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS")}

    def build_app() -> App:
        app = App("bench-workflows")

        @app.actor("Counter")
        async def counter(turn):
            turn.state["n"] = turn.state.get("n", 0) + 1
            return turn.state["n"]

        @app.workflow("saga")
        async def saga(ctx, order):
            for i in range(3):
                stock = await ctx.call_activity("reserve", {"i": i})
                ctx.register_compensation("release", stock)
            receipt = await ctx.call_activity("charge", order)
            ctx.register_compensation("refund", receipt)
            await ctx.call_activity("confirm", order)
            return receipt

        @app.workflow("chain")
        async def chain(ctx, n):
            total = 0
            for i in range(n):
                total += await ctx.call_activity("step", {"i": i})
            return total

        @app.activity("reserve")
        async def reserve(actx, data):
            actx.stage_effect(f"res||{actx.instance}||{data['i']}", data)
            return data

        @app.activity("release")
        async def release(actx, data):
            actx.stage_effect(f"res||{actx.instance}||{data['i']}",
                              operation="delete")
            return data["i"]

        @app.activity("charge")
        async def charge(actx, order):
            actx.stage_effect(f"charge||{actx.instance}", order)
            return {"amount": (order or {}).get("amount", 0)}

        @app.activity("refund")
        async def refund(actx, receipt):
            actx.stage_effect(f"charge||{actx.instance}",
                              operation="delete")
            return receipt

        @app.activity("confirm")
        async def confirm(actx, order):
            actx.stage_effect(f"confirm||{actx.instance}", order)
            return True

        @app.activity("step")
        async def step(actx, data):
            actx.stage_effect(f"eff||{actx.instance}||{actx.seq}", data)
            return 1

        @app.workflow("slowchain")
        async def slowchain(ctx, n):
            total = 0
            for i in range(n):
                total += await ctx.call_activity("slowstep", {"i": i})
            return total

        @app.activity("slowstep")
        async def slowstep(actx, data):
            await asyncio.sleep(0.02)  # a real activity does real work
            actx.stage_effect(f"eff||{actx.instance}||{actx.seq}", data)
            return 1

        return app

    def make_runtime(shared) -> Runtime:
        spec = ComponentSpec(name="statestore", type="state.in-memory")
        reg = ComponentRegistry([spec], app_id="bench-workflows")
        reg._instances["statestore"] = shared
        return Runtime("bench-workflows", reg,
                       app_channel=InProcAppChannel(build_app()))

    async def boot(shared, *, replay_batch: int | None = None) -> Runtime:
        rt = make_runtime(shared)
        await rt.start()
        assert rt.actors is not None and rt.workflows is not None
        rt.app_channel.app.workflow_engine.drive_period = 0.05
        if replay_batch is not None:
            rt.app_channel.app.workflow_engine.replay_batch = replay_batch
        return rt

    async def shutdown(rt, *, crashed: bool = False) -> None:
        if rt.workflows is not None:
            rt.workflows.detach()
            rt.workflows = None
        if crashed:
            rt.actors = None  # crashed replica: nothing to release
        elif rt.actors is not None:
            await rt.actors.stop()
            rt.actors = None
        await rt.stop()

    out: dict = {}
    lease_seconds = 0.4
    os.environ["TASKSRUNNER_WORKFLOWS"] = "1"
    os.environ["TASKSRUNNER_ACTOR_LEASE_SECONDS"] = str(lease_seconds)
    os.environ["TASKSRUNNER_ACTOR_REMINDER_POLL_SECONDS"] = "0.05"
    try:
        # -- saga throughput ---------------------------------------------
        rt = await boot(InMemoryStateStore("statestore"))
        per_worker = n_sagas // concurrency

        async def saga_worker(w: int) -> None:
            for i in range(per_worker):
                inst = await rt.workflows.start(
                    "saga", {"amount": 9.99}, instance=f"saga-{w}-{i}")
                status = await rt.workflows.wait(inst, timeout=30,
                                                 poll=0.005)
                assert status["status"] == "completed"

        t0 = time.perf_counter()
        await asyncio.gather(*(saga_worker(w) for w in range(concurrency)))
        sagas_per_sec = (per_worker * concurrency) / (time.perf_counter() - t0)
        await shutdown(rt)
        out["saga"] = {
            "sagas_per_sec": round(sagas_per_sec, 1),
            "activities_per_saga": 5,
            "concurrency": concurrency,
            "note": "checkout-shaped: 3 reserves (compensations "
                    "registered) + charge + confirm, every activity "
                    "staging an effect; in-memory store",
        }

        # -- replay-recovery drill ---------------------------------------
        # replay_batch=1 -> one commit per step, so the crash lands
        # mid-story at step granularity and the survivor's first new
        # commit measures adoption + replay, not leftover batch work
        shared = InMemoryStateStore("statestore")
        r1 = await boot(shared, replay_batch=1)
        r2 = await boot(shared, replay_batch=1)
        steps_total = 30
        inst = "recover-1"
        # start() drives the instance inline until it suspends or
        # finishes, so run it in the background and fell the owner as
        # soon as a committed prefix is visible in the shared store
        start_task = asyncio.ensure_future(
            r1.workflows.start("slowchain", steps_total, instance=inst))
        while await shared.get(f"bench-workflows||eff||{inst}||5") is None:
            await asyncio.sleep(0.002)
        r1.actors.simulate_crash()
        start_task.cancel()
        try:
            await start_task
        except (Exception, asyncio.CancelledError):
            pass  # the owner died mid-drive; that is the point

        async def committed_steps() -> int:
            history = await r2.workflows.history(inst)
            return len([e for e in history
                        if e["t"] == "activity_completed"])

        committed = await committed_steps()
        # recovery latency: crash -> the survivor's FIRST new commit
        # (sweep adopts, replay sprints the prefix, next step lands)
        t0 = time.perf_counter()
        while await committed_steps() <= committed:
            await r2.actors.sweep()
            assert time.perf_counter() - t0 < 30.0
            await asyncio.sleep(0.005)
        recovery_ms = (time.perf_counter() - t0) * 1000.0
        while True:
            await r2.actors.sweep()
            status = await r2.workflows.status(inst)
            if status["status"] == "completed":
                break
            assert time.perf_counter() - t0 < 30.0, status
            await asyncio.sleep(0.01)
        assert status["result"] == steps_total
        missing = [seq for seq in range(1, steps_total + 1)
                   if await shared.get(
                       f"bench-workflows||eff||{inst}||{seq}") is None]
        await shutdown(r2)
        await shutdown(r1, crashed=True)
        out["recovery"] = {
            "recovery_ms": round(recovery_ms, 1),
            "committed_steps_at_crash": committed,
            "steps_total": steps_total,
            "missing_effects": missing,
            "lease_seconds": lease_seconds,
            "note": "owner crashes WITHOUT lease release mid-workflow; "
                    "recovery = time to the survivor's first post-"
                    "crash commit (sweep adopts -> replay sprints the "
                    "committed prefix -> next step lands), dominated "
                    "by the lease TTL the dead owner still holds. "
                    "missing_effects must be [] "
                    "(exactly-once: the prefix did not re-stage, the "
                    "tail all landed)",
        }

        # -- history-append overhead vs bare actor turn ------------------
        rt = await boot(InMemoryStateStore("statestore"))
        n_turns = chain_instances * chain_steps

        async def bump_worker(w: int) -> None:
            for i in range(n_turns // concurrency):
                await rt.invoke_actor("Counter", f"c{w}", "bump")

        t0 = time.perf_counter()
        await asyncio.gather(*(bump_worker(w) for w in range(concurrency)))
        actor_turns_per_sec = n_turns / (time.perf_counter() - t0)

        async def chain_worker(w: int) -> None:
            for i in range(chain_instances // concurrency):
                inst = await rt.workflows.start(
                    "chain", chain_steps, instance=f"chain-{w}-{i}")
                status = await rt.workflows.wait(inst, timeout=30,
                                                 poll=0.005)
                assert status["status"] == "completed"

        t0 = time.perf_counter()
        await asyncio.gather(*(chain_worker(w) for w in range(concurrency)))
        step_turns_per_sec = n_turns / (time.perf_counter() - t0)
        await shutdown(rt)
        out["turn_overhead"] = {
            "actor_turns_per_sec": round(actor_turns_per_sec, 1),
            "workflow_steps_per_sec": round(step_turns_per_sec, 1),
            "overhead_ratio": round(
                actor_turns_per_sec / step_turns_per_sec, 2),
            "chain_steps": chain_steps,
            "concurrency": concurrency,
            "note": "same store, same concurrency: a workflow step is "
                    "an actor turn plus orchestrator replay, history "
                    "append, and effect staging — the ratio is the "
                    "price of durability per step",
        }
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
    return out



# ---------------------------------------------------------------------------
# optional: ML-extension step time on the real chip (EXTENSION ONLY)
# ---------------------------------------------------------------------------

# peak dense bf16 FLOP/s per chip, from published TPU specs
_TPU_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _peak_flops(device_kind: str) -> float | None:
    for name, peak in sorted(_TPU_PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if name.lower() in device_kind.lower():
            return peak
    return None


def run_tpu_step_bench() -> dict | None:
    """Train-step time + TFLOP/s + MFU of the demo scorer model
    (tasksrunner/ml/model.py) at a bench-sized config, on whatever chip
    jax sees. Returns None when no accelerator is attached (the metric
    is only meaningful on real hardware)."""
    try:
        import jax
        dev = jax.devices()[0]
    except Exception as exc:  # noqa: BLE001 - jax init can fail many ways
        _log(f"tpu bench skipped: jax unavailable ({exc})")
        return None
    force = os.environ.get("TASKSRUNNER_BENCH_TPU_FORCE") == "1"
    if dev.platform != "tpu" and not force:
        _log(f"tpu bench skipped: default device is {dev.platform!r}")
        return None

    import jax.numpy as jnp
    from tasksrunner.ml.model import ModelConfig, init_params, make_train_step

    if force and dev.platform != "tpu":
        cfg = ModelConfig()  # tiny: CPU smoke mode for local testing
        batch = 8
    else:
        cfg = ModelConfig(vocab=32768, seq_len=512, d_model=1024,
                          n_heads=16, d_ff=4096, n_layers=8)
        batch = 32

    key = jax.random.key(0)
    tokens = jax.random.randint(key, (batch, cfg.seq_len), 0, cfg.vocab,
                                dtype=jnp.int32)
    labels = jax.random.randint(key, (batch,), 0, cfg.n_classes,
                                dtype=jnp.int32)

    def measure() -> tuple[float, float]:
        """(compile_s, step_s) for the current attention-core toggle.

        NOTE: sync via value fetch, not jax.block_until_ready — on the
        tunneled single-chip backend block_until_ready returns before
        the computation finishes (verified: a float() fetch right after
        a "blocked" 20-step loop still waits multiple seconds), which
        would inflate the numbers ~500x."""
        params = init_params(cfg, key)
        step = make_train_step(cfg)
        t0 = time.perf_counter()
        params, loss = step(params, tokens, labels)
        float(loss)
        compile_s = time.perf_counter() - t0
        n_steps = 20
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, loss = step(params, tokens, labels)
        float(loss)  # forces device sync (see note above)
        return compile_s, (time.perf_counter() - t0) / n_steps

    # headline: the Pallas flash-attention core (tasksrunner/ml/flash.py,
    # the default); comparison: the plain einsum pair under XLA fusion
    prev_flash = os.environ.get("TASKSRUNNER_FLASH")
    try:
        os.environ["TASKSRUNNER_FLASH"] = "1"
        compile_s, step_s = measure()
        os.environ["TASKSRUNNER_FLASH"] = "0"
        _, einsum_step_s = measure()
    finally:
        if prev_flash is None:
            os.environ.pop("TASKSRUNNER_FLASH", None)
        else:
            os.environ["TASKSRUNNER_FLASH"] = prev_flash

    # analytic matmul FLOPs: per layer fwd = qkvo 8bsd² + attn 4bs²d +
    # ff 4bsd·ff; train step ≈ 3× fwd (bwd re-does ~2× the matmul work)
    b, s, d, ff = batch, cfg.seq_len, cfg.d_model, cfg.d_ff
    fwd = cfg.n_layers * (8 * b * s * d * d + 4 * b * s * s * d
                          + 4 * b * s * d * ff)
    flops_step = 3 * fwd
    tflops = flops_step / step_s / 1e12
    peak = _peak_flops(dev.device_kind)
    return {
        "device": dev.device_kind,
        "batch": batch,
        "seq_len": cfg.seq_len,
        "d_model": cfg.d_model,
        "n_layers": cfg.n_layers,
        "attention_core": "pallas-flash",
        "compile_s": round(compile_s, 1),
        "step_ms": round(step_s * 1000.0, 2),
        "tflops_per_sec": round(tflops, 1),
        "mfu": round(flops_step / step_s / peak, 3) if peak else None,
        "einsum_core_step_ms": round(einsum_step_s * 1000.0, 2),
        "einsum_core_mfu": (round(flops_step / einsum_step_s / peak, 3)
                            if peak else None),
    }


_TPU_CACHE = REPO / ".tpu_bench_cache.json"


def run_tpu_section() -> dict | None:
    """The on-chip measurement, made outage-proof.

    This host's chip tunnel is known to go unresponsive for hours at a
    time (jax init then HANGS rather than erroring), and a null ML
    figure in the round artifact costs more than the outage itself —
    so this section (a) probes the tunnel with a short-timeout
    subprocess, (b) retries the probe with bounded backoff, and (c) on
    final failure falls back to the last measured-on-chip result from
    the timestamped cache file ``.tpu_bench_cache.json``, marked
    ``stale: true``. A fresh measurement overwrites the cache.
    """
    reason = "no probe attempted"
    for attempt in range(3):
        if attempt:
            backoff = 20 * attempt
            _log(f"  tunnel probe retry in {backoff}s ...")
            time.sleep(backoff)
        # cheap liveness probe first: a dead tunnel hangs jax init, so
        # only a subprocess timeout can bound it
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=45, cwd=str(REPO))
        except subprocess.TimeoutExpired:
            reason = "chip tunnel unresponsive (jax init hung)"
            _log(f"  {reason}")
            continue
        if probe.returncode != 0:
            reason = (f"jax init failed: {probe.stderr.strip()[-200:]}")
            _log(f"  {reason}")
            continue
        out_lines = probe.stdout.strip().splitlines() if probe.stdout else []
        platform = out_lines[-1] if out_lines else ""
        if platform != "tpu" and os.environ.get(
                "TASKSRUNNER_BENCH_TPU_FORCE") != "1":
            # not an outage — there is genuinely no chip here (e.g. a
            # CPU-only CI host). Still surface the cached on-chip
            # figure so the artifact carries the real number.
            reason = f"no TPU visible (default device is {platform!r})"
            _log(f"  {reason}")
            break
        try:
            proc = subprocess.run(
                [sys.executable, str(REPO / "bench.py"), "--tpu-bench"],
                capture_output=True, text=True, timeout=600)
        except subprocess.TimeoutExpired:
            reason = "tpu bench timed out mid-run (tunnel died after probe)"
            _log(f"  {reason}")
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            try:
                tpu = json.loads(proc.stdout.strip().splitlines()[-1])
            except ValueError as exc:
                reason = f"tpu bench output unparsable: {exc}"
                _log(f"  {reason}")
                continue
            if tpu:
                import datetime
                measured_at = datetime.datetime.now(
                    datetime.timezone.utc).isoformat(timespec="seconds")
                _TPU_CACHE.write_text(json.dumps(
                    {"measured_at": measured_at,
                     "provenance": "measured on-chip by bench.py "
                                   "--tpu-bench on this host",
                     "result": tpu}, indent=1) + "\n")
                return {**tpu, "stale": False, "measured_at": measured_at}
            reason = "run_tpu_step_bench returned null on a live device"
            _log(f"  {reason}")
            break
        reason = (f"tpu bench failed rc={proc.returncode}: "
                  f"{proc.stderr.strip()[-300:]}")
        _log(f"  {reason}")

    # final failure: embed the last on-chip measurement, honestly marked
    if _TPU_CACHE.exists():
        try:
            cached = json.loads(_TPU_CACHE.read_text())
            _log(f"  using cached on-chip result from "
                 f"{cached.get('measured_at')} (stale)")
            return {**cached["result"], "stale": True,
                    "measured_at": cached.get("measured_at"),
                    "provenance": cached.get("provenance"),
                    "stale_reason": reason}
        except (ValueError, KeyError) as exc:
            _log(f"  tpu cache unreadable: {exc}")
    return None


async def run_replication_bench(n_ops: int = 3000, *, concurrency: int = 64,
                                n_keys: int = 1024, rounds: int = 3) -> dict:
    """``replication_bench``: the replicated state plane's two numbers.

    * **write overhead vs RF** — the same write-heavy mix as
      ``state_ops_per_sec`` swept over replication factor {1, 2, 3}.
      RF 1 is the exact unreplicated code path (build_replicated_store
      returns a plain SqliteStateStore), so its lane doubles as the
      no-regression control. Followers are in-process members on the
      same disk, so the ratio isolates the record-stream + quorum-ack
      machinery itself, not network or extra spindles.
    * **failover drill** — RF 2, ack quorum 2 (every acked write is on
      both members before the caller sees the ack). A writer banks
      acked keys; the leader crashes WITHOUT releasing its lease (the
      hard case) and the crashed member rejoins a beat later, as a
      restarted process would. Reported: time from the crash to the
      next successful write (bounded by the lease TTL + quorum
      re-forming) and ``lost_acked_keys`` — must be empty: zero lost
      acked writes is the acceptance bar, not a statistic.
    """
    from tasksrunner.state.replication import build_replicated_store

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-repl-")
    keys = [f"task-{i}" for i in range(n_keys)]

    lanes: dict[int, float] = {}
    for rf in (1, 2, 3):
        store = build_replicated_store(
            f"bench-repl{rf}", f"{tmp}/rf{rf}/state.db", replicas=rf)
        try:
            await _state_op_rate(store, "write", max(200, n_ops // 4),
                                 concurrency, keys)  # warmup, discarded
            rates = []
            for _ in range(rounds):
                rates.append(await _state_op_rate(
                    store, "write", n_ops, concurrency, keys))
            lanes[rf] = statistics.median(rates)
        finally:
            await store.aclose()

    base = lanes[1]
    sweep = {
        str(rf): {
            "ops_per_sec": round(rate, 1),
            "write_overhead_ratio": (round(base / rate, 2) if rate else None),
        }
        for rf, rate in lanes.items()
    }

    lease_s = 0.5
    store = build_replicated_store(
        "bench-repl-failover", f"{tmp}/failover/state.db", replicas=2,
        ack_quorum=2, lease_seconds=lease_s, ack_timeout=5.0)
    acked: list[str] = []
    try:
        for i in range(50):
            await store.set(f"pre-{i}", {"v": i})
            acked.append(f"pre-{i}")
        victim = next(n for n in store.nodes
                      if n.node_id == store.leader_member())
        victim.crash()
        t0 = time.perf_counter()
        # the killed host's process restarts and rejoins as a follower
        # while the survivor is still waiting out the zombie's lease
        asyncio.get_running_loop().call_later(0.1, victim.revive)
        await store.set("post-failover", {"v": -1})
        acked.append("post-failover")
        failover_ms = round((time.perf_counter() - t0) * 1000.0, 1)
        for i in range(20):
            await store.set(f"post-{i}", {"v": i})
            acked.append(f"post-{i}")
        lost = [key for key in acked if await store.get(key) is None]
        new_leader = store.leader_member()
    finally:
        await store.aclose()

    return {
        "rf_sweep": sweep,
        "failover": {
            "failover_ms": failover_ms,
            "lease_seconds": lease_s,
            "ack_quorum": 2,
            "new_leader": new_leader,
            "acked_writes": len(acked),
            "lost_acked_keys": lost,
        },
    }


async def run_reshard_bench(n_keys: int = 2000, *,
                            steady_seconds: float = 1.5) -> dict:
    """``reshard_bench``: elastic placement's three numbers.

    * **p99 during migration vs steady** — a writer hammers a 4-shard
      sqlite store recording per-op latency; first over a steady
      window, then with a live ``split_shard`` streaming ~1/5 of the
      keyspace to a fresh shard underneath it. The fenced flip's
      write-pause is the only stop-the-world moment, so the during/
      steady p99 ratio IS the cost of live resharding (acceptance:
      within 2x).
    * **time-to-rebalance after a hot-key storm** — a zipfian writer
      storms one shard; reported: time from storm start until the heat
      tracker's hysteresis window elapses and ``plan_rebalance``
      proposes an action (the control loop's detection knee).
    * **zero lost acked writes** — the migration-window writer banks
      every acked key; after the flip each must read back.
      ``lost_acked_keys`` must be empty — an acceptance bar, not a
      statistic.
    """
    from tasksrunner.state.placement import plan_rebalance
    from tasksrunner.state.sqlite import build_sharded_store

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-reshard-")

    def _p99_ms(lat: list[float]) -> float:
        lat = sorted(lat)
        return round(lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1000, 3)

    store = build_sharded_store("bench-reshard", f"{tmp}/state.db", shards=4)
    acked: list[str] = []
    try:
        for i in range(n_keys):
            await store.set(f"task-{i}", {"v": i})

        async def writer(lat: list[float], stop: asyncio.Event,
                         bank: bool) -> None:
            i = 0
            while not stop.is_set():
                key = f"live-{i % n_keys}"
                t0 = time.perf_counter()
                await store.set(key, {"v": i})
                lat.append(time.perf_counter() - t0)
                if bank:
                    acked.append(key)
                i += 1

        # steady window
        steady_lat: list[float] = []
        stop = asyncio.Event()
        task = asyncio.create_task(writer(steady_lat, stop, bank=False))
        await asyncio.sleep(steady_seconds)
        stop.set()
        await task

        # migration window: the same writer runs while a split streams
        # ~1/(N+1) of the keyspace out and flips routing underneath it
        during_lat: list[float] = []
        stop = asyncio.Event()
        task = asyncio.create_task(writer(during_lat, stop, bank=True))
        await asyncio.sleep(0.1)  # writer in flight before the split
        t0 = time.perf_counter()
        split = await store.split_shard()
        migration_s = time.perf_counter() - t0
        await asyncio.sleep(0.2)  # post-flip writes through the new map
        stop.set()
        await task

        lost = [k for k in set(acked) if await store.get(k) is None]
        epoch = store.placement.epoch
    finally:
        await store.aclose()

    steady_p99 = _p99_ms(steady_lat)
    during_p99 = _p99_ms(during_lat)

    # hot-key storm → detection knee, on a fresh store with a tight
    # hysteresis window so the bench stays fast (the knob operators
    # turn: TASKSRUNNER_RESHARD_HYSTERESIS_SECONDS)
    saved = {k: os.environ.get(k) for k in
             ("TASKSRUNNER_RESHARD_HEAT_THRESHOLD",
              "TASKSRUNNER_RESHARD_HYSTERESIS_SECONDS")}
    os.environ["TASKSRUNNER_RESHARD_HEAT_THRESHOLD"] = "50"
    os.environ["TASKSRUNNER_RESHARD_HYSTERESIS_SECONDS"] = "0.4"
    try:
        hot_store = build_sharded_store(
            "bench-reshard-hot", f"{tmp}/hot.db", shards=4)
        try:
            t0 = time.perf_counter()
            plan = None
            deadline = t0 + 15.0
            i = 0
            while plan is None and time.perf_counter() < deadline:
                # zipf-ish: 80% of writes land on one hot key's shard
                key = "hot-key" if i % 5 else f"cold-{i}"
                await hot_store.set(key, {"v": i})
                i += 1
                if i % 200 == 0:
                    plan = plan_rebalance(hot_store.placement_doc())
            time_to_plan_s = (round(time.perf_counter() - t0, 3)
                              if plan is not None else None)
        finally:
            await hot_store.aclose()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    return {
        "steady": {"writes": len(steady_lat), "p99_ms": steady_p99},
        "during_migration": {
            "writes": len(during_lat),
            "p99_ms": during_p99,
            "p99_ratio": (round(during_p99 / steady_p99, 2)
                          if steady_p99 else None),
            "pause_ms": round(split["pause_seconds"] * 1000, 2),
            "keys_moved": split["keys_moved"],
            "migration_seconds": round(migration_s, 3),
            "epoch_after": epoch,
            "within_2x": during_p99 <= 2 * steady_p99,
        },
        "lost_acked_keys": lost,
        "acked_writes": len(set(acked)),
        "hot_key_storm": {
            "time_to_plan_s": time_to_plan_s,
            "plan": plan,
        },
    }


async def _mesh_combo(codec: str, coalesce: bool, *, rtt_n: int = 300,
                      n_ops: int = 3000, concurrency: int = 64) -> dict:
    """One rung of the fast-lane ladder: the framed mesh transport
    measured alone over a real localhost socket, with the two levers —
    header codec and write coalescing — set explicitly via the same
    env flags operators use, fresh server + pool per rung so nothing
    inherits a previously negotiated codec."""
    from tasksrunner.invoke.mesh import MeshPool, MeshServer

    class EchoRuntime:
        async def invoke(self, target, path, *, http_method="POST",
                         query="", headers=None, body=b""):
            return 200, {"content-type": "application/json"}, body

    saved = {k: os.environ.get(k) for k in
             ("TASKSRUNNER_MESH_CODEC", "TASKSRUNNER_MESH_COALESCE")}
    os.environ["TASKSRUNNER_MESH_CODEC"] = codec
    os.environ["TASKSRUNNER_MESH_COALESCE"] = "1" if coalesce else "0"
    body = b"x" * 256
    try:
        srv = MeshServer(EchoRuntime(), api_token=None)
        await srv.start()
        pool = MeshPool()
        try:
            async def one(i: int) -> None:
                status, _, _ = await pool.request(
                    "127.0.0.1", srv.port, "bench", "POST",
                    f"/api/{i}", body=body)
                assert status == 200

            for i in range(50):  # warmup: dial, negotiate, settle
                await one(i)

            lat = []
            for i in range(rtt_n):  # sequential: pure round-trip time
                t0 = time.perf_counter()
                await one(i)
                lat.append((time.perf_counter() - t0) * 1000.0)
            lat.sort()

            sem = asyncio.Semaphore(concurrency)

            async def bounded(i: int) -> None:
                async with sem:
                    await one(i)

            t0 = time.perf_counter()
            await asyncio.gather(*(bounded(i) for i in range(n_ops)))
            elapsed = time.perf_counter() - t0
        finally:
            await pool.close()
            await srv.stop()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {
        "codec": codec,
        "coalesced_writes": coalesce,
        "rtt_p50_ms": round(lat[len(lat) // 2], 4),
        "rtt_p99_ms": round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 4),
        "requests_per_sec": round(n_ops / elapsed, 1),
        "concurrency": concurrency,
        "body_bytes": len(body),
    }


async def _mesh_warm_bench(rounds: int = 20) -> dict:
    """Cold vs pre-warmed first-request latency: what the keepalive
    pre-dialer saves the FIRST request to a peer (dial + codec hello
    off the request path)."""
    from tasksrunner.invoke.mesh import MeshPool, MeshServer

    class EchoRuntime:
        async def invoke(self, target, path, *, http_method="POST",
                         query="", headers=None, body=b""):
            return 200, {}, b"ok"

    srv = MeshServer(EchoRuntime(), api_token=None)
    await srv.start()
    key = ("127.0.0.1", srv.port, None)
    cold, warm = [], []
    try:
        for _ in range(rounds):
            pool = MeshPool()  # fresh pool: first request pays the dial
            t0 = time.perf_counter()
            await pool.request("127.0.0.1", srv.port, "b", "GET", "/x")
            cold.append((time.perf_counter() - t0) * 1000.0)
            await pool.close()

            pool = MeshPool()  # pre-warmed: keepalive dialed already
            pool.start_keepalive(lambda: [key], interval=60.0)
            pool.kick()
            for _ in range(500):
                conn = pool._conns.get(key)
                if conn is not None and not conn.closed:
                    break
                await asyncio.sleep(0.002)
            t0 = time.perf_counter()
            await pool.request("127.0.0.1", srv.port, "b", "GET", "/x")
            warm.append((time.perf_counter() - t0) * 1000.0)
            await pool.close()
    finally:
        await srv.stop()
    cold.sort()
    warm.sort()
    return {
        "cold_first_request_p50_ms": round(cold[len(cold) // 2], 4),
        "prewarmed_first_request_p50_ms": round(warm[len(warm) // 2], 4),
        "note": "cold pays TCP dial + codec hello on the request path; "
                "pre-warmed rides a connection the keepalive dialed",
    }


def run_mesh_bench() -> dict:
    """The mesh fast-lane ladder: each lever measured one at a time in
    the SAME run — JSON vs binary headers, per-frame drain vs coalesced
    writes, cold vs pre-warmed dial, and the default combo again under
    uvloop when the package exists (it is optional and absent in the
    stock image — reported honestly as unavailable then, never
    installed on the fly)."""
    from tasksrunner.eventloop import uvloop_available

    ladder = []
    for codec in ("json", "binary"):
        for coalesce in (False, True):
            rung = asyncio.run(_mesh_combo(codec, coalesce))
            _log(f"  -> codec={codec} coalesce={'on' if coalesce else 'off'}: "
                 f"rtt p50 {rung['rtt_p50_ms']} ms, "
                 f"{rung['requests_per_sec']} req/s @{rung['concurrency']}")
            ladder.append(rung)

    warm = asyncio.run(_mesh_warm_bench())
    _log(f"  -> first request: cold {warm['cold_first_request_p50_ms']} ms "
         f"vs pre-warmed {warm['prewarmed_first_request_p50_ms']} ms")

    if uvloop_available():
        import uvloop
        loop = uvloop.new_event_loop()
        try:
            rung = loop.run_until_complete(_mesh_combo("binary", True))
        finally:
            loop.close()
        uvloop_lane = {"available": True, **rung}
        _log(f"  -> uvloop (binary+coalesced): rtt p50 "
             f"{rung['rtt_p50_ms']} ms, {rung['requests_per_sec']} req/s")
    else:
        uvloop_lane = {
            "available": False,
            "note": "uvloop not installed in this image; "
                    "TASKSRUNNER_UVLOOP=1 is a no-op (warned once) until "
                    "the operator adds the package",
        }
        _log("  -> uvloop lane skipped: package not installed")

    baseline = next(r for r in ladder
                    if r["codec"] == "json" and not r["coalesced_writes"])
    fast = next(r for r in ladder
                if r["codec"] == "binary" and r["coalesced_writes"])
    return {
        "ladder": ladder,
        "first_request": warm,
        "uvloop": uvloop_lane,
        "fast_vs_v1_throughput_ratio": round(
            fast["requests_per_sec"] / baseline["requests_per_sec"], 3)
        if baseline["requests_per_sec"] else None,
        "fast_vs_v1_rtt_ratio": round(
            baseline["rtt_p50_ms"] / fast["rtt_p50_ms"], 3)
        if fast["rtt_p50_ms"] else None,
    }


# ---------------------------------------------------------------------------
# ML serving plane: continuous batching vs batch-of-one (`--ml-serve-bench`)
# ---------------------------------------------------------------------------

def _ml_hist_rows(before: dict, after: dict, name: str) -> dict:
    """Per-bucket p50/p99 rows for one histogram, from a snapshot diff
    (so each lane reports only its own observations despite the
    process-wide registry)."""
    from tasksrunner.observability.metrics import estimate_percentile

    hist = after.get(name)
    if not hist:
        return {}
    prior = {
        tuple(sorted(s["labels"].items())): s
        for s in before.get(name, {}).get("series", [])
    }
    rows = {}
    for series in hist["series"]:
        prev = prior.get(tuple(sorted(series["labels"].items())))
        counts = [c - (prev["counts"][i] if prev else 0)
                  for i, c in enumerate(series["counts"])]
        count = series["count"] - (prev["count"] if prev else 0)
        if count <= 0:
            continue
        rows[series["labels"].get("bucket", "all")] = {
            "count": count,
            "p50_ms": round(estimate_percentile(
                hist["bounds"], counts, 0.5) * 1000, 3),
            "p99_ms": round(estimate_percentile(
                hist["bounds"], counts, 0.99) * 1000, 3),
        }
    return rows


async def _ml_serve_lane(n_requests: int, concurrency: int,
                         env: dict[str, str]) -> dict:
    """One serving lane: the real priority-scorer app on an in-proc
    cluster, ``n_requests`` POST /score calls from ``concurrency``
    workers over sidecar invoke, every response checked against its
    request's taskId."""
    from tasksrunner import App, InProcCluster
    from tasksrunner.component.spec import parse_component
    from tasksrunner.ml import service as ml_service
    from tasksrunner.observability.metrics import metrics

    prior = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        specs = [
            parse_component({"componentType": "state.in-memory"},
                            default_name="scores"),
            parse_component({"componentType": "pubsub.in-memory"},
                            default_name="taskspubsub"),
        ]
        cluster = InProcCluster(specs)
        scorer = ml_service.make_app()
        driver = App("bench-driver")
        cluster.add_app(scorer)
        cluster.add_app(driver)
        await cluster.start()  # returns with warmup done (on_startup ran)
        try:
            client = cluster.client("bench-driver")
            stats0 = (await client.invoke_method(
                "priority-scorer", "ml/stats", http_method="GET")).json()
            hists0 = metrics.snapshot_histograms()
            latencies: list[float] = []
            mismatches = 0

            async def worker(w: int) -> None:
                nonlocal mismatches
                for i in range(n_requests // concurrency):
                    task_id = f"t-{w}-{i}"
                    t0 = time.perf_counter()
                    resp = await client.invoke_method(
                        "priority-scorer", "score",
                        data={"taskId": task_id,
                              "taskName": f"bench task {w} {i} "
                                          + "word " * (i % 7)})
                    latencies.append(time.perf_counter() - t0)
                    if resp.status != 200 or resp.json().get("taskId") != task_id:
                        mismatches += 1

            wall0 = time.perf_counter()
            await asyncio.gather(*(worker(w) for w in range(concurrency)))
            wall = time.perf_counter() - wall0
            stats1 = (await client.invoke_method(
                "priority-scorer", "ml/stats", http_method="GET")).json()
            hists1 = metrics.snapshot_histograms()
            latencies.sort()
            done = len(latencies)
            return {
                "requests": done,
                "concurrency": concurrency,
                "req_per_sec": round(done / wall, 1),
                "latency_p50_ms": round(latencies[done // 2] * 1000, 2),
                "latency_p99_ms": round(latencies[int(done * 0.99)] * 1000, 2),
                "response_mismatches": mismatches,
                "jit_cache_size_after_warmup": stats0["jit_cache_size"],
                "jit_cache_size_after_load": stats1["jit_cache_size"],
                "recompiles": stats1["jit_cache_size"] - stats0["jit_cache_size"],
                "batches": stats1["batches"],
                "shed": stats1["shed"],
                "queue_wait_per_bucket": _ml_hist_rows(
                    hists0, hists1, "ml_queue_wait_seconds"),
                "service_time_per_bucket": _ml_hist_rows(
                    hists0, hists1, "ml_infer_latency_seconds"),
            }
        finally:
            await cluster.stop()
    finally:
        for key, value in prior.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


async def _ml_flood_drill(max_queue: int, max_delay_ms: float, *,
                          concurrency: int = 64,
                          duration_s: float = 2.5,
                          ramp_s: float = 0.6) -> dict:
    """Admission-protected flood: sustain more offered load than the
    queue admits, assert the overflow sheds 429+Retry-After and the
    p99 queue wait of the *served* requests stays bounded by the
    assembly budget plus the device time of the batches ahead.

    The wait histogram is snapshotted ``ramp_s`` into the flood so the
    bound is checked against steady state — the opening convoy (every
    worker's first request lands on one event-loop tick) measures loop
    scheduling, not batch assembly. The admitted queue is pinned at
    ``max_queue`` throughout, so every flush is full-size: an admitted
    request waits at most its own assembly window plus
    ``ceil(max_queue / max_batch) + 1`` batch executions (the ``+1``
    is the batch holding the device when it arrives). Histogram bounds
    are powers of two, so the p99 estimate can overstate the true wait
    by up to 2x — the check compares against the bound scaled by that
    resolution factor (both numbers are reported raw)."""
    from tasksrunner import App, InProcCluster
    from tasksrunner.component.spec import parse_component
    from tasksrunner.ml import service as ml_service
    from tasksrunner.observability.metrics import metrics

    env = {"TASKSRUNNER_ML_MAX_QUEUE": str(max_queue),
           "TASKSRUNNER_ML_MAX_DELAY_MS": str(max_delay_ms)}
    prior = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        specs = [
            parse_component({"componentType": "state.in-memory"},
                            default_name="scores"),
            parse_component({"componentType": "pubsub.in-memory"},
                            default_name="taskspubsub"),
        ]
        cluster = InProcCluster(specs)
        scorer = ml_service.make_app()
        driver = App("bench-driver")
        cluster.add_app(scorer)
        cluster.add_app(driver)
        await cluster.start()
        try:
            client = cluster.client("bench-driver")
            loop = asyncio.get_running_loop()
            stop_at = loop.time() + duration_s
            served = shed = other = 0
            retry_afters: set[str] = set()
            hists0 = metrics.snapshot_histograms()

            async def ramp_snapshot() -> None:
                nonlocal hists0
                await asyncio.sleep(ramp_s)
                hists0 = metrics.snapshot_histograms()

            async def worker(w: int) -> None:
                nonlocal served, shed, other
                i = 0
                while loop.time() < stop_at:
                    resp = await client.invoke_method(
                        "priority-scorer", "score",
                        data={"taskId": f"flood-{w}-{i}",
                              "taskName": f"flood {w} {i}"})
                    i += 1
                    if resp.status == 200:
                        served += 1
                    elif resp.status == 429:
                        shed += 1
                        ra = (resp.headers.get("Retry-After")
                              or resp.headers.get("retry-after"))
                        if ra is not None:
                            retry_afters.add(ra)
                        # a shed response completes without touching the
                        # network, so a hot retry loop would never yield
                        # the event loop — back off briefly, the way a
                        # Retry-After-honoring client would (scaled down
                        # to keep the flood sustained)
                        await asyncio.sleep(0.002)
                    else:
                        other += 1

            await asyncio.gather(ramp_snapshot(),
                                 *(worker(w) for w in range(concurrency)))
            hists1 = metrics.snapshot_histograms()
            waits = _ml_hist_rows(hists0, hists1, "ml_queue_wait_seconds")
            infer = _ml_hist_rows(hists0, hists1, "ml_infer_latency_seconds")
            p99_wait = max((r["p99_ms"] for r in waits.values()), default=0.0)
            p50_wait = max((r["p50_ms"] for r in waits.values()), default=0.0)
            p99_infer = max((r["p99_ms"] for r in infer.values()), default=0.0)
            from tasksrunner.ml.batching import BatcherConfig
            max_batch = BatcherConfig.from_env().max_batch
            depth = -(-max_queue // max_batch) + 1
            bound_ms = max_delay_ms + depth * p99_infer
            return {
                "flooded": served + shed + other,
                "served": served,
                "shed": shed,
                "other_statuses": other,
                "shed_carry_retry_after": sorted(retry_afters),
                "max_queue": max_queue,
                "concurrency": concurrency,
                "budget_ms": max_delay_ms,
                "queue_wait_p50_ms": p50_wait,
                "queue_wait_p99_ms": p99_wait,
                "queue_wait_bound_ms": round(bound_ms, 3),
                "bound_with_resolution_ms": round(bound_ms * 2, 3),
                "queue_wait_bounded": p99_wait <= bound_ms * 2,
            }
        finally:
            await cluster.stop()
    finally:
        for key, value in prior.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


async def run_ml_serve_bench(n_requests: int = 384, *,
                             concurrency: int = 16) -> dict:
    """``ml_serving``: the continuous-batching inference plane measured
    against the batch-of-one path it replaced, through the real app +
    sidecar-invoke lane (EXTENSION ONLY). Three sections:

    * serial lane — ``TASKSRUNNER_ML_BATCHING=off``: every request its
      own device dispatch (the pre-change architecture);
    * batched lane — micro-batch assembly + padding buckets, same
      request mix and concurrency;
    * flood drill — 4x the queue bound at once: overflow sheds
      429+Retry-After, served p99 queue wait stays inside the
      assembly budget + device-occupancy bound.

    The jit cache size is read before and after each load: any growth
    after warmup is a recompile, and the acceptance bar is zero.
    """
    # CPU runs measure the SCHEDULING win, so keep attention on the
    # fused-einsum core: the Pallas kernels run in interpreter mode
    # off-TPU and would swamp the signal (the kernels get their own
    # parity suite + on-chip bench)
    import jax
    flash_forced_off = False
    if jax.default_backend() != "tpu" and "TASKSRUNNER_FLASH" not in os.environ:
        os.environ["TASKSRUNNER_FLASH"] = "0"
        flash_forced_off = True
    try:
        serial = await _ml_serve_lane(
            n_requests, concurrency, {"TASKSRUNNER_ML_BATCHING": "0"})
        batched = await _ml_serve_lane(
            n_requests, concurrency, {"TASKSRUNNER_ML_BATCHING": "1"})
        flood = await _ml_flood_drill(max_queue=32, max_delay_ms=25.0)
    finally:
        if flash_forced_off:
            os.environ.pop("TASKSRUNNER_FLASH", None)
    return {
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
        "flash_attention": not flash_forced_off,
        "serial": serial,
        "batched": batched,
        "flood": flood,
        "throughput_ratio": round(
            batched["req_per_sec"] / serial["req_per_sec"], 2)
        if serial["req_per_sec"] else None,
        "zero_recompiles": (serial["recompiles"] == 0
                            and batched["recompiles"] == 0),
    }


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", choices=["api", "processor"])
    parser.add_argument("--tmp")
    parser.add_argument("--idx", type=int, default=0)
    parser.add_argument("--tpu-bench", action="store_true",
                        help="run ONLY the TPU step bench, print its JSON "
                             "(invoked as a subprocess so a dead chip "
                             "tunnel can be timed out, not hung on)")
    parser.add_argument("--state-bench", action="store_true",
                        help="run ONLY the state-store ops/s section "
                             "(`make bench-state`) and print its JSON")
    parser.add_argument("--shard-bench", action="store_true",
                        help="run ONLY the state shard-scaling sweep "
                             "(`make bench-shard`): write-heavy ops/s "
                             "for shards in {1,2,4,8} and print its JSON")
    parser.add_argument("--chaos-bench", action="store_true",
                        help="run ONLY the chaos-overhead section "
                             "(`make chaos`): proves the disabled gate "
                             "adds <1%% to the write-heavy state path")
    parser.add_argument("--hist-bench", action="store_true",
                        help="run ONLY the histogram-overhead section "
                             "(`make bench-hist`): histograms-on vs -off "
                             "on the write-heavy state path and the "
                             "publish/deliver path (<3%% bar)")
    parser.add_argument("--trace-bench", action="store_true",
                        help="run ONLY the trace-overhead section "
                             "(`make bench-trace`): span recorder on vs "
                             "off on the state-write, publish/deliver, "
                             "and actor-turn paths (<3%% bar on, ~0%% "
                             "off) plus the flight-recorder ring-append "
                             "cost vs its disabled one-if path")
    parser.add_argument("--overload-bench", action="store_true",
                        help="run ONLY the overload section "
                             "(`make bench-overload`): admission-gate "
                             "overhead on the ingress path (<1%% bar "
                             "when off) plus the chaos overload drill's "
                             "shed/scale/recover trajectory")
    parser.add_argument("--actor-bench", action="store_true",
                        help="run ONLY the virtual-actor section "
                             "(`make bench-actors`): turn throughput, "
                             "the crash-failover drill (zero lost acked "
                             "turns, reminder refire), and the gate-off "
                             "sidecar ingress overhead (<1%% bar)")
    parser.add_argument("--workflow-bench", action="store_true",
                        help="durable-workflow subsystem numbers "
                             "(`make bench-workflows`): saga "
                             "throughput, replay-recovery latency "
                             "after an owner kill, history-append "
                             "overhead vs a bare actor turn")
    parser.add_argument("--replication-bench", action="store_true",
                        help="run ONLY the replicated-state section "
                             "(`make bench-repl`): write-overhead "
                             "ratios for RF {1,2,3} and the leader-"
                             "crash failover drill (zero lost acked "
                             "writes at RF 2, failover time)")
    parser.add_argument("--reshard-bench", action="store_true",
                        help="run ONLY the elastic-placement section "
                             "(`make bench-reshard`): p99 during a "
                             "live shard split vs steady state (within "
                             "2x), zero lost acked writes across the "
                             "fenced flip, and time-to-plan after a "
                             "zipfian hot-key storm")
    parser.add_argument("--mesh-bench", action="store_true",
                        help="run ONLY the mesh fast-lane ladder "
                             "(`make bench-mesh`): JSON vs binary "
                             "headers, per-frame drain vs coalesced "
                             "writes, cold vs pre-warmed dial, and the "
                             "uvloop lane when the package exists")
    parser.add_argument("--ml-serve-bench", action="store_true",
                        help="run ONLY the ML serving-plane section "
                             "(`make bench-ml-serve`): continuous "
                             "batching vs batch-of-one through the real "
                             "service, per-bucket queue-wait/service-"
                             "time percentiles, jit recompile count, "
                             "and the admission-protected flood drill")
    args = parser.parse_args()

    if args.tpu_bench:
        print(json.dumps(run_tpu_step_bench()))
        return

    if args.state_bench:
        _log("state-store ops/s (group-commit write queue) ...")
        state_ops = asyncio.run(run_state_bench())
        w, r = state_ops["write_heavy"], state_ops["read_heavy"]
        _log(f"  -> write-heavy {w['ops_per_sec']} ops/s "
             f"({w['speedup']}x vs pre-change {w['pre_change_ops_per_sec']}), "
             f"read-heavy {r['ops_per_sec']} ops/s "
             f"(cached {r['cached_ops_per_sec']}, {r['cache_speedup']}x)")
        print(json.dumps({"state_ops_per_sec": state_ops}))
        return

    if args.shard_bench:
        _log("state shard-scaling sweep (write-heavy mix) ...")
        shard_scaling = asyncio.run(run_shard_scaling_bench())
        for n, lane in shard_scaling["write_heavy"].items():
            _log(f"  -> shards={n}: {lane['ops_per_sec']} ops/s "
                 f"({lane['speedup_vs_shards1']}x vs shards=1)")
        print(json.dumps({"state_shard_scaling": shard_scaling}))
        return

    if args.chaos_bench:
        _log("chaos overhead on the write-heavy state path ...")
        chaos_overhead = asyncio.run(run_chaos_overhead_bench())
        _log(f"  -> baseline {chaos_overhead['baseline_ops_per_sec']} ops/s, "
             f"gate-off {chaos_overhead['gate_off_ops_per_sec']} ops/s "
             f"({chaos_overhead['gate_off_overhead_pct']:+.2f}%), "
             f"wrapped-idle {chaos_overhead['wrapped_idle_ops_per_sec']} "
             f"ops/s ({chaos_overhead['wrapped_idle_overhead_pct']:+.2f}%)")
        print(json.dumps({"chaos_overhead": chaos_overhead}))
        return

    if args.hist_bench:
        _log("histogram overhead (state write + publish/deliver) ...")
        hist_overhead = asyncio.run(run_histogram_overhead_bench())
        s, p = hist_overhead["state_write"], hist_overhead["publish_deliver"]
        _log(f"  -> state write {s['hist_on_ops_per_sec']} ops/s on vs "
             f"{s['hist_off_ops_per_sec']} off ({s['overhead_pct']:+.2f}%), "
             f"publish/deliver {p['hist_on_ops_per_sec']} ops/s on vs "
             f"{p['hist_off_ops_per_sec']} off ({p['overhead_pct']:+.2f}%)")
        print(json.dumps({"histogram_overhead": hist_overhead}))
        return

    if args.trace_bench:
        _log("trace overhead (state write + publish/deliver + actor turn) ...")
        trace_overhead = asyncio.run(run_trace_overhead_bench())
        for label, key in (("state write", "state_write"),
                           ("publish/deliver", "publish_deliver"),
                           ("actor turn", "actor_turn")):
            sec = trace_overhead[key]
            _log(f"  -> {label} {sec['trace_on_ops_per_sec']} ops/s on vs "
                 f"{sec['trace_off_ops_per_sec']} off "
                 f"({sec['overhead_pct']:+.2f}%)")
        fr = trace_overhead["flightrec_note"]
        _log(f"  -> flightrec note {fr['on_ns_per_note']} ns on vs "
             f"{fr['off_ns_per_note']} ns off ({fr['delta_ns']:+.1f} ns)")
        print(json.dumps({"trace_overhead": trace_overhead}))
        return

    if args.overload_bench:
        _log("admission-gate overhead on the ingress path ...")
        admission_overhead = asyncio.run(run_admission_overhead_bench())
        _log(f"  -> baseline {admission_overhead['baseline_req_per_sec']} "
             f"req/s, gate-off {admission_overhead['gate_off_req_per_sec']} "
             f"req/s ({admission_overhead['gate_off_overhead_pct']:+.2f}%), "
             f"attached-idle "
             f"{admission_overhead['attached_idle_req_per_sec']} req/s "
             f"({admission_overhead['attached_idle_overhead_pct']:+.2f}%)")
        _log("chaos overload drill (shed -> scale out -> recover) ...")
        drill = asyncio.run(run_overload_drill_bench())
        _log(f"  -> acked {drill['acked']}, shed {drill['shed']} "
             f"(Retry-After {drill['retry_after_min']}..{drill['retry_after_max']}s), "
             f"fleet peak {drill['max_replicas_seen']} "
             f"(desired peak {drill['desired_gauge_peak']:.0f}), "
             f"recovered_to_min={drill['recovered_to_min']}, "
             f"lost acked keys: {len(drill['lost_acked_keys'])}")
        print(json.dumps({"admission_overhead": admission_overhead,
                          "overload_drill": drill}))
        return

    if args.actor_bench:
        _log("virtual actors: turns, crash failover, gate-off ingress ...")
        actor_bench = asyncio.run(run_actor_bench())
        t, f, i = actor_bench["turns"], actor_bench["failover"], \
            actor_bench["ingress"]
        _log(f"  -> {t['turns_per_sec_64_actors']} turns/s over 64 actors "
             f"({t['turns_per_sec_single_actor']} on one), failover "
             f"{f['failover_ms']:.0f} ms (lease {f['lease_seconds']}s), "
             f"lost acked turns {f['lost_acked_turns']}, reminder refire "
             f"{f['reminder_refire_ms']} ms")
        _log(f"  -> ingress gate-off {i['gate_off_overhead_pct']:+.2f}% vs "
             f"baseline {i['baseline_req_per_sec']} req/s (bar <1%), "
             f"enabled {i['enabled_overhead_pct']:+.2f}%")
        print(json.dumps({"actor_bench": actor_bench}))
        return

    if args.workflow_bench:
        _log("durable workflows: sagas, crash recovery, turn overhead ...")
        workflow_bench = asyncio.run(run_workflow_bench())
        sg, rec, ov = workflow_bench["saga"], workflow_bench["recovery"], \
            workflow_bench["turn_overhead"]
        _log(f"  -> {sg['sagas_per_sec']} sagas/s "
             f"({sg['activities_per_saga']} activities each, "
             f"concurrency {sg['concurrency']})")
        _log(f"  -> recovery {rec['recovery_ms']:.0f} ms after owner "
             f"crash at step {rec['committed_steps_at_crash']}/"
             f"{rec['steps_total']} (lease {rec['lease_seconds']}s), "
             f"missing effects {len(rec['missing_effects'])}")
        _log(f"  -> workflow step {ov['workflow_steps_per_sec']} /s vs "
             f"bare actor turn {ov['actor_turns_per_sec']} /s "
             f"(x{ov['overhead_ratio']} per-step durability price)")
        print(json.dumps({"workflow_bench": workflow_bench}))
        return

    if args.replication_bench:
        _log("replicated state plane: RF sweep + leader-crash failover ...")
        replication_bench = asyncio.run(run_replication_bench())
        for rf, lane in replication_bench["rf_sweep"].items():
            _log(f"  -> RF {rf}: {lane['ops_per_sec']} ops/s "
                 f"(x{lane['write_overhead_ratio']} vs RF 1)")
        fo = replication_bench["failover"]
        _log(f"  -> failover {fo['failover_ms']:.0f} ms (lease "
             f"{fo['lease_seconds']}s, quorum {fo['ack_quorum']}), new "
             f"leader {fo['new_leader']}, lost acked keys "
             f"{len(fo['lost_acked_keys'])} of {fo['acked_writes']}")
        print(json.dumps({"replication_bench": replication_bench}))
        return

    if args.reshard_bench:
        _log("elastic placement: live split under load + hot-key storm ...")
        reshard_bench = asyncio.run(run_reshard_bench())
        d, s = reshard_bench["during_migration"], reshard_bench["steady"]
        _log(f"  -> steady p99 {s['p99_ms']} ms, during-split p99 "
             f"{d['p99_ms']} ms (x{d['p99_ratio']}, within_2x="
             f"{d['within_2x']}), pause {d['pause_ms']} ms, "
             f"{d['keys_moved']} keys moved in {d['migration_seconds']}s")
        _log(f"  -> lost acked keys {len(reshard_bench['lost_acked_keys'])} "
             f"of {reshard_bench['acked_writes']}")
        storm = reshard_bench["hot_key_storm"]
        plan = storm["plan"] or {}
        _log(f"  -> hot-key storm: plan {plan.get('action')!r} for shard "
             f"{plan.get('shard')} after {storm['time_to_plan_s']}s")
        print(json.dumps({"reshard_bench": reshard_bench}))
        return

    if args.mesh_bench:
        _log("mesh fast-lane ladder (codec x coalescing x warm x loop) ...")
        mesh_bench = run_mesh_bench()
        _log(f"  -> fast lane vs v1: x{mesh_bench['fast_vs_v1_throughput_ratio']}"
             f" throughput, x{mesh_bench['fast_vs_v1_rtt_ratio']} rtt")
        print(json.dumps({"mesh_fastpath": mesh_bench}))
        return

    if args.ml_serve_bench:
        _log("ML serving plane: continuous batching vs batch-of-one ...")
        ml_serving = asyncio.run(run_ml_serve_bench())
        s, b, f = ml_serving["serial"], ml_serving["batched"], ml_serving["flood"]
        _log(f"  -> serial {s['req_per_sec']} req/s, batched "
             f"{b['req_per_sec']} req/s "
             f"(x{ml_serving['throughput_ratio']}), recompiles "
             f"serial={s['recompiles']} batched={b['recompiles']}")
        _log(f"  -> flood: served {f['served']}, shed {f['shed']} "
             f"(Retry-After {f['shed_carry_retry_after']}), queue-wait "
             f"p50/p99 {f['queue_wait_p50_ms']}/{f['queue_wait_p99_ms']} ms "
             f"vs bound {f['queue_wait_bound_ms']} ms "
             f"(x2 resolution {f['bound_with_resolution_ms']}, "
             f"bounded={f['queue_wait_bounded']})")
        print(json.dumps({"ml_serving": ml_serving}))
        return

    if args.worker:
        # the bench worker processes are where the event loop earns its
        # keep: honor TASKSRUNNER_UVLOOP exactly like `tasksrunner run`
        from tasksrunner.eventloop import maybe_enable_uvloop
        maybe_enable_uvloop()
        profile_dir = os.environ.get("BENCH_PROFILE_DIR")
        if profile_dir:
            # per-worker cProfile dumps for write-path attribution
            # (BASELINE.md "where the time goes")
            import cProfile
            prof = cProfile.Profile()
            prof.enable()
            try:
                asyncio.run(_worker_main(args.worker, args.tmp, args.idx))
            finally:
                prof.disable()
                prof.dump_stats(
                    f"{profile_dir}/worker-{args.worker}-{args.idx}.prof")
        else:
            asyncio.run(_worker_main(args.worker, args.tmp, args.idx))
        return

    # the chip section runs FIRST: it is the scarcest measurement (the
    # tunnel has documented multi-hour outages) and must not queue
    # behind minutes of CPU benches that could overlap an outage window
    _log("bench 1/13: ML-extension train step on the attached chip ...")
    # belt over braces: the section is internally fault-tolerant, but
    # it also runs FIRST now — nothing it could raise may be allowed
    # to cost the CPU sections their numbers
    try:
        tpu = run_tpu_section()
    except Exception as exc:  # noqa: BLE001 - artifact must survive
        _log(f"  tpu section raised unexpectedly: {exc!r}")
        tpu = None
    if tpu and not tpu.get("stale"):
        _log(f"  -> {tpu['step_ms']} ms/step, {tpu['tflops_per_sec']} TFLOP/s, "
             f"MFU {tpu['mfu']} on {tpu['device']}")
    elif tpu:
        _log(f"  -> STALE (cache of {tpu.get('measured_at')}): "
             f"{tpu['step_ms']} ms/step, MFU {tpu['mfu']} on {tpu['device']}")

    # the component the e2e write path bottlenecks on, measured alone —
    # and the seed write path measured in the SAME run, so the group-
    # commit speedup is a same-host apples-to-apples figure
    _log("bench 2/13: state-store ops/s (group-commit write queue) ...")
    state_ops = asyncio.run(run_state_bench())
    _log(f"  -> write-heavy {state_ops['write_heavy']['ops_per_sec']} ops/s "
         f"({state_ops['write_heavy']['speedup']}x vs pre-change), "
         f"read-heavy {state_ops['read_heavy']['ops_per_sec']} ops/s "
         f"(cached {state_ops['read_heavy']['cached_ops_per_sec']})")

    # the sharded state plane's scaling claim: N writer shards ≈ N
    # independent group-commit engines (docs/modules/04 quotes this)
    _log("bench 3/13: state shard-scaling sweep (write-heavy mix) ...")
    shard_scaling = asyncio.run(run_shard_scaling_bench())
    _log("  -> " + ", ".join(
        f"shards={n}: {lane['ops_per_sec']} ops/s "
        f"({lane['speedup_vs_shards1']}x)"
        for n, lane in shard_scaling["write_heavy"].items()))

    # the chaos gate's "free when off" claim, measured on the same
    # write-heavy path (docs/modules/16-chaos.md quotes this number)
    _log("bench 4/13: chaos-gate overhead on the write-heavy state path ...")
    chaos_overhead = asyncio.run(run_chaos_overhead_bench())
    _log(f"  -> gate-off {chaos_overhead['gate_off_overhead_pct']:+.2f}% vs "
         f"baseline {chaos_overhead['baseline_ops_per_sec']} ops/s, "
         f"wrapped-idle {chaos_overhead['wrapped_idle_overhead_pct']:+.2f}%")

    # the latency-histogram instrumentation's "free when off, cheap when
    # on" claim on the same two hot paths (docs/modules/08 quotes this)
    _log("bench 5/13: histogram overhead (state write + publish/deliver) ...")
    hist_overhead = asyncio.run(run_histogram_overhead_bench())
    _hs = hist_overhead["state_write"]
    _hp = hist_overhead["publish_deliver"]
    _log(f"  -> state write {_hs['overhead_pct']:+.2f}%, "
         f"publish/deliver {_hp['overhead_pct']:+.2f}% (bar <3%)")

    # the overload-protection loop's two numbers: the admission gate is
    # free when off (<1% bar, docs module 09 quotes this) and the full
    # shed -> scale out -> recover trajectory holds end to end
    _log("bench 6/13: admission-gate overhead + chaos overload drill ...")
    admission_overhead = asyncio.run(run_admission_overhead_bench())
    _log(f"  -> gate-off {admission_overhead['gate_off_overhead_pct']:+.2f}% "
         f"vs baseline {admission_overhead['baseline_req_per_sec']} req/s, "
         f"attached-idle "
         f"{admission_overhead['attached_idle_overhead_pct']:+.2f}% (bar <1%)")
    overload_drill = asyncio.run(run_overload_drill_bench())
    _log(f"  -> drill: shed {overload_drill['shed']}, fleet peak "
         f"{overload_drill['max_replicas_seen']}, recovered_to_min="
         f"{overload_drill['recovered_to_min']}, lost acked keys "
         f"{len(overload_drill['lost_acked_keys'])}")

    # the virtual-actor runtime's three numbers: turn throughput, the
    # crash-failover drill (zero lost acked turns + reminder refire),
    # and the gate-off sidecar ingress overhead (docs module 18 / the
    # acceptance bar: <1% when TASKSRUNNER_ACTORS is unset)
    _log("bench 7/13: virtual actors (turns, failover, gate-off ingress) ...")
    actor_bench = asyncio.run(run_actor_bench())
    _log(f"  -> {actor_bench['turns']['turns_per_sec_64_actors']} turns/s, "
         f"failover {actor_bench['failover']['failover_ms']:.0f} ms, "
         f"lost acked turns {actor_bench['failover']['lost_acked_turns']}, "
         f"ingress gate-off "
         f"{actor_bench['ingress']['gate_off_overhead_pct']:+.2f}% (bar <1%)")

    # the replicated state plane's two numbers: what RF {2,3} costs the
    # write path, and the leader-crash failover drill at RF 2 with its
    # zero-lost-acked-writes proof (docs module 19 quotes both)
    _log("bench 8/13: replicated state plane (RF sweep + failover) ...")
    replication_bench = asyncio.run(run_replication_bench())
    _log("  -> " + ", ".join(
        f"RF {rf}: {lane['ops_per_sec']} ops/s "
        f"(x{lane['write_overhead_ratio']})"
        for rf, lane in replication_bench["rf_sweep"].items()))
    _fo = replication_bench["failover"]
    _log(f"  -> failover {_fo['failover_ms']:.0f} ms (lease "
         f"{_fo['lease_seconds']}s), lost acked keys "
         f"{len(_fo['lost_acked_keys'])} of {_fo['acked_writes']}")

    # the transport the headline topology rides, measured alone: each
    # fast-path lever (header codec, write coalescing, pre-warm,
    # optional uvloop) one at a time in the same run, so the xproc
    # delta below is attributable (docs modules 02/03 quote this)
    _log("bench 9/13: mesh fast-lane ladder (codec x coalescing x warm) ...")
    mesh_fastpath = run_mesh_bench()
    _log(f"  -> fast lane vs v1: "
         f"x{mesh_fastpath['fast_vs_v1_throughput_ratio']} throughput, "
         f"x{mesh_fastpath['fast_vs_v1_rtt_ratio']} rtt")

    _log("bench 10/13: cross-process write path (faithful [PB] topology) ...")
    xproc = asyncio.run(run_xproc(latency_probe=True, rounds=5))
    _log(f"  -> {xproc['throughput']} tasks/s, "
         f"p50 {xproc['p50_ms']} ms, p99 {xproc['p99_ms']} ms (conc=8), "
         f"p50 {xproc.get('p50_sequential_ms')} ms unloaded")

    # same topology under the recommended production posture: per-app
    # workload certs, every peer hop on the authenticated mesh lane —
    # module 15 quotes this delta instead of recommending an unmeasured
    # configuration
    _log("bench 11/13: cross-process write path under mesh mTLS ...")
    # same rounds as the plaintext headline — an asymmetric pair would
    # bake an ordering/averaging confound into the published delta.
    # PKI provisioning needs the `cryptography` package; on a host
    # without it the lane is reported unavailable rather than crashing
    # the run and losing every section's numbers
    try:
        mtls = asyncio.run(run_xproc(latency_probe=True, rounds=5,
                                     mesh_tls=True))
    except ModuleNotFoundError as exc:
        mtls = None
        _log(f"  -> mTLS lane unavailable on this host: {exc}")
    if mtls is None:
        mtls_overhead = None
        mtls_extras = {
            "unavailable": "cryptography package not installed; the "
                           "mTLS lane cannot provision its PKI on "
                           "this host",
        }
    # a lane that completed zero ops (wedged processor, chaos drill run
    # against the bench) reports throughput 0; the delta is undefined
    # then, not a division crash that loses the whole bench run
    elif xproc["throughput"]:
        mtls_overhead = round(
            (xproc["throughput"] - mtls["throughput"])
            / xproc["throughput"] * 100.0, 1)
        overhead_note = f" ({mtls_overhead:+.1f}% vs plaintext)"
    else:
        mtls_overhead = None
        overhead_note = " (overhead undefined: plaintext lane completed 0 ops)"
    if mtls is not None:
        mtls_extras = {
            "tasks_per_sec": mtls["throughput"],
            "p50_ms": mtls["p50_ms"],
            "p99_ms": mtls["p99_ms"],
            "p50_sequential_ms": mtls.get("p50_sequential_ms"),
            "throughput_rounds": mtls["throughput_runs"],
            "overhead_vs_plaintext_pct": mtls_overhead,
            "note": "same topology with per-app workload certs; "
                    "every peer-sidecar hop on the authenticated "
                    "TLS mesh lane (module 15's recommended "
                    "production posture). Runs back-to-back after "
                    "the plaintext section on a 1-core host with "
                    "±20% noise: a negative 'overhead' means the "
                    "later, warmer run measured faster, not that "
                    "TLS speeds anything up",
        }
        _log(f"  -> {mtls['throughput']} tasks/s, p50 {mtls['p50_ms']} ms, "
             f"p99 {mtls['p99_ms']} ms{overhead_note}")

    # scale-out: with 20 ms of simulated work per message (≙ the
    # reference processor's SendGrid call) consumers are the
    # bottleneck; 5 competing replicas vs 1 shows the KEDA-style
    # scale-out actually scaling (SURVEY.md §5.8)
    _log("bench 12/13: competing-consumer scale-out (20 ms work/message) ...")
    one = asyncio.run(run_xproc(n_tasks=300, n_processors=1, rounds=2,
                                work_ms=20.0))
    five = asyncio.run(run_xproc(n_tasks=300, n_processors=5, rounds=2,
                                 work_ms=20.0))
    speedup = round(five["throughput"] / one["throughput"], 2)
    _log(f"  -> 1 replica: {one['throughput']} tasks/s; "
         f"5 replicas: {five['throughput']} tasks/s ({speedup}x)")

    _log("bench 13/13: in-process cluster (round-1 continuity) ...")
    inproc = asyncio.run(run_inproc())
    _log(f"  -> {inproc} tasks/s")

    print(json.dumps({
        "metric": "e2e_xproc_write_throughput",
        "value": xproc["throughput"],
        "unit": "tasks/sec",
        "vs_baseline": None,
        "extras": {
            "topology": "3 OS processes (driver+frontend / api / "
                        "processor); process-boundary hops are real "
                        "localhost transports (framed sidecar mesh "
                        "for peer invoke — the default lane, ≙ Dapr's "
                        "internal gRPC — and the shared broker file); "
                        "app<->own-sidecar hops are direct in-process "
                        "calls (AppHost fuses them, as deployed); "
                        "durable sqlite state + broker; access logs "
                        "off (BASELINE.md)",
            "p50_ms": xproc["p50_ms"],
            "p99_ms": xproc["p99_ms"],
            "latency_concurrency": 8,
            "p50_sequential_ms": xproc.get("p50_sequential_ms"),
            "latency_host_note": "this host has ONE CPU core, so the "
                                 "three processes time-share it and "
                                 "the conc-8 p50 is queueing (Little's "
                                 "law: ~8/pipeline-throughput), not "
                                 "transport: p50_sequential_ms is the "
                                 "same frontend->api round trip with "
                                 "one request in flight — the actual "
                                 "service time the mesh fast lane "
                                 "carries. On a multi-core host the "
                                 "sidecar processes run in parallel "
                                 "and the conc-8 figure converges "
                                 "toward it",
            # noise-awareness: the headline value is the MEDIAN round;
            # the spread shows what host noise did to this run
            "throughput_rounds": xproc["throughput_runs"],
            "throughput_spread": {
                "min": xproc["throughput_min"],
                "max": xproc["throughput_max"],
            },
            "xproc_mtls": mtls_extras,
            "scaleout_20ms_work": {
                "replicas1_tasks_per_sec": one["throughput"],
                "replicas5_tasks_per_sec": five["throughput"],
                "speedup": speedup,
                "host_note": "this host has ONE CPU core and the "
                             "20 ms/message work is simulated sleep: "
                             "the figure proves competing-consumer "
                             "claim/lease correctness under scale-out, "
                             "not parallel CPU speedup",
            },
            "inproc_tasks_per_sec": inproc,
            "mesh_fastpath": mesh_fastpath,
            "state_ops_per_sec": state_ops,
            "state_shard_scaling": shard_scaling,
            "chaos_overhead": chaos_overhead,
            "histogram_overhead": hist_overhead,
            "admission_overhead": admission_overhead,
            "overload_drill": overload_drill,
            "actor_bench": actor_bench,
            "replication_bench": replication_bench,
            "ml_extension_tpu": tpu,
            **({} if tpu else {"ml_extension_note":
                "chip bench skipped (no TPU reachable within the "
                "retry budget and no cached on-chip measurement); "
                "last measured figures are tabulated in BASELINE.md"}),
        },
    }))


if __name__ == "__main__":
    main()
