"""Benchmark: end-to-end task-write throughput through the framework.

The reference publishes NO performance benchmarks (BASELINE.md: no
benchmarks directory, no throughput/latency numbers; `"published": {}`),
so there is no reference number to beat — ``vs_baseline`` is null. The
honest headline metric for this framework is the throughput of its
canonical end-to-end write path (SURVEY.md §3.1):

    client → service invocation → API handler → durable state write
    (sqlite engine) → CloudEvents publish (durable sqlite broker) →
    competing-consumer delivery to the processor handler

Each unit of work therefore exercises invocation, state, pub/sub, and
delivery — the whole runtime, not a micro-op.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import asyncio
import json
import sys
import tempfile
import time
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

N_TASKS = 600
WARMUP = 50


async def bench() -> float:
    from tasksrunner import App, InProcCluster
    from tasksrunner.component.spec import parse_component

    tmp = tempfile.mkdtemp(prefix="tasksrunner-bench-")
    specs = [
        parse_component({
            "componentType": "state.sqlite",
            "metadata": [{"name": "databasePath", "value": f"{tmp}/state.db"}],
            "scopes": ["bench-api"],
        }, default_name="statestore"),
        parse_component({
            "componentType": "pubsub.sqlite",
            "metadata": [
                {"name": "brokerPath", "value": f"{tmp}/broker.db"},
                {"name": "pollIntervalSeconds", "value": "0.001"},
            ],
        }, default_name="pubsub"),
    ]

    api = App("bench-api")

    @api.post("/api/tasks")
    async def create(req):
        doc = req.json()
        await api.client.save_state("statestore", doc["taskId"], doc)
        await api.client.publish_event("pubsub", "tasksavedtopic", doc)
        return 201, {"taskId": doc["taskId"]}

    received = 0
    done = asyncio.Event()
    done_at = [N_TASKS + WARMUP]
    processor = App("bench-processor")

    @processor.subscribe(pubsub="pubsub", topic="tasksavedtopic", route="/on-saved")
    async def on_saved(req):
        nonlocal received
        received += 1
        if received >= done_at[0]:
            done.set()
        return 200

    cluster = InProcCluster(specs)
    cluster.add_app(api)
    cluster.add_app(processor)
    await cluster.start()
    try:
        client = cluster.client("bench-api")

        async def create_task(i: int) -> None:
            resp = await client.invoke_method(
                "bench-api", "api/tasks", http_method="POST",
                data={"taskId": f"t{i}", "taskName": f"task {i}",
                      "taskCreatedBy": "bench@x.com",
                      "taskDueDate": "2026-08-01T00:00:00"})
            assert resp.status == 201, resp.body

        for i in range(WARMUP):
            await create_task(i)

        # drive with bounded concurrency, as a load generator would
        sem = asyncio.Semaphore(64)

        async def bounded(i: int) -> None:
            async with sem:
                await create_task(i)

        # best of 3 rounds: the throughput ceiling is a property of the
        # framework; transient host contention only ever lowers a round
        best = 0.0
        next_id = WARMUP
        for _ in range(3):
            # drain in-flight deliveries so each round measures exactly
            # its own N_TASKS completions (bounded: a lost delivery
            # must fail the bench, not hang it)
            drain_deadline = time.perf_counter() + 120
            while received < next_id:
                if time.perf_counter() > drain_deadline:
                    raise RuntimeError(
                        f"delivery stalled: {received}/{next_id} events")
                await asyncio.sleep(0.005)
            done.clear()
            done_at[0] = next_id + N_TASKS
            start = time.perf_counter()
            await asyncio.gather(
                *(bounded(i) for i in range(next_id, next_id + N_TASKS)))
            next_id += N_TASKS
            # throughput counts full pipeline completion: all events
            # delivered to the processor
            await asyncio.wait_for(done.wait(), timeout=120)
            elapsed = time.perf_counter() - start
            best = max(best, N_TASKS / elapsed)
        return best
    finally:
        await cluster.stop()


def main() -> None:
    throughput = asyncio.run(bench())
    print(json.dumps({
        "metric": "e2e_task_write_throughput",
        "value": round(throughput, 1),
        "unit": "tasks/sec",
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
