"""Module-5-era processor: the notifier BEFORE the bindings refactor.

≙ the reference's per-module code snapshot
`docs/aca/05-aca-dapr-pubsubapi/TasksNotifierController-SendGrid.cs:41-59`
— the version that talks to the email provider DIRECTLY: a provider
client object constructed in app code, credentials pulled from app
config, provider types inside the business logic. Module 6 replaces
all of it with ``invoke_binding("sendgrid", "create", ...)``
(`samples/tasks_tracker/processor/app.py`); this file preserves the
"before" state as a complete, runnable app so the evolution is
diffable:

    diff docs/modules/snippets/notifier_direct_email.py \\
         samples/tasks_tracker/processor/app.py

Unlike the reference's snapshots (which only compile as part of the
docs build), this one stays IMPORTABLE and smoke-tested
(tests/test_tasks_tracker.py) so the teaching artifact cannot rot.
"""

from __future__ import annotations

import logging
import os
import smtplib
from email.mime.text import MIMEText

from tasksrunner import App

logger = logging.getLogger(__name__)

APP_ID = "tasksmanager-backend-processor"
CLOUD_PUBSUB = "dapr-pubsub-servicebus"
LOCAL_PUBSUB = "taskspubsub"
TOPIC = "tasksavedtopic"


class DirectEmailClient:
    """The provider SDK living inside the app — exactly what module 6
    deletes. Provider address and credentials come from app config
    (≙ the SendGrid API key in appsettings), not from a component."""

    def __init__(self) -> None:
        self.host = os.environ.get("SMTP_HOST", "127.0.0.1")
        self.port = int(os.environ.get("SMTP_PORT", "25"))
        self.api_key = os.environ.get("SENDGRID_API_KEY", "")

    def send(self, *, to: str, subject: str, html: str) -> None:
        msg = MIMEText(html, "html")
        msg["From"] = "noreply@tasksrunner.local"
        msg["To"] = to
        msg["Subject"] = subject
        with smtplib.SMTP(self.host, self.port, timeout=10) as smtp:
            smtp.send_message(msg)


def make_app(email_client: DirectEmailClient | None = None) -> App:
    app = App(APP_ID)
    client = email_client or DirectEmailClient()
    app.state["notified"] = []

    async def _task_saved(req):
        task = req.data or {}
        logger.info("Started processing message with task name '%s'",
                    task.get("taskName"))
        app.state["notified"].append(task)
        assignee = task.get("taskAssignedTo", "")
        if assignee:
            # the provider call the module-6 refactor moves behind a
            # component name: synchronous SDK, provider wire format,
            # and failure modes all owned by the app
            client.send(
                to=assignee,
                subject="Tasks assigned to you",
                html=f"<p>Task <b>{task.get('taskName', '')}</b> "
                     f"is assigned to you.</p>")
        return 200

    app.subscribe(CLOUD_PUBSUB, TOPIC,
                  route="/api/tasksnotifier/tasksaved")(_task_saved)
    app.subscribe(LOCAL_PUBSUB, TOPIC,
                  route="/api/tasksnotifier/tasksaved")(_task_saved)
    return app
