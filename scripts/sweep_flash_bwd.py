"""On-chip sweep of the flash kernels' head-block sizes and backward
variant — the round-4 follow-up the chip tunnel interrupted
(BASELINE.md: "bwd keeps the same heuristic pending a finer sweep").

Every variant is numerically interchangeable (pinned by
tests/test_ml_extension.py::test_flash_backward_variants_match_einsum),
so this sweep is purely a clock question. Each variant runs in its OWN
python process (host quirk: chip experiments must not share a process;
first compile ~15-50 s) with the variant expressed as env overrides:

* TASKSRUNNER_FLASH_HBLK_FWD / _BWD — heads folded per grid program;
* TASKSRUNNER_FLASH_BWD_DELTA=precompute — Δ=Σ(dO∘O) outside the
  kernel, dropping the ``o`` stream (flash-v2 arrangement).

Usage (tunnel up):   python scripts/sweep_flash_bwd.py
No chip available:   python scripts/sweep_flash_bwd.py --cpu
  (interpret-mode run at a small config — ranks per-program overhead
  and stream count, not VMEM pressure; good enough to pick between
  numerically-identical arrangements when the tunnel is down)
Results: ranked table on stdout + build/sweep_flash_bwd.json.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
OUT = REPO / "build" / "sweep_flash_bwd.json"

#: (label, env overrides). The baseline row is the SHIPPED default —
#: heuristic blocks (4 at bench shapes) and, since the sweep promoted
#: it (flash.py module docstring), Δ precomputed outside the kernel.
#: ``delta_fused`` rows restore the round-4 in-kernel Δ for A/B.
VARIANTS: list[tuple[str, dict[str, str]]] = [
    ("baseline(heuristic+delta_pre)", {}),
    ("bwd_hblk=2", {"TASKSRUNNER_FLASH_HBLK_BWD": "2"}),
    ("bwd_hblk=8", {"TASKSRUNNER_FLASH_HBLK_BWD": "8"}),
    ("delta_fused", {"TASKSRUNNER_FLASH_BWD_DELTA": "fused"}),
    ("delta_fused+bwd8", {"TASKSRUNNER_FLASH_BWD_DELTA": "fused",
                          "TASKSRUNNER_FLASH_HBLK_BWD": "8"}),
    ("delta_fused+bwd2", {"TASKSRUNNER_FLASH_BWD_DELTA": "fused",
                          "TASKSRUNNER_FLASH_HBLK_BWD": "2"}),
    ("fwd_hblk=8", {"TASKSRUNNER_FLASH_HBLK_FWD": "8"}),
    ("fwd8+bwd8", {"TASKSRUNNER_FLASH_HBLK_FWD": "8",
                   "TASKSRUNNER_FLASH_HBLK_BWD": "8"}),
]


def child(cpu: bool = False) -> None:
    """One timing run under the current env. Bench-sized config, sync
    via value fetch (block_until_ready returns early on the tunneled
    backend — see bench.py measure()). ``--cpu`` shrinks to an
    interpret-mode-feasible shape (n_heads=8 so every hblk variant
    still divides) and fewer iterations."""
    import jax

    from tasksrunner.ml.model import ModelConfig, init_params, make_train_step

    if cpu:
        cfg = ModelConfig(vocab=1024, seq_len=128, d_model=128,
                          n_heads=8, d_ff=256, n_layers=2)
        batch, n = 4, 5
    else:
        cfg = ModelConfig(vocab=32768, seq_len=512, d_model=1024,
                          n_heads=16, d_ff=4096, n_layers=8)
        batch, n = 32, 20
    key = jax.random.key(0)
    import jax.numpy as jnp
    tokens = jax.random.randint(key, (batch, cfg.seq_len), 0, cfg.vocab,
                                dtype=jnp.int32)
    labels = jax.random.randint(key, (batch,), 0, cfg.n_classes,
                                dtype=jnp.int32)
    params = init_params(cfg, key)
    step = make_train_step(cfg)
    t0 = time.perf_counter()
    params, loss = step(params, tokens, labels)
    float(loss)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        params, loss = step(params, tokens, labels)
    float(loss)
    print(json.dumps({"step_ms": (time.perf_counter() - t0) / n * 1000.0,
                      "compile_s": compile_s}))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--child", action="store_true")
    parser.add_argument("--cpu", action="store_true",
                        help="interpret-mode sweep at a small config "
                             "(no chip required)")
    parser.add_argument("--timeout", type=int, default=600)
    args = parser.parse_args()
    if args.child:
        child(cpu=args.cpu)
        return

    child_cmd = [sys.executable, str(pathlib.Path(__file__)), "--child"]
    child_env = dict(os.environ)
    if args.cpu:
        child_cmd.append("--cpu")
        child_env["JAX_PLATFORMS"] = "cpu"

    results = []
    for label, env in VARIANTS:
        print(f"[{label}] ...", flush=True)
        try:
            proc = subprocess.run(
                child_cmd,
                capture_output=True, text=True, timeout=args.timeout,
                env={**child_env, **env}, cwd=str(REPO))
        except subprocess.TimeoutExpired:
            print(f"[{label}] TIMED OUT (tunnel?)", flush=True)
            results.append({"variant": label, "env": env, "error": "timeout"})
            continue
        if proc.returncode != 0:
            tail = proc.stderr.strip()[-300:]
            print(f"[{label}] FAILED: {tail}", flush=True)
            results.append({"variant": label, "env": env, "error": tail})
            continue
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        row = {"variant": label, "env": env, **row}
        print(f"[{label}] step {row['step_ms']:.2f} ms "
              f"(compile {row['compile_s']:.1f} s)", flush=True)
        results.append(row)

    ok = [r for r in results if "step_ms" in r]
    ok.sort(key=lambda r: r["step_ms"])
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps({"results": results, "ranked": ok}, indent=1))
    if ok:
        print("\nranked:")
        for r in ok:
            print(f"  {r['step_ms']:8.2f} ms  {r['variant']}")
        best = ok[0]
        exports = " ".join(f"{k}={v}" for k, v in best["env"].items())
        suffix = (f" — export {exports}" if best["env"]
                  else " (baseline: no overrides)")
        print(f"\nbest: {best['variant']}{suffix}")
    print(f"\nwrote {OUT}")


if __name__ == "__main__":
    sys.path.insert(0, str(REPO))
    main()
