#!/usr/bin/env python3
"""Build real, inspectable OCI images for the Tasks Tracker services —
without a container daemon.

≙ reference module 12 (docs/aca/12-optimize-containers/index.md:318-326:
each service measured as a real image, default 226 MB → chiseled
119 MB). Round 3 substituted an installed-footprint measurement because
no builder (docker/podman/buildah/kaniko/…) exists in this environment;
this script closes the gap from first principles: an OCI image is just
content-addressed blobs — gzipped layer tars, a config JSON, a manifest
JSON — plus a two-line ``oci-layout`` file and an ``index.json``. All of
that is writable with the stdlib.

For each service (backend-api, frontend-ui, processor) × variant
(default, optimized) the script assembles the layers its Dockerfile
describes, from the same live installation `measure_footprint.py`
measures:

* ``python-runtime`` — interpreter + stdlib (the slice of the base
  image a Python service actually needs; byte-identical blob shared by
  every image, exactly how registries deduplicate base layers);
* ``site-packages`` (default) — dependency closure **plus the
  pip/setuptools/wheel stack** that a full site-packages copy drags
  along, sources as shipped; or ``install`` (optimized) — dependency
  closure + framework only, byte-compiled (`compileall`), no tooling
  (≙ the chiseled image's smaller package inventory);
* ``app`` — the service's sample source under /app/samples;
* ``users`` — /etc/passwd + /etc/group with the non-root ``app`` user
  the Dockerfiles create (`USER app` works when the image runs).

Layers are built reproducibly (sorted entries, zeroed mtimes/uids,
gzip mtime 0, hash-based .pyc invalidation): the same tree always
yields the same digests, so artifact diffs across rounds are
meaningful. The on-disk result is a standard OCI image layout —
``skopeo copy oci:build/oci/backend-api-optimized docker://…`` or
``crane push`` consume it directly wherever those tools exist; here,
``--verify`` re-walks every digest/size/diff_id instead.

Base OS layers (Debian bookworm vs bookworm-slim) remain out of scope
on both sides — they are upstream constants this repo doesn't control
(BASELINE.md documents the exclusion).

Run: python scripts/build_oci_image.py [--out build/oci] [--json]
     [--verify] [--service NAME] [--variant default|optimized]
"""

from __future__ import annotations

import argparse
import compileall
import gzip
import hashlib
import importlib.metadata
import io
import json
import pathlib
import py_compile
import shutil
import sys
import sysconfig
import tarfile
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent

def _footprint_module():
    """The dependency-closure lists live in measure_footprint.py; import
    them so the footprint table and the OCI artifact can never measure
    different closures."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "measure_footprint", pathlib.Path(__file__).parent / "measure_footprint.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_fp = _footprint_module()
RUNTIME_DEPS = _fp.RUNTIME_DEPS
BUILD_TOOLING = _fp.BUILD_TOOLING

SITE = "usr/local/lib/python3.12/site-packages"

#: working-tree junk that must never ship in ANY variant (the
#: optimized path's copytree ignores the same set) — asymmetric
#: filtering would skew the measured saving
JUNK_PARTS = frozenset({"__pycache__", ".tasksrunner"})
JUNK_SUFFIXES = (".db", ".db-wal", ".db-shm")

SERVICES = {
    "backend-api": {
        "module": "samples.tasks_tracker.backend_api:make_app",
        "env": ["TASKS_MANAGER=store"],
        "sidecar_port": "3500",
    },
    "frontend-ui": {
        "module": "samples.tasks_tracker.frontend_ui:make_app",
        "env": [],
        "sidecar_port": "3501",
    },
    "processor": {
        "module": "samples.tasks_tracker.processor:make_app",
        "env": [],
        "sidecar_port": "3502",
    },
}


class LayoutError(Exception):
    """An OCI layout failed verification."""


def sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------------------
# deterministic tar layers
# ---------------------------------------------------------------------------

class _Symlink:
    def __init__(self, target: str):
        self.target = target


class Layer:
    """One OCI layer: a reproducible tar, gzipped; digests computed."""

    def __init__(self, title: str):
        self.title = title
        #: container path → (filesystem source path | bytes, mode)
        self._entries: dict[str, tuple[pathlib.Path | bytes, int]] = {}

    def add_file(self, arcname: str, src: pathlib.Path | bytes,
                 mode: int = 0o644) -> None:
        self._entries[arcname.lstrip("/")] = (src, mode)

    def add_symlink(self, arcname: str, target: str) -> None:
        """Deterministic symlink entry (what real images use for the
        `python` alias — a second full copy of the interpreter would
        inflate the layer by ~7 MB that gzip cannot dedupe)."""
        self._entries[arcname.lstrip("/")] = (_Symlink(target), 0o777)

    def add_tree(self, arc_prefix: str, root: pathlib.Path, *,
                 exclude_parts: frozenset[str] = frozenset({"__pycache__"}),
                 exclude_suffixes: tuple[str, ...] = ()) -> None:
        for p in sorted(root.rglob("*")):
            if not p.is_file() or p.is_symlink():
                continue
            rel = p.relative_to(root)
            if exclude_parts & set(rel.parts):
                continue
            if rel.name.endswith(exclude_suffixes):
                continue
            mode = 0o755 if (p.stat().st_mode & 0o100) else 0o644
            self.add_file(f"{arc_prefix}/{rel}", p, mode)

    def build(self) -> dict:
        """→ {digest, diff_id, size, uncompressed_size, bytes}."""
        raw = io.BytesIO()
        with tarfile.open(fileobj=raw, mode="w",
                          format=tarfile.PAX_FORMAT) as tar:
            dirs_done: set[str] = set()
            for arcname in sorted(self._entries):
                # parent dir entries, once each, for clean extraction
                parts = arcname.split("/")[:-1]
                for i in range(1, len(parts) + 1):
                    d = "/".join(parts[:i])
                    if d and d not in dirs_done:
                        dirs_done.add(d)
                        info = tarfile.TarInfo(d)
                        info.type = tarfile.DIRTYPE
                        info.mode = 0o755
                        info.mtime = 0
                        tar.addfile(info)
                src, mode = self._entries[arcname]
                info = tarfile.TarInfo(arcname)
                info.mode = mode
                info.mtime = 0
                if isinstance(src, _Symlink):
                    info.type = tarfile.SYMTYPE
                    info.linkname = src.target
                    tar.addfile(info)
                elif isinstance(src, bytes):
                    info.size = len(src)
                    tar.addfile(info, io.BytesIO(src))
                else:
                    info.size = src.stat().st_size
                    with src.open("rb") as f:
                        tar.addfile(info, f)
        tar_bytes = raw.getvalue()
        gz = io.BytesIO()
        with gzip.GzipFile(fileobj=gz, mode="wb", mtime=0) as z:
            z.write(tar_bytes)
        gz_bytes = gz.getvalue()
        return {
            "title": self.title,
            "digest": f"sha256:{sha256(gz_bytes)}",
            "diff_id": f"sha256:{sha256(tar_bytes)}",
            "size": len(gz_bytes),
            "uncompressed_size": len(tar_bytes),
            "bytes": gz_bytes,
        }


# ---------------------------------------------------------------------------
# layer contents
# ---------------------------------------------------------------------------

def _dist_files(name: str):
    """Yield (site-relative arcpath, absolute source path) for one
    installed distribution, skipping entries outside site-packages
    (console scripts land in usr/local/bin)."""
    dist = importlib.metadata.distribution(name)
    for f in dist.files or []:
        p = pathlib.Path(dist.locate_file(f))
        if not p.is_file():
            continue
        parts = f.parts
        if ".." in parts:
            # ../../../bin/foo style console script
            if "bin" in parts:
                yield f"usr/local/bin/{parts[-1]}", p
            continue
        # __pycache__ entries stay when RECORD lists them: the tooling
        # stack ships precompiled (that's half its footprint, and half
        # of what the optimized variant saves by dropping it)
        yield f"{SITE}/{f}", p


def _bytecompile_tree(src: pathlib.Path, scratch: pathlib.Path,
                      container_dir: str,
                      prune: tuple[str, ...] = ()) -> pathlib.Path:
    """Copy ``src`` into scratch and compile with hash-based pyc
    invalidation (no timestamps in pyc headers) and the CONTAINER
    path embedded as co_filename (stripdir/prependdir) — without
    that, every build would bake its own temp path into the pycs and
    the layer digest would never reproduce. ``prune`` drops named
    top-level subpackages before compiling (the Dockerfile's `rm -rf`
    of dev-only code)."""
    dst = scratch / src.name
    shutil.copytree(src, dst, ignore=shutil.ignore_patterns(
        "__pycache__", ".tasksrunner", "*.db", "*.db-wal", "*.db-shm"))
    for name in prune:
        shutil.rmtree(dst / name, ignore_errors=True)
    compileall.compile_dir(
        str(dst), quiet=2,
        stripdir=str(dst), prependdir=container_dir,
        invalidation_mode=py_compile.PycInvalidationMode.CHECKED_HASH)
    return dst


def runtime_layer() -> Layer:
    layer = Layer("python-runtime")
    stdlib = pathlib.Path(sysconfig.get_paths()["stdlib"])
    interp = pathlib.Path(sys.executable).resolve()
    layer.add_file("usr/local/bin/python3.12", interp, 0o755)
    layer.add_symlink("usr/local/bin/python", "python3.12")
    layer.add_tree(
        "usr/local/lib/python3.12", stdlib,
        exclude_parts=frozenset({"__pycache__", "site-packages", "test",
                                 "idlelib", "turtledemo"}))
    return layer


def payload_layer(variant: str, scratch: pathlib.Path) -> Layer:
    """The Dockerfile's site-packages/install COPY."""
    if variant == "default":
        layer = Layer("site-packages")
        for name in (*RUNTIME_DEPS, *BUILD_TOOLING):
            for arc, p in _dist_files(name):
                layer.add_file(arc, p, 0o755 if arc.startswith("usr/local/bin")
                               else 0o644)
        # the framework, as `pip install /src` lays it down (sources)
        layer.add_tree(f"{SITE}/tasksrunner", REPO / "tasksrunner",
                       exclude_parts=JUNK_PARTS,
                       exclude_suffixes=JUNK_SUFFIXES)
    else:
        layer = Layer("install")
        for name in RUNTIME_DEPS:
            for arc, p in _dist_files(name):
                layer.add_file(arc, p, 0o755 if arc.startswith("usr/local/bin")
                               else 0o644)
        # the linter (tasksrunner/analysis) is CI/dev tooling and is
        # imported lazily by the `lint` subcommand only — chisel it out
        compiled = _bytecompile_tree(REPO / "tasksrunner", scratch,
                                     f"/{SITE}/tasksrunner",
                                     prune=("analysis",))
        layer.add_tree(f"{SITE}/tasksrunner", compiled,
                       exclude_parts=frozenset())
    return layer


def app_layer(variant: str, scratch: pathlib.Path) -> Layer:
    layer = Layer("app")
    if variant == "default":
        layer.add_tree("app/samples", REPO / "samples",
                       exclude_parts=JUNK_PARTS,
                       exclude_suffixes=JUNK_SUFFIXES)
    else:
        compiled = _bytecompile_tree(REPO / "samples", scratch,
                                 "/app/samples")
        layer.add_tree("app/samples", compiled, exclude_parts=frozenset())
    return layer


def users_layer() -> Layer:
    """`RUN useradd --create-home app` without RUN: the two files the
    command actually produces, so `USER app` resolves at runtime."""
    layer = Layer("users")
    layer.add_file("etc/passwd",
                   b"root:x:0:0:root:/root:/bin/sh\n"
                   b"app:x:1000:1000::/home/app:/bin/sh\n")
    layer.add_file("etc/group", b"root:x:0:\napp:x:1000:\n")
    layer.add_file("home/app/.keep", b"")
    return layer


# ---------------------------------------------------------------------------
# image assembly
# ---------------------------------------------------------------------------

def build_image(service: str, variant: str, out_dir: pathlib.Path,
                shared_layers: dict) -> dict:
    svc = SERVICES[service]
    with tempfile.TemporaryDirectory() as scratch_s:
        scratch = pathlib.Path(scratch_s)
        if "runtime" not in shared_layers:
            shared_layers["runtime"] = runtime_layer().build()
        if ("payload", variant) not in shared_layers:
            shared_layers[("payload", variant)] = (
                payload_layer(variant, scratch).build())
        if ("app", variant) not in shared_layers:
            shared_layers[("app", variant)] = app_layer(variant, scratch).build()
        if "users" not in shared_layers:
            shared_layers["users"] = users_layer().build()

    layers = [shared_layers["runtime"], shared_layers[("payload", variant)],
              shared_layers[("app", variant)], shared_layers["users"]]

    config = {
        "architecture": "amd64",
        "os": "linux",
        "config": {
            "User": "app",
            "Env": ["PATH=/usr/local/bin:/usr/bin:/bin",
                    "PYTHONPATH=/app", *svc["env"]],
            "Entrypoint": ["python", "-m", "tasksrunner", "host",
                           svc["module"], "--app-port", "8080",
                           "--sidecar-port", svc["sidecar_port"],
                           "--host", "0.0.0.0"],
            "WorkingDir": "/app",
            "ExposedPorts": {"8080/tcp": {}},
            "Labels": {
                "org.opencontainers.image.title":
                    f"tasksmanager-{service} ({variant})",
                "org.opencontainers.image.source": "tasksrunner",
            },
        },
        "rootfs": {"type": "layers",
                   "diff_ids": [l["diff_id"] for l in layers]},
        "history": [
            {"created": "1970-01-01T00:00:00Z",
             "created_by": f"tasksrunner build_oci_image ({l['title']})"}
            for l in layers
        ],
    }
    config_bytes = json.dumps(config, sort_keys=True,
                              separators=(",", ":")).encode()
    manifest = {
        "schemaVersion": 2,
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "config": {
            "mediaType": "application/vnd.oci.image.config.v1+json",
            "digest": f"sha256:{sha256(config_bytes)}",
            "size": len(config_bytes),
        },
        "layers": [
            {"mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
             "digest": l["digest"], "size": l["size"],
             "annotations": {"org.opencontainers.image.title": l["title"]}}
            for l in layers
        ],
    }
    manifest_bytes = json.dumps(manifest, sort_keys=True,
                                separators=(",", ":")).encode()
    index = {
        "schemaVersion": 2,
        "mediaType": "application/vnd.oci.image.index.v1+json",
        "manifests": [{
            "mediaType": "application/vnd.oci.image.manifest.v1+json",
            "digest": f"sha256:{sha256(manifest_bytes)}",
            "size": len(manifest_bytes),
            "annotations": {
                "org.opencontainers.image.ref.name":
                    f"tasksmanager-{service}:{variant}",
            },
        }],
    }

    image_dir = out_dir / f"{service}-{variant}"
    blobs = image_dir / "blobs" / "sha256"
    if image_dir.exists():
        shutil.rmtree(image_dir)
    blobs.mkdir(parents=True)
    (image_dir / "oci-layout").write_text(
        json.dumps({"imageLayoutVersion": "1.0.0"}) + "\n")
    (image_dir / "index.json").write_text(
        json.dumps(index, sort_keys=True, separators=(",", ":")) + "\n")
    for l in layers:
        blob = blobs / l["digest"].split(":", 1)[1]
        if not blob.exists():
            blob.write_bytes(l["bytes"])
    (blobs / sha256(config_bytes)).write_bytes(config_bytes)
    (blobs / sha256(manifest_bytes)).write_bytes(manifest_bytes)

    payload_layers = layers[1:3]  # payload + app: what the variant controls
    return {
        "image": f"tasksmanager-{service}:{variant}",
        "path": str(image_dir),
        "layers": [{k: l[k] for k in
                    ("title", "digest", "size", "uncompressed_size")}
                   for l in layers],
        "total_compressed": sum(l["size"] for l in layers),
        "total_uncompressed": sum(l["uncompressed_size"] for l in layers),
        "payload_compressed": sum(l["size"] for l in payload_layers),
        "payload_uncompressed": sum(l["uncompressed_size"]
                                    for l in payload_layers),
    }


# ---------------------------------------------------------------------------
# verification (what skopeo/crane would check, minus the registry)
# ---------------------------------------------------------------------------

def verify_layout(image_dir: pathlib.Path) -> None:
    """Walk index → manifest → config + layers, re-hashing every blob
    and re-deriving every diff_id. Raises LayoutError on any mismatch
    — explicit raises, not assert, so `python -O` cannot strip the
    checks out of a verification tool. The replay test drives this."""

    def check(cond: bool, msg: str) -> None:
        if not cond:
            raise LayoutError(f"{image_dir.name}: {msg}")

    layout = json.loads((image_dir / "oci-layout").read_text())
    check(layout.get("imageLayoutVersion") == "1.0.0",
          f"bad oci-layout: {layout}")

    def blob(digest: str) -> bytes:
        algo, hexd = digest.split(":", 1)
        check(algo == "sha256", f"unsupported digest algo in {digest}")
        data = (image_dir / "blobs" / algo / hexd).read_bytes()
        check(sha256(data) == hexd, f"blob {digest} corrupt")
        return data

    index = json.loads((image_dir / "index.json").read_text())
    check(index.get("schemaVersion") == 2, "index schemaVersion != 2")
    for mdesc in index["manifests"]:
        manifest = json.loads(blob(mdesc["digest"]))
        check(manifest.get("mediaType")
              == "application/vnd.oci.image.manifest.v1+json",
              f"bad manifest mediaType: {manifest.get('mediaType')}")
        config_bytes = blob(manifest["config"]["digest"])
        check(len(config_bytes) == manifest["config"]["size"],
              "config size mismatch")
        config = json.loads(config_bytes)
        diff_ids = config["rootfs"]["diff_ids"]
        check(len(diff_ids) == len(manifest["layers"]),
              "diff_ids/layers count mismatch")
        for ldesc, diff_id in zip(manifest["layers"], diff_ids):
            gz_bytes = blob(ldesc["digest"])
            check(len(gz_bytes) == ldesc["size"],
                  f"layer size mismatch: {ldesc}")
            tar_bytes = gzip.decompress(gz_bytes)
            check(f"sha256:{sha256(tar_bytes)}" == diff_id,
                  f"diff_id mismatch for {ldesc}")
            # and the tar must actually parse
            with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tar:
                check(bool(tar.getmembers()), "empty layer tar")
        check(config["config"]["Entrypoint"][0] == "python",
              "unexpected entrypoint")


# ---------------------------------------------------------------------------

def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=str(REPO / "build" / "oci"))
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--verify", action="store_true",
                        help="verify existing layouts instead of building")
    parser.add_argument("--service", choices=sorted(SERVICES))
    parser.add_argument("--variant", choices=["default", "optimized"])
    args = parser.parse_args()
    out_dir = pathlib.Path(args.out)

    services = [args.service] if args.service else sorted(SERVICES)
    variants = [args.variant] if args.variant else ["default", "optimized"]

    if args.verify:
        for service in services:
            for variant in variants:
                layout = out_dir / f"{service}-{variant}"
                if not (layout / "oci-layout").is_file():
                    raise SystemExit(
                        f"no OCI layout at {layout} — build first "
                        f"(run without --verify)")
                verify_layout(layout)
                print(f"ok {service}-{variant}")
        return

    shared: dict = {}
    results = [build_image(s, v, out_dir, shared)
               for s in services for v in variants]
    for image_dir in [out_dir / f"{s}-{v}" for s in services for v in variants]:
        verify_layout(image_dir)

    mb = 1024.0 * 1024.0
    # fleet-wide saving: summed payload bytes across every built
    # service, per variant (a first-service-only figure would misstate
    # the fleet when app layers diverge)
    payload_by_variant: dict[str, int] = {}
    for r in results:
        variant = r["image"].rsplit(":", 1)[1]
        payload_by_variant[variant] = (payload_by_variant.get(variant, 0)
                                       + r["payload_uncompressed"])
    summary = {
        "images": results,
        "payload_saving_pct": None,
    }
    if {"default", "optimized"} <= payload_by_variant.keys():
        d = payload_by_variant["default"]
        o = payload_by_variant["optimized"]
        summary["payload_saving_pct"] = round(100.0 * (1 - o / d), 1)

    if args.json:
        for r in results:  # bytes are not JSON; sizes are
            for l in r["layers"]:
                l.pop("bytes", None)
        print(json.dumps(summary, indent=2))
        return

    for r in results:
        print(f"\n{r['image']}  ({r['path']})")
        for l in r["layers"]:
            print(f"  {l['title']:<16} {l['size']/mb:8.2f} MB gz "
                  f"({l['uncompressed_size']/mb:8.2f} MB)  {l['digest'][:25]}…")
        print(f"  {'TOTAL':<16} {r['total_compressed']/mb:8.2f} MB gz "
              f"({r['total_uncompressed']/mb:8.2f} MB)")
    if summary["payload_saving_pct"] is not None:
        print(f"\npayload saving (variant-controlled layers), "
              f"default → optimized: {summary['payload_saving_pct']}%")


if __name__ == "__main__":
    main()
