#!/usr/bin/env python3
"""Measure the deployable footprint of each Tasks Tracker service image.

≙ reference module 12's before/after table
(docs/aca/12-optimize-containers/index.md:318-326: default 226 MB →
chiseled 119 MB per service). This environment has no container
daemon, so instead of `docker image ls` this measures — exactly and
reproducibly — every byte the Dockerfiles COPY into the final layer,
from the same sources the build would use:

* framework + sample code (the `COPY tasksrunner/ samples/` layers,
  byte-compiled for the optimized variant, as its `compileall` step
  does);
* third-party dependencies (aiohttp + pyyaml + their transitive
  closure, measured from an actual installation);
* build tooling (pip/setuptools/wheel) — present in the default
  variant's site-packages copy, ABSENT from the optimized variant's
  `--prefix=/install` copy;
* the Python runtime (interpreter + stdlib) measured from the local
  installation — the part of the base image a Python app actually
  needs.

Base OS layers (Debian bookworm full vs slim) cannot be measured
without pulling images; the table reports the payload this repo
controls and notes the base-image choice separately.

Run: python scripts/measure_footprint.py  [--json]
"""

from __future__ import annotations

import argparse
import compileall
import importlib.metadata
import json
import pathlib
import shutil
import sys
import sysconfig
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent

#: the dependency closure of `pip install tasksrunner aiohttp pyyaml`
RUNTIME_DEPS = ("aiohttp", "pyyaml", "aiosignal", "attrs", "frozenlist",
                "multidict", "yarl", "propcache", "aiohappyeyeballs", "idna")
#: in the default variant the whole site-packages is copied, which
#: drags the installer stack along; the optimized variant's
#: --prefix=/install copy has none of it
BUILD_TOOLING = ("pip", "setuptools", "wheel")


def du(path: pathlib.Path, *, exclude_pycache: bool = False) -> int:
    if path.is_file():
        return path.stat().st_size
    total = 0
    for p in path.rglob("*"):
        if exclude_pycache and "__pycache__" in p.parts:
            continue
        if p.is_file() and not p.is_symlink():
            total += p.stat().st_size
    return total


def dist_size(name: str) -> int:
    """Installed size of one distribution, from its file manifest."""
    try:
        dist = importlib.metadata.distribution(name)
    except importlib.metadata.PackageNotFoundError:
        return 0
    total = 0
    for f in dist.files or []:
        try:
            p = pathlib.Path(dist.locate_file(f))
            if p.is_file():
                total += p.stat().st_size
        except OSError:
            continue
    return total


def compiled_size(tree: pathlib.Path, *, prune: tuple[str, ...] = ()) -> int:
    """Size of ``tree`` after the optimized variant's `compileall`
    (sources + .pyc), measured on a scratch copy. ``prune`` drops
    named top-level subpackages first, matching the Dockerfile's
    `rm -rf` of dev-only code."""
    with tempfile.TemporaryDirectory() as tmp:
        dst = pathlib.Path(tmp) / tree.name
        shutil.copytree(tree, dst, ignore=shutil.ignore_patterns(
            "__pycache__", ".tasksrunner", "*.db", "*.db-wal", "*.db-shm"))
        for name in prune:
            shutil.rmtree(dst / name, ignore_errors=True)
        compileall.compile_dir(str(dst), quiet=2)
        return du(dst)


def measure() -> dict:
    mb = 1024.0 * 1024.0
    stdlib = pathlib.Path(sysconfig.get_paths()["stdlib"])
    interpreter = pathlib.Path(sys.executable).resolve()

    framework_src = du(REPO / "tasksrunner", exclude_pycache=True)
    samples_src = du(REPO / "samples", exclude_pycache=True)
    # the optimized image drops the linter (tasksrunner/analysis):
    # it is CI/dev tooling, and `tasksrunner lint` imports it lazily
    framework_opt = compiled_size(REPO / "tasksrunner", prune=("analysis",))
    samples_opt = compiled_size(REPO / "samples")

    deps = {name: dist_size(name) for name in RUNTIME_DEPS}
    tooling = {name: dist_size(name) for name in BUILD_TOOLING}
    runtime = du(stdlib, exclude_pycache=True) + interpreter.stat().st_size

    default_payload = (framework_src + samples_src + sum(deps.values())
                       + sum(tooling.values()))
    optimized_payload = framework_opt + samples_opt + sum(deps.values())

    return {
        "method": "installed-footprint (no container daemon); bytes the "
                  "Dockerfiles COPY, from live installations",
        "python": sys.version.split()[0],
        "mb": {
            "framework_source": round(framework_src / mb, 2),
            "samples_source": round(samples_src / mb, 2),
            "framework_bytecompiled": round(framework_opt / mb, 2),
            "samples_bytecompiled": round(samples_opt / mb, 2),
            "runtime_deps": round(sum(deps.values()) / mb, 2),
            "build_tooling": round(sum(tooling.values()) / mb, 2),
            "python_runtime": round(runtime / mb, 2),
            "default_payload": round(default_payload / mb, 2),
            "optimized_payload": round(optimized_payload / mb, 2),
            "default_total_with_runtime": round(
                (default_payload + runtime) / mb, 2),
            "optimized_total_with_runtime": round(
                (optimized_payload + runtime) / mb, 2),
        },
        "deps_detail_mb": {k: round(v / mb, 2) for k, v in deps.items()},
        "tooling_detail_mb": {k: round(v / mb, 2) for k, v in tooling.items()},
        "payload_saving_pct": round(
            100.0 * (1 - optimized_payload / default_payload), 1),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args()
    result = measure()
    if args.json:
        print(json.dumps(result, indent=2))
        return
    m = result["mb"]
    print(f"method: {result['method']}")
    print(f"python: {result['python']}\n")
    rows = [
        ("framework (tasksrunner/, source)", m["framework_source"]),
        ("samples (3 services, source)", m["samples_source"]),
        ("runtime deps (aiohttp+pyyaml closure)", m["runtime_deps"]),
        ("build tooling (pip/setuptools/wheel)", m["build_tooling"]),
        ("python runtime (interpreter+stdlib)", m["python_runtime"]),
        ("", None),
        ("DEFAULT payload (site-packages copy)", m["default_payload"]),
        ("OPTIMIZED payload (/install copy, byte-compiled)",
         m["optimized_payload"]),
        ("default + python runtime", m["default_total_with_runtime"]),
        ("optimized + python runtime", m["optimized_total_with_runtime"]),
    ]
    for label, val in rows:
        print(f"{label:<50} {'' if val is None else f'{val:>9.2f} MB'}")
    print(f"\npayload saving, default -> optimized: "
          f"{result['payload_saving_pct']}%")


if __name__ == "__main__":
    main()
