#!/usr/bin/env python
"""Metric-name lint: every instrumentation site must use a name
declared in tasksrunner/observability/names.py, under the right
instrument kind.

A typo'd name (or the same name used as two kinds) forks a time series
silently — dashboards, the autoscaler, and the percentile views then
disagree about which series is real. This script greps every
``metrics.inc(...)`` / ``set_gauge(...)`` / ``observe(...)`` call in
the package and fails (exit 1) on any name the registry doesn't
declare for that kind. Run via ``make lint-metrics`` (wired into
``make test``).
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tasksrunner.observability import names  # noqa: E402

# metrics.inc("name", ...) / registry.set_gauge("name", ...) — the
# receiver is unconstrained so helper registries are linted too
CALL_RE = re.compile(
    r"\.(inc|set_gauge|observe_many|observe|recorder)\("
    r"\s*\n?\s*[\"']([A-Za-z0-9_]+)[\"']")

KIND_TABLE = {
    "inc": ("counter", names.COUNTERS),
    "set_gauge": ("gauge", names.GAUGES),
    "observe": ("histogram", names.HISTOGRAMS),
    "observe_many": ("histogram", names.HISTOGRAMS),
    "recorder": ("histogram", names.HISTOGRAMS),
}


def main() -> int:
    problems: list[str] = []
    sites = 0
    for path in sorted((REPO / "tasksrunner").rglob("*.py")):
        text = path.read_text()
        for match in CALL_RE.finditer(text):
            method, name = match.group(1), match.group(2)
            kind, table = KIND_TABLE[method]
            sites += 1
            if name not in table:
                line = text.count("\n", 0, match.start()) + 1
                where = f"{path.relative_to(REPO)}:{line}"
                if name in names.ALL:
                    problems.append(
                        f"{where}: {name!r} used as {kind} but declared as "
                        "a different kind in observability/names.py")
                else:
                    problems.append(
                        f"{where}: {kind} name {name!r} not declared in "
                        "observability/names.py")
    if problems:
        print("metric-name lint FAILED:", file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    print(f"metric-name lint OK ({sites} instrumentation sites, "
          f"{len(names.ALL)} declared names)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
