#!/usr/bin/env python
"""Metric-name lint — thin alias over the tasklint ``metric-names`` rule.

The regex-based checker that used to live here was absorbed into the
AST engine (``tasksrunner/analysis/rules/metricnames.py``), where it
shares inline suppressions, the baseline, ``--json`` output, and the
per-file cache with every other invariant rule. This shim keeps
``python scripts/check_metrics.py`` and the ``make lint-metrics``
workflow working unchanged.
"""

from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tasksrunner.analysis.engine import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--rules", "metric-names", *sys.argv[1:]]))
