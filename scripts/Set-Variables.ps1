# PowerShell twin of set_variables.sh.
# ≙ /root/reference docs/aca/30-appendix/03-variables.md (the workshop
# ships both a bash and a PowerShell variables workflow).
param(
    [ValidateSet("save", "restore", "show")]
    [string]$Action = "restore",
    [string]$VarsFile = ".tasksrunner/variables.env"
)

switch ($Action) {
    "save" {
        New-Item -ItemType Directory -Force -Path (Split-Path $VarsFile) | Out-Null
        Get-ChildItem env: |
            Where-Object { $_.Name -match '^(TASKSRUNNER_|TR_|TASKS_MANAGER$|SENDGRID_)' } |
            Sort-Object Name |
            ForEach-Object { "$($_.Name)=$($_.Value)" } |
            Set-Content $VarsFile
        Write-Host "saved $((Get-Content $VarsFile).Count) variable(s) to $VarsFile"
    }
    "restore" {
        if (Test-Path $VarsFile) {
            Get-Content $VarsFile | ForEach-Object {
                $name, $value = $_ -split '=', 2
                Set-Item -Path "env:$name" -Value $value
            }
            Write-Host "restored $((Get-Content $VarsFile).Count) variable(s) from $VarsFile"
        } else {
            Write-Host "no saved variables at $VarsFile"
        }
    }
    "show" {
        if (Test-Path $VarsFile) { Get-Content $VarsFile }
        else { Write-Host "no saved variables at $VarsFile" }
    }
}
