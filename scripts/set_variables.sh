#!/usr/bin/env bash
# Session variable save/restore.
# ≙ /root/reference docs/aca/30-appendix/03-variables.md:14-40 and
# snippets/restore-variables.md / update-variables.md: the workshop
# persists ~30 shell variables across sessions; the framework keeps the
# same capability for its own local workflows.
#
#   source scripts/set_variables.sh save    # snapshot TASKSRUNNER_*/TR_* vars
#   source scripts/set_variables.sh restore # re-export the snapshot
#   source scripts/set_variables.sh show    # list the snapshot
set -u

VARS_FILE="${TASKSRUNNER_VARS_FILE:-.tasksrunner/variables.env}"
ACTION="${1:-restore}"

# restore only works when SOURCED: a child process can export into
# itself, never into the shell that launched it — executed directly,
# "restore" would print success and change nothing
if [[ "$ACTION" == "restore" && "${BASH_SOURCE[0]:-}" == "$0" ]]; then
  echo "warning: run as 'source $0 restore' — executed directly, the" >&2
  echo "restored variables die with this subshell" >&2
  exit 1
fi

case "$ACTION" in
  save)
    mkdir -p "$(dirname "$VARS_FILE")"
    env | grep -E '^(TASKSRUNNER_|TR_|TASKS_MANAGER=|SENDGRID_)' | LC_ALL=C sort > "$VARS_FILE"
    echo "saved $(wc -l < "$VARS_FILE") variable(s) to $VARS_FILE"
    ;;
  restore)
    if [[ -f "$VARS_FILE" ]]; then
      set -a
      # shellcheck disable=SC1090
      source "$VARS_FILE"
      set +a
      echo "restored $(wc -l < "$VARS_FILE") variable(s) from $VARS_FILE"
    else
      echo "no saved variables at $VARS_FILE"
    fi
    ;;
  show)
    [[ -f "$VARS_FILE" ]] && cat "$VARS_FILE" || echo "no saved variables at $VARS_FILE"
    ;;
  *)
    echo "usage: source scripts/set_variables.sh [save|restore|show]" >&2
    ;;
esac
