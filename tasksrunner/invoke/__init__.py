from tasksrunner.invoke.resolver import AppAddress, NameResolver

__all__ = ["AppAddress", "NameResolver"]
