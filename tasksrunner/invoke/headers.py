"""Header-filtering policy shared by every invoke transport.

The sidecar HTTP route, the framed mesh lane, and the in-proc channel
must treat headers identically — an app must not be able to observe
which transport carried a call (runtime.py's behavioral-equivalence
contract). One definition here, imported by all of them, so the sets
cannot drift.
"""

from __future__ import annotations

#: response headers that describe the hop, not the payload — never
#: forwarded (≙ RFC 9110 §7.6.1 connection-oriented headers)
HOP_BY_HOP = frozenset({
    "content-length", "transfer-encoding", "connection",
    "keep-alive", "server", "date",
})


def inward_headers(headers: dict[str, str]) -> dict[str, str]:
    """The subset of caller headers forwarded to the target app:
    content negotiation plus ``x-*`` application headers — cookies,
    auth material, and transport noise stay behind."""
    return {
        k: v for k, v in ((k.lower(), v) for k, v in headers.items())
        if k in ("content-type", "accept") or k.startswith("x-")
    }


def outward_headers(headers: dict[str, str]) -> dict[str, str]:
    """App response headers minus hop-by-hop noise (redirect locations,
    cookies, etags all travel — HTTP mode must not lose what the direct
    transport delivers)."""
    return {k: v for k, v in headers.items() if k.lower() not in HOP_BY_HOP}
