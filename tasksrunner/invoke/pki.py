"""Mesh mTLS: a private CA per environment, one certificate per app.

The reference's architecture note is explicit that while apps talk
HTTP to their own sidecar, the sidecars talk to EACH OTHER over
**mutual TLS** (docs/aca/03-aca-dapr-integration/index.md:30-38 —
Dapr's sentry issues workload certs from a trust-domain CA). This
module is that machinery for the framework's mesh lane
(invoke/mesh.py): the orchestrator plays sentry — it generates an
environment CA at start and issues each app a certificate whose SAN
is its app-id — and the mesh endpoints authenticate BOTH ways:

* the dialing sidecar verifies the listener's cert chains to the
  environment CA **and** names the app-id it meant to reach (a
  hijacked registry entry pointing at a rogue port fails the
  handshake — the rogue can't present the right identity);
* the listening sidecar requires a client cert from the same CA
  (non-members can't even speak; app-level authorization on top of
  that stays with the per-app token digests, as on the HTTP surface).

Enabled when the three env vars point at PEM files (the orchestrator
sets them per replica when the manifest asks for ``mesh_tls``):

    TASKSRUNNER_MESH_CA    — the environment CA certificate
    TASKSRUNNER_MESH_CERT  — this app's certificate
    TASKSRUNNER_MESH_KEY   — this app's private key (mode 0600)

Unset → the mesh stays plaintext-on-localhost (the dev default, where
every process shares a kernel anyway); the HTTP surface is never TLS
— it is localhost-only app-facing API, exactly as in the reference.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import pathlib
import ssl

CA_ENV = "TASKSRUNNER_MESH_CA"
CERT_ENV = "TASKSRUNNER_MESH_CERT"
KEY_ENV = "TASKSRUNNER_MESH_KEY"


def mesh_tls_enabled() -> bool:
    return all(os.environ.get(v) for v in (CA_ENV, CERT_ENV, KEY_ENV))


# ---------------------------------------------------------------------------
# issuance (orchestrator side, ≙ Dapr sentry)
# ---------------------------------------------------------------------------

def _keypair():
    from cryptography.hazmat.primitives.asymmetric import ec
    return ec.generate_private_key(ec.SECP256R1())


def _key_pem(key) -> bytes:
    from cryptography.hazmat.primitives import serialization
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())


def generate_ca(common_name: str = "tasksrunner-mesh-ca",
                *, days: int = 365) -> tuple[bytes, bytes]:
    """→ (ca_cert_pem, ca_key_pem)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.x509.oid import NameOID

    key = _keypair()
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .add_extension(x509.KeyUsage(
            digital_signature=True, key_cert_sign=True, crl_sign=True,
            content_commitment=False, key_encipherment=False,
            data_encipherment=False, key_agreement=False,
            encipher_only=False, decipher_only=False), critical=True)
        .sign(key, hashes.SHA256())
    )
    from cryptography.hazmat.primitives import serialization
    return cert.public_bytes(serialization.Encoding.PEM), _key_pem(key)


def issue_cert(ca_cert_pem: bytes, ca_key_pem: bytes, app_id: str,
               *, days: int = 365) -> tuple[bytes, bytes]:
    """→ (cert_pem, key_pem) for one app: SAN carries the app-id (the
    identity the dialer pins) plus the loopback names the mesh
    actually connects to."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
    ca_key = serialization.load_pem_private_key(ca_key_pem, password=None)
    key = _keypair()
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, app_id)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.SubjectAlternativeName([
            x509.DNSName(app_id),
            x509.DNSName("localhost"),
            x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
        ]), critical=False)
        .add_extension(x509.ExtendedKeyUsage([
            ExtendedKeyUsageOID.SERVER_AUTH,
            ExtendedKeyUsageOID.CLIENT_AUTH,
        ]), critical=False)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    return cert.public_bytes(serialization.Encoding.PEM), _key_pem(key)


def write_pki(directory: str | pathlib.Path,
              app_ids: list[str]) -> dict[str, dict[str, str]]:
    """Generate a CA + per-app certs under ``directory``; private keys
    land mode 0600. → {app_id: {ca, cert, key}} env-ready path maps."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    ca_cert, ca_key = generate_ca()
    ca_path = directory / "ca.pem"
    ca_path.write_bytes(ca_cert)
    # the CA key never leaves this function's files; replicas get only
    # the CA *cert* (to verify) and their own leaf pair
    ca_key_path = directory / "ca-key.pem"
    ca_key_path.touch(mode=0o600)
    ca_key_path.write_bytes(ca_key)
    out: dict[str, dict[str, str]] = {}
    for app_id in app_ids:
        cert, key = issue_cert(ca_cert, ca_key, app_id)
        cert_path = directory / f"{app_id}.pem"
        key_path = directory / f"{app_id}-key.pem"
        cert_path.write_bytes(cert)
        key_path.touch(mode=0o600)
        key_path.write_bytes(key)
        out[app_id] = {"ca": str(ca_path), "cert": str(cert_path),
                       "key": str(key_path)}
    return out


# ---------------------------------------------------------------------------
# runtime side (both ends of the mesh)
# ---------------------------------------------------------------------------

def server_ssl_context() -> ssl.SSLContext | None:
    """mTLS listener context from the env, or None (plaintext mesh)."""
    if not mesh_tls_enabled():
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(os.environ[CERT_ENV], os.environ[KEY_ENV])
    ctx.load_verify_locations(os.environ[CA_ENV])
    ctx.verify_mode = ssl.CERT_REQUIRED  # the "m" in mTLS
    return ctx


def client_ssl_context() -> ssl.SSLContext | None:
    """Dialer context: presents this app's cert, verifies the peer
    against the environment CA; the caller passes the target app-id as
    ``server_hostname`` so the SAN check pins the peer's identity."""
    if not mesh_tls_enabled():
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(os.environ[CERT_ENV], os.environ[KEY_ENV])
    ctx.load_verify_locations(os.environ[CA_ENV])
    ctx.check_hostname = True
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx
