"""Framed peer-invocation transport (the sidecar↔sidecar lane).

In the reference, applications program the sidecar's HTTP surface, but
the sidecars talk to EACH OTHER over Dapr's internal gRPC transport
with mTLS (docs/aca/03-aca-dapr-integration/index.md:30-38 — "Dapr
sidecars communicate over mutual TLS"; the `/v1.0/invoke/...` HTTP
shape is the app→sidecar API, docs module 3 :107-127). This module is
that internal lane for this framework: a persistent TCP connection per
peer carrying length-prefixed multiplexed request/response frames —
no per-request connection setup, no HTTP/1.1 parsing on either end.

Behavioral contract (must stay identical to the sidecar HTTP route
``/v1.0/invoke/{app-id}/method/{path}`` in sidecar.py):

* same token rules — the receiving app's own API token OR a registered
  peer app's token (digest match) is accepted, nothing else;
* same trace adoption — the ``traceparent`` header opens a trace scope
  on the server before dispatch;
* same header filtering — only content-type/accept/x-* travel inward,
  hop-by-hop headers are dropped outward;
* same error mapping — TasksRunnerError → its http_status, anything
  else → 500, body ``{"error": ...}``.

Wire format, both directions::

    [u32 frame_len][u32 header_len][header][body bytes]

The header comes in two encodings, chosen **per connection, never per
frame**, by a hello handshake on the first frame:

* **v1 (JSON)** — the original format. Request header ``{"i": id,
  "t": target, "m": method, "p": path, "q": query, "h": {...}}``;
  response ``{"i": id, "s": status, "h": {...}}``. A JSON header
  always starts with ``{`` (0x7B).
* **v2 (binary)** — the same fields struct-packed
  (:class:`BinaryHeaderCodec`); first byte is the magic 0xB2, which no
  JSON header can start with. Roughly 3-4× cheaper to encode+decode
  than ``json.dumps``/``json.loads`` for the small per-frame headers
  that dominate the lane.

Negotiation: a v2 client's first frame is the JSON header
``{"i": 0, "hello": 2}``; a v2 server answers ``{"i": 0, "hello": v}``
with ``v = min(client, server)`` and both sides switch codecs iff
``v >= 2``. A legacy (pre-v2) server treats the hello as an ordinary
request and answers a failed JSON response with no ``hello`` key — the
client then stays on JSON. A legacy client sends no hello; the server
keeps JSON for that connection. Rolling upgrades therefore never
break: both directions degrade to v1. ``TASKSRUNNER_MESH_CODEC=json``
forces v1 on either side.

Writes are coalesced per connection (:class:`_FrameWriter`): frames
queue on a list and a write-behind flusher drains everything queued
into ONE ``writer.writelines`` + ONE ``drain()`` per wakeup — the
group-commit trick applied to the socket. Frames interleave freely;
``i`` correlates them.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import struct
import time
from typing import TYPE_CHECKING

from tasksrunner.envflag import env_flag
from tasksrunner.errors import TasksRunnerError
from tasksrunner.invoke.headers import inward_headers, outward_headers
from tasksrunner.observability.metrics import metrics
from tasksrunner.observability.tracing import (
    TRACEPARENT_HEADER,
    ensure_trace,
    trace_scope,
)
from tasksrunner.security import (
    TOKEN_ENV,
    TOKEN_HEADER,
    hash_token,
    load_token_map,
)

if TYPE_CHECKING:  # pragma: no cover - import cycle guard
    from tasksrunner.runtime import Runtime

logger = logging.getLogger(__name__)

_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
#: request cap matches the sidecar HTTP server's client_max_size —
#: and like HTTP (where client_max_size bounds requests only, not
#: responses) it applies to the request direction alone
MAX_FRAME = 16 * 1024 * 1024
#: headers are tiny metadata; anything bigger is a corrupt stream
MAX_HEADER = 64 * 1024
#: how long a dial may take before the peer is declared unreachable
#: and the caller falls back to HTTP (a blackholed host must not hold
#: invokes for the kernel's SYN-retry window)
CONNECT_TIMEOUT = 2.0
#: per-request ceiling, matching the HTTP lane's bounded failure
#: (aiohttp's default 300 s total timeout): a hung peer handler or a
#: half-open connection must surface as a retriable TimeoutError (an
#: OSError subclass), never an unbounded hang
REQUEST_TIMEOUT = 300.0
#: idle-ping cadence for pooled connections (pre-warm keepalive)
PING_INTERVAL = 15.0
#: consecutive request timeouts after which a connection is condemned
#: so the pool re-dials instead of queueing every later request behind
#: the same hung socket for REQUEST_TIMEOUT each
TIMEOUTS_BEFORE_CLOSE = 2

#: highest header version this build speaks
MESH_VERSION = 2


def _env_seconds(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def connect_timeout() -> float:
    return _env_seconds("TASKSRUNNER_MESH_CONNECT_TIMEOUT_SECONDS",
                        CONNECT_TIMEOUT)


def request_timeout() -> float:
    return _env_seconds("TASKSRUNNER_MESH_REQUEST_TIMEOUT_SECONDS",
                        REQUEST_TIMEOUT)


def ping_interval() -> float:
    return _env_seconds("TASKSRUNNER_MESH_PING_SECONDS", PING_INTERVAL)


def coalesce_window() -> float:
    return _env_seconds("TASKSRUNNER_MESH_COALESCE_SECONDS", 0.0)


def _forced_json() -> bool:
    return os.environ.get(
        "TASKSRUNNER_MESH_CODEC", "").strip().lower() == "json"


class MeshConnectError(ConnectionError):
    """Could not establish the peer connection (distinct from an
    in-flight drop so the caller can fall back to HTTP within the
    same attempt instead of burning a retry)."""


# ---------------------------------------------------------------------------
# header codecs — one chosen per connection at hello time
# ---------------------------------------------------------------------------

class JsonHeaderCodec:
    """v1 wire headers: compact JSON (always starts with ``{``)."""

    version = 1

    @staticmethod
    def encode(header: dict) -> bytes:
        return json.dumps(header, separators=(",", ":")).encode()

    @staticmethod
    def decode(raw: bytes) -> dict:
        try:
            header = json.loads(raw)
        except ValueError as exc:
            raise ConnectionError(
                f"mesh frame header not JSON: {exc}") from exc
        if not isinstance(header, dict):
            raise ConnectionError("mesh frame header not an object")
        return header


class MeshProtocolError(ConnectionError):
    """A frame violated the v2 header encoding (encode- or decode-side).

    From the codec's perspective the connection is unusable — callers
    tear it down and re-dial — so this is connection failure, not
    request validation: it must never surface as an app-level status.
    """


#: first header byte of every v2 frame — can never collide with a JSON
#: header (those start with ``{`` = 0x7B), so a server can tell a
#: protocol violation from a legacy peer on the FIRST frame
_BIN_MAGIC = 0xB2
_K_REQ, _K_RESP, _K_PING, _K_PONG, _K_RREQ, _K_RREP = 1, 2, 3, 4, 5, 6

_REQ_FIXED = struct.Struct(">BBQHHHHH")   # magic kind id |t| |m| |p| |q| n(h)
_RESP_FIXED = struct.Struct(">BBQHH")     # magic kind id status n(h)
_CTRL_FIXED = struct.Struct(">BBQ")       # magic kind id      (ping/pong)
_RREQ_FIXED = struct.Struct(">BBBIH")     # magic kind op shard |store|
_RREP_FIXED = struct.Struct(">BBBBQQH")   # magic kind flags rkind hwm epoch |err|

_REPL_OPS = {"append": 1, "install": 2, "position": 3}
_REPL_OP_NAMES = {v: k for k, v in _REPL_OPS.items()}
_REPL_KINDS = {"gap": 1, "fenced": 2, "error": 3}
_REPL_KIND_NAMES = {v: k for k, v in _REPL_KINDS.items()}


def _encode_pairs(h: dict) -> tuple[int, list[bytes]]:
    parts: list[bytes] = []
    for k, v in h.items():
        kb, vb = str(k).encode(), str(v).encode()
        if len(kb) > 0xFFFF or len(vb) > 0xFFFF:
            raise MeshProtocolError("mesh header field exceeds the v2 field limit")
        parts += (_U16.pack(len(kb)), kb, _U16.pack(len(vb)), vb)
    return len(h), parts


def _decode_pairs(raw: bytes, off: int, n: int) -> tuple[dict, int]:
    h: dict[str, str] = {}
    for _ in range(n):
        (lk,) = _U16.unpack_from(raw, off)
        off += 2
        k = raw[off:off + lk].decode()
        off += lk
        (lv,) = _U16.unpack_from(raw, off)
        off += 2
        h[k] = raw[off:off + lv].decode()
        off += lv
    return h, off


class BinaryHeaderCodec:
    """v2 wire headers: struct-packed, negotiated never guessed.

    Encodes/decodes the exact same header *dicts* the JSON codec moves
    (``{"i","t","m","p","q","h"}`` requests, ``{"i","s","h"}``
    responses, ``{"ping"|"pong": id}`` control frames, and the
    replication lane's ``{"op","store","shard"}`` / ``{"ok",...}``
    shapes), so every caller above the codec is encoding-agnostic.
    """

    version = 2

    @staticmethod
    def encode(header: dict) -> bytes:
        if "t" in header:
            t = str(header["t"]).encode()
            m = str(header.get("m", "POST")).encode()
            p = str(header.get("p", "/")).encode()
            q = str(header.get("q", "")).encode()
            if max(len(t), len(m), len(p), len(q)) > 0xFFFF:
                raise MeshProtocolError(
                    "mesh header field exceeds the v2 field limit")
            n, parts = _encode_pairs(header.get("h") or {})
            return b"".join([
                _REQ_FIXED.pack(_BIN_MAGIC, _K_REQ, int(header["i"]),
                                len(t), len(m), len(p), len(q), n),
                t, m, p, q, *parts])
        if "s" in header:
            n, parts = _encode_pairs(header.get("h") or {})
            return b"".join([
                _RESP_FIXED.pack(_BIN_MAGIC, _K_RESP,
                                 int(header.get("i") or 0),
                                 int(header["s"]), n), *parts])
        if "ping" in header:
            return _CTRL_FIXED.pack(_BIN_MAGIC, _K_PING, int(header["ping"]))
        if "pong" in header:
            return _CTRL_FIXED.pack(_BIN_MAGIC, _K_PONG, int(header["pong"]))
        if "op" in header:
            op = _REPL_OPS.get(header["op"])
            if op is None:
                raise MeshProtocolError(f"unknown replication op {header['op']!r}")
            store = str(header.get("store", "")).encode()
            # trace context rides the frame as an optional length-
            # prefixed tail — absent, the frame is byte-identical to
            # the original v2 shape (old v2 decoders parse it fine)
            tp = str(header.get("tp") or "").encode()
            if len(store) > 0xFFFF or len(tp) > 0xFFFF:
                raise MeshProtocolError(
                    "mesh header field exceeds the v2 field limit")
            frame = _RREQ_FIXED.pack(
                _BIN_MAGIC, _K_RREQ, op,
                int(header.get("shard", 0)), len(store)) + store
            if tp:
                frame += _U16.pack(len(tp)) + tp
            return frame
        if "ok" in header:
            flags = ((1 if header.get("ok") else 0)
                     | (2 if header.get("diverged") else 0))
            err = str(header.get("error") or "").encode()[:0xFFFF]
            return _RREP_FIXED.pack(
                _BIN_MAGIC, _K_RREP, flags,
                _REPL_KINDS.get(header.get("kind"), 0),
                int(header.get("hwm", 0)), int(header.get("epoch", 0)),
                len(err)) + err
        raise MeshProtocolError(f"unencodable mesh header: {sorted(header)}")

    @staticmethod
    def decode(raw: bytes) -> dict:
        try:
            if raw[0] != _BIN_MAGIC:
                raise MeshProtocolError(f"bad magic 0x{raw[0]:02x}")
            kind = raw[1]
            if kind == _K_REQ:
                (_, _, rid, lt, lm, lp, lq, n) = _REQ_FIXED.unpack_from(raw)
                off = _REQ_FIXED.size
                t = raw[off:off + lt].decode()
                off += lt
                m = raw[off:off + lm].decode()
                off += lm
                p = raw[off:off + lp].decode()
                off += lp
                q = raw[off:off + lq].decode()
                off += lq
                h, off = _decode_pairs(raw, off, n)
                if off != len(raw):
                    raise MeshProtocolError("length mismatch")
                return {"i": rid, "t": t, "m": m, "p": p, "q": q, "h": h}
            if kind == _K_RESP:
                (_, _, rid, status, n) = _RESP_FIXED.unpack_from(raw)
                h, off = _decode_pairs(raw, _RESP_FIXED.size, n)
                if off != len(raw):
                    raise MeshProtocolError("length mismatch")
                return {"i": rid, "s": status, "h": h}
            if kind in (_K_PING, _K_PONG):
                (_, _, rid) = _CTRL_FIXED.unpack_from(raw)
                if _CTRL_FIXED.size != len(raw):
                    raise MeshProtocolError("length mismatch")
                return {("ping" if kind == _K_PING else "pong"): rid}
            if kind == _K_RREQ:
                (_, _, op, shard, ls) = _RREQ_FIXED.unpack_from(raw)
                off = _RREQ_FIXED.size
                store = raw[off:off + ls].decode()
                off += ls
                out = {"op": _REPL_OP_NAMES.get(op, "?"),
                       "store": store, "shard": shard}
                if off != len(raw):
                    # optional trace-context tail (see encode)
                    (ltp,) = _U16.unpack_from(raw, off)
                    off += 2
                    tp = raw[off:off + ltp].decode()
                    off += ltp
                    if off != len(raw):
                        raise MeshProtocolError("length mismatch")
                    if tp:
                        out["tp"] = tp
                return out
            if kind == _K_RREP:
                (_, _, flags, rkind, hwm,
                 epoch, le) = _RREP_FIXED.unpack_from(raw)
                if _RREP_FIXED.size + le != len(raw):
                    raise MeshProtocolError("length mismatch")
                err = raw[_RREP_FIXED.size:_RREP_FIXED.size + le].decode()
                if flags & 1:
                    return {"ok": True}
                out: dict = {"ok": False,
                             "kind": _REPL_KIND_NAMES.get(rkind, "error")}
                if rkind == _REPL_KINDS["gap"]:
                    out["hwm"] = hwm
                    out["epoch"] = epoch
                    out["diverged"] = bool(flags & 2)
                if err:
                    out["error"] = err
                return out
            raise MeshProtocolError(f"unknown frame kind {kind}")
        except ConnectionError:
            raise
        except (struct.error, IndexError, UnicodeDecodeError, ValueError,
                OverflowError) as exc:
            raise ConnectionError(
                f"mesh v2 header corrupt: {exc}") from exc


def pack_frame(codec, header: dict, body: bytes) -> list[bytes]:
    """Encode one frame as zero-copy segments for ``writelines`` —
    never concatenated (the old ``prefix+hdr+body`` triple-copy)."""
    hdr = codec.encode(header)
    return [_U32.pack(4 + len(hdr) + len(body)), _U32.pack(len(hdr)),
            hdr, body]


def _pack(header: dict, body: bytes) -> bytes:
    """One JSON-header frame as contiguous bytes — the pre-negotiation
    format (hello frames) and the shape legacy peers speak."""
    return b"".join(pack_frame(JsonHeaderCodec, header, body))


#: absolute insanity bound on any frame (a corrupt length prefix must
#: not make readexactly buffer gigabytes); far above any legit payload
_SANITY_FRAME = 1 << 30

_rec_frame_in = metrics.recorder("mesh_frame_bytes", direction="in")
_rec_frame_out = metrics.recorder("mesh_frame_bytes", direction="out")
_rec_dial = metrics.recorder("mesh_dial_latency_seconds")


async def _read_frame_raw(reader: asyncio.StreamReader, *,
                          max_body: int | None = None
                          ) -> tuple[bytes, bytes | None]:
    """Read one frame's raw header and body bytes. With ``max_body``
    set (the server's request direction), an oversized body is drained
    off the wire and returned as ``None`` so the caller can answer 413
    and keep the connection — the same observable outcome as the HTTP
    route's client_max_size. A structurally corrupt frame raises
    ConnectionError (tear down)."""
    head = await reader.readexactly(8)
    frame_len, hdr_len = _U32.unpack_from(head, 0)[0], _U32.unpack_from(head, 4)[0]
    if frame_len < 4 or frame_len > _SANITY_FRAME:
        raise ConnectionError(f"mesh frame corrupt: len={frame_len}")
    if hdr_len > frame_len - 4 or hdr_len > MAX_HEADER:
        raise ConnectionError(f"mesh frame header corrupt: len={hdr_len}")
    hdr = await reader.readexactly(hdr_len)
    body_len = frame_len - 4 - hdr_len
    metrics.inc("mesh_frames_total", direction="in")
    _rec_frame_in(8 + hdr_len + body_len)
    if max_body is not None and body_len > max_body:
        remaining = body_len
        while remaining:
            chunk = await reader.read(min(1 << 16, remaining))
            if not chunk:
                raise asyncio.IncompleteReadError(b"", remaining)
            remaining -= len(chunk)
        return hdr, None
    return hdr, await reader.readexactly(body_len)


async def _read_frame(reader: asyncio.StreamReader, codec=JsonHeaderCodec, *,
                      max_body: int | None = None) -> tuple[dict, bytes | None]:
    hdr, body = await _read_frame_raw(reader, max_body=max_body)
    return codec.decode(hdr), body


# ---------------------------------------------------------------------------
# codec negotiation — per connection, decided by the FIRST frame only
# ---------------------------------------------------------------------------

async def negotiate_client(reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter, *,
                           timeout: float) -> tuple[type, bool]:
    """Client side of the hello handshake, run inline before the read
    loop starts. Returns ``(codec, peer_aware)`` — ``peer_aware`` is
    True iff the server acknowledged the hello (so it understands
    control frames like ping, even if it capped the codec at v1)."""
    if _forced_json():
        return JsonHeaderCodec, False
    writer.write(_pack({"i": 0, "hello": MESH_VERSION}, b""))
    await writer.drain()
    header, _ = await asyncio.wait_for(_read_frame(reader), timeout)
    ver = header.get("hello")
    if ver is None:
        # legacy JSON-only peer: it dispatched the hello as a (failed)
        # request and answered an ordinary response — consume it and
        # stay on the v1 JSON codec for this connection's lifetime
        return JsonHeaderCodec, False
    if not isinstance(ver, int) or isinstance(ver, bool) or ver < 1:
        raise ConnectionError(f"mesh hello corrupt: {ver!r}")
    return (BinaryHeaderCodec if ver >= 2 else JsonHeaderCodec), True


async def negotiate_server(reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter, *,
                           max_body: int | None,
                           max_version: int | None = None
                           ) -> tuple[type, tuple[dict, bytes | None] | None]:
    """Server side of the hello handshake. Returns ``(codec, first)``
    where ``first`` is a decoded request frame to dispatch when the
    peer skipped the hello (a legacy JSON client's first real request
    doubles as its codec declaration)."""
    if max_version is None:
        max_version = 1 if _forced_json() else MESH_VERSION
    hdr, body = await _read_frame_raw(reader, max_body=max_body)
    if hdr[:1] != b"{":
        # binary before negotiation: the codec is never guessed
        raise ConnectionError(
            "mesh peer sent a non-JSON frame before hello negotiation")
    header = JsonHeaderCodec.decode(hdr)
    ver = header.get("hello")
    if ver is None:
        return JsonHeaderCodec, (header, body)
    if not isinstance(ver, int) or isinstance(ver, bool) or ver < 1:
        raise ConnectionError(f"mesh hello corrupt: {ver!r}")
    ver = min(ver, max_version)
    writer.write(_pack({"i": header.get("i", 0), "hello": ver}, b""))
    await writer.drain()
    return (BinaryHeaderCodec if ver >= 2 else JsonHeaderCodec), None


# ---------------------------------------------------------------------------
# coalesced writer — one writelines + one drain per wakeup
# ---------------------------------------------------------------------------

class _FrameWriter:
    """Per-connection write-behind flusher.

    ``send()`` appends a frame's segments and returns immediately; the
    flusher task drains everything queued since its last wakeup into
    ONE ``writer.writelines`` + ONE ``drain()`` — under concurrency the
    event loop naturally batches every frame produced in the same tick
    into a single syscall (the PR 1 group-commit trick applied to the
    socket). ``TASKSRUNNER_MESH_COALESCE=0`` switches to the old
    locked write+drain per frame (the bench lever and safety valve);
    ``TASKSRUNNER_MESH_COALESCE_SECONDS`` adds a fixed window on top
    of the natural batching (default 0: latency is never traded away).

    A transport failure parks the writer: the error surfaces through
    ``on_error`` once and every later ``send()`` raises ConnectionError
    so callers see the dead socket promptly.
    """

    def __init__(self, writer: asyncio.StreamWriter, *,
                 on_error=None) -> None:
        self._writer = writer
        self._on_error = on_error
        self._window = coalesce_window()
        self._buf: list[bytes] = []
        self._wake = asyncio.Event()
        self._failed: Exception | None = None
        self._closed = False
        if env_flag("TASKSRUNNER_MESH_COALESCE"):
            self._wlock: asyncio.Lock | None = None
            self._task: asyncio.Task | None = asyncio.create_task(self._run())
        else:
            self._wlock = asyncio.Lock()
            self._task = None

    async def send(self, segments: list[bytes]) -> None:
        if self._failed is not None:
            raise ConnectionError(
                f"mesh writer failed: {self._failed}") from self._failed
        if self._closed:
            raise ConnectionError("mesh writer closed")
        metrics.inc("mesh_frames_total", direction="out")
        _rec_frame_out(sum(map(len, segments)))
        if self._wlock is not None:  # coalescing off: per-frame drain
            async with self._wlock:
                try:
                    self._writer.writelines(segments)
                    await self._writer.drain()
                except (ConnectionError, OSError) as exc:
                    self._fail(exc)
                    raise
            return
        self._buf.extend(segments)
        self._wake.set()

    async def _run(self) -> None:
        try:
            while True:
                await self._wake.wait()
                if self._window > 0:
                    await asyncio.sleep(self._window)
                self._wake.clear()
                batch, self._buf = self._buf, []
                if not batch:
                    continue
                self._writer.writelines(batch)
                await self._writer.drain()
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError) as exc:
            self._fail(exc)
        except Exception as exc:  # noqa: BLE001 - park, never strand senders
            self._fail(exc)

    def _fail(self, exc: Exception) -> None:
        if self._failed is None:
            self._failed = exc
            if self._on_error is not None:
                self._on_error(exc)

    async def aclose(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._failed is None and self._buf:
            # best-effort final flush so a response written just before
            # teardown still reaches the peer (the old per-frame drain
            # gave that guarantee implicitly)
            batch, self._buf = self._buf, []
            try:
                self._writer.writelines(batch)
                await self._writer.drain()
            except (ConnectionError, OSError):
                pass


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class MeshServer:
    """Accepts peer frames and dispatches them into the local Runtime —
    the same entry point the sidecar HTTP invoke route uses."""

    def __init__(self, runtime: "Runtime", *, host: str = "127.0.0.1",
                 port: int = 0, api_token: str | None = None,
                 peer_tokens: set[str] | None = None):
        self.runtime = runtime
        self.host = host
        self.port = port
        if api_token is None:
            api_token = os.environ.get(TOKEN_ENV) or None
        self.api_token = api_token
        if peer_tokens is None:
            # sha256 digests: authenticate inbound peers without being
            # able to replay their tokens (sidecar.py does the same)
            peer_tokens = set(load_token_map().values())
        self.peer_tokens = peer_tokens
        #: codec ceiling offered in the hello ack; None → env-resolved
        #: (tests pin it to 1 to emulate a JSON-only server in-process)
        self.max_version: int | None = None
        self._server: asyncio.base_events.Server | None = None
        self._conn_writers: set[asyncio.StreamWriter] = set()

    async def start(self) -> None:
        from tasksrunner.invoke.pki import server_ssl_context

        # mTLS when the environment provisioned certs (invoke/pki.py,
        # ≙ Dapr sentry's workload certificates); plaintext otherwise
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port,
            ssl=server_ssl_context())
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # established peer connections are long-lived by design —
            # close them or wait_closed() (which on 3.12+ waits for the
            # per-connection handlers too) never returns
            for writer in list(self._conn_writers):
                writer.close()
            await self._server.wait_closed()
            self._server = None

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        inflight: set[asyncio.Task] = set()
        self._conn_writers.add(writer)
        fw: _FrameWriter | None = None
        try:
            try:
                codec, first = await negotiate_server(
                    reader, writer, max_body=MAX_FRAME,
                    max_version=self.max_version)
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                return
            fw = _FrameWriter(writer)
            while True:
                if first is not None:
                    header, body = first
                    first = None
                else:
                    try:
                        header, body = await _read_frame(reader, codec,
                                                         max_body=MAX_FRAME)
                    except (asyncio.IncompleteReadError, ConnectionError,
                            OSError):
                        return
                if "ping" in header:
                    try:
                        await fw.send(pack_frame(
                            codec, {"pong": header["ping"]}, b""))
                    except (ConnectionError, OSError):
                        return
                    continue
                # handle concurrently: one slow handler must not stall
                # the other requests multiplexed on this connection
                task = asyncio.create_task(
                    self._handle(header, body, fw, codec))
                inflight.add(task)
                task.add_done_callback(inflight.discard)
        finally:
            self._conn_writers.discard(writer)
            for task in inflight:
                task.cancel()
            if fw is not None:
                # stop() cancels this handler; the close must still run
                await asyncio.shield(fw.aclose())
            writer.close()
            try:
                await asyncio.shield(writer.wait_closed())
            except (ConnectionError, OSError):  # pragma: no cover
                pass

    async def _handle(self, header: dict, body: bytes | None,
                      fw: _FrameWriter, codec) -> None:
        rid = header.get("i")
        req_headers = {str(k).lower(): str(v)
                       for k, v in (header.get("h") or {}).items()}
        if body is None:  # oversized request, drained by _read_frame
            status, resp_headers, resp_body = (
                413, {"content-type": "application/json"},
                b'{"error": "request body exceeds the 16 MiB invoke limit"}')
        else:
            status, resp_headers, resp_body = await self._dispatch(
                header, body, req_headers)
        try:
            await fw.send(pack_frame(
                codec, {"i": rid, "s": status,
                        "h": outward_headers(resp_headers)}, resp_body))
        except (ConnectionError, OSError):  # peer went away mid-response
            pass

    async def _dispatch(self, header: dict, body: bytes,
                        req_headers: dict[str, str]) -> tuple[int, dict, bytes]:
        # token gate — identical policy to the HTTP invoke route
        # (allow_peer=True handler): own API token or a registered
        # peer's token; other apps' identities unlock nothing else
        if self.api_token is not None:
            supplied = req_headers.get(TOKEN_HEADER.lower())
            peer_ok = (supplied is not None
                       and hash_token(supplied) in self.peer_tokens)
            if supplied != self.api_token and not peer_ok:
                return 401, {"content-type": "application/json"}, \
                    b'{"error": "missing or bad api token"}'
        fwd = inward_headers(req_headers)
        ctx = ensure_trace(req_headers.get(TRACEPARENT_HEADER))
        try:
            with trace_scope(ctx):
                return await self.runtime.invoke(
                    header["t"], header.get("p", "/"),
                    http_method=header.get("m", "POST"),
                    query=header.get("q", ""), headers=fwd, body=body)
        except Exception as exc:  # noqa: BLE001 - mapped to status
            status = exc.http_status if isinstance(exc, TasksRunnerError) else 500
            if not isinstance(exc, TasksRunnerError):
                logger.exception("unhandled mesh invoke error")
            payload = json.dumps(
                {"error": str(exc) or type(exc).__name__}).encode()
            return status, {"content-type": "application/json"}, payload


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class _MeshConnection:
    def __init__(self, host: str, port: int, server_hostname: str | None = None):
        self.host = host
        self.port = port
        #: under mTLS, the app-id this connection expects the peer to
        #: prove (SAN check) — None on the plaintext mesh
        self.server_hostname = server_hostname
        self.closed = False
        self.codec = JsonHeaderCodec
        #: True iff the peer acked the hello — only then are control
        #: frames (idle pings) on the wire; a legacy peer would try to
        #: dispatch them as requests
        self.peer_aware = False
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._timeouts = 0  # consecutive request timeouts
        self._fw: _FrameWriter | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._reader_task: asyncio.Task | None = None

    async def connect(self) -> None:
        from tasksrunner.invoke.pki import client_ssl_context

        ctx = client_ssl_context()
        t0 = time.perf_counter()
        try:
            reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(
                    self.host, self.port, ssl=ctx,
                    server_hostname=(self.server_hostname
                                     if ctx is not None else None)),
                connect_timeout())
            self.codec, self.peer_aware = await negotiate_client(
                reader, self._writer, timeout=connect_timeout())
        except (OSError, asyncio.TimeoutError, ConnectionError,
                asyncio.IncompleteReadError) as exc:  # SSLError ⊂ OSError
            # a blackholed host times out here instead of holding the
            # caller for the kernel SYN-retry window; a failed TLS
            # handshake (wrong CA, wrong identity) or a garbled hello
            # is equally a this-peer-is-not-usable signal
            self.closed = True
            if self._writer is not None:
                self._writer.close()
            raise MeshConnectError(
                f"mesh peer {self.host}:{self.port} unreachable: {exc}") from exc
        _rec_dial(time.perf_counter() - t0)
        self._fw = _FrameWriter(self._writer, on_error=self._on_write_error)
        self._reader_task = asyncio.create_task(self._read_loop(reader))

    def _on_write_error(self, exc: Exception) -> None:
        self._fail_all(ConnectionError(
            f"mesh connection to {self.host}:{self.port} write failed: {exc}"))
        if self._writer is not None:
            self._writer.close()

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                header, body = await _read_frame(reader, self.codec)
                if "pong" in header:
                    fut = self._pending.pop(header["pong"], None)
                    if fut is not None and not fut.done():
                        fut.set_result((200, {}, b""))
                    continue
                fut = self._pending.pop(header.get("i"), None)
                if fut is not None and not fut.done():
                    fut.set_result((header.get("s", 500),
                                    header.get("h") or {}, body))
        except asyncio.CancelledError:
            self._fail_all(ConnectionError("mesh connection closed"))
            raise
        except BaseException as exc:  # noqa: BLE001 - ANY reader death
            # must resolve the pending futures (a malformed frame — not
            # just socket errors — would otherwise strand every caller
            # awaiting a response on this connection, forever)
            self._fail_all(ConnectionError(
                f"mesh connection to {self.host}:{self.port} lost: {exc}"))
        finally:
            self.closed = True
            # release the socket too — the pool may never touch this
            # connection again (peers restart onto fresh ephemeral
            # ports, so the (host, port) key can go stale)
            if self._writer is not None:
                self._writer.close()

    def _fail_all(self, exc: Exception) -> None:
        self.closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    def _condemn(self, reason: str) -> None:
        """Mark this connection dead NOW so the pool re-dials — used
        when the socket still looks open but the peer stopped
        answering (consecutive request timeouts, failed idle ping)."""
        logger.warning("mesh: %s", reason)
        self._fail_all(ConnectionError(reason))
        if self._writer is not None:
            self._writer.close()

    async def request(self, target: str, method: str, path: str, *,
                      query: str = "", headers: dict[str, str] | None = None,
                      body: bytes = b"") -> tuple[int, dict[str, str], bytes]:
        if self.closed:
            raise ConnectionError("mesh connection closed")
        rid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            assert self._fw is not None
            await self._fw.send(pack_frame(
                self.codec, {"i": rid, "t": target, "m": method, "p": path,
                             "q": query, "h": headers or {}}, body))
        except (ConnectionError, OSError):
            self._pending.pop(rid, None)
            self.closed = True
            raise
        try:
            result = await asyncio.wait_for(fut, request_timeout())
            self._timeouts = 0
            return result
        except asyncio.TimeoutError as exc:
            self._timeouts += 1
            if self._timeouts >= TIMEOUTS_BEFORE_CLOSE and not self.closed:
                self._condemn(
                    f"mesh peer {self.host}:{self.port} condemned after "
                    f"{self._timeouts} consecutive request timeouts")
            # bounded like the HTTP lane — re-raised as the BUILTIN
            # TimeoutError, which is an OSError subclass on every
            # supported Python (asyncio's own class only merged with it
            # in 3.11), so the runtime's transport retry policy treats
            # a hung peer exactly like a connection failure
            raise TimeoutError(
                f"mesh request to {self.host}:{self.port} timed out") from exc
        finally:
            self._pending.pop(rid, None)

    async def ping(self, timeout: float = 5.0) -> bool:
        """Idle liveness probe. Returns True when the peer answered (or
        cannot be probed: a legacy peer would dispatch the control
        frame as a request); a failed ping condemns the connection so
        the pool re-dials before any caller blocks on it."""
        if self.closed:
            return False
        if not self.peer_aware:
            return True
        rid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            assert self._fw is not None
            await self._fw.send(pack_frame(self.codec, {"ping": rid}, b""))
            await asyncio.wait_for(fut, timeout)
            return True
        except (ConnectionError, OSError, asyncio.TimeoutError):
            if not self.closed:
                self._condemn(
                    f"mesh peer {self.host}:{self.port} failed idle ping")
            return False
        finally:
            self._pending.pop(rid, None)

    async def close(self) -> None:
        self.closed = True
        if self._fw is not None:
            await self._fw.aclose()
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except asyncio.CancelledError:
                pass
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):  # pragma: no cover
                pass


class MeshPool:
    """One persistent multiplexed connection per peer address; dead
    connections are dropped and re-dialed on the next request — or
    re-dialed *before* it by the keepalive loop (pre-warmed routing:
    the resolver knows every peer at registration time, so dial cost
    is paid off the request path and dead peers are found early)."""

    def __init__(self):
        self._conns: dict[tuple, _MeshConnection] = {}
        self._dial_locks: dict[tuple, asyncio.Lock] = {}
        # refcount of callers currently inside (or queued on) a key's
        # dial section — _prune must never sweep those keys, or two
        # callers end up holding *different* lock objects for the same
        # key and dial concurrently (the loser's socket/reader leak)
        self._dialing: dict[tuple, int] = {}
        self._closed = False
        self._keepalive_task: asyncio.Task | None = None
        self._kick: asyncio.Event | None = None

    def _prune(self) -> None:
        """Drop dead connections under stale keys (peers restart onto
        fresh ephemeral ports, so old keys are never re-requested —
        without this sweep their sockets/locks accumulate forever).
        Keys with a dial in progress are skipped: their lock object is
        live in another task's hands."""
        for key, conn in list(self._conns.items()):
            if conn.closed and key not in self._dialing:
                del self._conns[key]
                self._dial_locks.pop(key, None)

    def _publish_gauge(self) -> None:
        metrics.set_gauge(
            "mesh_pool_connections",
            float(sum(1 for c in self._conns.values() if not c.closed)))

    async def ensure(self, host: str, port: int,
                     pin: str | None = None) -> _MeshConnection:
        """Return a live connection to ``(host, port)``, dialing one if
        absent — the pre-warm entry point (request() and the keepalive
        loop both come through here, so they share one dial section)."""
        if self._closed:
            raise ConnectionError("mesh pool closed")
        key = (host, port, pin)
        conn = self._conns.get(key)
        if conn is not None and not conn.closed:
            return conn
        # serialize dialing PER PEER so concurrent first requests
        # share one connection instead of leaking N-1 reader tasks
        # — while a slow/unreachable peer's dial never queues dials
        # to healthy peers behind it
        lock = self._dial_locks.setdefault(key, asyncio.Lock())
        self._dialing[key] = self._dialing.get(key, 0) + 1
        try:
            async with lock:
                conn = self._conns.get(key)
                if conn is None or conn.closed:
                    self._prune()  # dialing is rare: sweep stale keys
                    # the handshake must prove the app-id this request
                    # targets (one sidecar = one app)
                    conn = _MeshConnection(host, port, server_hostname=pin)
                    await conn.connect()
                    if self._closed:  # pool closed mid-dial
                        await conn.close()
                        raise ConnectionError("mesh pool closed")
                    self._conns[key] = conn
                    self._publish_gauge()
        finally:
            left = self._dialing[key] - 1
            if left:
                self._dialing[key] = left
            else:
                del self._dialing[key]
                live = self._conns.get(key)
                if live is None or live.closed:
                    # every dialer for this key failed and none are
                    # queued: reclaim the lock now. _prune can't —
                    # it walks _conns, and a never-connected key
                    # has no entry there (a dead-peer address would
                    # otherwise leak one Lock forever).
                    self._dial_locks.pop(key, None)
        return conn

    async def request(self, host: str, port: int, target: str, method: str,
                      path: str, *, query: str = "",
                      headers: dict[str, str] | None = None,
                      body: bytes = b"") -> tuple[int, dict[str, str], bytes]:
        if self._closed:
            raise ConnectionError("mesh pool closed")
        from tasksrunner.invoke.pki import mesh_tls_enabled

        # under mTLS a connection IS an identity: key it by the pinned
        # app-id too, so a pooled connection verified as app A is never
        # reused for a request targeting app B (that reuse would skip
        # the SAN check entirely). Plaintext mode keeps one connection
        # per address — identity there is the token layer's job.
        pin = target if mesh_tls_enabled() else None
        conn = await self.ensure(host, port, pin)
        return await conn.request(target, method, path, query=query,
                                  headers=headers, body=body)

    def start_keepalive(self, peers, *, interval: float | None = None) -> None:
        """Start the pre-warm/keepalive loop. ``peers`` is a callable
        returning ``(host, port, pin)`` triples — typically bound to
        the name resolver, which learns every peer at registration
        time. Each tick dials absent peers off the request path and
        idle-pings pooled ones (a failed ping condemns the connection
        so the next tick — or the next caller — re-dials). Disabled
        when the interval is <= 0."""
        if interval is None:
            interval = ping_interval()
        if interval <= 0 or self._keepalive_task is not None or self._closed:
            return
        self._kick = asyncio.Event()
        self._keepalive_task = asyncio.create_task(
            self._keepalive_loop(peers, interval))

    def kick(self) -> None:
        """Wake the keepalive loop now (a registration just landed, so
        new peers are dialable before the first interval elapses)."""
        if self._kick is not None:
            self._kick.set()

    async def _keepalive_loop(self, peers, interval: float) -> None:
        while not self._closed:
            try:
                targets = list(peers())
            except Exception:  # noqa: BLE001 - resolver hiccup, retry next tick
                logger.debug("mesh keepalive: peer enumeration failed",
                             exc_info=True)
                targets = []
            for host, port, pin in targets:
                if self._closed:
                    return
                conn = self._conns.get((host, port, pin))
                try:
                    if conn is None or conn.closed:
                        await self.ensure(host, port, pin)
                    else:
                        await conn.ping()
                except (ConnectionError, OSError):
                    pass  # peer down; callers fall back, next tick retries
            self._publish_gauge()
            assert self._kick is not None
            try:
                await asyncio.wait_for(self._kick.wait(), interval)
            except asyncio.TimeoutError:
                pass
            self._kick.clear()

    async def close(self) -> None:
        self._closed = True  # stop request() from inserting new conns
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
            try:
                await self._keepalive_task
            except asyncio.CancelledError:
                pass
            self._keepalive_task = None
        for conn in list(self._conns.values()):
            await conn.close()
        self._conns.clear()
        self._dial_locks.clear()
        self._publish_gauge()
