"""Framed peer-invocation transport (the sidecar↔sidecar lane).

In the reference, applications program the sidecar's HTTP surface, but
the sidecars talk to EACH OTHER over Dapr's internal gRPC transport
with mTLS (docs/aca/03-aca-dapr-integration/index.md:30-38 — "Dapr
sidecars communicate over mutual TLS"; the `/v1.0/invoke/...` HTTP
shape is the app→sidecar API, docs module 3 :107-127). This module is
that internal lane for this framework: a persistent TCP connection per
peer carrying length-prefixed multiplexed request/response frames —
no per-request connection setup, no HTTP/1.1 parsing on either end.
Measured on the bench topology it cuts the peer-hop cost roughly 3×
versus aiohttp client+server.

Behavioral contract (must stay identical to the sidecar HTTP route
``/v1.0/invoke/{app-id}/method/{path}`` in sidecar.py):

* same token rules — the receiving app's own API token OR a registered
  peer app's token (digest match) is accepted, nothing else;
* same trace adoption — the ``traceparent`` header opens a trace scope
  on the server before dispatch;
* same header filtering — only content-type/accept/x-* travel inward,
  hop-by-hop headers are dropped outward;
* same error mapping — TasksRunnerError → its http_status, anything
  else → 500, body ``{"error": ...}``.

Wire format, both directions::

    [u32 frame_len][u32 header_len][header JSON][body bytes]

Request header ``{"i": id, "t": target, "m": method, "p": path,
"q": query, "h": {...}}``; response ``{"i": id, "s": status,
"h": {...}}``. Frames interleave freely; ``i`` correlates them.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import struct
from typing import TYPE_CHECKING

from tasksrunner.errors import TasksRunnerError
from tasksrunner.invoke.headers import inward_headers, outward_headers
from tasksrunner.observability.tracing import (
    TRACEPARENT_HEADER,
    ensure_trace,
    trace_scope,
)
from tasksrunner.security import (
    TOKEN_ENV,
    TOKEN_HEADER,
    hash_token,
    load_token_map,
)

if TYPE_CHECKING:  # pragma: no cover - import cycle guard
    from tasksrunner.runtime import Runtime

logger = logging.getLogger(__name__)

_U32 = struct.Struct(">I")
#: request cap matches the sidecar HTTP server's client_max_size —
#: and like HTTP (where client_max_size bounds requests only, not
#: responses) it applies to the request direction alone
MAX_FRAME = 16 * 1024 * 1024
#: header JSON is tiny metadata; anything bigger is a corrupt stream
MAX_HEADER = 64 * 1024
#: how long a dial may take before the peer is declared unreachable
#: and the caller falls back to HTTP (a blackholed host must not hold
#: invokes for the kernel's SYN-retry window)
CONNECT_TIMEOUT = 2.0
#: per-request ceiling, matching the HTTP lane's bounded failure
#: (aiohttp's default 300 s total timeout): a hung peer handler or a
#: half-open connection must surface as a retriable TimeoutError (an
#: OSError subclass), never an unbounded hang
REQUEST_TIMEOUT = 300.0


class MeshConnectError(ConnectionError):
    """Could not establish the peer connection (distinct from an
    in-flight drop so the caller can fall back to HTTP within the
    same attempt instead of burning a retry)."""


def _pack(header: dict, body: bytes) -> bytes:
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return _U32.pack(4 + len(hdr) + len(body)) + _U32.pack(len(hdr)) + hdr + body


#: absolute insanity bound on any frame (a corrupt length prefix must
#: not make readexactly buffer gigabytes); far above any legit payload
_SANITY_FRAME = 1 << 30


async def _read_frame(reader: asyncio.StreamReader, *,
                      max_body: int | None = None) -> tuple[dict, bytes | None]:
    """Read one frame. With ``max_body`` set (the server's request
    direction), an oversized body is drained off the wire and returned
    as ``None`` so the caller can answer 413 and keep the connection —
    the same observable outcome as the HTTP route's client_max_size.
    A structurally corrupt frame raises ConnectionError (tear down)."""
    (frame_len,) = _U32.unpack(await reader.readexactly(4))
    if frame_len < 4 or frame_len > _SANITY_FRAME:
        raise ConnectionError(f"mesh frame corrupt: len={frame_len}")
    (hdr_len,) = _U32.unpack(await reader.readexactly(4))
    if hdr_len > frame_len - 4 or hdr_len > MAX_HEADER:
        raise ConnectionError(f"mesh frame header corrupt: len={hdr_len}")
    try:
        header = json.loads(await reader.readexactly(hdr_len))
    except ValueError as exc:
        raise ConnectionError(f"mesh frame header not JSON: {exc}") from exc
    body_len = frame_len - 4 - hdr_len
    if max_body is not None and body_len > max_body:
        remaining = body_len
        while remaining:
            chunk = await reader.read(min(1 << 16, remaining))
            if not chunk:
                raise asyncio.IncompleteReadError(b"", remaining)
            remaining -= len(chunk)
        return header, None
    return header, await reader.readexactly(body_len)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class MeshServer:
    """Accepts peer frames and dispatches them into the local Runtime —
    the same entry point the sidecar HTTP invoke route uses."""

    def __init__(self, runtime: "Runtime", *, host: str = "127.0.0.1",
                 port: int = 0, api_token: str | None = None,
                 peer_tokens: set[str] | None = None):
        self.runtime = runtime
        self.host = host
        self.port = port
        if api_token is None:
            api_token = os.environ.get(TOKEN_ENV) or None
        self.api_token = api_token
        if peer_tokens is None:
            # sha256 digests: authenticate inbound peers without being
            # able to replay their tokens (sidecar.py does the same)
            peer_tokens = set(load_token_map().values())
        self.peer_tokens = peer_tokens
        self._server: asyncio.base_events.Server | None = None
        self._conn_writers: set[asyncio.StreamWriter] = set()

    async def start(self) -> None:
        from tasksrunner.invoke.pki import server_ssl_context

        # mTLS when the environment provisioned certs (invoke/pki.py,
        # ≙ Dapr sentry's workload certificates); plaintext otherwise
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port,
            ssl=server_ssl_context())
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # established peer connections are long-lived by design —
            # close them or wait_closed() (which on 3.12+ waits for the
            # per-connection handlers too) never returns
            for writer in list(self._conn_writers):
                writer.close()
            await self._server.wait_closed()
            self._server = None

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        wlock = asyncio.Lock()
        inflight: set[asyncio.Task] = set()
        self._conn_writers.add(writer)
        try:
            while True:
                try:
                    header, body = await _read_frame(reader,
                                                     max_body=MAX_FRAME)
                except (asyncio.IncompleteReadError, ConnectionError, OSError):
                    return
                # handle concurrently: one slow handler must not stall
                # the other requests multiplexed on this connection
                task = asyncio.create_task(
                    self._handle(header, body, writer, wlock))
                inflight.add(task)
                task.add_done_callback(inflight.discard)
        finally:
            self._conn_writers.discard(writer)
            for task in inflight:
                task.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):  # pragma: no cover
                pass

    async def _handle(self, header: dict, body: bytes | None,
                      writer: asyncio.StreamWriter, wlock: asyncio.Lock) -> None:
        rid = header.get("i")
        req_headers = {str(k).lower(): str(v)
                       for k, v in (header.get("h") or {}).items()}
        if body is None:  # oversized request, drained by _read_frame
            status, resp_headers, resp_body = (
                413, {"content-type": "application/json"},
                b'{"error": "request body exceeds the 16 MiB invoke limit"}')
        else:
            status, resp_headers, resp_body = await self._dispatch(
                header, body, req_headers)
        frame = _pack({"i": rid, "s": status,
                       "h": outward_headers(resp_headers)}, resp_body)
        try:
            async with wlock:
                writer.write(frame)
                await writer.drain()
        except (ConnectionError, OSError):  # peer went away mid-response
            pass

    async def _dispatch(self, header: dict, body: bytes,
                        req_headers: dict[str, str]) -> tuple[int, dict, bytes]:
        # token gate — identical policy to the HTTP invoke route
        # (allow_peer=True handler): own API token or a registered
        # peer's token; other apps' identities unlock nothing else
        if self.api_token is not None:
            supplied = req_headers.get(TOKEN_HEADER.lower())
            peer_ok = (supplied is not None
                       and hash_token(supplied) in self.peer_tokens)
            if supplied != self.api_token and not peer_ok:
                return 401, {"content-type": "application/json"}, \
                    b'{"error": "missing or bad api token"}'
        fwd = inward_headers(req_headers)
        ctx = ensure_trace(req_headers.get(TRACEPARENT_HEADER))
        try:
            with trace_scope(ctx):
                return await self.runtime.invoke(
                    header["t"], header.get("p", "/"),
                    http_method=header.get("m", "POST"),
                    query=header.get("q", ""), headers=fwd, body=body)
        except Exception as exc:  # noqa: BLE001 - mapped to status
            status = exc.http_status if isinstance(exc, TasksRunnerError) else 500
            if not isinstance(exc, TasksRunnerError):
                logger.exception("unhandled mesh invoke error")
            payload = json.dumps(
                {"error": str(exc) or type(exc).__name__}).encode()
            return status, {"content-type": "application/json"}, payload


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class _MeshConnection:
    def __init__(self, host: str, port: int, server_hostname: str | None = None):
        self.host = host
        self.port = port
        #: under mTLS, the app-id this connection expects the peer to
        #: prove (SAN check) — None on the plaintext mesh
        self.server_hostname = server_hostname
        self.closed = False
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._wlock = asyncio.Lock()
        self._writer: asyncio.StreamWriter | None = None
        self._reader_task: asyncio.Task | None = None

    async def connect(self) -> None:
        from tasksrunner.invoke.pki import client_ssl_context

        ctx = client_ssl_context()
        try:
            reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(
                    self.host, self.port, ssl=ctx,
                    server_hostname=(self.server_hostname
                                     if ctx is not None else None)),
                CONNECT_TIMEOUT)
        except (OSError, asyncio.TimeoutError) as exc:  # SSLError ⊂ OSError
            # a blackholed host times out here instead of holding the
            # caller for the kernel SYN-retry window; a failed TLS
            # handshake (wrong CA, wrong identity) is equally a
            # this-peer-is-not-usable signal
            self.closed = True
            raise MeshConnectError(
                f"mesh peer {self.host}:{self.port} unreachable: {exc}") from exc
        self._reader_task = asyncio.create_task(self._read_loop(reader))

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                header, body = await _read_frame(reader)
                fut = self._pending.pop(header.get("i"), None)
                if fut is not None and not fut.done():
                    fut.set_result((header.get("s", 500),
                                    header.get("h") or {}, body))
        except asyncio.CancelledError:
            self._fail_all(ConnectionError("mesh connection closed"))
            raise
        except BaseException as exc:  # noqa: BLE001 - ANY reader death
            # must resolve the pending futures (a malformed frame — not
            # just socket errors — would otherwise strand every caller
            # awaiting a response on this connection, forever)
            self._fail_all(ConnectionError(
                f"mesh connection to {self.host}:{self.port} lost: {exc}"))
        finally:
            self.closed = True
            # release the socket too — the pool may never touch this
            # connection again (peers restart onto fresh ephemeral
            # ports, so the (host, port) key can go stale)
            if self._writer is not None:
                self._writer.close()

    def _fail_all(self, exc: Exception) -> None:
        self.closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    async def request(self, target: str, method: str, path: str, *,
                      query: str = "", headers: dict[str, str] | None = None,
                      body: bytes = b"") -> tuple[int, dict[str, str], bytes]:
        if self.closed:
            raise ConnectionError("mesh connection closed")
        rid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        frame = _pack({"i": rid, "t": target, "m": method, "p": path,
                       "q": query, "h": headers or {}}, body)
        try:
            async with self._wlock:
                assert self._writer is not None
                self._writer.write(frame)
                await self._writer.drain()
        except (ConnectionError, OSError):
            self._pending.pop(rid, None)
            self.closed = True
            raise
        try:
            # bounded like the HTTP lane: TimeoutError is an OSError
            # subclass, so the runtime's transport retry policy treats
            # a hung peer exactly like a connection failure
            return await asyncio.wait_for(fut, REQUEST_TIMEOUT)
        finally:
            self._pending.pop(rid, None)

    async def close(self) -> None:
        self.closed = True
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except asyncio.CancelledError:
                pass
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):  # pragma: no cover
                pass


class MeshPool:
    """One persistent multiplexed connection per peer address; dead
    connections are dropped and re-dialed on the next request."""

    def __init__(self):
        self._conns: dict[tuple, _MeshConnection] = {}
        self._dial_locks: dict[tuple, asyncio.Lock] = {}
        # refcount of callers currently inside (or queued on) a key's
        # dial section — _prune must never sweep those keys, or two
        # callers end up holding *different* lock objects for the same
        # key and dial concurrently (the loser's socket/reader leak)
        self._dialing: dict[tuple, int] = {}
        self._closed = False

    def _prune(self) -> None:
        """Drop dead connections under stale keys (peers restart onto
        fresh ephemeral ports, so old keys are never re-requested —
        without this sweep their sockets/locks accumulate forever).
        Keys with a dial in progress are skipped: their lock object is
        live in another task's hands."""
        for key, conn in list(self._conns.items()):
            if conn.closed and key not in self._dialing:
                del self._conns[key]
                self._dial_locks.pop(key, None)

    async def request(self, host: str, port: int, target: str, method: str,
                      path: str, *, query: str = "",
                      headers: dict[str, str] | None = None,
                      body: bytes = b"") -> tuple[int, dict[str, str], bytes]:
        if self._closed:
            raise ConnectionError("mesh pool closed")
        from tasksrunner.invoke.pki import mesh_tls_enabled

        # under mTLS a connection IS an identity: key it by the pinned
        # app-id too, so a pooled connection verified as app A is never
        # reused for a request targeting app B (that reuse would skip
        # the SAN check entirely). Plaintext mode keeps one connection
        # per address — identity there is the token layer's job.
        pin = target if mesh_tls_enabled() else None
        key = (host, port, pin)
        conn = self._conns.get(key)
        if conn is None or conn.closed:
            # serialize dialing PER PEER so concurrent first requests
            # share one connection instead of leaking N-1 reader tasks
            # — while a slow/unreachable peer's dial never queues dials
            # to healthy peers behind it
            lock = self._dial_locks.setdefault(key, asyncio.Lock())
            self._dialing[key] = self._dialing.get(key, 0) + 1
            try:
                async with lock:
                    conn = self._conns.get(key)
                    if conn is None or conn.closed:
                        self._prune()  # dialing is rare: sweep stale keys
                        # the handshake must prove the app-id this request
                        # targets (one sidecar = one app)
                        conn = _MeshConnection(host, port,
                                               server_hostname=pin)
                        await conn.connect()
                        if self._closed:  # pool closed mid-dial
                            await conn.close()
                            raise ConnectionError("mesh pool closed")
                        self._conns[key] = conn
            finally:
                left = self._dialing[key] - 1
                if left:
                    self._dialing[key] = left
                else:
                    del self._dialing[key]
                    live = self._conns.get(key)
                    if live is None or live.closed:
                        # every dialer for this key failed and none are
                        # queued: reclaim the lock now. _prune can't —
                        # it walks _conns, and a never-connected key
                        # has no entry there (a dead-peer address would
                        # otherwise leak one Lock forever).
                        self._dial_locks.pop(key, None)
        return await conn.request(target, method, path, query=query,
                                  headers=headers, body=body)

    async def close(self) -> None:
        self._closed = True  # stop request() from inserting new conns
        for conn in list(self._conns.values()):
            await conn.close()
        self._conns.clear()
        self._dial_locks.clear()
