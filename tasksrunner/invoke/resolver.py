"""App-id name resolution for service invocation.

The reference's sidecar resolves ``InvokeMethodAsync(..., "tasksmanager-
backend-api", ...)`` to a peer sidecar by app-id (mDNS locally, the ACA
control plane in the cloud — docs/aca/03-aca-dapr-integration/index.md:
107-127). Here the registry is a JSON file shared by all local
sidecars: each sidecar registers itself on startup, peers re-read on
miss or mtime change. A static in-memory mode serves tests and
single-process setups.
"""

from __future__ import annotations

import json
import os
import pathlib
import tempfile
import time
from dataclasses import asdict, dataclass

from tasksrunner.errors import AppNotFound


@dataclass
class AppAddress:
    app_id: str
    host: str
    sidecar_port: int
    app_port: int | None = None
    pid: int | None = None
    registered_at: float = 0.0
    #: framed peer-transport port (invoke/mesh.py — the sidecar↔sidecar
    #: lane, ≙ Dapr's internal gRPC). None = peer only speaks HTTP.
    mesh_port: int | None = None

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.sidecar_port}"


class NameResolver:
    """app-id → AppAddress, backed by a static table and/or a registry file."""

    def __init__(self, *, registry_file: str | pathlib.Path | None = None,
                 static: dict[str, AppAddress] | None = None):
        self.registry_file = pathlib.Path(registry_file) if registry_file else None
        self._static = dict(static or {})
        self._cache: dict[str, AppAddress] = {}
        self._mtime = 0.0

    # -- registration ----------------------------------------------------

    def register(self, addr: AppAddress) -> None:
        addr.registered_at = time.time()
        if addr.pid is None:
            addr.pid = os.getpid()
        if self.registry_file is None:
            self._static[addr.app_id] = addr
            return
        self._mutate(lambda entries: entries.__setitem__(addr.app_id, asdict(addr)))

    def unregister(self, app_id: str) -> None:
        if self.registry_file is None:
            self._static.pop(app_id, None)
            return
        self._mutate(lambda entries: entries.pop(app_id, None))

    def _mutate(self, fn) -> None:
        """Atomic read-modify-write with a lock file (cross-process)."""
        assert self.registry_file is not None
        self.registry_file.parent.mkdir(parents=True, exist_ok=True)
        lock = self.registry_file.with_suffix(".lock")
        deadline = time.time() + 5.0
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                break
            except FileExistsError:
                if time.time() > deadline:
                    # stale lock (holder crashed): steal it once, then
                    # give the normal acquisition window again so we
                    # don't unlink locks live processes just created
                    try:
                        lock.unlink()
                    except FileNotFoundError:
                        pass
                    deadline = time.time() + 5.0
                time.sleep(0.01)
        try:
            entries = self._read_file()
            fn(entries)
            tmp_fd, tmp_path = tempfile.mkstemp(dir=self.registry_file.parent)
            with os.fdopen(tmp_fd, "w") as f:
                json.dump(entries, f, indent=2)
            os.replace(tmp_path, self.registry_file)
        finally:
            os.close(fd)
            try:
                lock.unlink()
            except FileNotFoundError:
                pass

    def _read_file(self) -> dict[str, dict]:
        if self.registry_file is None or not self.registry_file.is_file():
            return {}
        try:
            return json.loads(self.registry_file.read_text() or "{}")
        except ValueError:
            return {}

    # -- resolution ------------------------------------------------------

    def _refresh(self) -> None:
        if self.registry_file is None:
            return
        try:
            mtime = self.registry_file.stat().st_mtime
        except OSError:
            return
        if mtime == self._mtime:
            return
        self._mtime = mtime
        self._cache = {
            app_id: AppAddress(**entry) for app_id, entry in self._read_file().items()
        }

    def resolve(self, app_id: str) -> AppAddress:
        if app_id in self._static:
            return self._static[app_id]
        self._refresh()
        if app_id in self._cache:
            return self._cache[app_id]
        # force one re-read in case the peer registered this instant
        self._mtime = 0.0
        self._refresh()
        try:
            return self._cache[app_id]
        except KeyError:
            known = sorted({*self._static, *self._cache})
            raise AppNotFound(
                f"no app registered with id {app_id!r} (known: {known})"
            ) from None

    def known_apps(self) -> list[str]:
        self._refresh()
        return sorted({*self._static, *self._cache})
